"""Explore targets: the model adapter a campaign/triage/shrink run drives.

A ``Target`` is everything the explore loop needs to know about a model:
how to build a (workload, engine config) pair for a candidate fault spec,
how to summarize a finished sweep (the summary must carry
``coverage_map`` — any ``models/_common.make_sweep_summary`` product
does), and how to read an event's victim node out of a trace row for
fingerprinting. Keeping this a 5-field adapter means a new model joins
the explore pipeline with ~10 lines, no changes to the loop.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

from ..engine.core import EngineConfig, Workload


class Target(NamedTuple):
    """One explorable model configuration family.

    ``build(faults)`` maps a fault spec (``FaultSpec`` or ``FixedFaults``)
    to a ready ``(Workload, EngineConfig)`` pair — everything else about
    the model (nodes, workload plan, time limit) stays pinned, so
    coverage/violation differences between candidates are attributable
    to the fault environment alone."""

    name: str
    build: Callable[[object], Tuple[Workload, EngineConfig]]
    summarize: Callable[[object], dict]
    num_nodes: int
    fault_kind: int
    #: (kind, pay_row) -> victim node of the event, for fingerprints
    node_of: Callable[[int, object], int]
    #: finished batched EngineState -> violating seed array (the model
    #: decides what "violating" means; raft latches wstate.violation,
    #: history targets run the linearizability checker per lane)
    violating: Callable[[object], object]
    #: sequential spec (oracle/specs.py) for the workload's recorded op
    #: histories; set iff the workload records one (enables the
    #: ``history`` triage flavor and history-verified shrinking)
    hist_spec: Optional[object] = None


def amnesia_raft_target(
    time_limit_ns: int = 3_000_000_000,
    max_steps: int = 30_000,
    hist_slots: int = 0,
) -> Target:
    """The canonical explore target: the 3-node amnesia Raft cluster of
    ``replay.amnesia_raft_config()`` — crash wipes durable state, so the
    election-safety detector (``V_ELECTION``) can actually fire — with
    the fault campaign left OPEN for the explore loop to choose.

    ``hist_slots > 0`` turns on election-history recording and the
    oracle leg: the target gains ``hist_spec``
    (``oracle.specs.ElectionSpec``), so campaigns run the device-side
    election screen behind every chunk and the checker over the suspect
    lanes — the coverage-guided + history-checked configuration the
    sharded million-seed campaign sweeps (``explore.fleet``).
    "Violating" stays the model's latched flag either way: for raft the
    election screen is PRECISE (== ``ElectionSpec.structural``), so the
    two signals agree seed for seed (asserted in tests/test_oracle.py)."""
    from ..models import raft
    from ..replay import amnesia_raft_config, violation_seeds

    base_cfg, _ = amnesia_raft_config()
    if hist_slots:
        base_cfg = base_cfg._replace(hist_slots=hist_slots)

    def build(faults) -> Tuple[Workload, EngineConfig]:
        cfg = base_cfg._replace(faults=faults)
        ecfg = raft.engine_config(
            cfg, time_limit_ns=time_limit_ns, max_steps=max_steps
        )
        return raft.workload(cfg), ecfg

    def node_of(kind: int, pay) -> int:
        return int(pay[1]) if kind == raft.K_FAULT else int(pay[0])

    return Target(
        name="raft-amnesia",
        build=build,
        summarize=raft.sweep_summary,
        num_nodes=base_cfg.num_nodes,
        fault_kind=raft.K_FAULT,
        node_of=node_of,
        violating=violation_seeds,
        hist_spec=raft.history_spec() if hist_slots else None,
    )


# the (target, base FaultSpec) pair the multichip gates sweep — ONE
# definition shared by the __graft_entry__ dryrun curve and
# scripts/multichip_campaign.py, so retuning the gate spec (e.g. a
# crash-window change that keeps violations > 0) retunes every gate
def amnesia_gate(smoke: bool = True):
    from ..engine.faults import FaultSpec

    target = amnesia_raft_target(
        time_limit_ns=1_500_000_000 if smoke else 3_000_000_000,
        max_steps=15_000 if smoke else 30_000,
        hist_slots=16,
    )
    base = FaultSpec(
        crashes=3,
        crash_window_ns=1_200_000_000 if smoke else 2_000_000_000,
        restart_lo_ns=50_000_000,
        restart_hi_ns=300_000_000,
    )
    return target, base


# the (target, base FaultSpec) pairs the steering A/B drills sweep
# (scripts/steer_demo.py, bench.py --steering, the determinism gate's
# steering leg). The raft pair reuses the amnesia gate spec on purpose:
# its base family is pure crashes, so the default family universe
# (explore.steer.family_universe) is mostly amnesia-blind duds — the
# exact shape where a uniform grid burns budget and the bandit's
# early-kill pays. The etcd pair reuses the oracle demo's partition
# spec the same way.
def steer_gate(smoke: bool = True):
    return amnesia_gate(smoke)


def etcd_steer_gate(smoke: bool = True):
    target = stale_etcd_target(
        time_limit_ns=1_000_000_000 if smoke else 2_000_000_000,
        max_steps=10_000 if smoke else 20_000,
    )
    return target, oracle_demo_faults()


# the fault environment the history-oracle pipeline runs under — ONE
# definition shared by scripts/oracle_demo.py, scripts/replay_seed.py
# (--model etcd) and the determinism gate's history leg, so a seed one
# of them reports reproduces under the others (same (spec, seed) ->
# same schedule -> same decoded history)
def oracle_demo_faults():
    from ..engine.faults import FaultSpec

    return FaultSpec(
        partitions=2, part_window_ns=1_500_000_000, part_group=(1, -1)
    )


def stale_etcd_target(
    time_limit_ns: int = 2_000_000_000,
    max_steps: int = 20_000,
    hist_slots: int = 256,
    bug_stale_read: bool = True,
) -> Target:
    """The history-oracle demo target: the etcd cluster with
    ``bug_stale_read`` seeded — GETs serve the pre-mutation value, which
    no online invariant latch can see (revision and lease bookkeeping
    stay intact) — and history recording on, so "violating" means *the
    WGL checker rejects the seed's decoded history* against the KV
    register spec. Pass ``bug_stale_read=False`` for the matching clean
    control (the checker must stay quiet over any pinned seed range)."""
    from ..models import etcd
    from ..oracle.check import violating_seeds as history_violating

    base_cfg = etcd.EtcdConfig(
        bug_stale_read=bug_stale_read, hist_slots=hist_slots
    )
    spec = etcd.history_spec()

    def build(faults) -> Tuple[Workload, EngineConfig]:
        cfg = base_cfg._replace(faults=faults)
        ecfg = etcd.engine_config(
            cfg, time_limit_ns=time_limit_ns, max_steps=max_steps
        )
        return etcd.workload(cfg), ecfg

    def node_of(kind: int, pay) -> int:
        return int(pay[1]) if kind == etcd.K_FAULT else int(pay[0])

    return Target(
        name="etcd-stale" if bug_stale_read else "etcd-clean",
        build=build,
        summarize=etcd.sweep_summary,
        num_nodes=base_cfg.num_nodes,
        fault_kind=etcd.K_FAULT,
        node_of=node_of,
        # screened: the device first pass (oracle/screen.py) clears the
        # boring lanes and WGL runs on the suspects only — identical
        # seeds by the conservatism contract, so campaign loops can use
        # the oracle as their red-seed signal at sweep speed
        violating=lambda final: history_violating(final, spec, screen=True),
        hist_spec=spec,
    )
