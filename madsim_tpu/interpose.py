"""Python-level interposition of stdlib nondeterminism sources.

The reference interposes at the libc boundary with ``#[no_mangle]`` symbol
overrides: ``getrandom``/``getentropy`` route into GlobalRng
(madsim/src/sim/rand.rs:197-260), ``clock_gettime``/``gettimeofday`` return
sim time (sim/time/system_time.rs:4-113), and ``pthread_attr_init`` blocks
thread creation unless ``MADSIM_ALLOW_SYSTEM_THREAD`` is set
(sim/task/mod.rs:707-785).

The Python analogue patches the stdlib entry points **once**, with dispatching
wrappers that check the ambient sim context per call: inside a simulation they
produce deterministic values from the runtime's GlobalRng / virtual clock;
outside they fall through to the real implementation.  This makes the patch
safe under concurrent seed-sweep threads (each thread has its own ambient
handle) — the same property the reference gets from thread-local context.

Intercepted:  ``random.*`` (module-level functions), ``os.urandom``,
``uuid.uuid4``, ``time.{time,time_ns,monotonic,monotonic_ns,perf_counter,
perf_counter_ns}``, ``threading.Thread.start`` (blocked in sim unless
allowed), ``os.cpu_count`` (reports the node's configured cores), and
``datetime.datetime`` / ``datetime.date`` (module attributes swapped for
sim-aware subclasses whose ``now``/``utcnow``/``today`` read the virtual
clock; the C methods themselves are unpatchable).  Pre-existing aliases
— code that ran ``from datetime import datetime`` *before* the sim
started — are rebound by scanning every loaded module's dict for
attributes holding the real classes (freezegun's approach) and restored
on uninstall; the remaining (documented) hole is non-module references
captured before install, e.g. a class attribute or closure cell holding
the real class.  Sim-aware ``datetime.now()`` returns UTC-based naive
time so results don't depend on the host machine's timezone database.
"""

from __future__ import annotations

import os
import threading
import uuid as _uuid_mod
from contextlib import contextmanager
from typing import Any, Iterator

from .context import try_current_handle

_lock = threading.Lock()
_install_count = 0
_originals: dict = {}
# (module, attr, real_class) triples rebound by the alias scan, for restore
_rebound_aliases: list = []
# alias-scan memo: module name -> id() at last scan, and the discovered
# (attr, kind) sites per module — repeat installs only rescan modules
# that appeared (or were reloaded) since, instead of every attribute of
# every module (measured ~3 ms/scan; installs happen per block_on)
# name -> module (weak): compared by identity against the LIVE object, so a
# re-imported module whose object happens to reuse a freed id is re-scanned
# instead of silently skipped
import weakref as _weakref

_scanned_mods: "_weakref.WeakValueDictionary" = _weakref.WeakValueDictionary()
_alias_sites: dict = {}


class _SimRandomDispatch:
    """random-module replacement functions backed by the ambient GlobalRng."""

    @staticmethod
    def random() -> float:
        h = try_current_handle()
        if h is None:
            return _originals["random.random"]()
        return h.rng.random()

    @staticmethod
    def getrandbits(k: int) -> int:
        h = try_current_handle()
        if h is None:
            return _originals["random.getrandbits"](k)
        out = 0
        bits = 0
        while bits < k:
            out |= h.rng.next_u64() << bits
            bits += 64
        return out & ((1 << k) - 1)

    @staticmethod
    def randbytes(n: int) -> bytes:
        h = try_current_handle()
        if h is None:
            return _originals["random.randbytes"](n)
        return h.rng.sample_bytes(n)

    @staticmethod
    def randrange(start: int, stop: Any = None, step: int = 1) -> int:
        h = try_current_handle()
        if h is None:
            return _originals["random.randrange"](start, stop, step)
        if stop is None:
            start, stop = 0, start
        width = (stop - start + step - 1) // step if step > 0 else None
        if width is None or width <= 0:
            raise ValueError("empty range for randrange")
        return start + step * h.rng.gen_range(0, width)

    @staticmethod
    def randint(a: int, b: int) -> int:
        h = try_current_handle()
        if h is None:
            return _originals["random.randint"](a, b)
        return h.rng.gen_range(a, b + 1)

    @staticmethod
    def uniform(a: float, b: float) -> float:
        h = try_current_handle()
        if h is None:
            return _originals["random.uniform"](a, b)
        return h.rng.uniform(a, b)

    @staticmethod
    def choice(seq: Any) -> Any:
        h = try_current_handle()
        if h is None:
            return _originals["random.choice"](seq)
        return h.rng.choice(seq)

    @staticmethod
    def shuffle(seq: Any) -> None:
        h = try_current_handle()
        if h is None:
            return _originals["random.shuffle"](seq)
        return h.rng.shuffle(seq)


def _sim_urandom(n: int) -> bytes:
    h = try_current_handle()
    if h is None:
        return _originals["os.urandom"](n)
    return h.rng.sample_bytes(n)


def _sim_uuid4() -> "_uuid_mod.UUID":
    h = try_current_handle()
    if h is None:
        return _originals["uuid.uuid4"]()
    return _uuid_mod.UUID(bytes=h.rng.sample_bytes(16), version=4)


def _make_clock(name: str, kind: str, ns: bool):
    def clock() -> Any:
        h = try_current_handle()
        if h is None:
            return _originals[name]()
        t = h.time.now_time_ns() if kind == "wall" else h.time.now_ns
        return t if ns else t / 1e9

    clock.__name__ = name.split(".")[-1]
    return clock


def _sim_thread_start(self: threading.Thread, *args: Any, **kwargs: Any) -> Any:
    h = try_current_handle()
    if h is not None and not getattr(h, "allow_system_thread", False):
        raise RuntimeError(
            "attempted to spawn an OS thread inside a deterministic "
            "simulation; real threads break determinism. Use "
            "madsim_tpu.spawn() for concurrency, or set "
            "MADSIM_ALLOW_SYSTEM_THREAD=1 if you know what you are doing "
            "(ref: madsim blocks pthread creation, sim/task/mod.rs:761-785)"
        )
    return _originals["threading.Thread.start"](self, *args, **kwargs)


def _make_datetime_classes():
    """Sim-aware ``datetime``/``date`` subclasses (built lazily at install
    so the saved originals are whatever the process currently has).

    The reference fixes this whole class of leak at the libc boundary —
    ``clock_gettime``/``gettimeofday`` overrides (sim/time/system_time.rs:
    4-113) — which Python cannot do; swapping the module attributes is the
    closest interposition point above the C layer.
    """
    import datetime as _dt

    real_datetime = _originals["datetime.datetime"]
    real_date = _originals["datetime.date"]

    # isinstance/issubclass against the swapped classes must behave exactly
    # like checks against the real ones (freezegun-style): a real datetime
    # created before the swap is an instance of SimDateTime, and
    # SimDateTime.now() is an instance of SimDate (datetime ⊂ date holds).
    # Without this, serializer-style `isinstance(x, datetime.date)` dispatch
    # would take different branches inside vs outside the sim.
    def _delegating_meta(real_cls):
        class _Meta(type):
            def __instancecheck__(cls, obj):
                return isinstance(obj, real_cls)

            def __subclasscheck__(cls, sub):
                return issubclass(sub, real_cls)

        return _Meta

    class SimDateTime(
        real_datetime, metaclass=_delegating_meta(real_datetime)
    ):  # type: ignore[valid-type, misc]
        @classmethod
        def now(cls, tz=None):
            h = try_current_handle()
            if h is None:
                return real_datetime.now(tz)
            ts = h.time.now_time_ns() / 1e9
            if tz is not None:
                return cls.fromtimestamp(ts, tz)
            # UTC-based naive: local-tz conversion would make the same seed
            # produce different datetimes on differently-configured hosts
            return cls.fromtimestamp(ts, _dt.timezone.utc).replace(tzinfo=None)

        @classmethod
        def utcnow(cls):
            h = try_current_handle()
            if h is None:
                return real_datetime.utcnow()
            ts = h.time.now_time_ns() / 1e9
            return cls.fromtimestamp(ts, _dt.timezone.utc).replace(tzinfo=None)

        @classmethod
        def today(cls):
            return cls.now()

    class SimDate(
        real_date, metaclass=_delegating_meta(real_date)
    ):  # type: ignore[valid-type, misc]
        @classmethod
        def today(cls):
            h = try_current_handle()
            if h is None:
                return real_date.today()
            d = SimDateTime.now()
            return cls(d.year, d.month, d.day)

    return SimDateTime, SimDate


def _rebind_datetime_aliases(sim_datetime, sim_date) -> None:
    """Close the pre-import alias hole: rebind every loaded module's
    attributes that hold the REAL ``datetime``/``date`` classes (bound by
    ``from datetime import datetime`` before the sim started) to the
    sim-aware subclasses, recording each for restore at uninstall.

    freezegun's module-scan approach; the libc interposition it stands in
    for (sim/time/system_time.rs:4-113) has no such hole because it
    patches below the class, at ``clock_gettime``. Residual (documented)
    gaps: non-module references captured pre-install (class attributes,
    closure cells), and attributes *assigned into an already-imported
    module's dict* between sims — the memo below rescans a module only
    when it first appears in (or is reloaded into) ``sys.modules``,
    which covers the real flow (``from datetime import datetime`` runs
    at module import)."""
    import sys

    real_datetime = _originals["datetime.datetime"]
    real_date = _originals["datetime.date"]
    real_by_kind = {"datetime": real_datetime, "date": real_date}
    sim_by_kind = {"datetime": sim_datetime, "date": sim_date}

    # pass 1: discover sites in modules not seen (or reloaded) since the
    # last scan; already-scanned modules are skipped entirely
    for name, mod in list(sys.modules.items()):
        if mod is None or name in ("datetime", __name__):
            continue
        if _scanned_mods.get(name) is mod:
            continue
        sites = []
        try:
            items = list(vars(mod).items())
        except Exception:
            items = []  # lazy-loader modules may raise on dict access
        for attr, val in items:
            if val is real_datetime:
                sites.append((attr, "datetime"))
            elif val is real_date:
                sites.append((attr, "date"))
        try:
            _scanned_mods[name] = mod
        except TypeError:
            pass  # non-weakref-able module-like object: rescan next time
        if sites:
            _alias_sites[name] = sites
        else:
            _alias_sites.pop(name, None)

    # pass 2: rebind every known site that still holds the real class
    for name, sites in list(_alias_sites.items()):
        mod = sys.modules.get(name)
        if mod is None:
            continue
        for attr, kind in sites:
            try:
                if getattr(mod, attr, None) is real_by_kind[kind]:
                    setattr(mod, attr, sim_by_kind[kind])
                    _rebound_aliases.append((mod, attr, real_by_kind[kind]))
            except Exception:
                continue  # read-only module attribute; leave it


def _restore_datetime_aliases() -> None:
    for mod, attr, real_cls in _rebound_aliases:
        try:
            setattr(mod, attr, real_cls)
        except Exception:
            pass
    _rebound_aliases.clear()


def _sim_cpu_count() -> Any:
    """Inside a sim task, report the node's configured cores — the
    analogue of the reference faking ``available_parallelism`` via
    ``sched_getaffinity``/``sysconf`` (task/mod.rs:707-760)."""
    from . import context

    task = context.try_current_task()
    if task is None:
        return _originals["os.cpu_count"]()
    return task.node.cores


def _install() -> None:
    import datetime as _dt
    import random as _r
    import time as _t

    _originals.update(
        {
            "datetime.datetime": _dt.datetime,
            "datetime.date": _dt.date,
        }
    )
    _originals.update(
        {
            "random.random": _r.random,
            "random.getrandbits": _r.getrandbits,
            "random.randbytes": _r.randbytes,
            "random.randrange": _r.randrange,
            "random.randint": _r.randint,
            "random.uniform": _r.uniform,
            "random.choice": _r.choice,
            "random.shuffle": _r.shuffle,
            "os.urandom": os.urandom,
            "uuid.uuid4": _uuid_mod.uuid4,
            "time.time": _t.time,
            "time.time_ns": _t.time_ns,
            "time.monotonic": _t.monotonic,
            "time.monotonic_ns": _t.monotonic_ns,
            "time.perf_counter": _t.perf_counter,
            "time.perf_counter_ns": _t.perf_counter_ns,
            "threading.Thread.start": threading.Thread.start,
            "os.cpu_count": os.cpu_count,
        }
    )
    os.cpu_count = _sim_cpu_count
    _r.random = _SimRandomDispatch.random
    _r.getrandbits = _SimRandomDispatch.getrandbits
    _r.randbytes = _SimRandomDispatch.randbytes
    _r.randrange = _SimRandomDispatch.randrange
    _r.randint = _SimRandomDispatch.randint
    _r.uniform = _SimRandomDispatch.uniform
    _r.choice = _SimRandomDispatch.choice
    _r.shuffle = _SimRandomDispatch.shuffle
    os.urandom = _sim_urandom
    _uuid_mod.uuid4 = _sim_uuid4
    _t.time = _make_clock("time.time", "wall", ns=False)
    _t.time_ns = _make_clock("time.time_ns", "wall", ns=True)
    _t.monotonic = _make_clock("time.monotonic", "mono", ns=False)
    _t.monotonic_ns = _make_clock("time.monotonic_ns", "mono", ns=True)
    _t.perf_counter = _make_clock("time.perf_counter", "mono", ns=False)
    _t.perf_counter_ns = _make_clock("time.perf_counter_ns", "mono", ns=True)
    threading.Thread.start = _sim_thread_start  # type: ignore[method-assign]
    _dt.datetime, _dt.date = _make_datetime_classes()
    _rebind_datetime_aliases(_dt.datetime, _dt.date)


def _uninstall() -> None:
    import datetime as _dt
    import random as _r
    import time as _t

    _restore_datetime_aliases()
    _dt.datetime = _originals["datetime.datetime"]
    _dt.date = _originals["datetime.date"]

    _r.random = _originals["random.random"]
    _r.getrandbits = _originals["random.getrandbits"]
    _r.randbytes = _originals["random.randbytes"]
    _r.randrange = _originals["random.randrange"]
    _r.randint = _originals["random.randint"]
    _r.uniform = _originals["random.uniform"]
    _r.choice = _originals["random.choice"]
    _r.shuffle = _originals["random.shuffle"]
    os.urandom = _originals["os.urandom"]
    _uuid_mod.uuid4 = _originals["uuid.uuid4"]
    _t.time = _originals["time.time"]
    _t.time_ns = _originals["time.time_ns"]
    _t.monotonic = _originals["time.monotonic"]
    _t.monotonic_ns = _originals["time.monotonic_ns"]
    _t.perf_counter = _originals["time.perf_counter"]
    _t.perf_counter_ns = _originals["time.perf_counter_ns"]
    threading.Thread.start = _originals["threading.Thread.start"]
    os.cpu_count = _originals["os.cpu_count"]
    _originals.clear()


@contextmanager
def interposed(handle: Any, allow_system_thread: bool = False) -> Iterator[None]:
    """Enable stdlib interposition for the duration of a simulation run.

    Installation is global but refcounted and dispatch is per-thread via the
    ambient context, so concurrent seed-sweep threads are safe.
    """
    global _install_count
    handle.allow_system_thread = allow_system_thread
    with _lock:
        if _install_count == 0:
            _install()
        _install_count += 1
    try:
        yield
    finally:
        with _lock:
            _install_count -= 1
            if _install_count == 0:
                _uninstall()
