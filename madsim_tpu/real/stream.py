"""Real-mode framed streams: the ``connect1``/``accept1`` shape over TCP.

The sim tier's connection-oriented protocols (gRPC, etcd) ride on
``(tx, rx)`` pipe halves from ``net/netsim.py`` (``PipeSender`` /
``PipeReceiver``).  This module provides the same surface over a real TCP
connection so those protocol layers run unmodified outside the simulator —
the analogue of the reference's std transports backing its shim crates
(madsim-tonic/src/lib.rs:1-8 compiles to real tonic without ``--cfg
madsim``; here the same service classes bind to real sockets).

Semantics match the sim pipes:

- ``tx.send(obj)``     — one codec frame; ``BrokenPipeError`` if the
                         connection is gone or the peer receiver closed it;
- ``tx.close()``       — clean EOF (TCP half-close): the peer's ``recv``
                         returns ``None`` after the in-flight frames;
- ``rx.recv()``        — next object; ``None`` on clean EOF;
                         ``ConnectionResetError`` on abort/reset;
- ``rx.close()``       — hard-drop the connection (the peer's next send
                         observes ``BrokenPipeError``), mirroring
                         ``PipeReceiver.close``.

Frames are 4-byte big-endian length + restricted-codec body (real/codec.py)
— never pickle, so a hostile peer cannot execute code.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional, Tuple

from . import codec

Addr = Tuple[str, int]

# the single source of truth for the wire rules — real/net.py imports these
_LEN = struct.Struct(">I")
_MAX_FRAME = 64 * 1024 * 1024  # sanity bound, not a protocol limit


def encode_frame(body: bytes) -> bytes:
    """Length-prefix one frame; oversize fails at the SENDER (the receiver
    would kill the connection)."""
    if len(body) > _MAX_FRAME:
        raise ValueError(
            f"frame of {len(body)} bytes exceeds the {_MAX_FRAME}-byte bound"
        )
    return _LEN.pack(len(body)) + body


async def read_frame_raw(reader: asyncio.StreamReader) -> Optional[bytes]:
    """Read one raw length-prefixed frame body (no codec): ``None`` on
    clean EOF at a frame boundary, ``ConnectionResetError`` mid-frame.
    The Kafka binary wire (kafka/wire.py) uses exactly this framing, so
    its real tier reads genuine protocol bytes through the same rules."""
    try:
        head = await reader.readexactly(_LEN.size)
    except asyncio.IncompleteReadError as e:
        if e.partial:
            raise ConnectionResetError("truncated frame") from None
        return None
    (n,) = _LEN.unpack(head)
    if n > _MAX_FRAME:
        raise ConnectionResetError(f"frame of {n} bytes exceeds sanity bound")
    try:
        return await reader.readexactly(n)
    except asyncio.IncompleteReadError:
        raise ConnectionResetError("truncated frame") from None


async def write_frame_raw(writer: asyncio.StreamWriter, body: bytes) -> None:
    """Write one raw length-prefixed frame body (no codec) and drain."""
    writer.write(encode_frame(body))
    await writer.drain()


def parse_addr(addr: "str | Addr") -> Addr:
    if isinstance(addr, tuple):
        return (addr[0], int(addr[1]))
    host, _, port = addr.rpartition(":")
    return (host or "127.0.0.1", int(port))


class _Conn:
    """Shared state of one TCP connection carrying a (tx, rx) pair."""

    __slots__ = ("reader", "writer", "tx_closed", "rx_done")

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.tx_closed = False  # our write half is done (EOF sent)
        self.rx_done = False  # read half hit EOF or was closed

    def maybe_close(self) -> None:
        """Fully close the socket once both directions are finished."""
        if self.tx_closed and self.rx_done:
            try:
                self.writer.close()
            except Exception:
                pass

    def abort(self) -> None:
        """Hard-drop: the peer sees a reset, not a clean EOF."""
        self.tx_closed = True
        self.rx_done = True
        try:
            transport = self.writer.transport
            if transport is not None:
                transport.abort()
            else:  # pragma: no cover - transport already detached
                self.writer.close()
        except Exception:
            pass


class StreamSender:
    """The ``PipeSender`` analogue over a real connection half."""

    def __init__(self, conn: _Conn):
        self._conn = conn

    async def send(self, msg: object) -> None:
        conn = self._conn
        if conn.tx_closed or conn.writer.is_closing():
            raise BrokenPipeError("connection closed")
        try:
            conn.writer.write(encode_frame(codec.dumps(msg)))
            await conn.writer.drain()
        except (ConnectionError, OSError) as e:
            raise BrokenPipeError(str(e) or "connection lost") from None

    def close(self) -> None:
        conn = self._conn
        if conn.tx_closed:
            return
        conn.tx_closed = True
        try:
            if conn.writer.can_write_eof() and not conn.writer.is_closing():
                conn.writer.write_eof()
            else:
                conn.writer.close()
        except (OSError, RuntimeError):
            pass
        conn.maybe_close()

    def is_closed(self) -> bool:
        return self._conn.tx_closed or self._conn.writer.is_closing()


class StreamReceiver:
    """The ``PipeReceiver`` analogue over a real connection half."""

    def __init__(self, conn: _Conn):
        self._conn = conn

    async def recv(self) -> Optional[object]:
        conn = self._conn
        if conn.rx_done:
            return None
        try:
            head = await conn.reader.readexactly(_LEN.size)
        except asyncio.IncompleteReadError as e:
            conn.rx_done = True
            if e.partial:  # connection died mid-frame
                conn.abort()
                raise ConnectionResetError("truncated frame") from None
            conn.maybe_close()
            return None  # clean EOF — the peer's tx.close()
        except (ConnectionError, OSError) as e:
            conn.rx_done = True
            raise ConnectionResetError(str(e) or "connection reset") from None
        (n,) = _LEN.unpack(head)
        if n > _MAX_FRAME:
            conn.abort()
            raise ConnectionResetError(f"frame of {n} bytes exceeds sanity bound")
        try:
            body = await conn.reader.readexactly(n)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            conn.rx_done = True
            conn.abort()
            raise ConnectionResetError(str(e) or "connection reset") from None
        try:
            return codec.loads(body)
        except codec.CodecError as e:
            # a frame we refuse to decode kills the connection, like a
            # protocol violation on a real wire
            conn.abort()
            raise ConnectionResetError(f"bad frame: {e}") from None

    def close(self) -> None:
        """Drop the connection hard (the ``PipeReceiver.close`` analogue:
        the peer's next send fails instead of silently buffering)."""
        conn = self._conn
        if conn.rx_done and conn.tx_closed:
            return
        conn.abort()


def _wrap(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
    conn = _Conn(reader, writer)
    return StreamSender(conn), StreamReceiver(conn)


async def connect(addr: "str | Addr") -> Tuple[StreamSender, StreamReceiver]:
    """Open one framed connection — the ``connect1_ephemeral`` analogue."""
    host, port = parse_addr(addr)
    reader, writer = await asyncio.open_connection(host, port)
    return _wrap(reader, writer)


class StreamListener:
    """Accept-side of the framed transport — the ``accept1`` analogue.

    Closed-listener semantics: after :meth:`close`, ``accept1`` raises
    ``ConnectionAbortedError`` (it must not block forever on a listener
    that will never accept again), queued-but-unclaimed connections are
    hard-dropped so their clients see a reset instead of hanging, and a
    connection that races the close through the kernel backlog is
    aborted on arrival.
    """

    #: queue sentinel: wakes accept1 blocked at close time
    _CLOSED = (None, None, ("closed", 0))

    def __init__(self) -> None:
        self._server: Optional[asyncio.AbstractServer] = None
        self._local: Addr = ("0.0.0.0", 0)
        self._closed = False
        self._pending: "asyncio.Queue[Tuple[StreamSender, StreamReceiver, Addr]]" = (
            asyncio.Queue()
        )

    @staticmethod
    async def bind(addr: "str | Addr") -> "StreamListener":
        self = StreamListener()
        host, port = parse_addr(addr)

        async def on_accept(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
            # peername is None for a socket that disconnected before the
            # callback ran; don't let a TypeError drop the connection
            peer = (writer.get_extra_info("peername") or ("?", 0))[:2]
            tx, rx = _wrap(reader, writer)
            if self._closed:
                # raced the close through the kernel backlog: nobody
                # will ever claim this connection — reset it now
                rx.close()
                return
            await self._pending.put((tx, rx, peer))

        self._server = await asyncio.start_server(on_accept, host, port)
        self._local = self._server.sockets[0].getsockname()[:2]
        return self

    def local_addr(self) -> Addr:
        return self._local

    async def accept1(self) -> Tuple[StreamSender, StreamReceiver, Addr]:
        if self._closed:
            raise ConnectionAbortedError("listener closed")
        item = await self._pending.get()
        if item[0] is None:  # the close sentinel
            self._pending.put_nowait(StreamListener._CLOSED)  # for siblings
            raise ConnectionAbortedError("listener closed")
        return item

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._server is not None:
            self._server.close()
        # accepted-but-unclaimed connections would otherwise hang their
        # clients forever (no EOF, no reset) — drop them hard
        while not self._pending.empty():
            try:
                item = self._pending.get_nowait()
            except asyncio.QueueEmpty:  # pragma: no cover - raced drain
                break
            if item[0] is not None:
                item[1].close()
        self._pending.put_nowait(StreamListener._CLOSED)
