"""Restricted binary codec for real-mode network frames.

Replaces pickle on the wire (a pickled frame from an untrusted peer is
remote code execution; the reference's std transport uses typed bincode,
madsim/src/std/net/tcp.rs:42-327, which can only materialize the types the
program declared). This codec is the Python analogue of that property:

- plain data (None, bool, int, float, str, bytes, tuple, list, dict)
  round-trips structurally;
- user-defined objects decode ONLY if their class is a registered RPC
  ``Request`` subclass (auto-registered by ``Request.__init_subclass__``)
  or explicitly ``register()``-ed. Decoding never imports anything and
  never calls ``__init__``/``__reduce__`` — an unknown class name raises
  ``CodecError``, and a known one is rebuilt via ``__new__`` + ``__dict__``
  update with plain-data fields only.

Integers are arbitrary precision (length-prefixed two's-complement), so
u64 RPC ids and tags round-trip exactly.
"""

from __future__ import annotations

import struct
from enum import Enum
from typing import Any, Dict

from ..net import rpc as _rpc


class CodecError(Exception):
    """Malformed frame or disallowed type."""


_EXTRA_TYPES: Dict[str, type] = {}


def register(cls: type) -> type:
    """Explicitly allow a non-Request class on the wire (decorator-friendly).
    Instances are encoded as their ``__dict__`` of plain data; ``Enum``
    subclasses are encoded by member name (decoded via ``cls[name]``, never
    by constructing)."""
    if not issubclass(cls, Enum) and getattr(cls, "__dictoffset__", 0) == 0:
        raise CodecError(
            f"cannot register {cls.__qualname__}: its instances have no "
            "__dict__ (__slots__ class?) — the codec round-trips objects "
            "through their instance dict"
        )
    _EXTRA_TYPES[f"{cls.__module__}::{cls.__qualname__}"] = cls
    return cls


def _lookup(name: str) -> type:
    cls = _EXTRA_TYPES.get(name)
    if cls is None:
        # Request subclasses register themselves at class-creation time
        # (net/rpc.py) — a live registry, never an import
        cls = _rpc.request_types().get(name)
    if cls is None:
        raise CodecError(f"refusing to decode unregistered type {name!r}")
    return cls


# type tags
_NONE, _TRUE, _FALSE = b"N", b"T", b"F"
_INT, _FLOAT, _STR, _BYTES = b"i", b"f", b"s", b"b"
_TUPLE, _LIST, _DICT, _OBJ, _ENUM = b"t", b"l", b"d", b"O", b"E"

_MAX_DEPTH = 32


def _enc(obj: Any, out: bytearray, depth: int) -> None:
    if depth > _MAX_DEPTH:
        raise CodecError("structure too deeply nested")
    if obj is None:
        out += _NONE
    elif obj is True:
        out += _TRUE
    elif obj is False:
        out += _FALSE
    elif isinstance(obj, Enum):
        # checked before int so IntEnum members (e.g. grpc Code) keep
        # their type across the wire instead of flattening to int
        cls = type(obj)
        name = f"{cls.__module__}::{cls.__qualname__}"
        _lookup(name)  # refuse to encode unregistered enums too
        raw, member = name.encode(), obj.name.encode()
        out += _ENUM + struct.pack(">I", len(raw)) + raw
        out += struct.pack(">I", len(member)) + member
    elif isinstance(obj, int):
        raw = obj.to_bytes((obj.bit_length() + 8) // 8 or 1, "big", signed=True)
        out += _INT + struct.pack(">I", len(raw)) + raw
    elif isinstance(obj, float):
        out += _FLOAT + struct.pack(">d", obj)
    elif isinstance(obj, str):
        raw = obj.encode()
        out += _STR + struct.pack(">I", len(raw)) + raw
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out += _BYTES + struct.pack(">I", len(raw)) + raw
    elif isinstance(obj, tuple):
        out += _TUPLE + struct.pack(">I", len(obj))
        for item in obj:
            _enc(item, out, depth + 1)
    elif isinstance(obj, list):
        out += _LIST + struct.pack(">I", len(obj))
        for item in obj:
            _enc(item, out, depth + 1)
    elif isinstance(obj, dict):
        out += _DICT + struct.pack(">I", len(obj))
        for k, v in obj.items():
            _enc(k, out, depth + 1)
            _enc(v, out, depth + 1)
    else:
        cls = type(obj)
        name = f"{cls.__module__}::{cls.__qualname__}"
        _lookup(name)  # refuse to *encode* unregistered types too
        fields = getattr(obj, "__dict__", None)
        if fields is None:
            raise CodecError(
                f"cannot encode {cls.__qualname__}: instance has no "
                "__dict__ (__slots__ class?)"
            )
        raw = name.encode()
        out += _OBJ + struct.pack(">I", len(raw)) + raw
        _enc(dict(fields), out, depth + 1)


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise CodecError("truncated frame")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]


def _dec(r: _Reader, depth: int) -> Any:
    if depth > _MAX_DEPTH:
        raise CodecError("structure too deeply nested")
    tag = r.take(1)
    if tag == _NONE:
        return None
    if tag == _TRUE:
        return True
    if tag == _FALSE:
        return False
    if tag == _INT:
        return int.from_bytes(r.take(r.u32()), "big", signed=True)
    if tag == _FLOAT:
        return struct.unpack(">d", r.take(8))[0]
    if tag == _STR:
        return r.take(r.u32()).decode()
    if tag == _BYTES:
        return r.take(r.u32())
    if tag == _TUPLE:
        return tuple(_dec(r, depth + 1) for _ in range(r.u32()))
    if tag == _LIST:
        return [_dec(r, depth + 1) for _ in range(r.u32())]
    if tag == _DICT:
        n = r.u32()
        out = {}
        for _ in range(n):
            k = _dec(r, depth + 1)
            out[k] = _dec(r, depth + 1)
        return out
    if tag == _ENUM:
        name = r.take(r.u32()).decode()
        cls = _lookup(name)
        if not (isinstance(cls, type) and issubclass(cls, Enum)):
            raise CodecError(f"{name!r} is not a registered Enum")
        member = r.take(r.u32()).decode()
        try:
            return cls[member]
        except KeyError:
            raise CodecError(f"{name!r} has no member {member!r}") from None
    if tag == _OBJ:
        name = r.take(r.u32()).decode()
        cls = _lookup(name)
        fields = _dec(r, depth + 1)
        if not isinstance(fields, dict):
            raise CodecError("object fields must decode to a dict")
        if issubclass(cls, BaseException):
            # object.__new__ refuses exception types; BaseException.__new__
            # allocates without running any user __init__/__new__
            obj = BaseException.__new__(cls)
        else:
            obj = object.__new__(cls)
        obj.__dict__.update(fields)
        return obj
    raise CodecError(f"unknown type tag {tag!r}")


def dumps(obj: Any) -> bytes:
    out = bytearray()
    _enc(obj, out, 0)
    return bytes(out)


def loads(data: bytes) -> Any:
    """Decode one frame; ANY malformed input raises ``CodecError`` (hostile
    bytes must not leak UnicodeDecodeError/TypeError/... to callers)."""
    try:
        r = _Reader(bytes(data))
        obj = _dec(r, 0)
        if r.pos != len(r.data):
            raise CodecError("trailing bytes after frame")
        return obj
    except CodecError:
        raise
    except Exception as e:
        raise CodecError(f"malformed frame: {type(e).__name__}: {e}") from e
