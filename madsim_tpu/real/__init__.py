"""Real-mode twin — the analogue of the reference's ``std`` tree.

The reference compiles every API to the real library when ``--cfg madsim``
is absent: tokio re-exports, a tag-matching Endpoint over real TCP with
length-delimited frames, and real RPC on top (madsim/src/std/, SURVEY.md
§2.1 "std twin"). This package is the same idea for Python: the simulation
API surface backed by asyncio and real sockets, so workload code written
against madsim_tpu runs unmodified against a real network:

    from madsim_tpu import real as ms       # instead of `import madsim_tpu as ms`
    rt = ms.Runtime()
    rt.block_on(main())

Provided: ``Runtime.block_on``, ``spawn``, ``sleep``/``timeout``/
``interval``/``Instant``, tag-matching ``Endpoint`` (UDP datagrams) and
``TcpEndpoint`` (length-delimited frames over persistent connections, the
reference std transport's shape), the built-in RPC (``call`` /
``add_rpc_handler``) on either, and real-mode twins of ALL FOUR ecosystem
shims — ``real.grpc`` (the same @service classes over framed TCP),
``real.etcd``, ``real.kafka``, ``real.s3`` (the unchanged client APIs
against the framework's own state machines on real sockets) — plus
``real.fs`` (the sim fs API over actual files, the std/fs.rs analogue)
and ``real.signal`` (``ctrl_c`` over a real SIGINT). Frames use
the restricted binary codec (real/codec.py) — never pickle, so a hostile
peer cannot execute code.
Randomness is real randomness; there is no determinism in real mode
(matching the reference, where buggify is a no-op and seeds don't exist,
std/buggify.rs:6-30).
"""

from .runtime import Runtime, spawn
from .time import Instant, interval, now_instant, sleep, timeout
from .net import Endpoint, TcpEndpoint
from . import codec
from . import stream
from . import grpc
from . import etcd
from . import fs
from . import kafka
from . import s3
from . import signal

__all__ = [
    "Endpoint",
    "TcpEndpoint",
    "codec",
    "etcd",
    "fs",
    "grpc",
    "kafka",
    "s3",
    "signal",
    "stream",
    "Instant",
    "Runtime",
    "interval",
    "now_instant",
    "sleep",
    "spawn",
    "timeout",
]
