"""Real-mode gRPC twin: the same service classes over real TCP.

The reference's madsim-tonic compiles to *real* tonic when ``--cfg madsim``
is absent (madsim-tonic/src/lib.rs:1-8) — an app written against the shim
runs against real HTTP/2 without code changes.  This module is that
property for the Python framework: every piece of the sim gRPC stack
(service decorators, typed clients, the four call shapes, interceptors,
grpc-timeout, Status mapping, load-balanced channels) is reused verbatim;
only the executor bindings (asyncio instead of the deterministic scheduler)
and the transport (framed TCP streams, real/stream.py) are swapped::

    from madsim_tpu import real
    from madsim_tpu.real import grpc

    # server
    await grpc.Server.builder().add_service(Greeter()).serve("127.0.0.1:50051")
    # client
    channel = await grpc.Endpoint.from_static("http://127.0.0.1:50051").connect()
    client = grpc.ServiceClient(Greeter, channel)

Wire safety: frames use the restricted codec (real/codec.py), so only plain
data and registered classes travel.  The envelope types (Request, Response,
Status, Code) are registered here; user message classes must be registered
with ``real.codec.register`` (the analogue of deriving Serialize in the
reference — wire types are always declared explicitly).
"""

from __future__ import annotations

import random as _pyrandom
from typing import Any, Optional

from ..grpc import codec as _gcodec
from ..grpc.channel import Change, Channel as _SimChannel, Endpoint as _SimEndpoint
from ..grpc.client import Grpc as _SimGrpc, Request, Response
from ..grpc.codec import Streaming
from ..grpc.server import Router as _SimRouter, ServerBuilder as _SimServerBuilder
from ..grpc.service import (
    ServiceClient as _SimServiceClient,
    bidi_streaming,
    client_streaming,
    server_streaming,
    service,
    unary,
)
from ..grpc.status import Code, Status
from . import codec, stream
from . import time as rtime
from .runtime import spawn

# envelope types every call carries — registered once, like the serde
# derives on the reference's envelope structs
codec.register(Request)
codec.register(Response)
codec.register(Status)
codec.register(Code)


class Grpc(_SimGrpc):
    """The generic caller bound to asyncio (spawn/timeout swapped)."""

    _spawn = staticmethod(spawn)
    _timeout = staticmethod(rtime.timeout)
    _timeout_error = rtime.TimeoutError


class Channel(_SimChannel):
    """Load-balanced channel dialing real framed-TCP connections."""

    @staticmethod
    def _randint(n: int) -> int:
        return _pyrandom.randrange(n)  # real mode: real randomness

    async def _open(self, addr: str):
        try:
            return await stream.connect(addr)
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(f"transport error: {e}") from None


class Endpoint(_SimEndpoint):
    """The tonic ``transport::Endpoint`` builder, real-mode flavor."""

    _channel_cls = Channel
    _timeout_fn = staticmethod(rtime.timeout)
    _timeout_error = rtime.TimeoutError


class ServiceClient(_SimServiceClient):
    """Typed client for a @service class over the real transport."""

    _grpc_cls = Grpc


class Router(_SimRouter):
    """The sim router/dispatcher serving on a real TCP listener.

    Connections are multiplexed by the shared serving core
    (``madsim_tpu/serve/``); the per-connection dispatcher
    (``_serve_conn``) is unchanged, fed through a ``ChannelAdapter``.
    """

    _spawn = staticmethod(spawn)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await stream.StreamListener.bind(addr)

    async def serve_with_shutdown(
        self, addr: "str | tuple", signal: "Any | None"
    ) -> None:
        import asyncio

        from ..serve import AsyncWireServer, ChannelAdapter

        adapter = ChannelAdapter(self._serve_conn, codec, name="grpc")
        self._core = AsyncWireServer(adapter)
        self.bound_addr = await self._core.start(addr)
        try:
            if signal is None:
                await self._core._stopped.wait()
            else:
                stop = asyncio.ensure_future(self._core._stopped.wait())
                sig = asyncio.ensure_future(signal)
                _done, pending = await asyncio.wait(
                    {stop, sig}, return_when=asyncio.FIRST_COMPLETED
                )
                for p in pending:
                    p.cancel()
        finally:
            self._core.close()
            self._core._teardown()


class ServerBuilder(_SimServerBuilder):
    _router_cls = Router


class Server:
    @staticmethod
    def builder() -> ServerBuilder:
        return ServerBuilder()


# --------------------------------------------------------------- grpcio
# The genuine-wire tier: the SAME protogen service classes served and
# called over actual gRPC (HTTP/2 + protobuf) via the installed grpcio,
# so a stock gRPC peer in any language interoperates. This is the full
# analogue of the reference's std mode being real tonic
# (madsim-tonic/src/lib.rs:1-8, madsim-tonic-build/src/prost.rs:599-680:
# the same app binary speaks to any gRPC ecosystem peer).
#
# Requires proto-derived services (``pkg.implement``/``pkg.stub``): real
# protobuf wire bytes need the per-method message classes that protogen
# attaches; hand-decorated @service classes have no message schema.

from ..grpc.service import (
    _IO_ATTR,
    _NAME_ATTR,
    _TABLE_ATTR,
    _WIRE_ATTR,
    camel as _camel,
)


def _grpc_mod():
    import grpc as grpcio  # deferred: real mode must import without grpcio

    return grpcio


def _to_status(e) -> Status:
    """Map a grpcio error (code, details) onto this framework's Status."""
    code = e.code()
    return Status(Code(code.value[0]), e.details() or "")


def _from_status_code(code: Code):
    grpcio = _grpc_mod()
    for sc in grpcio.StatusCode:
        if sc.value[0] == int(code):
            return sc
    return grpcio.StatusCode.UNKNOWN


def _io_table(service_cls: type) -> dict:
    io = getattr(service_cls, _IO_ATTR, None)
    if io is None:
        raise TypeError(
            f"{service_cls.__name__} carries no protobuf message types; "
            "the grpcio wire tier needs a proto-derived service "
            "(grpc.compile_protos(...).implement/stub), not a "
            "hand-decorated @service class"
        )
    return io


def _unwrap_msg(result: Any):
    """Handler return value -> raw protobuf message for the wire."""
    return result.message if isinstance(result, Response) else result


def _clean_metadata(md: dict) -> tuple:
    """User metadata for the wire; grpc-* keys are reserved headers that
    grpcio derives itself (timeout travels as the deadline)."""
    return tuple(
        (k.lower(), v) for k, v in md.items() if not k.lower().startswith("grpc-")
    )


class _RequestStream:
    """Server-side request stream: grpcio's request iterator behind the
    Streaming surface handlers already use (async-for / .message())."""

    def __init__(self, request_iterator):
        self._it = request_iterator.__aiter__()
        self._done = False

    async def message(self) -> Optional[Any]:
        if self._done:
            return None
        try:
            return await self._it.__anext__()
        except StopAsyncIteration:
            self._done = True
            return None

    def __aiter__(self) -> "_RequestStream":
        return self

    async def __anext__(self) -> Any:
        msg = await self.message()
        if msg is None:
            raise StopAsyncIteration
        return msg


class GrpcioStreaming:
    """Client-side response stream over a grpcio call object, with the
    Streaming surface (async-for / .message() / .close())."""

    def __init__(self, call):
        self._call = call
        self._it = call.__aiter__()
        self._done = False
        # captured once: resolving the module per streamed message would
        # put an import-machinery lookup on the hot read path
        self._rpc_error = _grpc_mod().aio.AioRpcError

    async def message(self) -> Optional[Any]:
        if self._done:
            return None
        try:
            return await self._it.__anext__()
        except StopAsyncIteration:
            self._done = True
            return None
        except self._rpc_error as e:
            self._done = True
            raise _to_status(e) from None

    def close(self) -> None:
        self._done = True
        self._call.cancel()

    def __aiter__(self) -> "GrpcioStreaming":
        return self

    async def __anext__(self) -> Any:
        msg = await self.message()
        if msg is None:
            raise StopAsyncIteration
        return msg


async def _aiter_messages(messages):
    """Message bodies may be sync/async iterables or an awaitable of one
    (same contract as the framed tier's _serve_stream); grpcio wants an
    async iterator of raw messages."""
    import inspect

    if inspect.iscoroutine(messages):
        messages = await messages
    if hasattr(messages, "__aiter__"):
        async for m in messages:
            yield _unwrap_msg(m)
    else:
        for m in messages:
            yield _unwrap_msg(m)


class GrpcioChannel:
    """A real gRPC channel (``grpc.aio.insecure_channel``) behind the
    minimal surface the typed client uses."""

    def __init__(self, target: str, default_timeout: Optional[float] = None):
        grpcio = _grpc_mod()
        self.target = target
        self.default_timeout = default_timeout
        self._ch = grpcio.aio.insecure_channel(target)

    async def close(self) -> None:
        await self._ch.close()


class GrpcioGrpc(Grpc):
    """The generic caller over real gRPC wire — the four call shapes are
    reimplemented on grpcio multicallables; ``_prepare`` (interceptor then
    default-timeout injection) is INHERITED from the one implementation in
    grpc/client.py so the three tiers cannot drift."""

    def __init__(self, channel: GrpcioChannel, interceptor=None,
                 service_cls: Optional[type] = None):
        super().__init__(channel, interceptor)
        self._io = _io_table(service_cls) if service_cls is not None else {}
        # literal proto method name -> snake (acronym-safe path resolution)
        wire = getattr(service_cls, _WIRE_ATTR, {}) if service_cls else {}
        self._wire_to_snake = {v: k for k, v in wire.items()}
        # multicallables are fixed per (shape, path) for the channel's
        # lifetime — build each once, like grpcio's generated stubs do
        self._mc_cache: dict = {}

    def with_interceptor(self, f) -> "GrpcioGrpc":
        g = GrpcioGrpc(self.channel, f)
        g._io = self._io
        g._wire_to_snake = self._wire_to_snake
        return g

    def _multicallable(self, shape: str, path: str):
        """The cached grpcio multicallable for one method path."""
        mc = self._mc_cache.get((shape, path))
        if mc is not None:
            return mc
        from ..grpc.protogen import _snake

        seg = path.rsplit("/", 1)[-1]
        snake = self._wire_to_snake.get(seg) or _snake(seg)
        io = self._io.get(snake)
        if io is None:
            raise TypeError(
                f"no protobuf message types known for {path!r}; grpcio "
                "calls need a proto-derived stub (pkg.stub/pkg.implement)"
            )
        _req_cls, rsp_cls = io
        mc = getattr(self.channel._ch, shape)(
            path,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=rsp_cls.FromString,
        )
        self._mc_cache[(shape, path)] = mc
        return mc

    async def unary(self, path: str, request) -> Response:
        grpcio = _grpc_mod()
        request = self._prepare(Request.wrap(request))
        mc = self._multicallable("unary_unary", path)
        try:
            msg = await mc(
                _unwrap_msg(request.message),
                timeout=request.timeout(),
                metadata=_clean_metadata(request.metadata),
            )
        except grpcio.aio.AioRpcError as e:
            raise _to_status(e) from None
        return Response(msg)

    async def client_streaming(self, path: str, messages,
                               request: Optional[Request] = None) -> Response:
        grpcio = _grpc_mod()
        request = self._prepare(request or Request())
        mc = self._multicallable("stream_unary", path)
        try:
            msg = await mc(
                _aiter_messages(messages),
                timeout=request.timeout(),
                metadata=_clean_metadata(request.metadata),
            )
        except grpcio.aio.AioRpcError as e:
            raise _to_status(e) from None
        return Response(msg)

    async def _open_stream(self, call) -> GrpcioStreaming:
        """Surface call-setup failures (dead peer, unknown method) at the
        await, like the sim and framed tiers, instead of deferring the
        Status to the first message read."""
        grpcio = _grpc_mod()
        try:
            await call.wait_for_connection()
        except grpcio.aio.AioRpcError as e:
            raise _to_status(e) from None
        return GrpcioStreaming(call)

    async def server_streaming(self, path: str, request) -> GrpcioStreaming:
        request = self._prepare(Request.wrap(request))
        mc = self._multicallable("unary_stream", path)
        call = mc(
            _unwrap_msg(request.message),
            timeout=request.timeout(),
            metadata=_clean_metadata(request.metadata),
        )
        return await self._open_stream(call)

    async def streaming(self, path: str, messages,
                        request: Optional[Request] = None) -> GrpcioStreaming:
        request = self._prepare(request or Request())
        mc = self._multicallable("stream_stream", path)
        call = mc(
            _aiter_messages(messages),
            timeout=request.timeout(),
            metadata=_clean_metadata(request.metadata),
        )
        return await self._open_stream(call)


class GrpcioServiceClient(_SimServiceClient):
    """Typed client for a proto-derived service over real gRPC wire."""

    def __init__(self, service_cls: type, channel: GrpcioChannel,
                 interceptor=None):
        self._cls = service_cls
        self._name = getattr(service_cls, _NAME_ATTR)
        self._table = getattr(service_cls, _TABLE_ATTR)
        self._wire = getattr(service_cls, _WIRE_ATTR, {})
        self._grpc = GrpcioGrpc(channel, interceptor, service_cls)

    def _path(self, method: str) -> str:
        # the LITERAL descriptor method name: stock peers route by it, and
        # camel() does not round-trip acronyms (GetTPUInfo != GetTpuInfo)
        seg = self._wire.get(method) or _camel(method)
        return f"/{self._name}/{seg}"


class _GrpcioHandler:
    """Routes every inbound wire call to the registered service instances
    (a ``grpc.GenericRpcHandler``; the base class is resolved lazily so
    importing this module never requires grpcio)."""

    def __init__(self, services: dict):
        self._services = services  # full name -> instance

    def service(self, handler_call_details):
        grpcio = _grpc_mod()
        path = handler_call_details.method
        svc_name, _, method_path = path.strip("/").partition("/")
        svc = self._services.get(svc_name)
        if svc is None:
            return None  # grpcio answers UNIMPLEMENTED
        table = getattr(svc, _TABLE_ATTR, {})
        wire = getattr(svc, _WIRE_ATTR, {})
        snake = kind = None
        for name, k in table.items():
            if method_path in (name, _camel(name), wire.get(name)):
                snake, kind = name, k
                break
        if snake is None:
            return None
        io = _io_table(type(svc)).get(snake)
        if io is None:
            # matched the service but its message schema never resolved
            # (e.g. nested message types, which compile_protos does not
            # register): answer by NAME, not a bare UNIMPLEMENTED
            async def no_schema(msg, context):
                await context.abort(
                    grpcio.StatusCode.UNIMPLEMENTED,
                    f"method {path!r} exists on {svc_name} but its "
                    "protobuf message types were not among the compiled "
                    "messages (nested message types are not registered "
                    "by compile_protos)",
                )

            return grpcio.unary_unary_rpc_method_handler(no_schema)
        req_cls, _rsp_cls = io
        handler = getattr(svc, snake)
        deser = req_cls.FromString
        ser = lambda m: m.SerializeToString()  # noqa: E731

        async def _abort(context, st: Status):
            await context.abort(_from_status_code(st.code), st.message)

        if kind == "unary":
            async def behavior(msg, context):
                try:
                    result = await handler(_wire_request(msg, context))
                except Status as st:
                    await _abort(context, st)
                return _unwrap_msg(result)

            return grpcio.unary_unary_rpc_method_handler(
                behavior, request_deserializer=deser, response_serializer=ser
            )
        if kind == "server_streaming":
            async def behavior(msg, context):
                agen = handler(_wire_request(msg, context))
                try:
                    async for m in _aiter_messages(agen):
                        yield m
                except Status as st:
                    await _abort(context, st)

            return grpcio.unary_stream_rpc_method_handler(
                behavior, request_deserializer=deser, response_serializer=ser
            )
        if kind == "client_streaming":
            async def behavior(request_iterator, context):
                try:
                    result = await handler(_RequestStream(request_iterator))
                except Status as st:
                    await _abort(context, st)
                return _unwrap_msg(result)

            return grpcio.stream_unary_rpc_method_handler(
                behavior, request_deserializer=deser, response_serializer=ser
            )

        async def behavior(request_iterator, context):
            agen = handler(_RequestStream(request_iterator))
            try:
                async for m in _aiter_messages(agen):
                    yield m
            except Status as st:
                await _abort(context, st)

        return grpcio.stream_stream_rpc_method_handler(
            behavior, request_deserializer=deser, response_serializer=ser
        )


def _wire_request(msg, context) -> Request:
    """Inbound message + metadata as the Request envelope handlers see."""
    md = {k: v for k, v in (context.invocation_metadata() or ())
          if not isinstance(v, bytes)}
    return Request(msg, metadata=md)


class GrpcioRouter:
    """Serves proto-derived service instances via ``grpc.aio.server()``."""

    def __init__(self, builder: "GrpcioServerBuilder"):
        self._services = dict(builder._services)
        self.bound_addr: Optional[tuple] = None

    def _add(self, svc: Any) -> "GrpcioRouter":
        self._services[getattr(svc, _NAME_ATTR)] = svc
        _io_table(type(svc))  # fail at registration, not first call
        return self

    def add_service(self, svc: Any) -> "GrpcioRouter":
        return self._add(svc)

    async def serve(self, addr: "str | tuple") -> None:
        await self.serve_with_shutdown(addr, None)

    async def serve_with_shutdown(self, addr: "str | tuple",
                                  signal: Optional[Any]) -> None:
        grpcio = _grpc_mod()
        server = grpcio.aio.server()
        server.add_generic_rpc_handlers((_GrpcioHandler(self._services),))
        addr_str = addr if isinstance(addr, str) else f"{addr[0]}:{addr[1]}"
        port = server.add_insecure_port(addr_str)
        if port == 0:
            raise OSError(f"grpcio bind failed: {addr_str}")
        await server.start()
        self.bound_addr = (addr_str.rsplit(":", 1)[0], port)
        try:
            if signal is None:
                await server.wait_for_termination()
            else:
                await signal
        finally:
            await server.stop(None)


class GrpcioServerBuilder:
    def __init__(self) -> None:
        self._services: dict = {}

    def add_service(self, svc: Any) -> GrpcioRouter:
        return GrpcioRouter(self)._add(svc)


class GrpcioServer:
    """``Server``'s genuine-wire sibling: same builder surface, real gRPC."""

    @staticmethod
    def builder() -> GrpcioServerBuilder:
        return GrpcioServerBuilder()


__all__ = [
    "Change",
    "Channel",
    "Code",
    "Endpoint",
    "Grpc",
    "GrpcioChannel",
    "GrpcioGrpc",
    "GrpcioRouter",
    "GrpcioServer",
    "GrpcioServiceClient",
    "GrpcioStreaming",
    "Request",
    "Response",
    "Router",
    "Server",
    "ServerBuilder",
    "ServiceClient",
    "Status",
    "Streaming",
    "bidi_streaming",
    "client_streaming",
    "server_streaming",
    "service",
    "unary",
]
