"""Real-mode gRPC twin: the same service classes over real TCP.

The reference's madsim-tonic compiles to *real* tonic when ``--cfg madsim``
is absent (madsim-tonic/src/lib.rs:1-8) — an app written against the shim
runs against real HTTP/2 without code changes.  This module is that
property for the Python framework: every piece of the sim gRPC stack
(service decorators, typed clients, the four call shapes, interceptors,
grpc-timeout, Status mapping, load-balanced channels) is reused verbatim;
only the executor bindings (asyncio instead of the deterministic scheduler)
and the transport (framed TCP streams, real/stream.py) are swapped::

    from madsim_tpu import real
    from madsim_tpu.real import grpc

    # server
    await grpc.Server.builder().add_service(Greeter()).serve("127.0.0.1:50051")
    # client
    channel = await grpc.Endpoint.from_static("http://127.0.0.1:50051").connect()
    client = grpc.ServiceClient(Greeter, channel)

Wire safety: frames use the restricted codec (real/codec.py), so only plain
data and registered classes travel.  The envelope types (Request, Response,
Status, Code) are registered here; user message classes must be registered
with ``real.codec.register`` (the analogue of deriving Serialize in the
reference — wire types are always declared explicitly).
"""

from __future__ import annotations

import random as _pyrandom
from typing import Any, Optional

from ..grpc import codec as _gcodec
from ..grpc.channel import Change, Channel as _SimChannel, Endpoint as _SimEndpoint
from ..grpc.client import Grpc as _SimGrpc, Request, Response
from ..grpc.codec import Streaming
from ..grpc.server import Router as _SimRouter, ServerBuilder as _SimServerBuilder
from ..grpc.service import (
    ServiceClient as _SimServiceClient,
    bidi_streaming,
    client_streaming,
    server_streaming,
    service,
    unary,
)
from ..grpc.status import Code, Status
from . import codec, stream
from . import time as rtime
from .runtime import spawn

# envelope types every call carries — registered once, like the serde
# derives on the reference's envelope structs
codec.register(Request)
codec.register(Response)
codec.register(Status)
codec.register(Code)


class Grpc(_SimGrpc):
    """The generic caller bound to asyncio (spawn/timeout swapped)."""

    _spawn = staticmethod(spawn)
    _timeout = staticmethod(rtime.timeout)
    _timeout_error = rtime.TimeoutError


class Channel(_SimChannel):
    """Load-balanced channel dialing real framed-TCP connections."""

    @staticmethod
    def _randint(n: int) -> int:
        return _pyrandom.randrange(n)  # real mode: real randomness

    async def _open(self, addr: str):
        try:
            return await stream.connect(addr)
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(f"transport error: {e}") from None


class Endpoint(_SimEndpoint):
    """The tonic ``transport::Endpoint`` builder, real-mode flavor."""

    _channel_cls = Channel
    _timeout_fn = staticmethod(rtime.timeout)
    _timeout_error = rtime.TimeoutError


class ServiceClient(_SimServiceClient):
    """Typed client for a @service class over the real transport."""

    _grpc_cls = Grpc


class Router(_SimRouter):
    """The sim router/dispatcher serving on a real TCP listener."""

    _spawn = staticmethod(spawn)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await stream.StreamListener.bind(addr)


class ServerBuilder(_SimServerBuilder):
    _router_cls = Router


class Server:
    @staticmethod
    def builder() -> ServerBuilder:
        return ServerBuilder()


__all__ = [
    "Change",
    "Channel",
    "Code",
    "Endpoint",
    "Grpc",
    "Request",
    "Response",
    "Router",
    "Server",
    "ServerBuilder",
    "ServiceClient",
    "Status",
    "Streaming",
    "bidi_streaming",
    "client_streaming",
    "server_streaming",
    "service",
    "unary",
]
