"""Real-mode networking: tag-matching Endpoints over real UDP and TCP.

The reference's std Endpoint speaks length-delimited frames over real TCP
with a tag→mailbox dispatcher and RPC on top (madsim/src/std/net/tcp.rs:
42-327, std/net/rpc.rs). Two transports here:

- ``Endpoint`` — asyncio UDP: datagram framing for free, lowest latency,
  but a ~64 KiB payload ceiling and no delivery guarantee;
- ``TcpEndpoint`` — the reference-parity transport: 4-byte length-prefixed
  frames over persistent TCP connections. Each endpoint listens; a dialer
  opens one connection per peer, announces its own listen port in a hello
  frame (so replies ride the same connection back — the peer map of
  tcp.rs). A cached connection that errors or EOFs is evicted and the
  next send redials. Delivery is at-most-once, as in the sim tier: a
  frame written just as the peer dies is lost without an error (TCP
  buffers locally), so reliability — retries, RPC timeouts — belongs to
  the layer above, exactly as with the simulated lossy network.

Both speak the restricted binary codec (real/codec.py) — NOT pickle: a
frame from an untrusted peer can only materialize plain data or registered
``Request`` types, never run code. The mailbox matches tags exactly like
the sim side, and the built-in RPC reuses the sim's Request/hash
conventions so the same service classes work in both modes.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Tuple

from ..net.rpc import request_id
from . import codec
from . import time as rtime
from .runtime import spawn

# one source of truth for the wire rules, shared with the connection-
# oriented transport (real/stream.py)
from .stream import _LEN, _MAX_FRAME, encode_frame, parse_addr as _parse

Addr = Tuple[str, int]


class _Mailbox:
    def __init__(self) -> None:
        self.msgs: Dict[int, List[Tuple[Any, Addr]]] = {}
        self.waiters: Dict[int, List[asyncio.Future]] = {}

    def deliver(self, tag: int, payload: Any, src: Addr) -> None:
        waiters = self.waiters.get(tag)
        while waiters:
            fut = waiters.pop(0)
            if not fut.done():
                fut.set_result((payload, src))
                return
        self.msgs.setdefault(tag, []).append((payload, src))

    async def recv(self, tag: int) -> Tuple[Any, Addr]:
        pending = self.msgs.get(tag)
        if pending:
            return pending.pop(0)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.waiters.setdefault(tag, []).append(fut)
        return await fut


# marker for a server-side response-encoding failure: without it the
# client would wait forever on a response the server could never send
_RPC_ERR = "__madsim_rpc_error__"


class RpcError(Exception):
    """Server-side RPC failure relayed to the caller (e.g. a response type
    that is not wire-encodable — register it or return plain data)."""


class _RpcAPI:
    """Built-in RPC over any tag-matching transport (same wire convention
    as the sim side: ``(rsp_tag, req, data)`` on ``tag=RPC_ID``)."""

    async def send_to_raw(self, dst, tag, payload) -> None:  # pragma: no cover
        raise NotImplementedError

    async def recv_from_raw(self, tag):  # pragma: no cover
        raise NotImplementedError

    async def call(self, dst: "str | Addr", req: Any) -> Any:
        import random as _random

        rsp_tag = _random.getrandbits(64)
        await self.send_to_raw(dst, request_id(req), (rsp_tag, req, b""))
        payload, _src = await self.recv_from_raw(rsp_tag)
        rsp, _data = payload
        if isinstance(rsp, tuple) and len(rsp) == 2 and rsp[0] == _RPC_ERR:
            raise RpcError(rsp[1])
        return rsp

    async def call_timeout(self, dst: "str | Addr", req: Any, timeout_s: float) -> Any:
        return await rtime.timeout(timeout_s, self.call(dst, req))

    def add_rpc_handler(self, req_type: type, handler: Any) -> None:
        rid = request_id(req_type)

        async def accept_loop() -> None:
            while True:
                payload, src = await self.recv_from_raw(rid)
                rsp_tag, req, _data = payload

                async def handle_one(req=req, rsp_tag=rsp_tag, src=src) -> None:
                    rsp = await handler(req)
                    try:
                        await self.send_to_raw(src, rsp_tag, (rsp, b""))
                    except codec.CodecError as e:
                        # un-encodable response: fail the CALLER loudly
                        # instead of hanging it forever
                        await self.send_to_raw(
                            src, rsp_tag, ((_RPC_ERR, str(e)), b"")
                        )

                spawn(handle_one())

        spawn(accept_loop())


class _Proto(asyncio.DatagramProtocol):
    def __init__(self, mailbox: _Mailbox):
        self.mailbox = mailbox

    def datagram_received(self, data: bytes, addr: Addr) -> None:
        try:
            tag, payload = codec.loads(data)
        except Exception:
            return  # malformed or disallowed frame — drop, like a bad packet
        self.mailbox.deliver(tag, payload, addr)


class Endpoint(_RpcAPI):
    """Tag-matching datagram endpoint over a real UDP socket."""

    def __init__(self, transport: asyncio.DatagramTransport, mailbox: _Mailbox):
        self._transport = transport
        self._mailbox = mailbox
        self._peer: Optional[Addr] = None

    @staticmethod
    async def bind(addr: "str | Addr") -> "Endpoint":
        loop = asyncio.get_running_loop()
        mailbox = _Mailbox()
        transport, _ = await loop.create_datagram_endpoint(
            lambda: _Proto(mailbox), local_addr=_parse(addr)
        )
        return Endpoint(transport, mailbox)

    @staticmethod
    async def connect(addr: "str | Addr") -> "Endpoint":
        ep = await Endpoint.bind(("127.0.0.1", 0))
        ep._peer = _parse(addr)
        return ep

    def local_addr(self) -> Addr:
        return self._transport.get_extra_info("sockname")[:2]

    def peer_addr(self) -> Addr:
        if self._peer is None:
            raise OSError("endpoint is not connected")
        return self._peer

    def close(self) -> None:
        self._transport.close()

    # -- tag-matching datagram API ----------------------------------------

    async def send_to_raw(self, dst: "str | Addr", tag: int, payload: Any) -> None:
        self._transport.sendto(codec.dumps((tag, payload)), _parse(dst))

    async def recv_from_raw(self, tag: int) -> Tuple[Any, Addr]:
        return await self._mailbox.recv(tag)

    async def send_to(self, dst: "str | Addr", tag: int, data: bytes) -> None:
        await self.send_to_raw(dst, tag, bytes(data))

    async def recv_from(self, tag: int) -> Tuple[bytes, Addr]:
        return await self.recv_from_raw(tag)

    async def send(self, tag: int, data: bytes) -> None:
        await self.send_to(self.peer_addr(), tag, data)

    async def recv(self, tag: int) -> bytes:
        data, _ = await self.recv_from(tag)
        return data


class _TcpConn:
    """One live framed connection to a peer (either direction)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    async def write_frame(self, body: bytes) -> None:
        self.writer.write(encode_frame(body))
        await self.writer.drain()

    async def read_frame(self) -> bytes:
        head = await self.reader.readexactly(_LEN.size)
        (n,) = _LEN.unpack(head)
        if n > _MAX_FRAME:
            raise ConnectionError(f"frame of {n} bytes exceeds sanity bound")
        return await self.reader.readexactly(n)

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass


class TcpEndpoint(_RpcAPI):
    """Tag-matching endpoint over persistent length-delimited TCP
    connections — the reference std transport's shape (std/net/tcp.rs:
    42-327: listener + peer map + (tag, payload) frames)."""

    def __init__(self) -> None:
        self._mailbox = _Mailbox()
        self._conns: Dict[Addr, _TcpConn] = {}
        self._dial_locks: Dict[Addr, asyncio.Lock] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._local: Addr = ("0.0.0.0", 0)

    @staticmethod
    async def bind(addr: "str | Addr") -> "TcpEndpoint":
        ep = TcpEndpoint()
        host, port = _parse(addr)
        ep._server = await asyncio.start_server(ep._on_accept, host, port)
        ep._local = ep._server.sockets[0].getsockname()[:2]
        return ep

    def local_addr(self) -> Addr:
        return self._local

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
        for conn in list(self._conns.values()):
            conn.close()
        self._conns.clear()

    # -- connection management ---------------------------------------------

    async def _on_accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = _TcpConn(reader, writer)
        try:
            # The hello frame announces the dialer's LISTEN PORT (its
            # socket peername is an undialable ephemeral port). Only the
            # port is trusted: the host half of the key is the IP the TCP
            # connection actually comes from, so a peer can neither claim
            # another node's address (hello poisoning) nor collide with
            # other nodes by announcing a wildcard bind like 0.0.0.0.
            kind, claimed = codec.loads(await conn.read_frame())
            if kind != "hello":
                raise ConnectionError("expected hello frame")
            observed_ip = writer.get_extra_info("peername")[0]
            peer = (observed_ip, int(claimed[1]))
        except Exception:
            conn.close()
            return
        self._conns.setdefault(peer, conn)
        await self._read_loop(peer, conn)

    async def _read_loop(self, peer: Addr, conn: _TcpConn) -> None:
        try:
            while True:
                tag, payload = codec.loads(await conn.read_frame())
                self._mailbox.deliver(tag, payload, peer)
        except Exception:
            pass  # EOF, reset, or malformed frame: connection is done
        finally:
            if self._conns.get(peer) is conn:
                del self._conns[peer]
            conn.close()

    async def _connection(self, dst: Addr) -> _TcpConn:
        conn = self._conns.get(dst)
        if conn is not None:
            return conn
        lock = self._dial_locks.setdefault(dst, asyncio.Lock())
        async with lock:
            conn = self._conns.get(dst)  # raced dialer won
            if conn is not None:
                return conn
            reader, writer = await asyncio.open_connection(dst[0], dst[1])
            conn = _TcpConn(reader, writer)
            await conn.write_frame(codec.dumps(("hello", self._local)))
            self._conns[dst] = conn
            spawn(self._read_loop(dst, conn))
            return conn

    # -- tag-matching API ----------------------------------------------------

    async def send_to_raw(self, dst: "str | Addr", tag: int, payload: Any) -> None:
        dst = _parse(dst)
        body = codec.dumps((tag, payload))
        for attempt in (0, 1):
            conn = await self._connection(dst)
            try:
                await conn.write_frame(body)
                return
            except Exception:
                # cached connection died: evict and redial once
                if self._conns.get(dst) is conn:
                    del self._conns[dst]
                conn.close()
                if attempt == 1:
                    raise

    async def recv_from_raw(self, tag: int) -> Tuple[Any, Addr]:
        return await self._mailbox.recv(tag)

    async def send_to(self, dst: "str | Addr", tag: int, data: bytes) -> None:
        await self.send_to_raw(dst, tag, bytes(data))

    async def recv_from(self, tag: int) -> Tuple[bytes, Addr]:
        return await self.recv_from_raw(tag)
