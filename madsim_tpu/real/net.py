"""Real-mode networking: the tag-matching Endpoint over real UDP.

The reference's std Endpoint speaks length-delimited frames over real TCP
with a tag→mailbox dispatcher and RPC on top (madsim/src/std/net/tcp.rs:
42-327, std/net/rpc.rs). Here each Endpoint is an asyncio UDP socket;
frames are pickled ``(tag, payload)`` tuples (datagram framing comes for
free), the mailbox matches tags exactly like the sim side, and the
built-in RPC reuses the sim's Request/hash conventions so the same
service classes work in both modes.
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Any, Dict, List, Optional, Tuple

from ..net.rpc import request_id
from . import time as rtime
from .runtime import spawn

Addr = Tuple[str, int]


def _parse(addr: "str | Addr") -> Addr:
    if isinstance(addr, tuple):
        return (addr[0], int(addr[1]))
    host, _, port = addr.rpartition(":")
    return (host or "127.0.0.1", int(port))


class _Mailbox:
    def __init__(self) -> None:
        self.msgs: Dict[int, List[Tuple[Any, Addr]]] = {}
        self.waiters: Dict[int, List[asyncio.Future]] = {}

    def deliver(self, tag: int, payload: Any, src: Addr) -> None:
        waiters = self.waiters.get(tag)
        while waiters:
            fut = waiters.pop(0)
            if not fut.done():
                fut.set_result((payload, src))
                return
        self.msgs.setdefault(tag, []).append((payload, src))

    async def recv(self, tag: int) -> Tuple[Any, Addr]:
        pending = self.msgs.get(tag)
        if pending:
            return pending.pop(0)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.waiters.setdefault(tag, []).append(fut)
        return await fut


class _Proto(asyncio.DatagramProtocol):
    def __init__(self, mailbox: _Mailbox):
        self.mailbox = mailbox

    def datagram_received(self, data: bytes, addr: Addr) -> None:
        try:
            tag, payload = pickle.loads(data)
        except Exception:
            return  # malformed frame — drop, like a bad packet
        self.mailbox.deliver(tag, payload, addr)


class Endpoint:
    """Tag-matching datagram endpoint over a real UDP socket."""

    def __init__(self, transport: asyncio.DatagramTransport, mailbox: _Mailbox):
        self._transport = transport
        self._mailbox = mailbox
        self._peer: Optional[Addr] = None

    @staticmethod
    async def bind(addr: "str | Addr") -> "Endpoint":
        loop = asyncio.get_running_loop()
        mailbox = _Mailbox()
        transport, _ = await loop.create_datagram_endpoint(
            lambda: _Proto(mailbox), local_addr=_parse(addr)
        )
        return Endpoint(transport, mailbox)

    @staticmethod
    async def connect(addr: "str | Addr") -> "Endpoint":
        ep = await Endpoint.bind(("127.0.0.1", 0))
        ep._peer = _parse(addr)
        return ep

    def local_addr(self) -> Addr:
        return self._transport.get_extra_info("sockname")[:2]

    def peer_addr(self) -> Addr:
        if self._peer is None:
            raise OSError("endpoint is not connected")
        return self._peer

    def close(self) -> None:
        self._transport.close()

    # -- tag-matching datagram API ----------------------------------------

    async def send_to_raw(self, dst: "str | Addr", tag: int, payload: Any) -> None:
        self._transport.sendto(pickle.dumps((tag, payload)), _parse(dst))

    async def recv_from_raw(self, tag: int) -> Tuple[Any, Addr]:
        return await self._mailbox.recv(tag)

    async def send_to(self, dst: "str | Addr", tag: int, data: bytes) -> None:
        await self.send_to_raw(dst, tag, bytes(data))

    async def recv_from(self, tag: int) -> Tuple[bytes, Addr]:
        return await self.recv_from_raw(tag)

    async def send(self, tag: int, data: bytes) -> None:
        await self.send_to(self.peer_addr(), tag, data)

    async def recv(self, tag: int) -> bytes:
        data, _ = await self.recv_from(tag)
        return data

    # -- built-in RPC (same wire convention as the sim side) ---------------

    async def call(self, dst: "str | Addr", req: Any) -> Any:
        import random as _random

        rsp_tag = _random.getrandbits(64)
        await self.send_to_raw(dst, request_id(req), (rsp_tag, req, b""))
        payload, _src = await self.recv_from_raw(rsp_tag)
        rsp, _data = payload
        return rsp

    async def call_timeout(self, dst: "str | Addr", req: Any, timeout_s: float) -> Any:
        return await rtime.timeout(timeout_s, self.call(dst, req))

    def add_rpc_handler(self, req_type: type, handler: Any) -> None:
        rid = request_id(req_type)

        async def accept_loop() -> None:
            while True:
                payload, src = await self.recv_from_raw(rid)
                rsp_tag, req, _data = payload

                async def handle_one(req=req, rsp_tag=rsp_tag, src=src) -> None:
                    rsp = await handler(req)
                    await self.send_to_raw(src, rsp_tag, (rsp, b""))

                spawn(handle_one())

        spawn(accept_loop())
