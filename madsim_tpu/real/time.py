"""Real-mode time: wall clock behind the sim time API shape."""

from __future__ import annotations

import asyncio
import time as _time
from typing import Any


class TimeoutError(Exception):  # same name as the sim's (tokio Elapsed)
    pass


class Instant:
    __slots__ = ("ns",)

    def __init__(self, ns: int):
        self.ns = ns

    def __sub__(self, other: "Instant") -> float:
        return (self.ns - other.ns) / 1e9

    def __add__(self, seconds: float) -> "Instant":
        return Instant(self.ns + int(seconds * 1e9))

    def elapsed(self) -> float:
        return now_instant() - self

    def __lt__(self, other: "Instant") -> bool:
        return self.ns < other.ns

    def __le__(self, other: "Instant") -> bool:
        return self.ns <= other.ns


def now_instant() -> Instant:
    return Instant(_time.monotonic_ns())


def now() -> float:
    return _time.time()


def elapsed() -> float:
    return _time.monotonic()


async def sleep(seconds: float) -> None:
    await asyncio.sleep(seconds)


async def sleep_until(deadline: Instant) -> None:
    await asyncio.sleep(max(0.0, deadline - now_instant()))


async def timeout(seconds: float, awaitable: Any) -> Any:
    try:
        return await asyncio.wait_for(awaitable, seconds)
    except asyncio.TimeoutError:
        raise TimeoutError(f"deadline has elapsed after {seconds}s") from None


class Interval:
    def __init__(self, period: float):
        self._period = period
        self._next = _time.monotonic() + period

    async def tick(self) -> Instant:
        delay = self._next - _time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        scheduled = self._next
        self._next = scheduled + self._period
        return Instant(int(scheduled * 1e9))


def interval(period: float) -> Interval:
    iv = Interval(period)
    iv._next = _time.monotonic()  # first tick immediate, tokio parity
    return iv
