"""Real-mode etcd twin: the same client API and server state machine over
real TCP.

The reference's madsim-etcd-client compiles to the *real* etcd-client crate
without ``--cfg madsim`` (madsim-etcd-client/src/lib.rs) — sim and
production share one API.  Python has no production etcd server to link
against in this image, so real mode here pairs the unchanged client surface
with the framework's own EtcdService state machine served over real sockets
(the shape of etcd's own integration harness): every request is one framed
TCP exchange, watches/observe/campaign hold their stream open, leases tick
on wall-clock seconds.

    from madsim_tpu.real import etcd

    # server (own task / process)
    await etcd.Server.builder().serve(("127.0.0.1", 2379))
    # client
    client = await etcd.Client.connect("127.0.0.1:2379")
    await client.put("k", "v")

Wire safety: the restricted codec only materializes the option/data classes
registered below — a hostile peer cannot execute code.
"""

from __future__ import annotations

import asyncio
import random as _pyrandom
from typing import Any

from ..etcd.client import (
    Client as _SimClient,
    ConnectOptions,
    LeaderKey,
)
from ..etcd.server import SimServer as _SimServer, SimServerBuilder as _SimServerBuilder
from ..etcd.service import (
    Compare,
    CompareOp,
    DeleteOptions,
    EtcdService,
    Event,
    EventType,
    GetOptions,
    KeyValue,
    PutOptions,
    Txn,
    TxnOp,
)
from ..grpc.status import Code, Status
from . import codec, stream
from . import time as rtime
from .runtime import spawn

# the wire vocabulary of the etcd protocol — explicit, like the serde
# derives on the reference's request/response types
for _cls in (
    PutOptions,
    GetOptions,
    DeleteOptions,
    Compare,
    CompareOp,
    TxnOp,
    Txn,
    KeyValue,
    Event,
    EventType,
    Status,
    Code,
):
    codec.register(_cls)


def _asyncio_future() -> "asyncio.Future":
    return asyncio.get_running_loop().create_future()


class Server(_SimServer):
    """The EtcdService dispatcher on a real listener + wall-clock ticks.

    Serving rides the shared core (``madsim_tpu/serve/``): the pull-
    style ``_serve_conn(tx, rx)`` dispatcher is unchanged — a
    ``ChannelAdapter`` recreates the pipe surface per connection while
    the core owns sockets, framing, backpressure, and metrics. (The
    grpcio wire tier, ``etcd/wire.py``, keeps its own HTTP/2 accept
    loop — grpc.aio owns it; see docs/wire.md.)
    """

    _spawn = staticmethod(spawn)
    _sleep = staticmethod(rtime.sleep)
    _rand01 = staticmethod(_pyrandom.random)
    _uniform = staticmethod(_pyrandom.uniform)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await stream.StreamListener.bind(addr)

    async def serve(self, addr: "str | tuple") -> None:
        from ..serve import AsyncWireServer, ChannelAdapter

        # watchers must block on asyncio futures, not sim futures
        self.service.bus.future_factory = _asyncio_future
        adapter = ChannelAdapter(self._serve_conn, codec, name="etcd")
        self._core = AsyncWireServer(adapter, telemetry=self.telemetry)
        self.bound_addr = await self._core.start(addr)
        tick = spawn(self._tick_loop(), name="etcd-tick")
        try:
            await self._core._stopped.wait()
        finally:
            self._core._teardown()
            tick.cancel()

    def close(self) -> None:
        core = getattr(self, "_core", None)
        if core is not None:
            core.close()

    @staticmethod
    def builder() -> "ServerBuilder":
        return ServerBuilder()


class LegacyServer(Server):
    """The pre-core accept loop (``StreamListener.accept1`` + one task
    per connection) — the A/B baseline for parity gates; deprecated for
    serving."""

    async def serve(self, addr: "str | tuple") -> None:
        self.service.bus.future_factory = _asyncio_future
        await _SimServer.serve(self, addr)


class ServerBuilder(_SimServerBuilder):
    _server_cls = Server


class Client(_SimClient):
    """The etcd client surface dialing real framed-TCP connections."""

    @staticmethod
    def _randint(n: int) -> int:
        return _pyrandom.randrange(n)  # real mode: real randomness

    async def _open(self):
        try:
            return await stream.connect(self._pick())
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(f"etcd transport error: {e}") from None


__all__ = [
    "Client",
    "Compare",
    "CompareOp",
    "ConnectOptions",
    "DeleteOptions",
    "EtcdService",
    "Event",
    "EventType",
    "GetOptions",
    "KeyValue",
    "LeaderKey",
    "LegacyServer",
    "PutOptions",
    "Server",
    "ServerBuilder",
    "Txn",
    "TxnOp",
]
