"""Real-mode Kafka twin: the unchanged client API + the broker state
machine over real TCP.

The reference's madsim-rdkafka compiles to the *real* rdkafka bindings
without ``--cfg madsim`` (madsim-rdkafka/src/lib.rs:3-12). No librdkafka
exists in this image, so real mode pairs the unchanged client surface
(producers, consumers, admin) with the framework's own ``Broker`` served
over real sockets — one framed TCP exchange per operation, wall-clock
produce timestamps and poll deadlines::

    from madsim_tpu.real import kafka

    await kafka.SimBroker().serve(("127.0.0.1", 9092))      # server task
    p = await config.create(kafka.FutureProducer)           # client side
"""

from __future__ import annotations

from typing import Any
import time as _walltime

from ..kafka.broker import OwnedMessage, Watermarks
from ..kafka.client import (
    AdminClient as _SimAdminClient,
    BaseConsumer as _SimBaseConsumer,
    BaseProducer as _SimBaseProducer,
    BaseRecord,
    ClientConfig,
    FutureProducer as _SimFutureProducer,
    FutureRecord,
    KafkaError,
    StreamConsumer as _SimStreamConsumer,
    TopicPartitionList,
    _BrokerConn as _SimBrokerConn,
)
from ..kafka.server import SimBroker as _SimBroker
from . import codec, stream
from . import time as rtime
from .runtime import spawn

# the wire vocabulary (responses carry these dataclasses)
codec.register(OwnedMessage)
codec.register(Watermarks)


class SimBroker(_SimBroker):
    """The broker dispatcher on a real listener, wall-clock timestamps."""

    _spawn = staticmethod(spawn)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await stream.StreamListener.bind(addr)

    @staticmethod
    def _now_ms() -> int:
        return _walltime.time_ns() // 1_000_000


Broker = SimBroker  # the natural real-mode name


class _BrokerConn(_SimBrokerConn):
    _connect = staticmethod(stream.connect)


class BaseProducer(_SimBaseProducer):
    _conn_cls = _BrokerConn


class FutureProducer(_SimFutureProducer):
    _conn_cls = _BrokerConn
    _sleep = staticmethod(rtime.sleep)


class BaseConsumer(_SimBaseConsumer):
    _conn_cls = _BrokerConn
    _sleep = staticmethod(rtime.sleep)
    _now_instant = staticmethod(rtime.now_instant)


class StreamConsumer(_SimStreamConsumer, BaseConsumer):
    pass


class AdminClient(_SimAdminClient):
    _conn_cls = _BrokerConn


__all__ = [
    "AdminClient",
    "BaseConsumer",
    "BaseProducer",
    "BaseRecord",
    "Broker",
    "ClientConfig",
    "FutureProducer",
    "FutureRecord",
    "KafkaError",
    "OwnedMessage",
    "SimBroker",
    "StreamConsumer",
    "TopicPartitionList",
    "Watermarks",
]
