"""Real-mode Kafka twin: the unchanged client API over the GENUINE
Kafka binary wire protocol.

The reference's madsim-rdkafka compiles to the *real* rdkafka bindings
without ``--cfg madsim`` (madsim-rdkafka/src/lib.rs:3-12). No librdkafka
exists in this image, so real mode pairs the unchanged client surface
(producers, consumers, admin) with the framework's own ``Broker`` served
over **real Kafka protocol TCP** (``kafka/wire.py``: 4-byte framing,
correlation-id headers, record-batch v2 + CRC32C) — any stock Kafka
client can connect to the same port. The client classes here translate
their operations onto genuine wire requests (client-side partitioning,
Join/Sync/Heartbeat group sessions, OffsetCommit/OffsetFetch), with
wall-clock produce timestamps and poll deadlines::

    from madsim_tpu.real import kafka

    await kafka.SimBroker().serve(("127.0.0.1", 9092))      # server task
    p = await config.create(kafka.FutureProducer)           # client side

The pre-wire private framed codec stays A/B-able behind
``MADSIM_KAFKA_LEGACY=1`` (both sides switch together, like the engine's
``legacy_queue`` layout flag): useful for bisecting a wire-layer bug
against the old transport, never the default.
"""

from __future__ import annotations

import os
import zlib
from typing import Any, Dict, List, Optional, Tuple
import time as _walltime

from ..kafka import wire as kwire
from ..kafka.broker import OwnedMessage, Watermarks
from ..kafka.client import (
    AdminClient as _SimAdminClient,
    BaseConsumer as _SimBaseConsumer,
    BaseProducer as _SimBaseProducer,
    BaseRecord,
    ClientConfig,
    FutureProducer as _SimFutureProducer,
    FutureRecord,
    KafkaError,
    StreamConsumer as _SimStreamConsumer,
    TopicPartitionList,
    _BrokerConn as _SimBrokerConn,
)
from ..kafka.probe import ProbeClient, ProbeError, RealTransport
from ..kafka.server import SimBroker as _SimBroker
from . import codec, stream
from . import time as rtime
from .runtime import spawn

# the legacy wire vocabulary (A/B path responses carry these dataclasses)
codec.register(OwnedMessage)
codec.register(Watermarks)


def _legacy_wire() -> bool:
    return os.environ.get("MADSIM_KAFKA_LEGACY", "") in ("1", "true")


class SimBroker(_SimBroker):
    """The broker on a real listener: genuine Kafka wire by default,
    the legacy private codec under ``MADSIM_KAFKA_LEGACY=1``."""

    # legacy-path bindings (the pre-wire framed-codec dispatcher)
    _spawn = staticmethod(spawn)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await stream.StreamListener.bind(addr)

    @staticmethod
    def _now_ms() -> int:
        return _walltime.time_ns() // 1_000_000

    def __init__(self) -> None:
        super().__init__()
        self.wire_server: Optional[kwire.WireServer] = None

    async def serve(self, addr: "str | tuple") -> None:
        if _legacy_wire():
            await super().serve(addr)
            return
        ws = kwire.WireServer(broker=self.broker)
        self.wire_server = ws
        await ws.start(addr)
        self.bound_addr = ws.bound_addr
        try:
            await ws._core._stopped.wait()
        finally:
            ws._core._teardown()


Broker = SimBroker  # the natural real-mode name


class _WireAdapter:
    """Translate the client classes' op tuples onto genuine wire calls.

    Holds one persistent TCP connection plus the client-side state real
    Kafka keeps client-side too: a metadata cache and round-robin cursor
    for partitioning (the broker no longer partitions for us — the real
    protocol's Produce names a partition), and per-group session state
    (member id, generation, subscription, assignment) so a heartbeat can
    answer ``(generation, assignment)`` and a REBALANCE_IN_PROGRESS can
    trigger the eager protocol's rejoin."""

    def __init__(self, addr: str):
        import asyncio

        self._addr = addr
        self._client: Optional[ProbeClient] = None
        self._parts: Dict[str, int] = {}
        self._rr: Dict[str, int] = {}
        self._groups: Dict[str, Dict[str, Any]] = {}
        # one connection carries every call: serialize them, or two
        # concurrent ops (gather'd sends — fine on the legacy per-call
        # transport) would interleave frames on one stream reader
        self._lock = asyncio.Lock()

    async def _c(self) -> ProbeClient:
        if self._client is None:
            try:
                self._client = ProbeClient(
                    await RealTransport.connect(self._addr)
                )
            except (ConnectionError, OSError) as e:
                raise KafkaError(f"broker transport error: {e}") from None
        return self._client

    def _drop_conn(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None

    @staticmethod
    def _err(code: int, what: str) -> KafkaError:
        name = kwire.ERROR_NAMES.get(code, f"error {code}")
        return KafkaError(f"{name}: {what}")

    async def call(self, req: tuple) -> Any:
        async with self._lock:
            try:
                return await self._dispatch(req)
            except (ConnectionError, OSError) as e:
                self._drop_conn()
                raise KafkaError(f"broker transport error: {e}") from None
            except ProbeError as e:
                self._drop_conn()
                raise KafkaError(f"broker transport error: {e}") from None

    async def _partitions(self, topic: str) -> int:
        n = self._parts.get(topic)
        if n is None:
            md = await (await self._c()).metadata([topic])
            n = md.get(topic)
            if n is None:
                raise KafkaError(f"unknown topic: {topic!r}")
            self._parts[topic] = n
        return n

    async def _dispatch(self, req: tuple) -> Any:
        c = await self._c()
        op = req[0]

        if op == "create_topic":
            _, name, partitions = req
            (name, err, msg), = await c.create_topics([(name, partitions)])
            if err != kwire.ERR_NONE:
                raise KafkaError(msg or self._err(err, name).args[0])
            return None

        if op == "delete_topic":
            (name, err), = await c.delete_topics([req[1]])
            self._parts.pop(name, None)
            if err != kwire.ERR_NONE:
                raise KafkaError(f"unknown topic: {name!r}")
            return None

        if op == "produce":
            _, topic, partition, key, payload = req
            if partition is None:
                n = await self._partitions(topic)
                if key is not None:
                    partition = zlib.crc32(key) % n
                else:
                    partition = self._rr.get(topic, 0) % n
                    self._rr[topic] = self._rr.get(topic, 0) + 1
            err, base = await c.produce(
                topic, partition,
                [(_walltime.time_ns() // 1_000_000, key, payload)],
            )
            if err != kwire.ERR_NONE:
                raise self._err(err, f"{topic}[{partition}]")
            return partition, base

        if op == "fetch":
            _, topic, partition, offset, fmax, pmax = req
            err, _high, rows = await c.fetch(
                topic, partition, offset, max_bytes=fmax,
                partition_max_bytes=pmax,
            )
            if err != kwire.ERR_NONE:
                raise self._err(err, f"{topic}[{partition}]")
            return [
                OwnedMessage(topic, partition, off, ts, k, v)
                for off, ts, k, v in rows
            ]

        if op == "watermarks":
            _, topic, partition = req
            err, _ts, low = await c.list_offsets(topic, partition, -2)
            if err != kwire.ERR_NONE:
                raise self._err(err, f"{topic}[{partition}]")
            err, _ts, high = await c.list_offsets(topic, partition, -1)
            if err != kwire.ERR_NONE:
                raise self._err(err, f"{topic}[{partition}]")
            return Watermarks(low=low, high=high)

        if op == "offsets_for_times":
            out = []
            for topic, partition, ts in req[1]:
                err, _t, off = await c.list_offsets(topic, partition, ts)
                if err != kwire.ERR_NONE:
                    raise self._err(err, f"{topic}[{partition}]")
                out.append((topic, partition, None if off < 0 else off))
            return out

        if op == "metadata":
            topic = req[1]
            md = await c.metadata(None if topic is None else [topic])
            for name, n in list(md.items()):
                if n is None:
                    raise KafkaError(f"unknown topic: {name!r}")
            return md

        if op == "join_group":
            _, group, member, topics = req
            return await self._join(c, group, member or "", list(topics))

        if op == "leave_group":
            _, group, member = req
            err = await c.leave_group(group, member)
            self._groups.pop(group, None)
            if err not in (kwire.ERR_NONE, kwire.ERR_GROUP_ID_NOT_FOUND):
                raise self._err(err, group)
            return None

        if op == "heartbeat":
            _, group, member = req
            st = self._groups.get(group)
            if st is None or st["member"] != member:
                raise KafkaError(
                    f"unknown member {member!r} in group {group!r}"
                )
            err = await c.heartbeat(group, st["gen"], member)
            if err == kwire.ERR_NONE:
                return st["gen"], st["assignment"]
            if err in (kwire.ERR_REBALANCE_IN_PROGRESS,
                       kwire.ERR_ILLEGAL_GENERATION,
                       kwire.ERR_UNKNOWN_MEMBER_ID):
                # the eager protocol: a moved generation means rejoin
                _m, gen, assignment = await self._join(
                    c, group, member, st["topics"]
                )
                return gen, assignment
            raise self._err(err, group)

        if op == "commit":
            _, group, offsets = req[:3]
            generation = req[3] if len(req) > 3 else None
            st = self._groups.get(group)
            member = st["member"] if st else ""
            results = await c.offset_commit(
                group, -1 if generation is None else generation,
                member, [tuple(o) for o in offsets],
            )
            for topic, partition, err in results:
                if err == kwire.ERR_ILLEGAL_GENERATION:
                    raise KafkaError(
                        f"ILLEGAL_GENERATION: commit for group {group!r} "
                        f"carries a stale generation (zombie member — "
                        "rejoin before committing)"
                    )
                if err != kwire.ERR_NONE:
                    raise self._err(err, f"{topic}[{partition}]")
            return None

        if op == "committed":
            _, group, tps = req
            got = await c.offset_fetch(group, [tuple(tp) for tp in tps])
            by_tp = {(t, p): off for t, p, off in got}
            return [(t, p, by_tp.get((t, p))) for t, p in tps]

        raise KafkaError(f"unknown request {op!r}")

    async def _join(
        self, c: ProbeClient, group: str, member: str, topics: List[str]
    ) -> Tuple[str, int, List[Tuple[str, int]]]:
        member_id, gen, assignment = await c.group_session(
            group, topics, member_id=member
        )
        self._groups[group] = {
            "member": member_id, "gen": gen,
            "topics": list(topics), "assignment": assignment,
        }
        return member_id, gen, assignment


class _BrokerConn(_SimBrokerConn):
    """The per-client connection: wire adapter by default, the legacy
    one-exchange framed codec under ``MADSIM_KAFKA_LEGACY=1``."""

    _connect = staticmethod(stream.connect)  # legacy path transport

    def __init__(self, config: ClientConfig):
        super().__init__(config)
        self._wire = None if _legacy_wire() else _WireAdapter(self._addr)

    async def call(self, req: tuple) -> Any:
        if self._wire is None:
            return await super().call(req)
        return await self._wire.call(req)


class BaseProducer(_SimBaseProducer):
    _conn_cls = _BrokerConn


class FutureProducer(_SimFutureProducer):
    _conn_cls = _BrokerConn
    _sleep = staticmethod(rtime.sleep)


class BaseConsumer(_SimBaseConsumer):
    _conn_cls = _BrokerConn
    _sleep = staticmethod(rtime.sleep)
    _now_instant = staticmethod(rtime.now_instant)


class StreamConsumer(_SimStreamConsumer, BaseConsumer):
    pass


class AdminClient(_SimAdminClient):
    _conn_cls = _BrokerConn


__all__ = [
    "AdminClient",
    "BaseConsumer",
    "BaseProducer",
    "BaseRecord",
    "Broker",
    "ClientConfig",
    "FutureProducer",
    "FutureRecord",
    "KafkaError",
    "OwnedMessage",
    "SimBroker",
    "StreamConsumer",
    "TopicPartitionList",
    "Watermarks",
]
