"""Real-mode runtime: asyncio event loop behind the sim API shape."""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Callable, Coroutine, Union


class JoinHandle:
    """asyncio.Task behind the sim JoinHandle surface."""

    def __init__(self, task: asyncio.Task):
        self._task = task

    def done(self) -> bool:
        return self._task.done()

    def is_finished(self) -> bool:
        return self._task.done()

    def abort(self) -> None:
        self._task.cancel()

    def abort_handle(self) -> "JoinHandle":
        return self

    def result(self) -> Any:
        return self._task.result()

    def __await__(self):
        return self._task.__await__()


def spawn(coro: Coroutine[Any, Any, Any], name: str = None) -> JoinHandle:
    """Real ``task::spawn`` (ref std/mod.rs re-exports tokio spawn)."""
    return JoinHandle(asyncio.get_running_loop().create_task(coro, name=name))


spawn_local = spawn


class Runtime:
    """Real runtime: ``block_on`` = asyncio.run (ref std twin)."""

    def __init__(self, seed: int = None, config: Any = None):
        # seed/config accepted for signature parity; real mode ignores them
        pass

    def block_on(
        self,
        main: Union[Coroutine[Any, Any, Any], Callable[[], Coroutine[Any, Any, Any]]],
    ) -> Any:
        coro = main() if callable(main) and not inspect.iscoroutine(main) else main
        return asyncio.run(coro)
