"""Real-mode filesystem twin: the sim ``fs`` API over actual files.

The reference's std tree wraps real tokio fs (madsim/src/std/fs.rs) so the
same ``fs::File`` code compiles against the OS filesystem outside the sim.
This module is that twin: the surface of ``madsim_tpu.fs`` (File.open/
create/open_or_create, positional read/write, set_len, sync_all, read/
write/metadata/remove_file) backed by real file descriptors, with every
blocking syscall offloaded via ``asyncio.to_thread`` (the analogue of
tokio's blocking-pool offload).

Semantics differences from the sim, by design: there is no crash shadow
state — ``sync_all`` is a real ``fsync`` and durability is the kernel's
business (the sim's power_fail model exists to TEST the code; real mode
runs it). ``remove_file(durable=True)`` additionally fsyncs the parent
directory (the "journaled fs + directory fsync" contract the sim models).
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional


class Metadata:
    def __init__(self, size: int):
        self._size = size

    def len(self) -> int:
        return self._size

    def is_file(self) -> bool:
        return True


class File:
    """Async file handle over a real fd (positional I/O via pread/pwrite,
    so concurrent readers never race a shared cursor — same contract as
    the sim handle)."""

    def __init__(self, fd: int, path: str):
        self._fd: Optional[int] = fd
        self.path = path

    # -- constructors (sim File.open/create/open_or_create) ---------------

    @staticmethod
    async def open(path: str) -> "File":
        fd = await asyncio.to_thread(os.open, str(path), os.O_RDWR)
        return File(fd, str(path))

    @staticmethod
    async def create(path: str) -> "File":
        fd = await asyncio.to_thread(
            os.open, str(path), os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644
        )
        return File(fd, str(path))

    @staticmethod
    async def open_or_create(path: str) -> "File":
        fd = await asyncio.to_thread(
            os.open, str(path), os.O_RDWR | os.O_CREAT, 0o644
        )
        return File(fd, str(path))

    # -- I/O ----------------------------------------------------------------

    def _live(self) -> int:
        if self._fd is None:
            raise ValueError(f"file {self.path!r} is closed")
        return self._fd

    async def read_at(self, buf_len: int, offset: int) -> bytes:
        return await asyncio.to_thread(os.pread, self._live(), buf_len, offset)

    async def read_all(self) -> bytes:
        fd = self._live()

        def _read() -> bytes:
            size = os.fstat(fd).st_size
            return os.pread(fd, size, 0)

        return await asyncio.to_thread(_read)

    async def write_all_at(self, buf: bytes, offset: int) -> None:
        fd = self._live()

        def _write() -> None:
            view = memoryview(bytes(buf))
            pos = offset
            while view:
                n = os.pwrite(fd, view, pos)
                view = view[n:]
                pos += n

        await asyncio.to_thread(_write)

    async def write_all(self, buf: bytes) -> None:
        """Append at end-of-file (the sim's write_all extends the buffer)."""
        fd = self._live()

        def _append() -> None:
            pos = os.fstat(fd).st_size
            view = memoryview(bytes(buf))
            while view:
                n = os.pwrite(fd, view, pos)
                view = view[n:]
                pos += n

        await asyncio.to_thread(_append)

    async def set_len(self, size: int) -> None:
        await asyncio.to_thread(os.ftruncate, self._live(), size)

    async def sync_all(self) -> None:
        await asyncio.to_thread(os.fsync, self._live())

    async def metadata(self) -> Metadata:
        st = await asyncio.to_thread(os.fstat, self._live())
        return Metadata(st.st_size)

    def close(self) -> None:
        fd, self._fd = self._fd, None
        if fd is not None:
            os.close(fd)

    def __del__(self) -> None:  # fd hygiene if the handle is dropped
        try:
            self.close()
        except OSError:
            pass

    async def __aenter__(self) -> "File":
        return self

    async def __aexit__(self, *_exc) -> None:
        self.close()


# -- module-level helpers (sim fs.read/write/metadata/remove_file) ----------


async def read(path: str) -> bytes:
    f = await File.open(path)
    try:
        return await f.read_all()
    finally:
        f.close()


async def write(path: str, data: bytes) -> None:
    f = await File.create(path)
    try:
        await f.write_all(data)
        await f.sync_all()
    finally:
        f.close()


async def metadata(path: str) -> Metadata:
    st = await asyncio.to_thread(os.stat, str(path))
    return Metadata(st.st_size)


async def remove_file(path: str, durable: bool = False) -> None:
    """Unlink; ``durable=True`` also fsyncs the parent directory so the
    unlink itself survives a crash (what the sim's durable flag models)."""

    def _unlink() -> None:
        os.unlink(str(path))
        if durable:
            dirfd = os.open(os.path.dirname(os.path.abspath(str(path))) or ".",
                            os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)

    await asyncio.to_thread(_unlink)
