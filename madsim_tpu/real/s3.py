"""Real-mode S3 twin: the unchanged SDK-shaped client + the S3Service
state machine over real TCP (the dual-mode property of
madsim-aws-sdk-s3/src/lib.rs:3-10 — sim and production share one API)::

    from madsim_tpu.real import s3

    await s3.SimServer().serve(("127.0.0.1", 9000))    # server task
    client = s3.Client.from_addr("127.0.0.1:9000")     # client side
    await client.put_object().bucket("b").key("k").body(b"...").send()
"""

from __future__ import annotations

import time as _walltime
from typing import Any

from ..s3.client import (
    ByteStream,
    Client as _SimClient,
    CompletedMultipartUpload,
    CompletedPart,
    Delete,
    ObjectIdentifier,
)
from ..s3.server import SimServer as _SimServer
from ..s3.service import S3Error, S3Service
from . import codec, stream
from .runtime import spawn


class SimServer(_SimServer):
    """The S3Service dispatcher on a real listener, wall-clock mtimes.

    Serving rides the shared core (``madsim_tpu/serve/``) through a
    ``ChannelAdapter``: the one-exchange ``_serve_conn(tx, rx)``
    dispatcher is unchanged."""

    _spawn = staticmethod(spawn)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await stream.StreamListener.bind(addr)

    def _now_ms(self) -> int:
        return _walltime.time_ns() // 1_000_000

    async def serve(self, addr: "str | tuple") -> None:
        from ..serve import AsyncWireServer, ChannelAdapter

        adapter = ChannelAdapter(self._serve_conn, codec, name="s3-enum")
        self._core = AsyncWireServer(adapter)
        self.bound_addr = await self._core.start(addr)
        try:
            await self._core._stopped.wait()
        finally:
            self._core._teardown()

    def close(self) -> None:
        core = getattr(self, "_core", None)
        if core is not None:
            core.close()


class LegacyServer(SimServer):
    """The pre-core accept loop (one task per ``accept1``) — kept as an
    A/B baseline; deprecated for serving."""

    async def serve(self, addr: "str | tuple") -> None:
        await _SimServer.serve(self, addr)


Server = SimServer  # the natural real-mode name


class Client(_SimClient):
    """The fluent-builder client dialing real framed-TCP connections."""

    _connect = staticmethod(stream.connect)


__all__ = [
    "ByteStream",
    "Client",
    "CompletedMultipartUpload",
    "CompletedPart",
    "Delete",
    "LegacyServer",
    "ObjectIdentifier",
    "S3Error",
    "S3Service",
    "Server",
    "SimServer",
]
