"""Real-mode signal twin: ``ctrl_c`` over actual OS signals.

The sim's ``signal.ctrl_c`` waits for a simulated ctrl-c delivered by the
supervisor (``Handle.send_ctrl_c``); outside the sim the same call must
wait for a real SIGINT — the reference's std tree gets this for free by
re-exporting tokio's ``signal::ctrl_c``. One shared handler serves ALL
concurrent waiters (the sim twin wakes every waiter too, signal.py), and
it is removed once the last waiter finishes. Caveat: an event loop allows
one SIGINT handler at a time, so while a waiter is pending a host-installed
*loop* handler is superseded; after the last waiter the loop reverts to
Python's default SIGINT behavior (KeyboardInterrupt)."""

from __future__ import annotations

import asyncio
import signal as _signal
from typing import List, Optional

_waiters: List[asyncio.Future] = []
_installed_loop: Optional[asyncio.AbstractEventLoop] = None


def _on_sigint() -> None:
    waiters, _waiters[:] = list(_waiters), []
    for fut in waiters:
        if fut.done() or fut.get_loop().is_closed():
            # a waiter whose runtime was abandoned without cancellation
            # leaves a future bound to a closed loop; resolving it would
            # raise mid-iteration and strand every later live waiter
            continue
        try:
            fut.set_result(None)
        except RuntimeError:
            pass  # loop torn down between the check and the call


async def ctrl_c() -> None:
    """Wait for one SIGINT delivered to this process; every concurrent
    waiter resolves on the same signal."""
    global _installed_loop
    loop = asyncio.get_running_loop()
    fut: asyncio.Future = loop.create_future()
    _waiters.append(fut)
    if _installed_loop is not loop:
        loop.add_signal_handler(_signal.SIGINT, _on_sigint)
        _installed_loop = loop
    try:
        await fut
    finally:
        if fut in _waiters:  # cancelled/timeout before the signal fired
            _waiters.remove(fut)
        if not _waiters and _installed_loop is loop:
            loop.remove_signal_handler(_signal.SIGINT)
            _installed_loop = None
