"""Simulator plugin framework (ref madsim/src/sim/plugin.rs:18-59).

A *simulator* is a pluggable device model (network, filesystem, etcd server,
...) registered on the runtime.  The registry is keyed by class; lookups from
user code resolve through the ambient handle, mirroring the reference's
TypeId-keyed registry + ``plugin::simulator::<S>()`` downcast.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Type, TypeVar

from .context import current_handle

if TYPE_CHECKING:
    from .config import Config
    from .rand import GlobalRng
    from .task import NodeId
    from .time import TimeHandle

S = TypeVar("S", bound="Simulator")


class Simulator:
    """Base class for device simulators (ref ``Simulator`` trait).

    Subclasses get the runtime's rng/time/config at registration
    (``Simulator::new``) and are notified of node lifecycle events.
    """

    def __init__(self, rng: "GlobalRng", time: "TimeHandle", config: "Config"):
        self.rng = rng
        self.time = time
        self.config = config

    def create_node(self, id: "NodeId") -> None:
        """A new node was created (ref plugin.rs:34-36)."""

    def reset_node(self, id: "NodeId") -> None:
        """Node was killed or restarted — drop its state (plugin.rs:38-40)."""


def simulator(cls: Type[S]) -> S:
    """Fetch the registered simulator of type ``cls`` from the ambient
    runtime (ref ``plugin::simulator::<S>()``, plugin.rs:42-54)."""
    return current_handle().simulator(cls)
