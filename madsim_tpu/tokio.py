"""Async-runtime façade — the madsim-tokio analogue.

The reference ships a tokio drop-in that re-exports the simulator's
net/time/task/signal, keeps the runtime-agnostic pieces (sync primitives,
macros), and fakes ``runtime::{Builder, Runtime, Handle}`` — ``Runtime``
collects the abort handles of everything it spawned and aborts them all on
shutdown, while ``block_on`` inside a simulation is a hard error
(madsim-tokio/src/lib.rs:38-50, sim/runtime.rs:51-112).

Users porting tokio-shaped Python code get the same shape:

    from madsim_tpu import tokio
    rt = tokio.runtime.Builder().build()
    rt.spawn(worker())          # tracked; aborted on rt.shutdown()
    await tokio.time.sleep(1.0)
    tx, rx = tokio.sync.channel(16)
"""

from __future__ import annotations

from typing import Any, Coroutine, List, Optional

# re-exports, mirroring the façade's module layout (lib.rs:38-50)
from . import fs as fs
from . import net as net
from . import signal as signal
from . import sync as sync
from . import task as task
from . import time as time
from .futures import JoinHandle, join, select
from .task import spawn, spawn_local
from .time import interval, sleep, sleep_until, timeout


class runtime:
    """Namespace mirroring ``tokio::runtime``."""

    class Builder:
        """Accepts-and-ignores the threading knobs (a simulation is
        single-threaded by construction), builds a tracking Runtime."""

        def __init__(self) -> None:
            pass

        @staticmethod
        def new_multi_thread() -> "runtime.Builder":
            return runtime.Builder()

        @staticmethod
        def new_current_thread() -> "runtime.Builder":
            return runtime.Builder()

        def worker_threads(self, _n: int) -> "runtime.Builder":
            return self

        def thread_name(self, _name: str) -> "runtime.Builder":
            return self

        def thread_stack_size(self, _n: int) -> "runtime.Builder":
            return self

        def enable_all(self) -> "runtime.Builder":
            return self

        def enable_time(self) -> "runtime.Builder":
            return self

        def enable_io(self) -> "runtime.Builder":
            return self

        def build(self) -> "runtime.Runtime":
            return runtime.Runtime()

    class Runtime:
        """Spawn-tracking runtime: every task spawned through it is
        aborted when the runtime shuts down (sim/runtime.rs:51-112)."""

        def __init__(self) -> None:
            self._handles: List[JoinHandle] = []
            self._closed = False

        def spawn(self, coro: Coroutine[Any, Any, Any],
                  name: Optional[str] = None) -> JoinHandle:
            if self._closed:
                coro.close()
                raise RuntimeError("runtime has been shut down")
            handle = spawn(coro, name=name)
            if len(self._handles) >= 64:
                self._handles = [h for h in self._handles if not h.done()]
            self._handles.append(handle)
            return handle

        def block_on(self, _coro: Any) -> Any:
            raise RuntimeError(
                "cannot block_on inside a simulation — spawn the future or "
                "await it (the reference's sim tokio Runtime::block_on is "
                "unimplemented!(), sim/runtime.rs:91-93)"
            )

        def handle(self) -> "runtime.Runtime":
            return self

        def shutdown(self) -> None:
            """Abort everything this runtime spawned (Drop impl)."""
            self._closed = True
            handles, self._handles = self._handles, []
            for h in handles:
                h.abort()

        shutdown_background = shutdown
        shutdown_timeout = lambda self, _t: self.shutdown()  # noqa: E731

        def __enter__(self) -> "runtime.Runtime":
            return self

        def __exit__(self, *_exc: Any) -> None:
            self.shutdown()

    Handle = Runtime


__all__ = [
    "JoinHandle",
    "fs",
    "interval",
    "join",
    "net",
    "runtime",
    "select",
    "signal",
    "sleep",
    "sleep_until",
    "spawn",
    "spawn_local",
    "sync",
    "task",
    "time",
    "timeout",
]
