"""Async-runtime façade — the madsim-tokio analogue.

The reference ships a tokio drop-in that re-exports the simulator's
net/time/task/signal, keeps the runtime-agnostic pieces (sync primitives,
macros), and fakes ``runtime::{Builder, Runtime, Handle}`` — ``Runtime``
collects the abort handles of everything it spawned and aborts them all on
shutdown, while ``block_on`` inside a simulation is a hard error
(madsim-tokio/src/lib.rs:38-50, sim/runtime.rs:51-112).

Users porting tokio-shaped Python code get the same shape:

    from madsim_tpu import tokio
    rt = tokio.runtime.Builder().build()
    rt.spawn(worker())          # tracked; aborted on rt.shutdown()
    await tokio.time.sleep(1.0)
    tx, rx = tokio.sync.channel(16)
"""

from __future__ import annotations

from typing import Any, Coroutine, List, Optional

# re-exports, mirroring the façade's module layout (lib.rs:38-50)
from . import fs as fs
from . import net as net
from . import signal as signal
from . import sync as sync
from . import task as task
from . import time as time
from .futures import JoinHandle, join, select
from .task import spawn, spawn_local
from .time import interval, sleep, sleep_until, timeout


class io:
    """``tokio::io`` analogue — REAL asyncio streams.

    The reference's madsim-tokio keeps real tokio ``io`` available even in
    sim mode (madsim-tokio/src/lib.rs:38-50); this namespace is the same
    stance: asyncio's stream machinery re-exported plus a ``copy`` helper.
    Under the simulator there is no asyncio loop, so any await here fails
    loudly ("no running event loop") instead of leaking nondeterminism —
    use the sim ``net``/``fs`` surfaces inside simulations.
    """

    import asyncio as _aio

    StreamReader = _aio.StreamReader
    StreamWriter = _aio.StreamWriter
    open_connection = staticmethod(_aio.open_connection)
    start_server = staticmethod(_aio.start_server)

    @staticmethod
    async def copy(reader: "io.StreamReader", writer: "io.StreamWriter",
                   chunk_size: int = 64 * 1024) -> int:
        """``tokio::io::copy``: pump reader to writer until EOF; returns
        bytes copied."""
        total = 0
        while True:
            chunk = await reader.read(chunk_size)
            if not chunk:
                break
            writer.write(chunk)
            await writer.drain()
            total += len(chunk)
        return total

    @staticmethod
    async def duplex(_max_buf_size: int = 64 * 1024):
        """``tokio::io::duplex``: an in-memory bidirectional pipe as two
        (reader, writer) ends."""
        import asyncio

        a_to_b: asyncio.Queue = asyncio.Queue()
        b_to_a: asyncio.Queue = asyncio.Queue()

        class _End:
            def __init__(self, inbox, outbox):
                self._inbox, self._outbox = inbox, outbox
                self._buf = b""
                self._eof = False

            async def read(self, n: int = -1) -> bytes:
                if not self._buf and not self._eof:
                    chunk = await self._inbox.get()
                    if chunk is None:
                        self._eof = True
                    else:
                        self._buf += chunk
                if n < 0:
                    out, self._buf = self._buf, b""
                else:
                    out, self._buf = self._buf[:n], self._buf[n:]
                return out

            def write(self, data: bytes) -> None:
                self._outbox.put_nowait(bytes(data))

            async def drain(self) -> None:
                pass

            def close(self) -> None:
                self._outbox.put_nowait(None)

        return _End(b_to_a, a_to_b), _End(a_to_b, b_to_a)


class process:
    """``tokio::process`` analogue — REAL subprocesses over asyncio.

    Mirrors ``tokio::process::Command``'s builder shape on top of
    ``asyncio.create_subprocess_exec``. Like ``tokio.io``, this is real
    I/O kept available alongside the sim (madsim-tokio/src/lib.rs:38-50);
    inside the simulator the missing asyncio loop fails any await loudly.
    """

    import asyncio as _aio

    PIPE = _aio.subprocess.PIPE
    STDOUT = _aio.subprocess.STDOUT
    DEVNULL = _aio.subprocess.DEVNULL

    class ExitStatus:
        def __init__(self, code: Optional[int]):
            self._code = code

        def success(self) -> bool:
            return self._code == 0

        def code(self) -> Optional[int]:
            return self._code

        def __repr__(self) -> str:
            return f"ExitStatus({self._code})"

    class Output:
        def __init__(self, status: "process.ExitStatus", stdout: bytes,
                     stderr: bytes):
            self.status = status
            self.stdout = stdout
            self.stderr = stderr

    class Command:
        """``tokio::process::Command``: program + args/env/cwd builder,
        then ``spawn()`` / ``output()`` / ``status()``."""

        def __init__(self, program: str):
            self._program = str(program)
            self._args: List[str] = []
            self._env: Optional[dict] = None
            self._cwd: Optional[str] = None
            self._stdin = None
            self._stdout = None
            self._stderr = None

        def arg(self, a: Any) -> "process.Command":
            self._args.append(str(a))
            return self

        def args(self, it: Any) -> "process.Command":
            self._args.extend(str(a) for a in it)
            return self

        def env(self, key: str, val: str) -> "process.Command":
            if self._env is None:
                import os

                self._env = dict(os.environ)
            self._env[str(key)] = str(val)
            return self

        def env_clear(self) -> "process.Command":
            self._env = {}
            return self

        def current_dir(self, d: str) -> "process.Command":
            self._cwd = str(d)
            return self

        def stdin(self, v: Any) -> "process.Command":
            self._stdin = v
            return self

        def stdout(self, v: Any) -> "process.Command":
            self._stdout = v
            return self

        def stderr(self, v: Any) -> "process.Command":
            self._stderr = v
            return self

        async def spawn(self):
            """Start the child; returns the asyncio subprocess (``Child``
            analogue: .stdin/.stdout/.stderr/.wait()/.kill())."""
            import asyncio

            return await asyncio.create_subprocess_exec(
                self._program,
                *self._args,
                env=self._env,
                cwd=self._cwd,
                stdin=self._stdin,
                stdout=self._stdout,
                stderr=self._stderr,
            )

        async def output(self) -> "process.Output":
            """Run to completion capturing stdout/stderr."""
            import asyncio

            child = await asyncio.create_subprocess_exec(
                self._program,
                *self._args,
                env=self._env,
                cwd=self._cwd,
                stdin=self._stdin,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
            out, err = await child.communicate()
            return process.Output(process.ExitStatus(child.returncode), out, err)

        async def status(self) -> "process.ExitStatus":
            child = await self.spawn()
            return process.ExitStatus(await child.wait())


class runtime:
    """Namespace mirroring ``tokio::runtime``."""

    class Builder:
        """Accepts-and-ignores the threading knobs (a simulation is
        single-threaded by construction), builds a tracking Runtime."""

        def __init__(self) -> None:
            pass

        @staticmethod
        def new_multi_thread() -> "runtime.Builder":
            return runtime.Builder()

        @staticmethod
        def new_current_thread() -> "runtime.Builder":
            return runtime.Builder()

        def worker_threads(self, _n: int) -> "runtime.Builder":
            return self

        def thread_name(self, _name: str) -> "runtime.Builder":
            return self

        def thread_stack_size(self, _n: int) -> "runtime.Builder":
            return self

        def enable_all(self) -> "runtime.Builder":
            return self

        def enable_time(self) -> "runtime.Builder":
            return self

        def enable_io(self) -> "runtime.Builder":
            return self

        def build(self) -> "runtime.Runtime":
            return runtime.Runtime()

    class Runtime:
        """Spawn-tracking runtime: every task spawned through it is
        aborted when the runtime shuts down (sim/runtime.rs:51-112)."""

        def __init__(self) -> None:
            self._handles: List[JoinHandle] = []
            self._closed = False

        def spawn(self, coro: Coroutine[Any, Any, Any],
                  name: Optional[str] = None) -> JoinHandle:
            if self._closed:
                coro.close()
                raise RuntimeError("runtime has been shut down")
            handle = spawn(coro, name=name)
            if len(self._handles) >= 64:
                self._handles = [h for h in self._handles if not h.done()]
            self._handles.append(handle)
            return handle

        def block_on(self, _coro: Any) -> Any:
            raise RuntimeError(
                "cannot block_on inside a simulation — spawn the future or "
                "await it (the reference's sim tokio Runtime::block_on is "
                "unimplemented!(), sim/runtime.rs:91-93)"
            )

        def handle(self) -> "runtime.Runtime":
            return self

        def shutdown(self) -> None:
            """Abort everything this runtime spawned (Drop impl)."""
            self._closed = True
            handles, self._handles = self._handles, []
            for h in handles:
                h.abort()

        shutdown_background = shutdown
        shutdown_timeout = lambda self, _t: self.shutdown()  # noqa: E731

        def __enter__(self) -> "runtime.Runtime":
            return self

        def __exit__(self, *_exc: Any) -> None:
            self.shutdown()

    Handle = Runtime


__all__ = [
    "JoinHandle",
    "fs",
    "interval",
    "io",
    "join",
    "net",
    "process",
    "runtime",
    "select",
    "signal",
    "sleep",
    "sleep_until",
    "spawn",
    "spawn_local",
    "sync",
    "task",
    "time",
    "timeout",
]
