"""Runtime metrics (ref madsim/src/sim/runtime/metrics.rs:6-40;
impl task/mod.rs:490-534)."""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict

if TYPE_CHECKING:
    from .task import Executor


class RuntimeMetrics:
    def __init__(self, executor: "Executor"):
        self._executor = executor

    def num_nodes(self) -> int:
        return len(self._executor.nodes)

    def num_tasks(self) -> int:
        return self._executor.num_tasks()

    def num_tasks_by_node(self) -> Dict[str, int]:
        return self._executor.num_tasks_by_node()

    def num_tasks_by_spawn_site(self) -> Dict[str, int]:
        return self._executor.num_tasks_by_spawn_site()
