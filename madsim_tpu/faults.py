"""Host-tier fault campaigns: the second backend of the FaultSpec compiler.

``engine/faults.py`` owns the declarative ``FaultSpec`` and THE schedule
derivation (``schedule_events``); this module compiles the same spec for
the host tier:

- ``compile_host(spec, num_nodes, seed)`` evaluates the identical
  derivation for one seed and returns the time-sorted ``(time_ns, action,
  victim)`` schedule — byte-for-byte the schedule a device sweep of that
  seed injects (asserted by ``tests/test_faults.py``).
- ``apply_schedule`` is the async supervisor task: it sleeps to each
  event's virtual time and applies it through the live simulation's
  public APIs — ``Handle.kill/restart/pause/resume`` for crash/restart/
  pause events (ref runtime/mod.rs:272-303), the ``NetSim`` fault
  surface (directional ``clog_node_in/out``, latency/loss config) for
  partition and burst events (ref net/mod.rs:163-284), the ``FsSim``
  durability surface (``stall_fsync``/``unstall_fsync``/``power_fail``)
  for the slow-disk and power-fail gray failures, and the per-node
  clock-skew registry on ``TimeHandle`` for skew windows.
- ``run_campaign`` composes the two: one call drives a whole campaign
  against a list of nodes.

Semantics mirror the device interpreter exactly: crash/restart and
pause/resume are edge-gated (restarting a live node is a no-op, as in
``models/raft._on_fault``), partitions are refcounted per victim, and
latency/loss bursts are refcounted with base values restored from the
config present when the supervisor started.

This is the replay bridge's other half: a violation seed found by a TPU
sweep replays its *fault environment* on the host either from the spec
directly (``compile_host``) or from a traced schedule
(``replay.extract_fault_schedule``) — the two agree by construction.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional, Sequence, Tuple

if TYPE_CHECKING:  # the engine (and thus JAX) is only a runtime
    from .engine.faults import FaultSpec  # dependency of compile_host —
    # this module stays importable on the jax-free host tier (forked-procs
    # children poison jax deliberately; builder._poison_jax_in_child)

#: one schedule entry: (virtual time ns, action name, victim node index)
FaultEvent = Tuple[int, str, int]


def compile_host(spec: FaultSpec, num_nodes: int, seed: int) -> List[FaultEvent]:
    """Compile the campaign for one seed into a time-sorted schedule.

    Runs the shared derivation (tiny — a few dozen integer draws) on the
    current JAX backend; the result is integer-only and therefore
    identical to what the device tier injects for the same ``(spec,
    seed)``. A literal ``engine.faults.FixedFaults`` schedule (e.g. a
    shrunk one from ``explore/shrink.py``) compiles seed-independently —
    its events come back verbatim, time-sorted."""
    import jax.numpy as jnp
    import numpy as np

    from .engine.faults import ACTION_NAMES, schedule_events
    from .engine.rng import seed_key

    times, actions, victims = schedule_events(
        spec, num_nodes, seed_key(jnp.int64(seed))
    )
    events = [
        (int(t), ACTION_NAMES[int(a)], int(v))
        for t, a, v in zip(
            np.asarray(times), np.asarray(actions), np.asarray(victims)
        )
    ]
    return sorted(events)


async def apply_schedule(
    schedule: Sequence[FaultEvent],
    nodes: Sequence,
    spec: Optional[FaultSpec] = None,
    handle=None,
    net=None,
) -> None:
    """Apply a compiled schedule to live ``nodes`` at its virtual times.

    ``nodes[victim]`` maps schedule victims to node handles (any
    ``NodeRef``). ``spec`` is only required when the schedule contains
    latency-spike, loss-burst or clock-skew events (it carries the
    override values; ``FixedFaults`` carries them too). Must run inside
    a simulation (a supervisor task, like the manual kill/clog loops it
    replaces)."""
    from .context import current_handle
    from .fs import FsSim
    from .net import NetSim
    from .runtime import _node_id
    from .time import elapsed, sleep

    h = handle if handle is not None else current_handle()
    ns = net if net is not None else h.simulator(NetSim)

    dead = [False] * len(nodes)
    paused = [False] * len(nodes)
    # per-direction partition refcounts (mirrors FaultState.part_in_cnt /
    # part_out_cnt): a symmetric partition holds both directions, an
    # asymmetric window one — a heal never un-clogs a direction an
    # overlapping asymmetric window still holds, and vice versa
    part_in_cnt = [0] * len(nodes)
    part_out_cnt = [0] * len(nodes)
    fsync_cnt = [0] * len(nodes)
    skew_cnt = [0] * len(nodes)
    spike_cnt = 0
    loss_cnt = 0
    base_latency = ns.config.net.send_latency
    base_loss = ns.config.net.packet_loss_rate

    def _clog_dir(victim: int, cnt, clog, unclog, delta: int) -> None:
        """Refcounted one-direction clog: apply on 0->1, restore on 1->0."""
        nid = _node_id(nodes[victim])
        if delta > 0:
            if cnt[victim] == 0:
                clog(nid)
            cnt[victim] += 1
        else:
            if cnt[victim] == 1:
                unclog(nid)
            cnt[victim] = max(cnt[victim] - 1, 0)

    def _set_net(latency=None, loss=None):
        # NetSim and its Network normally share one Config object; write
        # through both in case a caller swapped one via update_config
        for cfg in (ns.config, ns.network.config):
            if latency is not None:
                cfg.net.send_latency = latency
            if loss is not None:
                cfg.net.packet_loss_rate = loss

    def _needs_spec() -> FaultSpec:
        if spec is None:
            raise ValueError(
                "schedule contains latency/loss burst events; pass the "
                "FaultSpec so the supervisor knows the override values"
            )
        return spec

    for t_ns, action, victim in schedule:
        dt = t_ns / 1e9 - elapsed()
        if dt > 0:
            await sleep(dt)
        if action in ("crash", "power_fail"):
            # both flavors drop unsynced storage: Handle.kill resets every
            # simulator (FsSim.reset_node == power_fail); the power_fail
            # action drives the fs machinery explicitly as well, so the
            # storage edge fires even under a custom fs configuration
            if not dead[victim]:
                if action == "power_fail":
                    h.simulator(FsSim).power_fail(_node_id(nodes[victim]))
                h.kill(nodes[victim])
                dead[victim] = True
                paused[victim] = False
        elif action == "restart":
            if dead[victim]:
                h.restart(nodes[victim])
                dead[victim] = False
        elif action == "partition":
            _clog_dir(victim, part_in_cnt, ns.clog_node_in, ns.unclog_node_in, +1)
            _clog_dir(victim, part_out_cnt, ns.clog_node_out, ns.unclog_node_out, +1)
        elif action == "heal":
            _clog_dir(victim, part_in_cnt, ns.clog_node_in, ns.unclog_node_in, -1)
            _clog_dir(victim, part_out_cnt, ns.clog_node_out, ns.unclog_node_out, -1)
        elif action == "part_in":
            _clog_dir(victim, part_in_cnt, ns.clog_node_in, ns.unclog_node_in, +1)
        elif action == "heal_in":
            _clog_dir(victim, part_in_cnt, ns.clog_node_in, ns.unclog_node_in, -1)
        elif action == "part_out":
            _clog_dir(victim, part_out_cnt, ns.clog_node_out, ns.unclog_node_out, +1)
        elif action == "heal_out":
            _clog_dir(victim, part_out_cnt, ns.clog_node_out, ns.unclog_node_out, -1)
        elif action == "fsync_stall":
            if fsync_cnt[victim] == 0:
                h.simulator(FsSim).stall_fsync(_node_id(nodes[victim]))
            fsync_cnt[victim] += 1
        elif action == "fsync_ok":
            if fsync_cnt[victim] == 1:
                h.simulator(FsSim).unstall_fsync(_node_id(nodes[victim]))
            fsync_cnt[victim] = max(fsync_cnt[victim] - 1, 0)
        elif action == "skew_on":
            s = _needs_spec()
            if skew_cnt[victim] == 0:
                h.time.set_node_skew(
                    _node_id(nodes[victim]), s.skew_num, s.skew_den
                )
            skew_cnt[victim] += 1
        elif action == "skew_off":
            if skew_cnt[victim] == 1:
                h.time.clear_node_skew(_node_id(nodes[victim]))
            skew_cnt[victim] = max(skew_cnt[victim] - 1, 0)
        elif action == "spike_on":
            spike_cnt += 1
            if spike_cnt == 1:
                s = _needs_spec()
                _set_net(
                    latency=(s.spike_lat_lo_ns / 1e9, s.spike_lat_hi_ns / 1e9)
                )
        elif action == "spike_off":
            if spike_cnt == 1:
                _set_net(latency=base_latency)
            spike_cnt = max(spike_cnt - 1, 0)
        elif action == "loss_on":
            loss_cnt += 1
            if loss_cnt == 1:
                s = _needs_spec()
                _set_net(loss=s.burst_loss_q32 / 2**32)
        elif action == "loss_off":
            if loss_cnt == 1:
                _set_net(loss=base_loss)
            loss_cnt = max(loss_cnt - 1, 0)
        elif action == "pause":
            if not dead[victim] and not paused[victim]:
                h.pause(nodes[victim])
                paused[victim] = True
        elif action == "resume":
            if not dead[victim] and paused[victim]:
                h.resume(nodes[victim])
                paused[victim] = False
        else:
            raise ValueError(f"unknown fault action {action!r}")


async def run_campaign(
    spec: FaultSpec,
    nodes: Sequence,
    seed: Optional[int] = None,
    handle=None,
    net=None,
) -> List[FaultEvent]:
    """Compile the campaign for ``seed`` (default: the running sim's own
    seed) and apply it to ``nodes``; returns the applied schedule."""
    from .context import current_handle

    h = handle if handle is not None else current_handle()
    schedule = compile_host(spec, len(nodes), h.seed if seed is None else seed)
    await apply_schedule(schedule, nodes, spec=spec, handle=h, net=net)
    return schedule
