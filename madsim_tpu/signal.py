"""Simulated ctrl-c / signal handling (ref madsim/src/sim/signal.rs:4-9 and
task/mod.rs:106-111,166-175,419-434).

The first ``await ctrl_c()`` on a node installs a handler; from then on
``Handle.send_ctrl_c(node)`` resolves the pending waiters instead of killing
the node.
"""

from __future__ import annotations

from .context import current_node
from .futures import Future


async def ctrl_c() -> None:
    """Wait for a simulated ctrl-c on the current node."""
    node = current_node()
    node.ctrl_c_installed = True
    fut: Future = Future()
    node.ctrl_c_waiters.append(fut)
    await fut
