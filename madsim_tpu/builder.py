"""Seed-sweep test driver (ref madsim/src/sim/runtime/builder.rs:7-162).

Reads ``MADSIM_TEST_{SEED,NUM,JOBS,PROCS,CONFIG,TIME_LIMIT,
CHECK_DETERMINISM}`` and ``MADSIM_ALLOW_SYSTEM_THREAD`` from the
environment, runs ``count`` seeds (seed, seed+1, ...) with ``jobs``
concurrent OS threads (one fresh thread per seed, like the reference's
``std::thread::spawn`` + ``buffer_unordered``) or — for CPU-bound sweeps
that Python threads would GIL-serialize — ``procs`` forked worker
processes, and on failure prints the reproducing ``MADSIM_TEST_SEED``
(ref runtime/mod.rs:205-210).

The ``@sim_test`` decorator is the analogue of ``#[madsim::test]``
(madsim-macros/src/lib.rs:88-152): it rewrites an async test into a sync
function that drives ``Builder.from_env().run(...)`` — directly collectable
by pytest.
"""

from __future__ import annotations

import functools
import os
import sys
import threading
from typing import Any, Callable, Coroutine, List, Optional

from .config import Config
from .runtime import Runtime

AsyncFn = Callable[..., Coroutine[Any, Any, Any]]


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0", "false", "False")


class ProcsDeviceTierError(RuntimeError):
    """A ``procs``-sweep child attempted to use JAX / the device tier.

    Children are forked from a parent where JAX may already hold
    threads and device handles; using JAX in a forked child hangs or
    crashes rather than failing cleanly. Device-tier seed parallelism is
    ``engine.run_sweep`` (seeds as array lanes), not OS processes.
    """

    def __init__(self, what: str = "jax"):
        super().__init__(
            f"device-tier workload under Builder(procs=N): {what} is not "
            f"usable in a forked sweep child (JAX state does not survive "
            f"fork). Use procs for HOST-tier workloads only; for parallel "
            f"device seeds use madsim_tpu.engine.run_sweep, which batches "
            f"seeds as array lanes on one process."
        )


def _poison_jax_in_child() -> None:
    """Make any jax use inside a forked procs child raise the named error
    instead of hanging: every already-imported ``jax*`` module is replaced
    in sys.modules by a stub whose attribute access raises (a sys.modules
    hit precedes the finders), and a meta-path finder refuses FRESH
    ``import jax`` too — a child whose parent never imported jax would
    otherwise initialize the real backend N times concurrently and hang
    or segfault rather than raise."""
    import importlib.abc
    import types

    class _Poisoned(types.ModuleType):
        def __getattr__(self, name):  # noqa: D105
            if name.startswith("__"):  # repr/spec introspection stays safe
                raise AttributeError(name)
            raise ProcsDeviceTierError(f"{self.__name__}.{name}")

    for name in [n for n in sys.modules if n == "jax" or n.startswith("jax.")]:
        sys.modules[name] = _Poisoned(name)

    class _JaxImportBlocker(importlib.abc.MetaPathFinder):
        def find_spec(self, fullname, path=None, target=None):
            if fullname == "jax" or fullname.startswith("jax."):
                raise ProcsDeviceTierError(f"import {fullname}")
            return None

    sys.meta_path.insert(0, _JaxImportBlocker())


class Builder:
    """Configurable multi-seed test runner (ref ``Builder``, builder.rs)."""

    def __init__(
        self,
        seed: Optional[int] = None,
        count: int = 1,
        jobs: int = 1,
        procs: int = 1,
        config: Optional[Config] = None,
        time_limit: Optional[float] = None,
        check_determinism: bool = False,
        allow_system_thread: bool = False,
    ):
        if seed is None:
            import time as _walltime

            seed = _walltime.time_ns()  # new schedule per run (builder.rs:64-73)
        self.seed = seed
        self.count = count
        self.jobs = jobs
        self.procs = procs
        self.config = config
        self.time_limit = time_limit
        self.check_determinism = check_determinism
        self.allow_system_thread = allow_system_thread

    @classmethod
    def from_env(cls, **overrides: Any) -> "Builder":
        """ref builder.rs:63-117."""
        cfg: Optional[Config] = None
        cfg_path = os.environ.get("MADSIM_TEST_CONFIG")
        if cfg_path:
            with open(cfg_path, "r") as f:
                cfg = Config.from_toml(f.read())
        kwargs: dict = dict(
            seed=_env_int("MADSIM_TEST_SEED"),
            count=_env_int("MADSIM_TEST_NUM") or 1,
            jobs=_env_int("MADSIM_TEST_JOBS") or 1,
            procs=_env_int("MADSIM_TEST_PROCS") or 1,
            config=cfg,
            time_limit=(
                float(os.environ["MADSIM_TEST_TIME_LIMIT"])
                if os.environ.get("MADSIM_TEST_TIME_LIMIT")
                else None
            ),
            check_determinism=_env_flag("MADSIM_TEST_CHECK_DETERMINISM"),
            allow_system_thread=_env_flag("MADSIM_ALLOW_SYSTEM_THREAD"),
        )
        for k, v in overrides.items():
            if v is not None:
                kwargs[k] = v
        return cls(**kwargs)

    def _run_one(self, seed: int, test_fn: Callable[[], Coroutine]) -> Any:
        if self.check_determinism:
            return Runtime.check_determinism(seed, test_fn, config=self.config)
        rt = Runtime(seed=seed, config=self.config)
        if self.time_limit is not None:
            rt.set_time_limit(self.time_limit)
        rt.set_allow_system_thread(self.allow_system_thread)
        return rt.block_on(test_fn())

    def run(self, test_fn: Callable[[], Coroutine]) -> Any:
        """Run the async test over ``count`` seeds (ref builder.rs:120-161)."""
        seeds = list(range(self.seed, self.seed + self.count))
        if self.procs > 1 and self.count > 1:
            return self._run_procs(seeds, test_fn)
        if self.jobs <= 1 or self.count == 1:
            last = None
            for seed in seeds:
                try:
                    last = self._run_one(seed, test_fn)
                except BaseException:
                    _print_repro(seed)
                    raise
            return last

        failures: List[tuple] = []
        results: dict = {}
        lock = threading.Lock()
        sem = threading.Semaphore(self.jobs)

        def worker(seed: int) -> None:
            try:
                r = self._run_one(seed, test_fn)
                with lock:
                    results[seed] = r
            except BaseException as e:  # noqa: BLE001
                with lock:
                    failures.append((seed, e))
            finally:
                sem.release()

        threads = []
        for seed in seeds:
            sem.acquire()
            if failures:
                sem.release()
                break
            t = threading.Thread(target=worker, args=(seed,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if failures:
            failures.sort(key=lambda f: f[0])
            seed, exc = failures[0]
            _print_repro(seed)
            raise exc
        # match the sequential path: the last seed's result
        return results[max(results)] if results else None


    def _run_procs(self, seeds: List[int], test_fn) -> Any:
        """Fork-based parallel sweep: ``procs`` OS processes, each running
        an interleaved shard of the seed range sequentially.

        The reference's sweep parallelism is real OS threads
        (builder.rs:120-161 buffer_unordered); Python threads serialize on
        the GIL for this CPU-bound work, so the multi-core path uses
        processes instead. Per-seed isolation is total (each child builds
        fresh Runtimes), so schedules are identical to the sequential
        sweep. Fork start method: the test function is inherited, never
        pickled; results cross back over a queue (unpicklable results
        degrade to None; the sequential path is unaffected).

        Constraint: procs-mode workloads must stay HOST-tier. Children are
        forked from a possibly multithreaded parent (JAX spawns threads at
        import), and JAX is never safe to use in a forked child — the
        device-tier path for parallel seeds is ``engine.run_sweep``, which
        batches seeds as array lanes instead of processes.
        """
        import multiprocessing as mp
        import pickle as _pickle
        import queue as _queue
        import traceback as _tb

        ctx = mp.get_context("fork")
        q = ctx.Queue()
        stop = ctx.Event()  # cooperative fail-fast (never terminate():
        # killing a child mid-Queue.put corrupts the queue's pipe frame
        # and hangs every later get())

        import io
        import os as _os

        def emit(buf: io.StringIO) -> None:
            # one os.write per seed: atomic on a pipe for payloads up to
            # PIPE_BUF (4 KiB on Linux — larger seed outputs may interleave
            # with other children, but are never LOST: the loop finishes
            # partial writes), vs Python's two-write print which garbles a
            # shared fd even for short lines
            data = memoryview(buf.getvalue().encode())
            try:
                fd = sys.stdout.fileno()
            except (OSError, ValueError):  # captured stdout (pytest)
                sys.stdout.write(buf.getvalue())
                sys.stdout.flush()
                return
            while data:
                try:
                    n = _os.write(fd, data)
                except OSError:
                    # e.g. non-blocking fd: push only the REMAINING bytes
                    # through the buffered layer (re-writing the whole
                    # buffer would duplicate what already reached the fd)
                    rest = bytes(data)
                    stream = getattr(sys.stdout, "buffer", None)
                    if stream is not None:
                        stream.write(rest)
                        stream.flush()
                    else:
                        sys.stdout.write(rest.decode(errors="replace"))
                        sys.stdout.flush()
                    return
                data = data[n:]

        def child(shard: List[int]) -> None:
            # structural fork-safety: device-tier use fails fast by name
            # instead of hanging in inherited JAX state. The sentinel is
            # pid-scoped so it only flags THIS forked process — an exec'd
            # descendant (fresh interpreter, no inherited JAX state) may
            # use the engine legitimately
            os.environ["MADSIM_IN_PROCS_CHILD"] = str(os.getpid())
            _poison_jax_in_child()
            try:
                for s in shard:
                    if stop.is_set():
                        return  # another shard failed; stop between seeds
                    buf = io.StringIO()
                    prev_out = sys.stdout
                    sys.stdout = buf  # group this seed's prints
                    try:
                        r = self._run_one(s, test_fn)
                    except BaseException:  # noqa: BLE001 - reported to parent
                        sys.stdout = prev_out
                        emit(buf)
                        q.put(("err", s, _tb.format_exc()))
                        return
                    sys.stdout = prev_out
                    emit(buf)
                    # pickle HERE, once: Queue.put pickles lazily in a
                    # feeder thread, so a put-side try/except never fires —
                    # the result would be silently dropped instead of
                    # degrading to None. Shipping the bytes avoids
                    # double-serializing every result.
                    try:
                        blob = _pickle.dumps(r)
                    except Exception:
                        blob = None
                    q.put(("ok", s, blob))
            finally:
                q.put(("done", shard[0], None))

        n = min(self.procs, len(seeds))
        shards = [seeds[i::n] for i in range(n)]
        procs = [ctx.Process(target=child, args=(sh,), daemon=True) for sh in shards]
        for p in procs:
            p.start()
        # drain WHILE children run — joining first deadlocks once queued
        # results exceed the pipe capacity (children block in q.put); the
        # sentinel counts children that finished, and a liveness check
        # covers children killed without one (segfault/OOM)
        results: dict = {}
        failures: List[tuple] = []
        done = 0
        while done < n:
            try:
                kind, s, payload = q.get(timeout=0.5)
            except _queue.Empty:
                if not any(p.is_alive() for p in procs):
                    break  # crashed child(s); nothing more is coming
                continue
            if kind == "ok":
                results[s] = None if payload is None else _pickle.loads(payload)
            elif kind == "err":
                failures.append((s, payload))
                # fail fast like the jobs path: stop COOPERATIVELY (the
                # other shards finish their in-flight seed, then exit —
                # so an also-failing lower seed still reports and wins
                # the repro print, and the queue stays intact)
                stop.set()
            else:
                done += 1
        for p in procs:
            p.join()
        if not failures:
            # a worker died without reporting (segfault/OOM): attribute
            # the death to the first seed its shard never reported — the
            # one it was running
            reported = set(results)
            for p, shard in zip(procs, shards):
                if p.exitcode not in (0, None):
                    unreported = [s for s in shard if s not in reported]
                    culprit = unreported[0] if unreported else shard[0]
                    failures.append(
                        (culprit,
                         f"worker running shard {shard} died with exit code "
                         f"{p.exitcode} around seed {culprit} (no traceback "
                         f"crossed the process boundary)")
                    )
        if failures:
            failures.sort(key=lambda f: f[0])
            s, tb_text = failures[0]
            _print_repro(s)
            raise SimSweepError(
                f"seed {s} failed in a sweep worker process:\n{tb_text}"
            )
        return results[max(results)] if results else None


class SimSweepError(RuntimeError):
    """A seed failed inside a process-sweep worker; carries the child's
    formatted traceback (the original exception object lives in the child
    — rerun with the printed MADSIM_TEST_SEED to debug it in-process)."""


def _print_repro(seed: int) -> None:
    print(
        f"note: run with `MADSIM_TEST_SEED={seed}` environment variable "
        f"to reproduce this failure",
        file=sys.stderr,
    )
    if sys.flags.hash_randomization and os.environ.get("PYTHONHASHSEED") in (
        None, "", "random",
    ):
        # the reference interposes HashMap seeding (sim/rand.rs:176-184);
        # Python's str-hash salt is fixed at interpreter start and cannot
        # be interposed, so iteration order of str-keyed sets/dicts-from-
        # sets can differ in a NEW process. Tell the user how to pin it.
        print(
            "note: PYTHONHASHSEED is unset — if the failure does not "
            "reproduce and the workload iterates str-keyed sets, also pin "
            "`PYTHONHASHSEED=0` (Python's hash salt is per-process and "
            "outside the simulator's control)",
            file=sys.stderr,
        )


def sim_test(
    fn: Optional[AsyncFn] = None,
    *,
    seed: Optional[int] = None,
    count: Optional[int] = None,
    jobs: Optional[int] = None,
    procs: Optional[int] = None,
    config: Optional[Config] = None,
    time_limit: Optional[float] = None,
    check_determinism: Optional[bool] = None,
    allow_system_thread: Optional[bool] = None,
) -> Any:
    """``#[madsim::test]`` analogue — decorate an async test function.

    Environment variables still win for seed/count/jobs unless explicitly
    overridden, so a failing seed printed by a CI run can be replayed with
    ``MADSIM_TEST_SEED=... pytest ...``.
    """

    def deco(f: AsyncFn) -> Callable[..., Any]:
        @functools.wraps(f)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            env_seed = _env_int("MADSIM_TEST_SEED")
            b = Builder.from_env(
                seed=env_seed if env_seed is not None else seed,
                count=count,
                jobs=jobs,
                procs=procs,
                config=config,
                time_limit=time_limit,
                check_determinism=check_determinism,
                allow_system_thread=allow_system_thread,
            )
            return b.run(lambda: f(*args, **kwargs))

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco


def main(fn: AsyncFn) -> Callable[..., Any]:
    """``#[madsim::main]`` analogue."""

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        return Builder.from_env().run(lambda: fn(*args, **kwargs))

    return wrapper
