"""Sweep checkpoint/resume: the engine state is arrays, so snapshots are
free.

The reference has no core snapshotting — only the etcd sim's dump/load
(SURVEY.md §5 "checkpoint/resume"). The SoA engine generalizes the
pattern: a whole in-flight seed batch (clocks, queues, RNG counters,
workload actor state) round-trips through one ``.npz`` file, and
``resume_sweep`` continues stepping it — enabling long sweeps to survive
preemption and failed seeds to be re-examined from mid-run state.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import EngineConfig, EngineState, Workload

# v2: EngineState gained qmax; draw layout adds tie-break.
# v3: packed queue layout — the redundant bool valid[Q] plane left the
#     EventQueue, so v2 files would load positionally misaligned.
# v4: EngineState gained the per-seed coverage bitmap (``cover``), so v3
#     files would load positionally misaligned.
# v5: EngineState gained the operation-history plane (``hist_rec``,
#     ``hist_t``, ``hist_len``, ``hist_overflow`` — madsim_tpu/oracle),
#     so v4 files would load positionally misaligned.
# v6: gray-failure grammar — ``FaultState`` split ``part_cnt`` into
#     per-direction refcounts and gained ``fsync_cnt``/``skew_cnt``, and
#     the raft model grew its durability shadows, so v5 files would load
#     positionally misaligned.
# v7: pipelined checked sweeps — a snapshot may carry ``__inflight__``
#     chunk metadata (which chunk of a pipelined sweep the state belongs
#     to, plus host-phase progress), so interrupt/resume of an
#     overlapped sweep+check pipeline stays bit-identical. v6 readers
#     would silently drop it and resume the state as a whole-sweep
#     snapshot, double-counting completed chunks — which is why v6
#     REJECTS v7, while this reader still ACCEPTS v6 files (the leaf
#     layout is unchanged; an old snapshot simply has no inflight tag).
# v8: mesh-sharded pipelined sweeps — a snapshot may carry
#     ``__mesh_layout__`` (device count + per-device chunk of the
#     sharded driver, ``parallel.mesh.mesh_layout``), so a sweep
#     interrupted on an 8-device mesh resumes on ANY device count with
#     the same GLOBAL chunk boundaries (``chunk_size`` rides in the
#     metadata; the state arrays themselves are layout-free host data).
#     v7 readers would drop the layout and could resume with mismatched
#     chunk granules — their per-chunk files silently never matching —
#     hence the bump; this reader still ACCEPTS v6/v7 files (the leaf
#     layout is unchanged; an old snapshot simply has no mesh tag).
# v9: streaming sweeps (engine/stream.py) — a snapshot may carry a
#     heterogeneous IN-FLIGHT LANE POOL: ``__stream__`` bookkeeping
#     (which work item each lane runs, per-lane step budgets, the queue
#     cursor, merged totals so far) plus stacked ``pend_*`` arrays of
#     captured-but-unflushed per-item results. v8 readers would load the
#     pool as a plain whole-sweep snapshot and silently drop the pending
#     results and queue position — hence the bump; this reader still
#     ACCEPTS v6-v8 files (the leaf layout is unchanged; an old snapshot
#     simply has no stream tag).
# v10: opt-in device-side EVENT-MIX plane (madsim_tpu/obs) — EngineState
#     gained ``evmix`` as its LAST field, so every pre-v10 leaf index is
#     unchanged and this reader still ACCEPTS v6-v9 files whenever the
#     resuming workload leaves the plane disabled (width 0: the missing
#     trailing leaf is substituted from ``like``). A v6-v9 snapshot
#     CANNOT resume an event-mix-ENABLED sweep — the counters for the
#     already-run steps were never recorded — and the reader rejects
#     that combination instead of silently zero-filling.
_FORMAT_VERSION = 10
_READABLE_VERSIONS = (6, 7, 8, 9, 10)


def _restore_leaf(data, i: int, leaf, path: str):
    """One positional leaf of a snapshot, honoring the v10 compat rule:
    a missing trailing leaf is legal ONLY when the resuming structure
    expects a width-0 plane there (``leaf.size == 0``) — then ``like``'s
    own empty leaf stands in for it."""
    if f"leaf_{i}__key" in data:
        return jax.random.wrap_key_data(jnp.asarray(data[f"leaf_{i}__key"]))
    if f"leaf_{i}" in data:
        return jnp.asarray(data[f"leaf_{i}"], dtype=leaf.dtype)
    if leaf.size == 0:
        return jnp.asarray(leaf)
    raise ValueError(
        f"{path} has no leaf_{i} but the resuming state expects a "
        f"non-empty array there (shape {leaf.shape}) — a pre-v10 "
        "snapshot cannot resume an event-mix-enabled sweep "
        "(engine/core.py event_mix_kinds); re-run from scratch"
    )


def save_sweep(
    state: EngineState,
    path: str,
    inflight: Optional[dict] = None,
    mesh_layout: Optional[dict] = None,
) -> None:
    """Serialize a batched EngineState to ``path`` (.npz).

    ``inflight`` (JSON-able dict, format v7) tags the snapshot as the
    IN-FLIGHT CHUNK of a pipelined sweep — at least ``{"lo": <chunk
    start index>, "k": <real lanes>}`` — so ``run_sweep_pipelined``
    can resume mid-chunk (``resume_from``) instead of restarting the
    chunk; read it back with ``load_inflight``. ``mesh_layout``
    (JSON-able dict, format v8 — ``parallel.mesh.mesh_layout``) records
    the sharded driver's device count and chunk sizing so a different-
    sized mesh resumes with identical global chunk boundaries; read it
    back with ``load_mesh_layout``."""
    import json

    leaves, treedef = jax.tree.flatten(state)
    arrays = {}
    for i, leaf in enumerate(leaves):
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            # typed PRNG keys serialize as their raw uint32 words
            arrays[f"leaf_{i}__key"] = np.asarray(jax.random.key_data(leaf))
        else:
            arrays[f"leaf_{i}"] = np.asarray(leaf)
    for name, meta in (
        ("__inflight__", inflight), ("__mesh_layout__", mesh_layout)
    ):
        if meta is not None:
            arrays[name] = np.frombuffer(
                json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8
            )
    np.savez_compressed(path, __version__=_FORMAT_VERSION, **arrays)


def _load_meta(path: str, name: str) -> Optional[dict]:
    import json

    data = np.load(path)
    if name not in data:
        return None
    return json.loads(bytes(bytearray(data[name])).decode())


def load_inflight(path: str) -> Optional[dict]:
    """The ``inflight`` chunk metadata of a v7+ snapshot, or None."""
    return _load_meta(path, "__inflight__")


def load_mesh_layout(path: str) -> Optional[dict]:
    """The mesh-layout metadata of a v8 snapshot, or None (an unsharded
    or pre-v8 snapshot). Resuming callers that honor
    ``layout["chunk_size"]`` keep per-chunk checkpoint files aligned
    across device counts."""
    return _load_meta(path, "__mesh_layout__")


def load_sweep(path: str, like: EngineState) -> EngineState:
    """Restore a checkpoint; ``like`` supplies the pytree structure (build
    it with ``init_sweep`` on any seed vector of the same shape/config)."""
    data = np.load(path)
    found = int(data["__version__"])
    if found not in _READABLE_VERSIONS:
        raise ValueError(
            f"checkpoint format version mismatch: {path} is v{found}, "
            f"this engine reads v{_READABLE_VERSIONS} (the draw layout / "
            "state schema changed between versions; re-run the sweep to "
            "produce a fresh checkpoint)"
        )
    leaves, treedef = jax.tree.flatten(like)
    out = [
        _restore_leaf(data, i, leaf, path) for i, leaf in enumerate(leaves)
    ]
    return jax.tree.unflatten(treedef, out)


def save_stream(
    path: str,
    state: EngineState,
    *,
    pending: dict,
    susp: dict,
    meta: dict,
) -> None:
    """Serialize a STREAMING sweep's full in-flight picture (checkpoint
    format v9; ``engine/stream.stream_sweep`` is the only writer):

    - the lane-pool ``EngineState`` (heterogeneous — each lane may run a
      different work item, candidate and step budget), leaf-encoded like
      ``save_sweep``;
    - ``pending``: item index -> captured row leaves (raw host arrays,
      key leaves as uint32 words — the stream's own row format) for
      results retired but not yet flushed into a virtual chunk; stored
      stacked per leaf (``pend_{j}``), item order in the meta;
    - ``susp``: item index -> device-screen suspect bit (absent when the
      stream runs unscreened);
    - ``meta``: JSON-able stream bookkeeping (stream.py owns the keys:
      lane->item map, budgets, queue cursor, flush cursor, merged totals,
      identity guards)."""
    import json

    leaves, _ = jax.tree.flatten(state)
    arrays = {}
    for i, leaf in enumerate(leaves):
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            arrays[f"leaf_{i}__key"] = np.asarray(jax.random.key_data(leaf))
        else:
            arrays[f"leaf_{i}"] = np.asarray(leaf)
    items = sorted(int(i) for i in pending)
    if items:
        for j in range(len(leaves)):
            arrays[f"pend_{j}"] = np.stack([pending[it][j] for it in items])
    stream_meta = dict(meta)
    stream_meta["items"] = items
    stream_meta["susp"] = [
        (None if it not in susp else bool(susp[it])) for it in items
    ]
    arrays["__stream__"] = np.frombuffer(
        json.dumps(stream_meta, sort_keys=True).encode(), dtype=np.uint8
    )
    np.savez_compressed(path, __version__=_FORMAT_VERSION, **arrays)


def load_stream(path: str, like: EngineState):
    """Restore a v9 stream snapshot: ``(pool state, pending rows dict,
    suspect-bit dict, stream meta)``. ``like`` supplies the pytree
    structure and dtypes — an ``init_sweep`` result of the same pool
    shape, or its ``jax.eval_shape`` (no device work needed)."""
    import json

    data = np.load(path)
    found = int(data["__version__"])
    if found not in _READABLE_VERSIONS or "__stream__" not in data:
        raise ValueError(
            f"{path} is not a readable stream snapshot (v{found}"
            f"{', no __stream__ tag' if '__stream__' not in data else ''}); "
            "stream snapshots are checkpoint format v9 "
            "(engine/stream.stream_sweep ckpt_path=)"
        )
    leaves, treedef = jax.tree.flatten(like)
    out = [
        _restore_leaf(data, i, leaf, path) for i, leaf in enumerate(leaves)
    ]
    state = jax.tree.unflatten(treedef, out)
    meta = json.loads(bytes(bytearray(data["__stream__"])).decode())
    pending = {}
    susp = {}
    for idx, it in enumerate(meta["items"]):
        # pre-v10 stream snapshots have no pend_{j} for the trailing
        # evmix leaf; a width-0 plane row is an empty array of the
        # like-leaf's per-lane shape (the only legal missing case —
        # _restore_leaf already rejected non-empty gaps above)
        pending[int(it)] = [
            (
                data[f"pend_{j}"][idx]
                if f"pend_{j}" in data
                else np.zeros(out[j].shape[1:], np.asarray(out[j]).dtype)
            )
            for j in range(len(leaves))
        ]
        bit = meta["susp"][idx]
        if bit is not None:
            susp[int(it)] = bool(bit)
    return state, pending, susp, meta


def resume_sweep(
    workload: Workload, cfg: EngineConfig, state: EngineState
) -> EngineState:
    """Continue a (possibly restored) sweep until every seed finishes."""
    from .core import _drive

    return _drive(workload, cfg, state)  # shares run_sweep's trace cache


def _chunk_sha(seeds_host: np.ndarray, lo: int, k: int) -> str:
    """Identity of one chunk's full seed slice — endpoints alone can
    collide across different seed vectors ([0,5,9] vs [0,7,9])."""
    import hashlib

    return hashlib.sha256(
        np.ascontiguousarray(seeds_host[lo : lo + k]).tobytes()
    ).hexdigest()


def _load_chunk_summary(
    path: str, first: int, last: int, sha: str, fp: str
) -> dict:
    """Validate a per-chunk checkpoint file against this sweep's
    identity and return its summary — shared by both chunk drivers so
    the guard protocol cannot fork between them. Records from before
    the sha was added lack the key; their endpoint+fingerprint check
    still applies (legacy-compatible)."""
    import json

    with open(path) as f:
        rec = json.load(f)
    if (
        rec["first_seed"] != first
        or rec["last_seed"] != last
        or rec.get("seeds_sha256", sha) != sha
        or rec.get("fingerprint") != fp
    ):
        raise ValueError(
            f"checkpoint {path} is from a different sweep: holds "
            f"seeds [{rec['first_seed']}, {rec['last_seed']}] "
            f"(sha {rec.get('seeds_sha256')!r}) with "
            f"fingerprint {rec.get('fingerprint')!r}, expected "
            f"[{first}, {last}] (sha {sha!r}) with {fp!r}"
        )
    return rec["summary"]


def _write_chunk_summary(
    path: str, first: int, last: int, sha: str, fp: str, summary: dict
) -> None:
    """Atomically write one chunk's checkpoint record (tmp + rename: a
    crash never leaves half a file) — shared by both chunk drivers."""
    import json
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "first_seed": first,
                "last_seed": last,
                "seeds_sha256": sha,
                "fingerprint": fp,
                "summary": summary,
            },
            f,
            sort_keys=True,
        )
    os.replace(tmp, path)


def params_digest(params) -> str:
    """Candidate identity of a per-lane spec-as-data pytree: a sha256
    over every leaf's bytes. Appended to ``_sweep_fingerprint`` so chunk
    checkpoints written for one candidate can never silently merge into
    another candidate's sweep (the envelope alone is shared by ALL
    candidates — that sharing is the point of the spec-as-data path, so
    the data itself must join the identity)."""
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(params):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def run_sweep_chunked_resumable(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    summarize,
    ckpt_dir: str,
    chunk_size: int = 16384,
    run_chunk: Optional[Callable] = None,
    params=None,
    telemetry=None,
) -> dict:
    """Pod-scale sweep that survives interruption at chunk granularity.

    Runs ``seeds`` as sequential ``chunk_size`` batches; after each chunk
    its ``summarize(final)`` dict is written atomically to ``ckpt_dir``,
    and a restarted call skips every chunk whose summary file already
    exists — sound because chunks are deterministic (re-running one
    yields bit-identical results). Returns the merged summary totals
    (per-chunk host merge, constant device memory — the million-seed
    pattern of scripts/sweep_million.py made preemption-safe; BASELINE
    config #5's recovery semantics at pod scale).

    Stale-reuse guard: each file records its seed range, a sha256 of
    the chunk's full seed array, AND a fingerprint of the workload +
    engine config; a mismatch (the directory belongs to a different
    sweep) raises instead of silently merging foreign counts. For mid-chunk snapshots of in-flight state
    use ``save_sweep``/``resume_sweep`` instead.

    ``run_chunk(seed_chunk) -> final state`` overrides the per-chunk
    sweep — the mesh driver injects ``parallel.run_sweep_sharded`` here
    (scripts/sweep_million.py ``--mesh``); the chunk files it writes are
    mesh-free (fingerprint + seed sha only), so a sweep can be
    interrupted under one device count and finished under another.

    ``telemetry`` (``obs.Telemetry`` or None) records chunk wall time,
    seeds-done progress and skip/resume events strictly OUT-OF-BAND:
    every recorder sits behind an ``is not None`` guard and never touches
    the summaries, so report bytes are identical with it on or off.
    """
    import os
    import time as _time

    from .core import (
        _concat_finals, _pad_params, _pad_seeds, _slice_params, run_sweep,
    )
    from ..models._common import merge_summaries  # lazy: models import us

    if run_chunk is None:
        if params is None:
            run_chunk = lambda chunk: run_sweep(workload, cfg, chunk)  # noqa: E731
        else:
            run_chunk = lambda chunk, pchunk: run_sweep(  # noqa: E731
                workload, cfg, chunk, params=pchunk
            )
    seeds = jnp.asarray(seeds, jnp.int64)
    seeds_host = np.asarray(seeds)  # bookkeeping reads skip the device
    n = int(seeds.shape[0])
    if n == 0:
        raise ValueError("seed batch is empty")
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    fp = _sweep_fingerprint(workload, cfg)
    if params is not None:
        fp += "|params" + params_digest(params)
    os.makedirs(ckpt_dir, exist_ok=True)
    totals: dict = {}
    for lo in range(0, n, chunk_size):
        k = min(chunk_size, n - lo)
        first, last = int(seeds_host[lo]), int(seeds_host[lo + k - 1])
        seeds_sha = _chunk_sha(seeds_host, lo, k)
        path = os.path.join(ckpt_dir, f"chunk_{lo:010d}_{k}.json")
        if os.path.exists(path):
            summary = _load_chunk_summary(path, first, last, seeds_sha, fp)
            if telemetry is not None:
                telemetry.count("sweep_chunks_skipped_total")
                telemetry.event("chunk_skipped", lo=lo, k=k)
        else:
            if telemetry is not None:
                t_chunk = _time.perf_counter()
            # pad a ragged final chunk so it reuses the one compiled
            # sweep program (a fresh batch shape recompiles for seconds);
            # a limit-aware summarize (models/_common.make_sweep_summary)
            # masks the padded lanes inside the SAME compiled summary
            # program, so the ragged chunk compiles nothing at all —
            # otherwise the padded lanes are trimmed by a (one-off)
            # k-shaped trim program
            chunk = seeds[lo : lo + chunk_size]
            pad = chunk_size - k
            if params is None:
                final = run_chunk(_pad_seeds(chunk, pad) if pad else chunk)
            else:
                pchunk = _slice_params(params, lo, lo + chunk_size)
                if pad:
                    pchunk = _pad_params(pchunk, pad)
                final = run_chunk(
                    _pad_seeds(chunk, pad) if pad else chunk, pchunk
                )
            if pad and getattr(summarize, "supports_limit", False):
                summary = summarize(final, limit=k)
            else:
                if pad:
                    final = _concat_finals(k, final)
                summary = summarize(final)
            _write_chunk_summary(path, first, last, seeds_sha, fp, summary)
            if telemetry is not None:
                dt = _time.perf_counter() - t_chunk
                telemetry.observe(
                    "sweep_chunk_seconds", dt,
                    help="device+summary wall time per chunk",
                )
                telemetry.count("sweep_chunks_total")
                telemetry.event("chunk", lo=lo, k=k, wall_s=round(dt, 6))
        if telemetry is not None:
            telemetry.count(
                "sweep_seeds_done_total", k, help="seeds merged so far"
            )
            telemetry.event_mix(summary)
        merge_summaries(totals, summary)
    return totals


def run_sweep_pipelined(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    summarize,
    *,
    host_work: Optional[Callable] = None,
    screen: Optional[Callable] = None,
    chunk_size: int = 16384,
    ckpt_dir: Optional[str] = None,
    stop_after: Optional[int] = None,
    resume_from: Optional[Tuple[EngineState, dict]] = None,
    run_chunk: Optional[Callable] = None,
    resume_chunk: Optional[Callable] = None,
    pad_multiple: int = 1,
    on_chunk: Optional[Callable] = None,
    params=None,
    telemetry=None,
) -> dict:
    """Chunked sweep with the host phase of chunk N overlapped against
    the device sweep of chunk N+1 — the driver that makes END-TO-END
    checked throughput (sweep + screen + check) the optimized quantity
    instead of raw sweep speed.

    Per chunk, in dispatch order:

    1. **device phase** — the chunk's sweep is enqueued, and ``screen``
       (``final -> bool[S]`` suspect mask, e.g.
       ``oracle.screen.screen_sweep``) is enqueued right behind it; both
       stay un-materialized device values.
    2. the PREVIOUS chunk's **host phase** runs while the device crunches
       this chunk: ``host_work(final, lo=, n=, seeds=, suspect=,
       summary=)`` gets the previous chunk's finished state, its host
       suspect mask (``np.asarray`` here costs a device->host transfer
       that overlaps compute, not a sync), and its summary dict; the
       dict it returns is folded into that chunk's summary. Decode,
       checking, triage — anything host-Python — belongs here.
    3. ``summarize(final)`` blocks until this chunk's sweep completes
       (its reduction program was enqueued behind the sweep, so the
       device never idles on it).

    A ragged final chunk is padded to ``chunk_size`` for program reuse;
    a limit-aware ``summarize`` masks the padded lanes in-program, and
    ``host_work`` always receives the trimmed real lanes.

    ``ckpt_dir`` makes the pipeline preemption-safe at chunk granularity
    exactly like ``run_sweep_chunked_resumable`` (per-chunk summary
    JSONs with seed-sha + workload fingerprint guards, written AFTER the
    chunk's host phase, atomically): a restarted call skips finished
    chunks and recomputes at most the in-flight one — bit-identical, as
    chunks are deterministic. ``stop_after`` returns after that many
    chunks were computed this call (preemption drills and tests).
    ``resume_from=(state, inflight)`` — a mid-chunk snapshot written by
    ``save_sweep(state, path, inflight={"lo": ..., "k": ...})`` and read
    back by ``load_sweep``/``load_inflight`` — finishes the in-flight
    chunk from its saved state instead of restarting it (checkpoint
    format v7), which is what keeps interrupt/resume bit-identical with
    overlap enabled.

    Determinism: chunk summaries merge in seed order regardless of
    overlap, and ``host_work`` must be a pure function of its chunk (the
    oracle's screened checker is), so the merged totals are byte-stable
    across pipelining, worker-pool sizes, and interruption points.

    A ``host_work`` advertising ``incremental = True`` (the oracle's
    ``history_host_work`` does) is driven through its
    ``submit``/``poll``/``drain`` protocol instead of being run to
    completion inside each overlap window: each chunk's checking is
    sliced under a budget tracking the device phase's EMA wall time, so
    one contended chunk's WGL work spreads across later chunks' device
    time rather than stalling dispatch. Disabled (sync fallback) under
    ``ckpt_dir``/``stop_after``/``resume_from``, whose chunk files need
    summaries finalized at their own boundaries. Byte-identical totals
    either way — the budget shapes scheduling, never verdict order.

    Scale-out hooks (``parallel.mesh.run_sweep_sharded_pipelined`` is
    the canonical injector): ``run_chunk(seed_chunk) -> final`` replaces
    the per-chunk sweep and ``resume_chunk(state) -> final`` the
    mid-chunk resume drive — the mesh driver passes the sharded sweep
    for both, so the identical pipeline spans 1 or N devices.
    ``pad_multiple`` pads a batch smaller than one chunk up to the next
    multiple (mesh divisibility) instead of not at all; the limit-masked
    summary and trimmed host phase treat that pad exactly like a ragged
    final chunk's. ``on_chunk(lo=, k=, summary=)`` fires as each chunk's
    summary is merged (in seed order) — progress reporting and
    time-to-first-violation measurement at the million-seed scale.

    ``params`` carries per-lane spec-as-data (engine/faults.py): each
    chunk's ``run_chunk(seed_chunk, param_chunk)`` receives the matching
    lane slice, edge-padded like the seeds; the checkpoint fingerprint
    gains the params digest so one candidate's chunk files can never
    merge into another candidate's sweep.

    ``telemetry`` (``obs.Telemetry`` or None) records chunk wall time,
    host-phase time, seeds-done progress and skip/resume events, and —
    when the handle carries a trace — one "device" span per chunk
    (dispatch -> summary-done) with the previous chunk's "host" flush
    span nested inside its wall window, which is exactly the overlap
    picture Perfetto renders. Strictly OUT-OF-BAND: every recorder is
    behind an ``is not None`` guard and summaries are never touched, so
    the merged report is byte-identical with telemetry on or off.
    """
    import os
    import time as _time

    from .core import (
        _concat_finals, _pad_params, _pad_seeds, _slice_params, run_sweep,
        _drive,
    )
    from ..models._common import merge_summaries  # lazy: models import us

    if run_chunk is None:
        if params is None:
            run_chunk = lambda chunk: run_sweep(workload, cfg, chunk)  # noqa: E731
        else:
            run_chunk = lambda chunk, pchunk: run_sweep(  # noqa: E731
                workload, cfg, chunk, params=pchunk
            )
    if resume_chunk is None:
        resume_chunk = lambda state: _drive(workload, cfg, state)  # noqa: E731
    seeds = jnp.asarray(seeds, jnp.int64)
    seeds_host = np.asarray(seeds)
    n = int(seeds.shape[0])
    if n == 0:
        raise ValueError("seed batch is empty")
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    fp = _sweep_fingerprint(workload, cfg)
    if params is not None:
        fp += "|params" + params_digest(params)
    if ckpt_dir is not None:
        os.makedirs(ckpt_dir, exist_ok=True)
    supports_limit = bool(getattr(summarize, "supports_limit", False))
    resume_lo = int(resume_from[1]["lo"]) if resume_from is not None else -1
    tracer = telemetry.tracer if telemetry is not None else None

    totals: dict = {}
    pending = None  # previous chunk awaiting its host phase
    computed = 0

    # budgeted incremental checking: a host_work advertising the
    # submit/poll/drain protocol (oracle.screen._HostWork) gets its WGL
    # work sliced under a per-chunk budget — the device phase's own EMA
    # wall time — instead of run to completion inside each overlap
    # window, so one expensive chunk's checking spreads across later
    # chunks' device time instead of stalling the dispatch loop. OFF
    # under checkpointing/stop/resume: those need each chunk's summary
    # finalized at its own boundary (the chunk file IS the resume
    # granule). Reports are byte-identical either way: verdicts are
    # computed and merged in submission (= seed) order regardless of
    # how the budget slices them.
    incr = (
        host_work is not None
        and getattr(host_work, "incremental", False)
        and ckpt_dir is None
        and stop_after is None
        and resume_from is None
    )
    deferred: dict = {}  # lo -> (k, base summary) awaiting a verdict
    ema = 0.0

    def absorb(finished) -> None:
        for flo, extra in finished:
            fk, summary = deferred.pop(flo)
            if extra:
                summary = {**summary, **extra}
            merge_summaries(totals, summary)
            if telemetry is not None:
                telemetry.count("sweep_chunks_total")
                telemetry.count(
                    "sweep_seeds_done_total", fk,
                    help="seeds merged so far",
                )
                telemetry.event_mix(summary)
                telemetry.event("chunk", lo=flo, k=fk)
            if on_chunk is not None:
                on_chunk(lo=flo, k=fk, summary=summary)

    def submit_pending(p, budget: float) -> None:
        lo, k, _sha, final, susp, summary, _path = p
        if telemetry is not None:
            t_host = _time.perf_counter()
        deferred[lo] = (k, summary)
        host_work.submit(
            final,
            lo=lo,
            n=k,
            seeds=seeds_host[lo : lo + k],
            suspect=None if susp is None else np.asarray(susp)[:k],
            summary=summary,
        )
        absorb(host_work.poll(budget))
        if telemetry is not None:
            telemetry.observe(
                "sweep_host_phase_seconds",
                _time.perf_counter() - t_host,
                help="host phase (decode/check/ckpt write) per chunk",
            )

    def flush(p) -> None:
        lo, k, sha, final, susp, summary, path = p
        if telemetry is not None:
            t_host = _time.perf_counter()
            h0 = tracer._now_us() if tracer is not None else 0.0
        if host_work is not None:
            extra = host_work(
                final,
                lo=lo,
                n=k,
                seeds=seeds_host[lo : lo + k],
                suspect=None if susp is None else np.asarray(susp)[:k],
                summary=summary,
            )
            if extra:
                summary = {**summary, **extra}
        if path is not None:
            _write_chunk_summary(
                path, int(seeds_host[lo]), int(seeds_host[lo + k - 1]),
                sha, fp, summary,
            )
        merge_summaries(totals, summary)
        if telemetry is not None:
            dt = _time.perf_counter() - t_host
            telemetry.observe(
                "sweep_host_phase_seconds", dt,
                help="host phase (decode/check/ckpt write) per chunk",
            )
            telemetry.count("sweep_chunks_total")
            telemetry.count(
                "sweep_seeds_done_total", k, help="seeds merged so far"
            )
            telemetry.event_mix(summary)
            telemetry.event("chunk", lo=lo, k=k, host_phase_s=round(dt, 6))
            if tracer is not None:
                tracer.complete(
                    f"host flush lo={lo}", h0, tracer._now_us() - h0,
                    track="host", args={"lo": lo, "k": k},
                )
        if on_chunk is not None:
            on_chunk(lo=lo, k=k, summary=summary)

    for lo in range(0, n, chunk_size):
        k = min(chunk_size, n - lo)
        sha = _chunk_sha(seeds_host, lo, k)
        path = (
            os.path.join(ckpt_dir, f"pchunk_{lo:010d}_{k}.json")
            if ckpt_dir is not None
            else None
        )
        if path is not None and os.path.exists(path):
            summary = _load_chunk_summary(
                path, int(seeds_host[lo]), int(seeds_host[lo + k - 1]),
                sha, fp,
            )
            if pending is not None:
                flush(pending)  # keep merge order = seed order
                pending = None
            merge_summaries(totals, summary)
            if telemetry is not None:
                telemetry.count("sweep_chunks_skipped_total")
                telemetry.count("sweep_seeds_done_total", k)
                telemetry.event_mix(summary)
                telemetry.event("chunk_skipped", lo=lo, k=k)
            if on_chunk is not None:
                on_chunk(lo=lo, k=k, summary=summary)
            continue

        # -- device phase: enqueue this chunk's sweep (+ screen) --------
        if telemetry is not None or incr:
            t_disp = _time.perf_counter()
        if telemetry is not None:
            d0 = tracer._now_us() if tracer is not None else 0.0
        pad = chunk_size - k if n > chunk_size else -k % pad_multiple
        if lo == resume_lo:
            state, inflight = resume_from
            if telemetry is not None:
                telemetry.count(
                    "sweep_resume_total",
                    help="mid-chunk snapshot resumes",
                )
                telemetry.event("chunk_resumed", lo=lo, k=k)
            if int(inflight.get("k", k)) != k or not np.array_equal(
                np.asarray(state.seed)[:k], seeds_host[lo : lo + k]
            ):
                raise ValueError(
                    f"resume_from snapshot does not match chunk at {lo}: "
                    f"inflight={inflight!r}"
                )
            # the snapshot carries its OWN padding (the saving process's
            # pad_multiple may differ across mesh sizes) — trust its lane
            # count, not this process's pad, so the limit mask/trim below
            # still hides exactly the synthetic lanes
            pad = int(state.seed.shape[0]) - k
            final = resume_chunk(state)
        else:
            chunk = seeds[lo : lo + chunk_size]
            if params is None:
                final = run_chunk(_pad_seeds(chunk, pad) if pad else chunk)
            else:
                pchunk = _slice_params(params, lo, lo + chunk_size)
                if pad:
                    pchunk = _pad_params(pchunk, pad)
                final = run_chunk(
                    _pad_seeds(chunk, pad) if pad else chunk, pchunk
                )
        susp = screen(final) if screen is not None else None

        # -- previous chunk's host phase overlaps this chunk's sweep ----
        if pending is not None:
            if incr:
                submit_pending(pending, ema)
            else:
                flush(pending)
            pending = None

        # -- this chunk's summary (blocks until its sweep completes) ----
        if pad and supports_limit:
            summary = summarize(final, limit=k)
        else:
            if pad:
                final = _concat_finals(k, final)
            summary = summarize(final)
        if pad and supports_limit and host_work is not None:
            # the host phase must never see the padded lanes (their
            # synthetic seeds would pollute e.g. violating-seed lists)
            final = _concat_finals(k, final)
        if susp is not None and pad:
            susp = susp[:k]
        if telemetry is not None or incr:
            # summarize() above synced on the device work, so this wall
            # window (dispatch -> summary materialized) IS the device
            # phase; the previous chunk's host flush ran inside it —
            # and its EMA is the incremental checker's poll budget (the
            # checking a chunk's device time can hide)
            dt = _time.perf_counter() - t_disp
            ema = dt if ema == 0.0 else 0.5 * ema + 0.5 * dt
        if telemetry is not None:
            telemetry.observe(
                "sweep_chunk_seconds", dt,
                help="device phase (dispatch -> summary) per chunk",
            )
            if tracer is not None:
                tracer.complete(
                    f"device chunk lo={lo}", d0, tracer._now_us() - d0,
                    track="device", args={"lo": lo, "k": k},
                )
        pending = (lo, k, sha, final, susp, summary, path)
        computed += 1
        if stop_after is not None and computed >= stop_after:
            break

    if pending is not None:
        if incr:
            submit_pending(pending, 0.0)
        else:
            flush(pending)
    if incr:
        absorb(host_work.drain())
    return totals


# EngineConfig fields that select equivalent-but-differently-laid-out
# implementations (A/B instrumentation, historical knobs): schedules and
# summaries are bit-identical across their values, so they must NOT
# invalidate resumable checkpoints — toggling legacy_queue between runs
# of one sweep directory resumes cleanly.
_LAYOUT_ONLY_FIELDS = frozenset({"legacy_queue", "cond_interval"})


def _sweep_fingerprint(workload: Workload, cfg: EngineConfig) -> str:
    """Identity of (model, model config, engine config) for the resumable
    sweep's stale-checkpoint guard. Model configs are NamedTuples of
    plain values, so their repr is a stable fingerprint. Layout-only
    engine fields (``_LAYOUT_ONLY_FIELDS``) are excluded: they cannot
    change a chunk's summary, only its wall-clock. ``cover_bits`` is
    INCLUDED: it changes the summary schema (``coverage_map`` appears),
    so chunk summaries written by a coverage-free workload must not
    silently merge into a coverage-guided sweep as zero coverage.
    ``hist_slots`` is included for the same reason in reverse: a resized
    history buffer changes which seeds latch ``hist_overflow``, so their
    chunk summaries are not interchangeable. ``event_mix_kinds`` is
    included because enabling the plane adds the ``event_mix`` key to
    every chunk summary (and disables pre-v10 snapshot reuse)."""
    from .core import hist_slots

    init = workload.init
    fn = getattr(init, "func", init)
    args = getattr(init, "args", ())
    cfg_id = tuple(
        v for f, v in zip(cfg._fields, cfg) if f not in _LAYOUT_ONLY_FIELDS
    )
    return (
        f"{fn.__module__}.{fn.__qualname__}|{args!r}|{cfg_id!r}"
        f"|cover{workload.cover_bits}|hist{hist_slots(workload)}"
        f"|emix{workload.event_mix_kinds}"
    )
