"""Sweep checkpoint/resume: the engine state is arrays, so snapshots are
free.

The reference has no core snapshotting — only the etcd sim's dump/load
(SURVEY.md §5 "checkpoint/resume"). The SoA engine generalizes the
pattern: a whole in-flight seed batch (clocks, queues, RNG counters,
workload actor state) round-trips through one ``.npz`` file, and
``resume_sweep`` continues stepping it — enabling long sweeps to survive
preemption and failed seeds to be re-examined from mid-run state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core import EngineConfig, EngineState, Workload

# v2: EngineState gained qmax; draw layout adds tie-break.
# v3: packed queue layout — the redundant bool valid[Q] plane left the
#     EventQueue, so v2 files would load positionally misaligned.
# v4: EngineState gained the per-seed coverage bitmap (``cover``), so v3
#     files would load positionally misaligned.
# v5: EngineState gained the operation-history plane (``hist_rec``,
#     ``hist_t``, ``hist_len``, ``hist_overflow`` — madsim_tpu/oracle),
#     so v4 files would load positionally misaligned.
# v6: gray-failure grammar — ``FaultState`` split ``part_cnt`` into
#     per-direction refcounts and gained ``fsync_cnt``/``skew_cnt``, and
#     the raft model grew its durability shadows, so v5 files would load
#     positionally misaligned.
_FORMAT_VERSION = 6


def save_sweep(state: EngineState, path: str) -> None:
    """Serialize a batched EngineState to ``path`` (.npz)."""
    leaves, treedef = jax.tree.flatten(state)
    arrays = {}
    for i, leaf in enumerate(leaves):
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            # typed PRNG keys serialize as their raw uint32 words
            arrays[f"leaf_{i}__key"] = np.asarray(jax.random.key_data(leaf))
        else:
            arrays[f"leaf_{i}"] = np.asarray(leaf)
    np.savez_compressed(path, __version__=_FORMAT_VERSION, **arrays)


def load_sweep(path: str, like: EngineState) -> EngineState:
    """Restore a checkpoint; ``like`` supplies the pytree structure (build
    it with ``init_sweep`` on any seed vector of the same shape/config)."""
    data = np.load(path)
    found = int(data["__version__"])
    if found != _FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format version mismatch: {path} is v{found}, "
            f"this engine reads v{_FORMAT_VERSION} (the draw layout / state "
            "schema changed between versions; re-run the sweep to produce a "
            "fresh checkpoint)"
        )
    leaves, treedef = jax.tree.flatten(like)
    out = []
    for i, leaf in enumerate(leaves):
        if f"leaf_{i}__key" in data:
            out.append(jax.random.wrap_key_data(jnp.asarray(data[f"leaf_{i}__key"])))
        else:
            out.append(jnp.asarray(data[f"leaf_{i}"], dtype=leaf.dtype))
    return jax.tree.unflatten(treedef, out)


def resume_sweep(
    workload: Workload, cfg: EngineConfig, state: EngineState
) -> EngineState:
    """Continue a (possibly restored) sweep until every seed finishes."""
    from .core import _drive

    return _drive(workload, cfg, state)  # shares run_sweep's trace cache


def run_sweep_chunked_resumable(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    summarize,
    ckpt_dir: str,
    chunk_size: int = 16384,
) -> dict:
    """Pod-scale sweep that survives interruption at chunk granularity.

    Runs ``seeds`` as sequential ``chunk_size`` batches; after each chunk
    its ``summarize(final)`` dict is written atomically to ``ckpt_dir``,
    and a restarted call skips every chunk whose summary file already
    exists — sound because chunks are deterministic (re-running one
    yields bit-identical results). Returns the merged summary totals
    (per-chunk host merge, constant device memory — the million-seed
    pattern of scripts/sweep_million.py made preemption-safe; BASELINE
    config #5's recovery semantics at pod scale).

    Stale-reuse guard: each file records its seed range, a sha256 of
    the chunk's full seed array, AND a fingerprint of the workload +
    engine config; a mismatch (the directory belongs to a different
    sweep) raises instead of silently merging foreign counts. For mid-chunk snapshots of in-flight state
    use ``save_sweep``/``resume_sweep`` instead.
    """
    import hashlib
    import json
    import os

    from .core import _concat_finals, _pad_seeds, run_sweep
    from ..models._common import merge_summaries  # lazy: models import us

    seeds = jnp.asarray(seeds, jnp.int64)
    seeds_host = np.asarray(seeds)  # bookkeeping reads skip the device
    n = int(seeds.shape[0])
    if n == 0:
        raise ValueError("seed batch is empty")
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    fp = _sweep_fingerprint(workload, cfg)
    os.makedirs(ckpt_dir, exist_ok=True)
    totals: dict = {}
    for lo in range(0, n, chunk_size):
        k = min(chunk_size, n - lo)
        first, last = int(seeds_host[lo]), int(seeds_host[lo + k - 1])
        # endpoints alone can collide across different seed vectors
        # ([0,5,9] vs [0,7,9]); hash the whole chunk's seeds
        seeds_sha = hashlib.sha256(
            np.ascontiguousarray(seeds_host[lo : lo + k]).tobytes()
        ).hexdigest()
        path = os.path.join(ckpt_dir, f"chunk_{lo:010d}_{k}.json")
        if os.path.exists(path):
            with open(path) as f:
                rec = json.load(f)
            # records from before the sha was added lack the key; their
            # endpoint+fingerprint check still applies (legacy-compatible)
            rec_sha = rec.get("seeds_sha256", seeds_sha)
            if (
                rec["first_seed"] != first
                or rec["last_seed"] != last
                or rec_sha != seeds_sha
                or rec.get("fingerprint") != fp
            ):
                raise ValueError(
                    f"checkpoint {path} is from a different sweep: holds "
                    f"seeds [{rec['first_seed']}, {rec['last_seed']}] "
                    f"(sha {rec.get('seeds_sha256')!r}) with "
                    f"fingerprint {rec.get('fingerprint')!r}, expected "
                    f"[{first}, {last}] (sha {seeds_sha!r}) with {fp!r}"
                )
            summary = rec["summary"]
        else:
            # pad a ragged final chunk so it reuses the one compiled
            # sweep program (a fresh batch shape recompiles for seconds);
            # padded lanes are trimmed inside one jitted program
            chunk = seeds[lo : lo + chunk_size]
            pad = chunk_size - k
            final = run_sweep(
                workload, cfg, _pad_seeds(chunk, pad) if pad else chunk
            )
            if pad:
                final = _concat_finals(k, final)
            summary = summarize(final)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "first_seed": first,
                        "last_seed": last,
                        "seeds_sha256": seeds_sha,
                        "fingerprint": fp,
                        "summary": summary,
                    },
                    f,
                )
            os.replace(tmp, path)  # atomic: a crash never leaves half a file
        merge_summaries(totals, summary)
    return totals


# EngineConfig fields that select equivalent-but-differently-laid-out
# implementations (A/B instrumentation, historical knobs): schedules and
# summaries are bit-identical across their values, so they must NOT
# invalidate resumable checkpoints — toggling legacy_queue between runs
# of one sweep directory resumes cleanly.
_LAYOUT_ONLY_FIELDS = frozenset({"legacy_queue", "cond_interval"})


def _sweep_fingerprint(workload: Workload, cfg: EngineConfig) -> str:
    """Identity of (model, model config, engine config) for the resumable
    sweep's stale-checkpoint guard. Model configs are NamedTuples of
    plain values, so their repr is a stable fingerprint. Layout-only
    engine fields (``_LAYOUT_ONLY_FIELDS``) are excluded: they cannot
    change a chunk's summary, only its wall-clock. ``cover_bits`` is
    INCLUDED: it changes the summary schema (``coverage_map`` appears),
    so chunk summaries written by a coverage-free workload must not
    silently merge into a coverage-guided sweep as zero coverage.
    ``hist_slots`` is included for the same reason in reverse: a resized
    history buffer changes which seeds latch ``hist_overflow``, so their
    chunk summaries are not interchangeable."""
    from .core import hist_slots

    init = workload.init
    fn = getattr(init, "func", init)
    args = getattr(init, "args", ())
    cfg_id = tuple(
        v for f, v in zip(cfg._fields, cfg) if f not in _LAYOUT_ONLY_FIELDS
    )
    return (
        f"{fn.__module__}.{fn.__qualname__}|{args!r}|{cfg_id!r}"
        f"|cover{workload.cover_bits}|hist{hist_slots(workload)}"
    )
