"""Sweep checkpoint/resume: the engine state is arrays, so snapshots are
free.

The reference has no core snapshotting — only the etcd sim's dump/load
(SURVEY.md §5 "checkpoint/resume"). The SoA engine generalizes the
pattern: a whole in-flight seed batch (clocks, queues, RNG counters,
workload actor state) round-trips through one ``.npz`` file, and
``resume_sweep`` continues stepping it — enabling long sweeps to survive
preemption and failed seeds to be re-examined from mid-run state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core import EngineConfig, EngineState, Workload

_FORMAT_VERSION = 2  # v2: EngineState gained qmax; draw layout adds tie-break


def save_sweep(state: EngineState, path: str) -> None:
    """Serialize a batched EngineState to ``path`` (.npz)."""
    leaves, treedef = jax.tree.flatten(state)
    arrays = {}
    for i, leaf in enumerate(leaves):
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            # typed PRNG keys serialize as their raw uint32 words
            arrays[f"leaf_{i}__key"] = np.asarray(jax.random.key_data(leaf))
        else:
            arrays[f"leaf_{i}"] = np.asarray(leaf)
    np.savez_compressed(path, __version__=_FORMAT_VERSION, **arrays)


def load_sweep(path: str, like: EngineState) -> EngineState:
    """Restore a checkpoint; ``like`` supplies the pytree structure (build
    it with ``init_sweep`` on any seed vector of the same shape/config)."""
    data = np.load(path)
    found = int(data["__version__"])
    if found != _FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format version mismatch: {path} is v{found}, "
            f"this engine reads v{_FORMAT_VERSION} (the draw layout / state "
            "schema changed between versions; re-run the sweep to produce a "
            "fresh checkpoint)"
        )
    leaves, treedef = jax.tree.flatten(like)
    out = []
    for i, leaf in enumerate(leaves):
        if f"leaf_{i}__key" in data:
            out.append(jax.random.wrap_key_data(jnp.asarray(data[f"leaf_{i}__key"])))
        else:
            out.append(jnp.asarray(data[f"leaf_{i}"], dtype=leaf.dtype))
    return jax.tree.unflatten(treedef, out)


def resume_sweep(
    workload: Workload, cfg: EngineConfig, state: EngineState
) -> EngineState:
    """Continue a (possibly restored) sweep until every seed finishes."""
    from .core import _drive

    return _drive(workload, cfg, state)  # shares run_sweep's trace cache
