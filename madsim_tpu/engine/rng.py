"""Counter-based randomness for the device engine.

The host tier's ``GlobalRng`` (madsim_tpu.rand) is a sequential stream — fine
for one seed on one CPU, impossible to batch. The device engine instead keys
every draw by ``(seed, event_counter)`` with threefry (`jax.random.fold_in`),
the TPU-native analogue of the reference's single Xoshiro stream
(madsim/src/sim/rand.rs:28-135): per seed, draw ``i`` is a pure function of
``(seed, i)``, so replaying one seed on CPU consumes bit-identical
randomness in any order and with any batch size.

All helpers are integer-only (uint32 in, integer or fixed-point compare out)
— no float rounding can diverge between backends.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

UINT32_SPAN = 1 << 32


def seed_key(seed: jax.Array) -> jax.Array:
    """Per-seed base PRNG key (uint32 typed key; int64-safe seed)."""
    return jax.random.key(seed)


def event_bits(key: jax.Array, ctr: jax.Array, n: int) -> jax.Array:
    """``n`` uint32 draws for event number ``ctr`` of this seed.

    Counter-based: (key, ctr) fully determines the draws — the device
    analogue of the reference's "one RNG draw sequence per seed"
    determinism contract (rand.rs:64-88).
    """
    return jax.random.bits(jax.random.fold_in(key, ctr), (n,), dtype=jnp.uint32)


def bounded(u32: jax.Array, low, high) -> jax.Array:
    """Map a uint32 draw to an integer in ``[low, high)``.

    Lemire-style multiply-shift reduction — same formula as the host tier's
    ``GlobalRng.gen_range`` so both tiers share bias characteristics.
    Result dtype is int64 (times are int64 ns).

    The 96-bit product ``u32 * span`` is computed as two half-width
    multiplies (the naive int64 product sign-wraps for spans above 2**31
    ns ≈ 2.1 s — fault/command windows routinely exceed that). Bit-
    identical to the single multiply wherever that didn't overflow; exact
    for spans up to 2**47 (~39 hours in ns).
    """
    span = jnp.asarray(high, jnp.int64) - jnp.asarray(low, jnp.int64)
    hi = (u32 >> 16).astype(jnp.int64)
    lo = (u32 & 0xFFFF).astype(jnp.int64)
    carry = (lo * span) >> 16
    return jnp.asarray(low, jnp.int64) + ((hi * span + carry) >> 16)


def coin(u32: jax.Array, prob_q32: jax.Array) -> jax.Array:
    """Bernoulli from a uint32 draw against a Q0.32 fixed-point probability.

    ``prob_q32 = round(p * 2**32)`` — comparing integers keeps the draw
    bit-exact across backends (no float compare).
    """
    return u32.astype(jnp.uint32) < jnp.asarray(prob_q32, jnp.uint32)


def prob_to_q32(p: float) -> int:
    """Host-side: convert a float probability to Q0.32 fixed point."""
    return min(UINT32_SPAN - 1, max(0, int(round(p * UINT32_SPAN))))
