"""Link-state network model as per-seed arrays.

The reference's ``Network`` (madsim/src/sim/net/network.rs:20-314) keeps
clogged-node/link sets and draws per-message loss + latency
(``test_link``, network.rs:261-269). Here the same model is data:

    clog    : bool[N,N]   directed link clogged (row = src, col = dst);
                          clogging a node = setting its row (out) / col (in)
    loss_q32: uint32      packet-loss probability, Q0.32 fixed point
    lat_lo/hi_ns          latency range, drawn uniformly per message
                          (reference default 1-10 ms, network.rs:87-89)
    buggify_q32           probability of a buggified latency *spike*
                          (reference: 10% → 1-5 s when buggify is on,
                          madsim/src/sim/net/mod.rs:287-295); 0 = off
    spike_lo/hi_ns        the spike latency range

Lookups are one-hot masked (no dynamic gather — see engine/ops.py): a
``route`` decision is a handful of dense vector ops, evaluated for every
in-flight message of every seed in lockstep.

The spike coin reuses the loss draw remixed by a multiplicative hash
rather than consuming an extra stream slot: a dropped packet never needs a
latency, so the two decisions are never observable together and the remix
keeps the per-event draw budget flat while staying bit-reproducible.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from .ops import get1, get2
from .rng import bounded, coin


class LinkState(NamedTuple):
    clog: jnp.ndarray  # bool[N, N]
    loss_q32: jnp.ndarray  # uint32 scalar
    lat_lo_ns: jnp.ndarray  # int64 scalar
    lat_hi_ns: jnp.ndarray  # int64 scalar
    buggify_q32: jnp.ndarray  # uint32 scalar (0 = spikes off)
    spike_lo_ns: jnp.ndarray  # int64 scalar
    spike_hi_ns: jnp.ndarray  # int64 scalar


def make(
    num_nodes: int,
    loss_q32: int = 0,
    lat_lo_ns: int = 1_000_000,
    lat_hi_ns: int = 10_000_000,
    buggify_q32: int = 0,
    spike_lo_ns: int = 1_000_000_000,
    spike_hi_ns: int = 5_000_000_000,
) -> LinkState:
    return LinkState(
        clog=jnp.zeros((num_nodes, num_nodes), bool),
        loss_q32=jnp.asarray(loss_q32, jnp.uint32),
        lat_lo_ns=jnp.asarray(lat_lo_ns, jnp.int64),
        lat_hi_ns=jnp.asarray(lat_hi_ns, jnp.int64),
        buggify_q32=jnp.asarray(buggify_q32, jnp.uint32),
        spike_lo_ns=jnp.asarray(spike_lo_ns, jnp.int64),
        spike_hi_ns=jnp.asarray(spike_hi_ns, jnp.int64),
    )


def _latency(links: LinkState, u_loss, u_lat):
    """Latency draw with buggified spikes (spike coin = remixed loss draw)."""
    u_spike = jnp.asarray(u_loss, jnp.uint32) * jnp.uint32(2654435761) + jnp.uint32(
        0x9E3779B9
    )
    spike = coin(u_spike, links.buggify_q32)
    normal = bounded(u_lat, links.lat_lo_ns, links.lat_hi_ns + 1)
    spiked = bounded(u_lat, links.spike_lo_ns, links.spike_hi_ns + 1)
    return jnp.where(spike, spiked, normal)


def route(
    links: LinkState,
    now_ns: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    u_loss: jnp.ndarray,
    u_lat: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-message link test (ref ``test_link``): returns
    ``(deliver_time_ns, deliver)`` — dropped when the directed link is
    clogged or the loss draw fires."""
    clogged = get2(links.clog, src, dst)
    lost = coin(u_loss, links.loss_q32)
    return now_ns + _latency(links, u_loss, u_lat), ~(clogged | lost)


def route_from(
    links: LinkState,
    now_ns: jnp.ndarray,
    src: jnp.ndarray,
    u_loss: jnp.ndarray,  # uint32[N]
    u_lat: jnp.ndarray,  # uint32[N]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Vectorized ``route`` for a broadcast: link-test src→every node at
    once. Returns ``(deliver_times[N], deliver[N])``."""
    clogged = get1(links.clog, src)
    lost = coin(u_loss, links.loss_q32)
    return now_ns + _latency(links, u_loss, u_lat), ~(clogged | lost)


def clog_node(links: LinkState, node: jnp.ndarray) -> LinkState:
    """Clog both directions of a node (ref ``NetSim::clog_node``)."""
    n = links.clog.shape[0]
    idx = jnp.arange(n)
    mask = (idx[:, None] == node) | (idx[None, :] == node)
    return links._replace(clog=links.clog | mask)


def unclog_node(links: LinkState, node: jnp.ndarray) -> LinkState:
    n = links.clog.shape[0]
    idx = jnp.arange(n)
    mask = (idx[:, None] == node) | (idx[None, :] == node)
    return links._replace(clog=links.clog & ~mask)


def clog_link(links: LinkState, src: jnp.ndarray, dst: jnp.ndarray) -> LinkState:
    n = links.clog.shape[0]
    idx = jnp.arange(n)
    mask = (idx[:, None] == src) & (idx[None, :] == dst)
    return links._replace(clog=links.clog | mask)


def unclog_link(links: LinkState, src: jnp.ndarray, dst: jnp.ndarray) -> LinkState:
    n = links.clog.shape[0]
    idx = jnp.arange(n)
    mask = (idx[:, None] == src) & (idx[None, :] == dst)
    return links._replace(clog=links.clog & ~mask)
