"""Link-state network model as per-seed arrays.

The reference's ``Network`` (madsim/src/sim/net/network.rs:20-314) keeps
clogged-node/link sets and draws per-message loss + latency
(``test_link``, network.rs:261-269). Here the same model is data:

    clog    : bool[N,N]   directed link clogged (row = src, col = dst);
                          clogging a node = setting its row (out) / col (in)
    loss_q32: uint32      packet-loss probability, Q0.32 fixed point
    lat_lo/hi_ns          latency range, drawn uniformly per message
                          (reference default 1-10 ms, network.rs:87-89)

``route`` turns one (src, dst, two uint32 draws) into a delivery deadline +
deliver flag — the whole decision is a handful of vector ops, evaluated for
every in-flight message of every seed in lockstep.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from .rng import bounded, coin


class LinkState(NamedTuple):
    clog: jnp.ndarray  # bool[N, N]
    loss_q32: jnp.ndarray  # uint32 scalar
    lat_lo_ns: jnp.ndarray  # int64 scalar
    lat_hi_ns: jnp.ndarray  # int64 scalar


def make(
    num_nodes: int,
    loss_q32: int = 0,
    lat_lo_ns: int = 1_000_000,
    lat_hi_ns: int = 10_000_000,
) -> LinkState:
    return LinkState(
        clog=jnp.zeros((num_nodes, num_nodes), bool),
        loss_q32=jnp.asarray(loss_q32, jnp.uint32),
        lat_lo_ns=jnp.asarray(lat_lo_ns, jnp.int64),
        lat_hi_ns=jnp.asarray(lat_hi_ns, jnp.int64),
    )


def route(
    links: LinkState,
    now_ns: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    u_loss: jnp.ndarray,
    u_lat: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-message link test (ref ``test_link``): returns
    ``(deliver_time_ns, deliver)`` — dropped when the directed link is
    clogged or the loss draw fires."""
    clogged = links.clog[src, dst]
    lost = coin(u_loss, links.loss_q32)
    latency = bounded(u_lat, links.lat_lo_ns, links.lat_hi_ns + 1)
    return now_ns + latency, ~(clogged | lost)


def route_from(
    links: LinkState,
    now_ns: jnp.ndarray,
    src: jnp.ndarray,
    u_loss: jnp.ndarray,  # uint32[N]
    u_lat: jnp.ndarray,  # uint32[N]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Vectorized ``route`` for a broadcast: link-test src→every node at
    once. Returns ``(deliver_times[N], deliver[N])``."""
    clogged = links.clog[src, :]
    lost = coin(u_loss, links.loss_q32)
    latency = bounded(u_lat, links.lat_lo_ns, links.lat_hi_ns + 1)
    return now_ns + latency, ~(clogged | lost)


def clog_node(links: LinkState, node: jnp.ndarray) -> LinkState:
    """Clog both directions of a node (ref ``NetSim::clog_node``)."""
    n = links.clog.shape[0]
    idx = jnp.arange(n)
    mask = (idx[:, None] == node) | (idx[None, :] == node)
    return links._replace(clog=links.clog | mask)


def unclog_node(links: LinkState, node: jnp.ndarray) -> LinkState:
    n = links.clog.shape[0]
    idx = jnp.arange(n)
    mask = (idx[:, None] == node) | (idx[None, :] == node)
    return links._replace(clog=links.clog & ~mask)


def clog_link(links: LinkState, src: jnp.ndarray, dst: jnp.ndarray) -> LinkState:
    return links._replace(clog=links.clog.at[src, dst].set(True))


def unclog_link(links: LinkState, src: jnp.ndarray, dst: jnp.ndarray) -> LinkState:
    return links._replace(clog=links.clog.at[src, dst].set(False))
