"""Declarative fault campaigns: one ``FaultSpec``, two compilation targets.

MadSim's value in the FoundationDB tradition is *systematic* fault
injection — buggify points, clogs, kills (madsim/src/sim/net/mod.rs:163-284,
task/mod.rs:347-394). Before this subsystem each device model hand-rolled
its own crash/restart or partition plan in ``_init`` and the host tier
relied on manual ``Handle.kill`` calls; now both tiers compile the SAME
declarative spec:

- ``FaultSpec`` is a pure NamedTuple (hashable — it rides inside model
  configs, which are jit cache keys): crash/restart storms, partition/heal
  cycles over a node group, network-wide latency-spike and message-loss
  bursts, node pause/resume windows — plus the GRAY-failure families
  (docs/faults.md): asymmetric one-directional partitions, slow-disk
  fsync-stall windows, power-fail windows that drop unsynced writes, and
  per-node clock-skew windows.
- ``schedule_events(spec, num_nodes, key)`` is THE schedule derivation —
  seeded draws of fire times, durations and victims in a dedicated RNG
  namespace (disjoint from every model's init/event streams). The device
  tier evaluates it inside ``vmap``/``jit`` per seed; the host tier
  (``madsim_tpu.faults.compile_host``) evaluates the identical function
  eagerly for one seed, so the two tiers agree on the schedule *by
  construction* — and ``tests/test_faults.py`` asserts it end-to-end
  through the device engine's queue and dispatch machinery.
- ``compile_device`` packs the schedule into a fault event stream
  (``Emits``) any ``Workload`` splices into its initial event set; each
  event's payload carries ``(action, victim, t_lo, t_hi)`` where
  ``t = t_hi << 31 | t_lo`` is the exact scheduled deadline, so a traced
  replay (``replay.extract_fault_schedule``) recovers the schedule
  without the engine's dispatch jitter.
- ``FaultState`` + ``on_event`` are the shared in-loop interpreter:
  node-liveness/pause masks, per-victim partition refcounts, and
  refcounted latency/loss overrides on ``engine.net.LinkState``. Models
  keep only their *model-specific* crash/restart resets.

Restore semantics: latency/loss bursts save nothing at runtime — the
"off" transition restores the model's base values (``NetBase``, static
python ints from the model config), so overlapping bursts compose via the
refcount with no array state beyond two counters.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import net as enet
from .core import Emits
from .ops import get1, set1
from .rng import bounded, prob_to_q32

# fault action codes (payload slot 0 of a fault event)
F_CRASH = 0
F_RESTART = 1
F_PART = 2
F_HEAL = 3
F_SPIKE_ON = 4
F_SPIKE_OFF = 5
F_LOSS_ON = 6
F_LOSS_OFF = 7
F_PAUSE = 8
F_RESUME = 9
# gray-failure actions (one-directional partitions, slow disks, power
# loss, clock skew) — appended so existing codes/wire names stay stable
F_PART_IN = 10  # clog only the victim's INBOUND links
F_HEAL_IN = 11
F_PART_OUT = 12  # clog only the victim's OUTBOUND links
F_HEAL_OUT = 13
F_FSYNC_STALL = 14  # the victim's disk stops making writes durable
F_FSYNC_OK = 15  # ... and catches up (pending syncs apply)
F_POWER_FAIL = 16  # node loses power: dies AND unsynced writes drop
F_SKEW_ON = 17  # the victim's clock drifts: timers stretch
F_SKEW_OFF = 18

#: action code -> stable wire name (used by the host supervisor + replay)
ACTION_NAMES = (
    "crash",
    "restart",
    "partition",
    "heal",
    "spike_on",
    "spike_off",
    "loss_on",
    "loss_off",
    "pause",
    "resume",
    "part_in",
    "heal_in",
    "part_out",
    "heal_out",
    "fsync_stall",
    "fsync_ok",
    "power_fail",
    "skew_on",
    "skew_off",
)

#: stable wire name -> action code (the inverse, for literal schedules)
ACTION_CODES = {name: i for i, name in enumerate(ACTION_NAMES)}

# dedicated fold_in namespace for fault-schedule draws: disjoint from every
# model's init namespace (0x7FFF_FFFF) and from per-event counters (< 2**31
# in practice, but this constant is distinct regardless)
FAULT_STREAM = 0x5EED_FA17 & 0x7FFF_FFFF

Group = Tuple[int, int]  # victim range [lo, hi); hi = -1 means num_nodes


class FaultSpec(NamedTuple):
    """A declarative fault campaign (pure python ints/tuples — hashable,
    reprs stably, rides inside model configs as part of the jit key).

    Every category is a set of ``(start, end)`` windows: ``count`` pairs
    whose start times are drawn uniformly in ``[0, window_ns)`` and whose
    durations are drawn uniformly in ``[dur_lo_ns, dur_hi_ns)``. Victims
    are drawn from the category's node group ``[lo, hi)`` (``hi = -1``
    resolves to ``num_nodes`` at compile time)."""

    # crash/restart storms (down-time = restart delay)
    crashes: int = 0
    crash_window_ns: int = 5_000_000_000
    restart_lo_ns: int = 100_000_000
    restart_hi_ns: int = 1_000_000_000
    crash_group: Group = (0, -1)
    # partition/heal cycles (clog both directions of the victim node)
    partitions: int = 0
    part_window_ns: int = 3_000_000_000
    part_lo_ns: int = 500_000_000
    part_hi_ns: int = 2_000_000_000
    part_group: Group = (0, -1)
    # network-wide latency-spike bursts (override the base latency range)
    spikes: int = 0
    spike_window_ns: int = 3_000_000_000
    spike_dur_lo_ns: int = 200_000_000
    spike_dur_hi_ns: int = 1_000_000_000
    spike_lat_lo_ns: int = 1_000_000_000
    spike_lat_hi_ns: int = 5_000_000_000
    # network-wide message-loss bursts (override the base loss probability)
    losses: int = 0
    loss_window_ns: int = 3_000_000_000
    loss_dur_lo_ns: int = 200_000_000
    loss_dur_hi_ns: int = 1_000_000_000
    burst_loss_q32: int = prob_to_q32(0.5)
    # node pause/resume windows (clock-stop for the victim: no processing,
    # no state loss; host tier = ``Handle.pause``/``resume``)
    pauses: int = 0
    pause_window_ns: int = 3_000_000_000
    pause_lo_ns: int = 100_000_000
    pause_hi_ns: int = 1_000_000_000
    pause_group: Group = (0, -1)
    # -- gray failures (appended: old specs keep their field positions) --
    # asymmetric partitions: clog ONE direction of the victim's links; the
    # direction (in vs out) is part of the victim draw, so half the
    # windows are inbound-only and half outbound-only
    aparts: int = 0
    apart_window_ns: int = 3_000_000_000
    apart_lo_ns: int = 500_000_000
    apart_hi_ns: int = 2_000_000_000
    apart_group: Group = (0, -1)
    # slow-disk windows: while open, the victim's fsync defers — writes
    # stay volatile; the window's end applies pending syncs (host tier:
    # ``FsSim.stall_fsync``/``unstall_fsync``)
    fsync_stalls: int = 0
    fsync_window_ns: int = 3_000_000_000
    fsync_lo_ns: int = 500_000_000
    fsync_hi_ns: int = 2_000_000_000
    fsync_group: Group = (0, -1)
    # power-fail windows: the victim dies losing every unsynced write
    # (host tier: ``fs.power_fail`` + ``Handle.kill``) and restarts after
    # the drawn down-time
    power_fails: int = 0
    power_window_ns: int = 5_000_000_000
    power_lo_ns: int = 100_000_000
    power_hi_ns: int = 1_000_000_000
    power_group: Group = (0, -1)
    # clock-skew windows: the victim's virtual clock drifts slow — every
    # timer it arms stretches by skew_num/skew_den (device: models route
    # timer deadlines through ``skewed_delay``; host: ``time.sleep`` and
    # ``TimeHandle.node_skew`` consumers)
    skews: int = 0
    skew_window_ns: int = 3_000_000_000
    skew_lo_ns: int = 500_000_000
    skew_hi_ns: int = 2_000_000_000
    skew_group: Group = (0, -1)
    skew_num: int = 3
    skew_den: int = 2


class FixedFaults(NamedTuple):
    """A LITERAL fault schedule — the seedless counterpart of ``FaultSpec``.

    ``events`` is a tuple of ``(time_ns, action_name, victim)`` triples —
    the exact wire format ``replay.extract_fault_schedule`` and
    ``madsim_tpu.faults.compile_host`` emit, so a recorded or shrunk
    schedule (explore/shrink.py) drops straight back into any model's
    ``faults=`` config slot and replays with NO randomness: the schedule
    derivation returns the literal events for every seed. Still a pure
    NamedTuple of python values (hashable, jit-key-safe). The three
    override fields carry what burst "on" transitions need — the same
    values ``FaultSpec`` carries — since a literal schedule has no spec
    to read them from.
    """

    events: Tuple[Tuple[int, str, int], ...] = ()
    spike_lat_lo_ns: int = 1_000_000_000
    spike_lat_hi_ns: int = 5_000_000_000
    burst_loss_q32: int = prob_to_q32(0.5)
    skew_num: int = 3
    skew_den: int = 2


# -- spec-as-data: the campaign envelope --------------------------------------
#
# A mutated campaign candidate used to be a NEW static spec and therefore
# a NEW jit cache key: every candidate paid the full sweep compile
# (~18-22 s on TPU) for ~0.4 s of run time. The envelope inverts that:
# the STATIC jit key is only the per-family schedule CAPACITY (row
# shapes), and the concrete spec rides in as traced data (``FaultParams``)
# — one compiled sweep program serves every candidate the envelope covers.

# fixed family order — matches ``_categories`` and the explore mutator's
# ``_COUNT_FIELDS`` (explore/campaign.py)
FAMILIES = (
    "crashes", "partitions", "spikes", "losses", "pauses",
    "aparts", "fsync_stalls", "power_fails", "skews",
)
N_FAMILIES = len(FAMILIES)
_F_APART = FAMILIES.index("aparts")
_F_FSYNC = FAMILIES.index("fsync_stalls")
_F_SKEW = FAMILIES.index("skews")

# (window, dur_lo, dur_hi, group) spec fields per family; group None =
# the network-wide burst families (victim range [0, 1), like _categories)
_FAMILY_FIELDS = (
    ("crash_window_ns", "restart_lo_ns", "restart_hi_ns", "crash_group"),
    ("part_window_ns", "part_lo_ns", "part_hi_ns", "part_group"),
    ("spike_window_ns", "spike_dur_lo_ns", "spike_dur_hi_ns", None),
    ("loss_window_ns", "loss_dur_lo_ns", "loss_dur_hi_ns", None),
    ("pause_window_ns", "pause_lo_ns", "pause_hi_ns", "pause_group"),
    ("apart_window_ns", "apart_lo_ns", "apart_hi_ns", "apart_group"),
    ("fsync_window_ns", "fsync_lo_ns", "fsync_hi_ns", "fsync_group"),
    ("power_window_ns", "power_lo_ns", "power_hi_ns", "power_group"),
    ("skew_window_ns", "skew_lo_ns", "skew_hi_ns", "skew_group"),
)
# (on, off) action codes per family; the apart pair is resolved per
# window from the victim draw's direction bit, exactly like _categories
_FAMILY_ACTIONS = (
    (F_CRASH, F_RESTART),
    (F_PART, F_HEAL),
    (F_SPIKE_ON, F_SPIKE_OFF),
    (F_LOSS_ON, F_LOSS_OFF),
    (F_PAUSE, F_RESUME),
    ((F_PART_IN, F_PART_OUT), (F_HEAL_IN, F_HEAL_OUT)),
    (F_FSYNC_STALL, F_FSYNC_OK),
    (F_POWER_FAIL, F_RESTART),
    (F_SKEW_ON, F_SKEW_OFF),
)


class FaultEnvelope(NamedTuple):
    """The STATIC shape of a fault campaign — the jit cache key of the
    spec-as-data path (docs/faults.md "Spec-as-data and the campaign
    envelope").

    ``maxima[f]`` is the padded window-pair capacity of family ``f`` (in
    ``FAMILIES`` order); ``fixed`` is the row capacity for literal
    ``FixedFaults`` schedules. Any concrete spec whose counts fit the
    envelope compiles to ``FaultParams`` (``spec_to_params``) and runs
    through the ONE sweep program compiled for this envelope — a mutated
    campaign candidate, a differential-grid spec, or a shrink
    re-verification of compatible width costs zero recompiles."""

    maxima: Tuple[int, ...] = (0,) * N_FAMILIES
    fixed: int = 0


# static (it IS the jit key): contributes no traced leaves when it rides
# inside a pytree like FaultRt-carrying workload state or a jit argument
jax.tree_util.register_static(FaultEnvelope)


class FaultRt(NamedTuple):
    """The RUNTIME override scalars of one candidate spec — the traced
    counterpart of the ``FaultSpec`` fields ``on_event``/``skewed_delay``
    read at event time. Models on the envelope path carry one per lane in
    their workload state and hand it to the interpreter in place of the
    static spec (the reads are duck-typed: both carry the same names)."""

    spike_lat_lo_ns: jnp.ndarray  # int64 ()
    spike_lat_hi_ns: jnp.ndarray  # int64 ()
    burst_loss_q32: jnp.ndarray  # uint32 ()
    skew_num: jnp.ndarray  # int64 ()
    skew_den: jnp.ndarray  # int64 ()


class FaultParams(NamedTuple):
    """One concrete fault campaign as DATA (a pytree of arrays) — what a
    ``FaultEnvelope``-keyed program consumes instead of recompiling.

    Per-family arrays are indexed in ``FAMILIES`` order; rows beyond
    ``counts[f]`` are enable-masked out of the emit stream. ``fx_*``
    carry a literal ``FixedFaults`` schedule padded to the envelope's
    ``fixed`` capacity. Build with ``spec_to_params``; batch per lane
    with ``tile_params``/``stack_params``."""

    counts: jnp.ndarray  # int32[N_FAMILIES] actual window pairs
    windows: jnp.ndarray  # int64[N_FAMILIES] start-draw window
    dur_lo: jnp.ndarray  # int64[N_FAMILIES]
    dur_hi: jnp.ndarray  # int64[N_FAMILIES]
    vic_lo: jnp.ndarray  # int32[N_FAMILIES] resolved group lo
    vic_hi: jnp.ndarray  # int32[N_FAMILIES] resolved group hi (exclusive)
    fx_times: jnp.ndarray  # int64[fixed] literal schedule rows
    fx_actions: jnp.ndarray  # int32[fixed]
    fx_victims: jnp.ndarray  # int32[fixed]
    fx_count: jnp.ndarray  # int32 () valid literal rows
    rt: FaultRt


def campaign_envelope(
    *specs, mutation_cap: int = 0, fixed: int = 0
) -> FaultEnvelope:
    """The envelope covering every given ``FaultSpec`` plus headroom:
    per-family capacity is the max over the specs' counts and
    ``mutation_cap`` (the explore mutator passes its ``_MAX_PHASES``
    clamp, so every reachable mutation of the corpus fits)."""
    maxima = [mutation_cap] * N_FAMILIES
    for spec in specs:
        if isinstance(spec, FixedFaults):
            fixed = max(fixed, len(spec.events))
            continue
        for i, f in enumerate(FAMILIES):
            maxima[i] = max(maxima[i], getattr(spec, f))
    return FaultEnvelope(maxima=tuple(maxima), fixed=fixed)


def spec_to_params(spec, envelope: FaultEnvelope, num_nodes: int) -> FaultParams:
    """Compile one concrete spec (``FaultSpec`` or ``FixedFaults``) to
    the envelope's data layout — host-side numpy, so validation (group
    resolution, capacity fit) happens eagerly, before any tracing.

    The derivation consuming these params (``schedule_events_padded``)
    produces the BIT-IDENTICAL ``(time_ns, action, victim)`` schedule
    the static path produces for the same ``(spec, seed)`` — asserted
    per family in tests/test_fault_params.py."""
    counts = np.zeros((N_FAMILIES,), np.int32)
    windows = np.ones((N_FAMILIES,), np.int64)
    dur_lo = np.zeros((N_FAMILIES,), np.int64)
    dur_hi = np.ones((N_FAMILIES,), np.int64)
    vic_lo = np.zeros((N_FAMILIES,), np.int32)
    vic_hi = np.ones((N_FAMILIES,), np.int32)
    fx_times = np.zeros((envelope.fixed,), np.int64)
    fx_actions = np.zeros((envelope.fixed,), np.int32)
    fx_victims = np.zeros((envelope.fixed,), np.int32)
    fx_count = np.int32(0)
    if isinstance(spec, FixedFaults):
        e = len(spec.events)
        if e > envelope.fixed:
            raise ValueError(
                f"FixedFaults schedule of {e} events exceeds the "
                f"envelope's fixed capacity {envelope.fixed}"
            )
        for i, (t, action, vic) in enumerate(spec.events):
            if action not in ACTION_CODES:
                raise ValueError(f"unknown fault action {action!r}")
            if not 0 <= vic < num_nodes:
                raise ValueError(
                    f"victim {vic} outside [0, {num_nodes}) in fixed "
                    f"schedule event {(t, action, vic)!r}"
                )
            fx_times[i] = t
            fx_actions[i] = ACTION_CODES[action]
            fx_victims[i] = vic
        fx_count = np.int32(e)
    else:
        for i, (fam, fields) in enumerate(zip(FAMILIES, _FAMILY_FIELDS)):
            count = getattr(spec, fam)
            if count > envelope.maxima[i]:
                raise ValueError(
                    f"spec draws {count} {fam} windows but the envelope "
                    f"caps the family at {envelope.maxima[i]}"
                )
            win_f, lo_f, hi_f, group_f = fields
            counts[i] = count
            windows[i] = getattr(spec, win_f)
            dur_lo[i] = getattr(spec, lo_f)
            dur_hi[i] = getattr(spec, hi_f)
            if group_f is None:
                vic_lo[i], vic_hi[i] = 0, 1
            else:
                # validate eagerly even for count-0 families, exactly
                # like the static derivation's _resolve_group does
                vic_lo[i], vic_hi[i] = _resolve_group(
                    getattr(spec, group_f), num_nodes, fam
                )
    return FaultParams(
        counts=counts,
        windows=windows,
        dur_lo=dur_lo,
        dur_hi=dur_hi,
        vic_lo=vic_lo,
        vic_hi=vic_hi,
        fx_times=fx_times,
        fx_actions=fx_actions,
        fx_victims=fx_victims,
        fx_count=fx_count,
        rt=FaultRt(
            spike_lat_lo_ns=np.int64(spec.spike_lat_lo_ns),
            spike_lat_hi_ns=np.int64(spec.spike_lat_hi_ns),
            burst_loss_q32=np.uint32(spec.burst_loss_q32),
            skew_num=np.int64(spec.skew_num),
            skew_den=np.int64(spec.skew_den),
        ),
    )


def tile_params(params: FaultParams, n: int) -> FaultParams:
    """Broadcast ONE candidate's params to an ``n``-lane batch (every
    sweep lane carries its candidate's params, so the candidate axis
    vmaps exactly like the seed axis)."""
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a), (n,) + np.shape(a)), params
    )


def stack_params(params_list) -> FaultParams:
    """Stack K candidates' params into one batch, leading axis K."""
    return jax.tree.map(lambda *ls: np.stack(ls), *params_list)


def grid_params(params_list, lanes: int) -> FaultParams:
    """The (candidate x seed) grid layout: each of the K candidates'
    params tiled over ``lanes`` seed lanes, concatenated to one flat
    ``K * lanes`` batch — candidate k owns lanes ``[k*lanes, (k+1)*lanes)``,
    matching a seed vector built by ``np.tile(seed_range, K)``."""
    return jax.tree.map(
        lambda *ls: np.concatenate(
            [np.broadcast_to(np.asarray(a), (lanes,) + np.shape(a)) for a in ls]
        ),
        *params_list,
    )


def runtime_spec(spec, frt):
    """The spec VIEW the in-loop interpreter should read values from:
    the static spec itself on the legacy path, the per-lane ``FaultRt``
    carried in workload state on the envelope path. Models call this in
    every fault-reading handler so both paths share one code line."""
    return frt if isinstance(spec, FaultEnvelope) else spec


def make_rt(spec, params: Optional[FaultParams]):
    """The workload-state ``frt`` slot for a model config: the traced
    override scalars on the envelope path, a leafless placeholder on the
    legacy path (costs nothing in the loop carry)."""
    if isinstance(spec, FaultEnvelope):
        if params is None:
            raise ValueError(
                "workload config carries a FaultEnvelope; the sweep needs "
                "per-lane FaultParams (pass params= through run_sweep — "
                "build them with spec_to_params + tile_params)"
            )
        return params.rt
    return ()


# -- threefry at explicit counters (the padded derivation's RNG) -------------
#
# The engine pins ``jax_threefry_partitionable`` (engine/__init__.py), so
# ``jax.random.bits(key, (s,), uint32)`` is element-wise in the counter:
# bits[i] = lane0 ^ lane1 of threefry-2x32(key, (hi32(i), lo32(i))) —
# independent of s. The padded derivation exploits exactly that: it
# evaluates the hash at explicit indices (a RUNTIME function of the
# candidate's actual window counts), reproducing the static path's draw
# stream bit for bit from inside one compiled program of envelope shape.

_THREEFRY_ROT = (13, 15, 26, 6, 17, 29, 16, 24)


def _threefry2x32(k0, k1, x0, x1):
    """Pure-jnp Threefry-2x32 (20 rounds), bit-identical to jax's
    ``threefry2x32`` kernel (validated against ``jax.random.bits`` in
    tests/test_fault_params.py)."""
    k0 = jnp.asarray(k0, jnp.uint32)
    k1 = jnp.asarray(k1, jnp.uint32)
    ks2 = k0 ^ k1 ^ jnp.uint32(0x1BD11BDA)
    x0 = jnp.asarray(x0, jnp.uint32) + k0
    x1 = jnp.asarray(x1, jnp.uint32) + k1
    ks = (k1, ks2, k0)

    def rotl(v, r):
        return (v << r) | (v >> (32 - r))

    for i in range(5):
        for j in range(4):
            r = _THREEFRY_ROT[(i % 2) * 4 + j]
            x0 = x0 + x1
            x1 = rotl(x1, r) ^ x0
        x0 = x0 + ks[i % 3]
        x1 = x1 + ks[(i + 1) % 3] + jnp.uint32(i + 1)
    return x0, x1


def bits_at(key: jax.Array, idx):
    """``jax.random.bits(key, (s,), uint32)[idx]`` for any ``s > idx``,
    with RUNTIME ``idx`` — the primitive that lets one compiled program
    reproduce the draw stream of every spec shape. Well-defined because
    the engine pins the partitionable threefry counter scheme, under
    which draw ``i`` is a pure function of ``(key, i)`` (validated
    against ``jax.random.bits`` in tests/test_fault_params.py)."""
    kd = jax.random.key_data(key)
    idx = jnp.asarray(idx, jnp.uint32)
    o0, o1 = _threefry2x32(kd[0], kd[1], jnp.zeros_like(idx), idx)
    return o0 ^ o1


def schedule_events_padded(
    envelope: FaultEnvelope, params: FaultParams, num_nodes: int, key: jax.Array
):
    """The schedule derivation of the spec-as-data path: ``(times
    int64[E], actions int32[E], victims int32[E], enables bool[E])``
    with ``E = num_events(envelope)`` STATIC rows, of which exactly the
    candidate's real events are enabled.

    Contract: the enabled rows, in order, equal ``schedule_events(spec,
    num_nodes, key)`` bit for bit (same draws, same pair order) — the
    device↔host differential from PR 1 holds through the padded path
    unchanged, and disabled rows never reach the queue (``push_many``
    assigns slots to enabled emits only), so the engine dispatches the
    identical event sequence."""
    pmax = sum(envelope.maxima)
    if pmax:
        # static per-row family metadata (row j = r-th padded window of
        # family fam[j]); runtime pair index = actual windows of earlier
        # families + r, so active rows draw at the exact indices the
        # dense static derivation would
        fam = np.repeat(np.arange(N_FAMILIES), envelope.maxima)
        row = np.concatenate([np.arange(m) for m in envelope.maxima])
        base = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(params.counts)]
        )
        pair = base[fam] + jnp.asarray(row, jnp.int32)
        active = jnp.asarray(row, jnp.int32) < params.counts[fam]
        fkey = jax.random.fold_in(key, FAULT_STREAM)
        # masked rows hash a harmless counter (their draws are never
        # used — enables=False keeps them out of the queue entirely)
        i3 = jnp.where(active, 3 * pair, 0)
        r_start = bits_at(fkey, i3)
        r_dur = bits_at(fkey, i3 + 1)
        r_vic = bits_at(fkey, i3 + 2)

        t0 = bounded(r_start, 0, params.windows[fam])
        dur = bounded(r_dur, params.dur_lo[fam], params.dur_hi[fam])
        vlo = params.vic_lo[fam]
        vhi = params.vic_hi[fam]
        directional = jnp.asarray(fam == _F_APART)
        d = bounded(r_vic, 0, 2 * (vhi - vlo))
        vic = jnp.where(
            directional,
            (vlo + (d >> 1)).astype(jnp.int32),
            bounded(r_vic, vlo, vhi).astype(jnp.int32),
        )
        out_dir = directional & ((d & 1) == 1)
        on_code = np.asarray(
            [a if not isinstance(a, tuple) else a[0] for a, _ in _FAMILY_ACTIONS],
            np.int32,
        )
        off_code = np.asarray(
            [a if not isinstance(a, tuple) else a[0] for _, a in _FAMILY_ACTIONS],
            np.int32,
        )
        on = jnp.where(out_dir, jnp.int32(F_PART_OUT), on_code[fam])
        off = jnp.where(out_dir, jnp.int32(F_HEAL_OUT), off_code[fam])
        # interleave (on, off) per pair — the static path's row order
        times = jnp.stack([t0, t0 + dur], axis=1).reshape(2 * pmax)
        actions = jnp.stack([on, off], axis=1).reshape(2 * pmax)
        victims = jnp.stack([vic, vic], axis=1).reshape(2 * pmax)
        enables = jnp.repeat(active, 2)
    else:
        times = jnp.zeros((0,), jnp.int64)
        actions = jnp.zeros((0,), jnp.int32)
        victims = jnp.zeros((0,), jnp.int32)
        enables = jnp.zeros((0,), bool)
    if envelope.fixed:
        fx_on = jnp.arange(envelope.fixed, dtype=jnp.int32) < params.fx_count
        times = jnp.concatenate([times, jnp.asarray(params.fx_times, jnp.int64)])
        actions = jnp.concatenate([actions, jnp.asarray(params.fx_actions, jnp.int32)])
        victims = jnp.concatenate([victims, jnp.asarray(params.fx_victims, jnp.int32)])
        enables = jnp.concatenate([enables, fx_on])
    return times, actions, victims, enables


def num_events(spec) -> int:
    """Static event count of the compiled campaign (every ``FaultSpec``
    category contributes an on/off pair per window; a ``FixedFaults``
    schedule is its literal length; a ``FaultEnvelope`` is its padded
    capacity — the emit-stream SHAPE one compiled program serves)."""
    if isinstance(spec, FixedFaults):
        return len(spec.events)
    if isinstance(spec, FaultEnvelope):
        return 2 * sum(spec.maxima) + spec.fixed
    return 2 * (
        spec.crashes
        + spec.partitions
        + spec.spikes
        + spec.losses
        + spec.pauses
        + spec.aparts
        + spec.fsync_stalls
        + spec.power_fails
        + spec.skews
    )


def _resolve_group(group: Group, num_nodes: int, what: str) -> Tuple[int, int]:
    lo, hi = group
    if hi < 0:
        hi = num_nodes
    if not 0 <= lo < hi <= num_nodes:
        raise ValueError(
            f"{what} group {group} does not resolve to a non-empty node "
            f"range within [0, {num_nodes})"
        )
    return lo, hi


def _categories(spec: FaultSpec, num_nodes: int):
    """(count, on_action, off_action, window, dur_lo, dur_hi, vic_lo,
    vic_hi) per category, in the fixed draw order. The asymmetric
    category's actions are ``(in, out)`` PAIRS — the direction rides in
    the victim draw's low bit (see ``schedule_events``)."""
    return (
        (
            spec.crashes, F_CRASH, F_RESTART, spec.crash_window_ns,
            spec.restart_lo_ns, spec.restart_hi_ns,
            *_resolve_group(spec.crash_group, num_nodes, "crash"),
        ),
        (
            spec.partitions, F_PART, F_HEAL, spec.part_window_ns,
            spec.part_lo_ns, spec.part_hi_ns,
            *_resolve_group(spec.part_group, num_nodes, "partition"),
        ),
        (
            spec.spikes, F_SPIKE_ON, F_SPIKE_OFF, spec.spike_window_ns,
            spec.spike_dur_lo_ns, spec.spike_dur_hi_ns, 0, 1,
        ),
        (
            spec.losses, F_LOSS_ON, F_LOSS_OFF, spec.loss_window_ns,
            spec.loss_dur_lo_ns, spec.loss_dur_hi_ns, 0, 1,
        ),
        (
            spec.pauses, F_PAUSE, F_RESUME, spec.pause_window_ns,
            spec.pause_lo_ns, spec.pause_hi_ns,
            *_resolve_group(spec.pause_group, num_nodes, "pause"),
        ),
        (
            spec.aparts, (F_PART_IN, F_PART_OUT), (F_HEAL_IN, F_HEAL_OUT),
            spec.apart_window_ns, spec.apart_lo_ns, spec.apart_hi_ns,
            *_resolve_group(spec.apart_group, num_nodes, "apart"),
        ),
        (
            spec.fsync_stalls, F_FSYNC_STALL, F_FSYNC_OK,
            spec.fsync_window_ns, spec.fsync_lo_ns, spec.fsync_hi_ns,
            *_resolve_group(spec.fsync_group, num_nodes, "fsync"),
        ),
        (
            spec.power_fails, F_POWER_FAIL, F_RESTART,
            spec.power_window_ns, spec.power_lo_ns, spec.power_hi_ns,
            *_resolve_group(spec.power_group, num_nodes, "power"),
        ),
        (
            spec.skews, F_SKEW_ON, F_SKEW_OFF, spec.skew_window_ns,
            spec.skew_lo_ns, spec.skew_hi_ns,
            *_resolve_group(spec.skew_group, num_nodes, "skew"),
        ),
    )


def schedule_events(spec, num_nodes: int, key: jax.Array):
    """The shared schedule derivation: ``(times int64[E], actions int32[E],
    victims int32[E])`` in pair order (NOT time-sorted — the device queue
    orders by time at dispatch; the host supervisor sorts).

    Draw layout: per window pair i (in category order) the draws are
    ``rand[3i] = start``, ``rand[3i+1] = duration``, ``rand[3i+2] =
    victim`` — a fixed layout so adding windows to one category never
    shifts another category's draws within the pair sequence.

    A ``FixedFaults`` spec bypasses the draws entirely: the literal
    events come back seed-independently (``key`` is unused), which is
    what lets a shrunk schedule replay identically under any seed."""
    if isinstance(spec, FixedFaults):
        for t, action, vic in spec.events:
            if action not in ACTION_CODES:
                raise ValueError(f"unknown fault action {action!r}")
            if not 0 <= vic < num_nodes:
                raise ValueError(
                    f"victim {vic} outside [0, {num_nodes}) in fixed "
                    f"schedule event {(t, action, vic)!r}"
                )
        e = len(spec.events)
        return (
            jnp.asarray([t for t, _, _ in spec.events], jnp.int64).reshape(e),
            jnp.asarray(
                [ACTION_CODES[a] for _, a, _ in spec.events], jnp.int32
            ).reshape(e),
            jnp.asarray([v for _, _, v in spec.events], jnp.int32).reshape(e),
        )
    e = num_events(spec)
    if e == 0:
        return (
            jnp.zeros((0,), jnp.int64),
            jnp.zeros((0,), jnp.int32),
            jnp.zeros((0,), jnp.int32),
        )
    rand = jax.random.bits(
        jax.random.fold_in(key, FAULT_STREAM), (3 * (e // 2),), dtype=jnp.uint32
    )
    times, actions, victims = [], [], []
    i = 0
    for count, a_on, a_off, window, dlo, dhi, vlo, vhi in _categories(
        spec, num_nodes
    ):
        for _ in range(count):
            t0 = bounded(rand[3 * i], 0, window)
            dur = bounded(rand[3 * i + 1], dlo, dhi)
            if isinstance(a_on, tuple):
                # directional category: the victim draw covers twice the
                # node range; the low bit picks in vs out, so the draw
                # budget stays at the fixed 3 per window pair
                d = bounded(rand[3 * i + 2], 0, 2 * (vhi - vlo))
                vic = (vlo + (d >> 1)).astype(jnp.int32)
                out = (d & 1) == 1
                on = jnp.where(out, a_on[1], a_on[0]).astype(jnp.int32)
                off = jnp.where(out, a_off[1], a_off[0]).astype(jnp.int32)
            else:
                vic = bounded(rand[3 * i + 2], vlo, vhi).astype(jnp.int32)
                on = jnp.asarray(a_on, jnp.int32)
                off = jnp.asarray(a_off, jnp.int32)
            times += [t0, t0 + dur]
            actions += [on, off]
            victims += [vic, vic]
            i += 1
    return jnp.stack(times), jnp.stack(actions), jnp.stack(victims)


def compile_device(
    spec,  # FaultSpec | FixedFaults | FaultEnvelope (with params)
    num_nodes: int,
    key: jax.Array,
    fault_kind: int,
    payload_slots: int,
    params: Optional[FaultParams] = None,
) -> Emits:
    """Compile the campaign into a fault event stream a model splices into
    its initial event set. Payload layout: ``(action, victim, t_lo, t_hi)``
    with ``t = t_hi << 31 | t_lo`` the exact scheduled deadline (both
    halves non-negative int32, so no sign-wrap ambiguity).

    A ``FaultEnvelope`` spec compiles the candidate carried in ``params``
    through the padded derivation: the emit stream has the envelope's
    STATIC row count with the unused rows enable-masked. The enabled
    rows are COMPACTED to the front (stable, original order) before
    packing: ``push_many`` maps emit index -> free-slot rank and
    ``pop_min`` breaks equal-time ties by a slot-index hash, so only a
    hole-free stream occupies the exact slots the dense static path's
    would — compaction is what upgrades "same events" to "bit-identical
    dispatch order" even on time ties (FixedFaults schedules place them
    deliberately)."""
    if payload_slots < 4:
        raise ValueError(
            f"fault events need 4 payload slots (action, victim, t_lo, "
            f"t_hi); the workload has {payload_slots}"
        )
    if isinstance(spec, FaultEnvelope):
        if params is None:
            raise ValueError(
                "compiling a FaultEnvelope needs the candidate's "
                "FaultParams (spec_to_params)"
            )
        times, actions, victims, enables = schedule_events_padded(
            spec, params, num_nodes, key
        )
        order = jnp.argsort(~enables, stable=True)  # enabled first
        times = times[order]
        actions = actions[order]
        victims = victims[order]
        enables = enables[order]
    else:
        times, actions, victims = schedule_events(spec, num_nodes, key)
        enables = jnp.ones((int(times.shape[0]),), bool)
    e = int(times.shape[0])
    pays = jnp.zeros((e, payload_slots), jnp.int32)
    if e:
        pays = pays.at[:, 0].set(actions)
        pays = pays.at[:, 1].set(victims)
        pays = pays.at[:, 2].set((times & 0x7FFF_FFFF).astype(jnp.int32))
        pays = pays.at[:, 3].set((times >> 31).astype(jnp.int32))
    return Emits(
        times=times,
        kinds=jnp.full((e,), fault_kind, jnp.int32),
        pays=pays,
        enables=enables,
    )


def decode_time(t_lo, t_hi):
    """Recover the scheduled deadline from a fault event payload."""
    return (jnp.asarray(t_hi, jnp.int64) << 31) | jnp.asarray(t_lo, jnp.int64)


class NetBase(NamedTuple):
    """The model's base network parameters (static python ints) — what a
    burst's "off" transition restores, so no runtime save is needed."""

    lat_lo_ns: int
    lat_hi_ns: int
    loss_q32: int


class FaultState(NamedTuple):
    """Per-seed interpreter state for the compiled campaign — the shared
    piece of every model's workload state.

    Partition refcounts are PER DIRECTION: a symmetric ``partition``
    holds both of its victim's directions, an asymmetric ``part_in`` /
    ``part_out`` holds exactly one — so a symmetric heal can never
    un-clog a direction an overlapping asymmetric window still holds
    (and vice versa). A direction is clogged iff its count is > 0."""

    alive: jnp.ndarray  # bool[N]
    paused: jnp.ndarray  # bool[N]
    part_in_cnt: jnp.ndarray  # int32[N] inbound-clog refcount
    part_out_cnt: jnp.ndarray  # int32[N] outbound-clog refcount
    fsync_cnt: jnp.ndarray  # int32[N] slow-disk (fsync-stall) refcount
    skew_cnt: jnp.ndarray  # int32[N] clock-skew refcount
    spike_cnt: jnp.ndarray  # int32 latency-burst refcount
    loss_cnt: jnp.ndarray  # int32 loss-burst refcount


class FaultEdges(NamedTuple):
    """The transitions one fault event ACTUALLY caused, gated exactly the
    way the host supervisor gates its ``Handle`` calls
    (``faults.apply_schedule``): killing a dead node, restarting a live
    one, and pausing/resuming a dead or already-paused/unpaused node are
    all no-edges. Models key their model-specific consequences (state
    wipes, timer-chain re-arms) off these booleans instead of re-deriving
    them, so the host-mirror semantics stay single-sourced."""

    crashed: jnp.ndarray  # bool: a live victim died (crash OR power_fail;
    # both roll durable state back to the synced frontier — models with a
    # durability plane key the rollback off this edge)
    restarted: jnp.ndarray  # bool: a dead victim revived
    paused: jnp.ndarray  # bool: a live, running victim paused
    resumed: jnp.ndarray  # bool: a live, paused victim resumed


def init_state(num_nodes: int) -> FaultState:
    return FaultState(
        alive=jnp.ones((num_nodes,), bool),
        paused=jnp.zeros((num_nodes,), bool),
        part_in_cnt=jnp.zeros((num_nodes,), jnp.int32),
        part_out_cnt=jnp.zeros((num_nodes,), jnp.int32),
        fsync_cnt=jnp.zeros((num_nodes,), jnp.int32),
        skew_cnt=jnp.zeros((num_nodes,), jnp.int32),
        spike_cnt=jnp.zeros((), jnp.int32),
        loss_cnt=jnp.zeros((), jnp.int32),
    )


def up(f: FaultState) -> jnp.ndarray:
    """bool[N]: node is processing events (alive and not paused)."""
    return f.alive & ~f.paused


def stalled(f: FaultState) -> jnp.ndarray:
    """bool[N]: node's disk is inside a slow-disk window (fsync defers).
    Models gate their durability plane on this: while True, the synced
    shadow of durable state freezes; the window's end catches it up."""
    return f.fsync_cnt > 0


def can_skew(spec) -> bool:
    """Whether the (static, trace-time) spec can ever open a skew
    window. Gates ``skewed_delay`` off entirely for skew-free specs.
    An envelope gates per CAMPAIGN: the identity optimization applies
    iff no candidate the envelope covers can draw a skew window."""
    if isinstance(spec, FixedFaults):
        return any(a in ("skew_on", "skew_off") for _, a, _ in spec.events)
    if isinstance(spec, FaultEnvelope):
        return spec.maxima[_F_SKEW] > 0 or spec.fixed > 0
    return spec.skews > 0


def can_stall(spec) -> bool:
    """Whether the (static, trace-time) spec can ever open a slow-disk
    window — the gate for model durability shadows (e.g. raft's, which
    go width-0 for stall-free specs). Like ``can_skew``, an envelope
    decides this once per campaign, not per candidate."""
    if isinstance(spec, FixedFaults):
        return any(a == "fsync_stall" for _, a, _ in spec.events)
    if isinstance(spec, FaultEnvelope):
        return spec.maxima[_F_FSYNC] > 0 or spec.fixed > 0
    return spec.fsync_stalls > 0


def skewed_delay(spec, f: FaultState, node, delay_ns, rt=None):
    """A timer interval as the (possibly skewed) victim's clock measures
    it: while ``node`` is inside a clock-skew window its timers stretch
    by ``spec.skew_num / spec.skew_den`` (both ``FaultSpec`` and
    ``FixedFaults`` carry the ratio). Models route every node-owned
    timer re-arm through this — the device half of the host tier's
    ``time.node_skew`` (docs/faults.md gray failures). Statically an
    identity when the spec draws no skew windows (``skew_cnt`` is
    provably zero then), so the common case pays nothing.

    ``rt`` supplies the ratio on the spec-as-data path (``spec`` is then
    the envelope — the static gate — and the values are per-lane traced
    scalars, ``runtime_spec``'s result)."""
    d = jnp.asarray(delay_ns, jnp.int64)
    if not can_skew(spec):
        return d
    v = spec if rt is None else rt
    slow = get1(f.skew_cnt, node) > 0
    return jnp.where(slow, d * v.skew_num // v.skew_den, d)


def on_event(
    spec,  # FaultSpec | FixedFaults (both carry the burst override fields)
    base: NetBase,
    links: enet.LinkState,
    f: FaultState,
    action: jnp.ndarray,
    victim: jnp.ndarray,
):
    """Apply one fault event to the shared state; returns ``(links,
    fstate, edges)``. Model-specific consequences (wiping volatile state
    on crash, re-arming timer chains on restart/resume) stay in the
    model's fault handler, keyed off the returned ``FaultEdges``.

    Partition and burst transitions are refcounted so overlapping windows
    compose exactly: only the 0→1 edge applies and only the 1→0 edge
    restores (same discipline the etcd model used for its private
    partition plan)."""
    is_crash = (action == F_CRASH) | (action == F_POWER_FAIL)
    is_restart = action == F_RESTART
    is_part = action == F_PART
    is_heal = action == F_HEAL
    is_spike_on = action == F_SPIKE_ON
    is_spike_off = action == F_SPIKE_OFF
    is_loss_on = action == F_LOSS_ON
    is_loss_off = action == F_LOSS_OFF
    is_pause = action == F_PAUSE
    is_resume = action == F_RESUME

    was_alive = get1(f.alive, victim)
    was_paused = get1(f.paused, victim)
    edges = FaultEdges(
        crashed=is_crash & was_alive,
        restarted=is_restart & ~was_alive,
        paused=is_pause & was_alive & ~was_paused,
        resumed=is_resume & was_alive & was_paused,
    )
    alive = set1(f.alive, victim, False, is_crash)
    alive = set1(alive, victim, True, is_restart)
    # mirror the host supervisor exactly (faults.apply_schedule): a kill
    # clears a pause (the node's tasks are gone — its restart revives it
    # running), and pausing/resuming a dead node is a no-op
    paused = set1(f.paused, victim, False, is_crash)
    paused = set1(paused, victim, True, is_pause & was_alive)
    paused = set1(paused, victim, False, is_resume & was_alive)

    # partitions, per direction (ref NetSim::clog_node_in/out): a
    # symmetric partition holds BOTH of the victim's directions, an
    # asymmetric window exactly one. The clog matrix is DERIVED from the
    # refcounts — clog[s, d] iff s's outbound or d's inbound count is
    # held — so overlapping symmetric/asymmetric windows of the same OR
    # different victims compose exactly (a heal can never un-clog a cell
    # any other live window still holds; the old incremental clog_node
    # masks could, for two victims sharing a link cell)
    inc_in = is_part | (action == F_PART_IN)
    dec_in = is_heal | (action == F_HEAL_IN)
    inc_out = is_part | (action == F_PART_OUT)
    dec_out = is_heal | (action == F_HEAL_OUT)
    in_cnt = get1(f.part_in_cnt, victim)
    out_cnt = get1(f.part_out_cnt, victim)
    part_in_cnt = set1(f.part_in_cnt, victim, in_cnt + 1, inc_in)
    part_in_cnt = set1(part_in_cnt, victim, jnp.maximum(in_cnt - 1, 0), dec_in)
    part_out_cnt = set1(f.part_out_cnt, victim, out_cnt + 1, inc_out)
    part_out_cnt = set1(
        part_out_cnt, victim, jnp.maximum(out_cnt - 1, 0), dec_out
    )
    touched = inc_in | dec_in | inc_out | dec_out
    derived = (part_out_cnt > 0)[:, None] | (part_in_cnt > 0)[None, :]
    links = links._replace(clog=jnp.where(touched, derived, links.clog))

    # slow-disk and clock-skew windows: plain per-victim refcounts; the
    # consequences live in the models (durability shadows gated on
    # ``stalled``, timer arming through ``skewed_delay``)
    fs_cnt = get1(f.fsync_cnt, victim)
    fsync_cnt = set1(f.fsync_cnt, victim, fs_cnt + 1, action == F_FSYNC_STALL)
    fsync_cnt = set1(
        fsync_cnt, victim, jnp.maximum(fs_cnt - 1, 0), action == F_FSYNC_OK
    )
    sk_cnt = get1(f.skew_cnt, victim)
    skew_cnt = set1(f.skew_cnt, victim, sk_cnt + 1, action == F_SKEW_ON)
    skew_cnt = set1(
        skew_cnt, victim, jnp.maximum(sk_cnt - 1, 0), action == F_SKEW_OFF
    )

    # latency-spike bursts: override the whole link latency range
    spike_apply = is_spike_on & (f.spike_cnt == 0)
    spike_restore = is_spike_off & (f.spike_cnt == 1)
    lat_lo = jnp.where(
        spike_apply,
        jnp.int64(spec.spike_lat_lo_ns),
        jnp.where(spike_restore, jnp.int64(base.lat_lo_ns), links.lat_lo_ns),
    )
    lat_hi = jnp.where(
        spike_apply,
        jnp.int64(spec.spike_lat_hi_ns),
        jnp.where(spike_restore, jnp.int64(base.lat_hi_ns), links.lat_hi_ns),
    )
    spike_cnt = jnp.where(
        is_spike_on,
        f.spike_cnt + 1,
        jnp.where(is_spike_off, jnp.maximum(f.spike_cnt - 1, 0), f.spike_cnt),
    )

    # message-loss bursts: override the loss probability
    loss_apply = is_loss_on & (f.loss_cnt == 0)
    loss_restore = is_loss_off & (f.loss_cnt == 1)
    loss_q32 = jnp.where(
        loss_apply,
        jnp.uint32(spec.burst_loss_q32),
        jnp.where(loss_restore, jnp.uint32(base.loss_q32), links.loss_q32),
    )
    loss_cnt = jnp.where(
        is_loss_on,
        f.loss_cnt + 1,
        jnp.where(is_loss_off, jnp.maximum(f.loss_cnt - 1, 0), f.loss_cnt),
    )

    links = links._replace(lat_lo_ns=lat_lo, lat_hi_ns=lat_hi, loss_q32=loss_q32)
    f2 = FaultState(
        alive=alive,
        paused=paused,
        part_in_cnt=part_in_cnt,
        part_out_cnt=part_out_cnt,
        fsync_cnt=fsync_cnt,
        skew_cnt=skew_cnt,
        spike_cnt=spike_cnt,
        loss_cnt=loss_cnt,
    )
    return links, f2, edges
