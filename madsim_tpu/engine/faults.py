"""Declarative fault campaigns: one ``FaultSpec``, two compilation targets.

MadSim's value in the FoundationDB tradition is *systematic* fault
injection — buggify points, clogs, kills (madsim/src/sim/net/mod.rs:163-284,
task/mod.rs:347-394). Before this subsystem each device model hand-rolled
its own crash/restart or partition plan in ``_init`` and the host tier
relied on manual ``Handle.kill`` calls; now both tiers compile the SAME
declarative spec:

- ``FaultSpec`` is a pure NamedTuple (hashable — it rides inside model
  configs, which are jit cache keys): crash/restart storms, partition/heal
  cycles over a node group, network-wide latency-spike and message-loss
  bursts, node pause/resume windows — plus the GRAY-failure families
  (docs/faults.md): asymmetric one-directional partitions, slow-disk
  fsync-stall windows, power-fail windows that drop unsynced writes, and
  per-node clock-skew windows.
- ``schedule_events(spec, num_nodes, key)`` is THE schedule derivation —
  seeded draws of fire times, durations and victims in a dedicated RNG
  namespace (disjoint from every model's init/event streams). The device
  tier evaluates it inside ``vmap``/``jit`` per seed; the host tier
  (``madsim_tpu.faults.compile_host``) evaluates the identical function
  eagerly for one seed, so the two tiers agree on the schedule *by
  construction* — and ``tests/test_faults.py`` asserts it end-to-end
  through the device engine's queue and dispatch machinery.
- ``compile_device`` packs the schedule into a fault event stream
  (``Emits``) any ``Workload`` splices into its initial event set; each
  event's payload carries ``(action, victim, t_lo, t_hi)`` where
  ``t = t_hi << 31 | t_lo`` is the exact scheduled deadline, so a traced
  replay (``replay.extract_fault_schedule``) recovers the schedule
  without the engine's dispatch jitter.
- ``FaultState`` + ``on_event`` are the shared in-loop interpreter:
  node-liveness/pause masks, per-victim partition refcounts, and
  refcounted latency/loss overrides on ``engine.net.LinkState``. Models
  keep only their *model-specific* crash/restart resets.

Restore semantics: latency/loss bursts save nothing at runtime — the
"off" transition restores the model's base values (``NetBase``, static
python ints from the model config), so overlapping bursts compose via the
refcount with no array state beyond two counters.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import net as enet
from .core import Emits
from .ops import get1, set1
from .rng import bounded, prob_to_q32

# fault action codes (payload slot 0 of a fault event)
F_CRASH = 0
F_RESTART = 1
F_PART = 2
F_HEAL = 3
F_SPIKE_ON = 4
F_SPIKE_OFF = 5
F_LOSS_ON = 6
F_LOSS_OFF = 7
F_PAUSE = 8
F_RESUME = 9
# gray-failure actions (one-directional partitions, slow disks, power
# loss, clock skew) — appended so existing codes/wire names stay stable
F_PART_IN = 10  # clog only the victim's INBOUND links
F_HEAL_IN = 11
F_PART_OUT = 12  # clog only the victim's OUTBOUND links
F_HEAL_OUT = 13
F_FSYNC_STALL = 14  # the victim's disk stops making writes durable
F_FSYNC_OK = 15  # ... and catches up (pending syncs apply)
F_POWER_FAIL = 16  # node loses power: dies AND unsynced writes drop
F_SKEW_ON = 17  # the victim's clock drifts: timers stretch
F_SKEW_OFF = 18

#: action code -> stable wire name (used by the host supervisor + replay)
ACTION_NAMES = (
    "crash",
    "restart",
    "partition",
    "heal",
    "spike_on",
    "spike_off",
    "loss_on",
    "loss_off",
    "pause",
    "resume",
    "part_in",
    "heal_in",
    "part_out",
    "heal_out",
    "fsync_stall",
    "fsync_ok",
    "power_fail",
    "skew_on",
    "skew_off",
)

#: stable wire name -> action code (the inverse, for literal schedules)
ACTION_CODES = {name: i for i, name in enumerate(ACTION_NAMES)}

# dedicated fold_in namespace for fault-schedule draws: disjoint from every
# model's init namespace (0x7FFF_FFFF) and from per-event counters (< 2**31
# in practice, but this constant is distinct regardless)
FAULT_STREAM = 0x5EED_FA17 & 0x7FFF_FFFF

Group = Tuple[int, int]  # victim range [lo, hi); hi = -1 means num_nodes


class FaultSpec(NamedTuple):
    """A declarative fault campaign (pure python ints/tuples — hashable,
    reprs stably, rides inside model configs as part of the jit key).

    Every category is a set of ``(start, end)`` windows: ``count`` pairs
    whose start times are drawn uniformly in ``[0, window_ns)`` and whose
    durations are drawn uniformly in ``[dur_lo_ns, dur_hi_ns)``. Victims
    are drawn from the category's node group ``[lo, hi)`` (``hi = -1``
    resolves to ``num_nodes`` at compile time)."""

    # crash/restart storms (down-time = restart delay)
    crashes: int = 0
    crash_window_ns: int = 5_000_000_000
    restart_lo_ns: int = 100_000_000
    restart_hi_ns: int = 1_000_000_000
    crash_group: Group = (0, -1)
    # partition/heal cycles (clog both directions of the victim node)
    partitions: int = 0
    part_window_ns: int = 3_000_000_000
    part_lo_ns: int = 500_000_000
    part_hi_ns: int = 2_000_000_000
    part_group: Group = (0, -1)
    # network-wide latency-spike bursts (override the base latency range)
    spikes: int = 0
    spike_window_ns: int = 3_000_000_000
    spike_dur_lo_ns: int = 200_000_000
    spike_dur_hi_ns: int = 1_000_000_000
    spike_lat_lo_ns: int = 1_000_000_000
    spike_lat_hi_ns: int = 5_000_000_000
    # network-wide message-loss bursts (override the base loss probability)
    losses: int = 0
    loss_window_ns: int = 3_000_000_000
    loss_dur_lo_ns: int = 200_000_000
    loss_dur_hi_ns: int = 1_000_000_000
    burst_loss_q32: int = prob_to_q32(0.5)
    # node pause/resume windows (clock-stop for the victim: no processing,
    # no state loss; host tier = ``Handle.pause``/``resume``)
    pauses: int = 0
    pause_window_ns: int = 3_000_000_000
    pause_lo_ns: int = 100_000_000
    pause_hi_ns: int = 1_000_000_000
    pause_group: Group = (0, -1)
    # -- gray failures (appended: old specs keep their field positions) --
    # asymmetric partitions: clog ONE direction of the victim's links; the
    # direction (in vs out) is part of the victim draw, so half the
    # windows are inbound-only and half outbound-only
    aparts: int = 0
    apart_window_ns: int = 3_000_000_000
    apart_lo_ns: int = 500_000_000
    apart_hi_ns: int = 2_000_000_000
    apart_group: Group = (0, -1)
    # slow-disk windows: while open, the victim's fsync defers — writes
    # stay volatile; the window's end applies pending syncs (host tier:
    # ``FsSim.stall_fsync``/``unstall_fsync``)
    fsync_stalls: int = 0
    fsync_window_ns: int = 3_000_000_000
    fsync_lo_ns: int = 500_000_000
    fsync_hi_ns: int = 2_000_000_000
    fsync_group: Group = (0, -1)
    # power-fail windows: the victim dies losing every unsynced write
    # (host tier: ``fs.power_fail`` + ``Handle.kill``) and restarts after
    # the drawn down-time
    power_fails: int = 0
    power_window_ns: int = 5_000_000_000
    power_lo_ns: int = 100_000_000
    power_hi_ns: int = 1_000_000_000
    power_group: Group = (0, -1)
    # clock-skew windows: the victim's virtual clock drifts slow — every
    # timer it arms stretches by skew_num/skew_den (device: models route
    # timer deadlines through ``skewed_delay``; host: ``time.sleep`` and
    # ``TimeHandle.node_skew`` consumers)
    skews: int = 0
    skew_window_ns: int = 3_000_000_000
    skew_lo_ns: int = 500_000_000
    skew_hi_ns: int = 2_000_000_000
    skew_group: Group = (0, -1)
    skew_num: int = 3
    skew_den: int = 2


class FixedFaults(NamedTuple):
    """A LITERAL fault schedule — the seedless counterpart of ``FaultSpec``.

    ``events`` is a tuple of ``(time_ns, action_name, victim)`` triples —
    the exact wire format ``replay.extract_fault_schedule`` and
    ``madsim_tpu.faults.compile_host`` emit, so a recorded or shrunk
    schedule (explore/shrink.py) drops straight back into any model's
    ``faults=`` config slot and replays with NO randomness: the schedule
    derivation returns the literal events for every seed. Still a pure
    NamedTuple of python values (hashable, jit-key-safe). The three
    override fields carry what burst "on" transitions need — the same
    values ``FaultSpec`` carries — since a literal schedule has no spec
    to read them from.
    """

    events: Tuple[Tuple[int, str, int], ...] = ()
    spike_lat_lo_ns: int = 1_000_000_000
    spike_lat_hi_ns: int = 5_000_000_000
    burst_loss_q32: int = prob_to_q32(0.5)
    skew_num: int = 3
    skew_den: int = 2


def num_events(spec) -> int:
    """Static event count of the compiled campaign (every ``FaultSpec``
    category contributes an on/off pair per window; a ``FixedFaults``
    schedule is its literal length)."""
    if isinstance(spec, FixedFaults):
        return len(spec.events)
    return 2 * (
        spec.crashes
        + spec.partitions
        + spec.spikes
        + spec.losses
        + spec.pauses
        + spec.aparts
        + spec.fsync_stalls
        + spec.power_fails
        + spec.skews
    )


def _resolve_group(group: Group, num_nodes: int, what: str) -> Tuple[int, int]:
    lo, hi = group
    if hi < 0:
        hi = num_nodes
    if not 0 <= lo < hi <= num_nodes:
        raise ValueError(
            f"{what} group {group} does not resolve to a non-empty node "
            f"range within [0, {num_nodes})"
        )
    return lo, hi


def _categories(spec: FaultSpec, num_nodes: int):
    """(count, on_action, off_action, window, dur_lo, dur_hi, vic_lo,
    vic_hi) per category, in the fixed draw order. The asymmetric
    category's actions are ``(in, out)`` PAIRS — the direction rides in
    the victim draw's low bit (see ``schedule_events``)."""
    return (
        (
            spec.crashes, F_CRASH, F_RESTART, spec.crash_window_ns,
            spec.restart_lo_ns, spec.restart_hi_ns,
            *_resolve_group(spec.crash_group, num_nodes, "crash"),
        ),
        (
            spec.partitions, F_PART, F_HEAL, spec.part_window_ns,
            spec.part_lo_ns, spec.part_hi_ns,
            *_resolve_group(spec.part_group, num_nodes, "partition"),
        ),
        (
            spec.spikes, F_SPIKE_ON, F_SPIKE_OFF, spec.spike_window_ns,
            spec.spike_dur_lo_ns, spec.spike_dur_hi_ns, 0, 1,
        ),
        (
            spec.losses, F_LOSS_ON, F_LOSS_OFF, spec.loss_window_ns,
            spec.loss_dur_lo_ns, spec.loss_dur_hi_ns, 0, 1,
        ),
        (
            spec.pauses, F_PAUSE, F_RESUME, spec.pause_window_ns,
            spec.pause_lo_ns, spec.pause_hi_ns,
            *_resolve_group(spec.pause_group, num_nodes, "pause"),
        ),
        (
            spec.aparts, (F_PART_IN, F_PART_OUT), (F_HEAL_IN, F_HEAL_OUT),
            spec.apart_window_ns, spec.apart_lo_ns, spec.apart_hi_ns,
            *_resolve_group(spec.apart_group, num_nodes, "apart"),
        ),
        (
            spec.fsync_stalls, F_FSYNC_STALL, F_FSYNC_OK,
            spec.fsync_window_ns, spec.fsync_lo_ns, spec.fsync_hi_ns,
            *_resolve_group(spec.fsync_group, num_nodes, "fsync"),
        ),
        (
            spec.power_fails, F_POWER_FAIL, F_RESTART,
            spec.power_window_ns, spec.power_lo_ns, spec.power_hi_ns,
            *_resolve_group(spec.power_group, num_nodes, "power"),
        ),
        (
            spec.skews, F_SKEW_ON, F_SKEW_OFF, spec.skew_window_ns,
            spec.skew_lo_ns, spec.skew_hi_ns,
            *_resolve_group(spec.skew_group, num_nodes, "skew"),
        ),
    )


def schedule_events(spec, num_nodes: int, key: jax.Array):
    """The shared schedule derivation: ``(times int64[E], actions int32[E],
    victims int32[E])`` in pair order (NOT time-sorted — the device queue
    orders by time at dispatch; the host supervisor sorts).

    Draw layout: per window pair i (in category order) the draws are
    ``rand[3i] = start``, ``rand[3i+1] = duration``, ``rand[3i+2] =
    victim`` — a fixed layout so adding windows to one category never
    shifts another category's draws within the pair sequence.

    A ``FixedFaults`` spec bypasses the draws entirely: the literal
    events come back seed-independently (``key`` is unused), which is
    what lets a shrunk schedule replay identically under any seed."""
    if isinstance(spec, FixedFaults):
        for t, action, vic in spec.events:
            if action not in ACTION_CODES:
                raise ValueError(f"unknown fault action {action!r}")
            if not 0 <= vic < num_nodes:
                raise ValueError(
                    f"victim {vic} outside [0, {num_nodes}) in fixed "
                    f"schedule event {(t, action, vic)!r}"
                )
        e = len(spec.events)
        return (
            jnp.asarray([t for t, _, _ in spec.events], jnp.int64).reshape(e),
            jnp.asarray(
                [ACTION_CODES[a] for _, a, _ in spec.events], jnp.int32
            ).reshape(e),
            jnp.asarray([v for _, _, v in spec.events], jnp.int32).reshape(e),
        )
    e = num_events(spec)
    if e == 0:
        return (
            jnp.zeros((0,), jnp.int64),
            jnp.zeros((0,), jnp.int32),
            jnp.zeros((0,), jnp.int32),
        )
    rand = jax.random.bits(
        jax.random.fold_in(key, FAULT_STREAM), (3 * (e // 2),), dtype=jnp.uint32
    )
    times, actions, victims = [], [], []
    i = 0
    for count, a_on, a_off, window, dlo, dhi, vlo, vhi in _categories(
        spec, num_nodes
    ):
        for _ in range(count):
            t0 = bounded(rand[3 * i], 0, window)
            dur = bounded(rand[3 * i + 1], dlo, dhi)
            if isinstance(a_on, tuple):
                # directional category: the victim draw covers twice the
                # node range; the low bit picks in vs out, so the draw
                # budget stays at the fixed 3 per window pair
                d = bounded(rand[3 * i + 2], 0, 2 * (vhi - vlo))
                vic = (vlo + (d >> 1)).astype(jnp.int32)
                out = (d & 1) == 1
                on = jnp.where(out, a_on[1], a_on[0]).astype(jnp.int32)
                off = jnp.where(out, a_off[1], a_off[0]).astype(jnp.int32)
            else:
                vic = bounded(rand[3 * i + 2], vlo, vhi).astype(jnp.int32)
                on = jnp.asarray(a_on, jnp.int32)
                off = jnp.asarray(a_off, jnp.int32)
            times += [t0, t0 + dur]
            actions += [on, off]
            victims += [vic, vic]
            i += 1
    return jnp.stack(times), jnp.stack(actions), jnp.stack(victims)


def compile_device(
    spec,  # FaultSpec | FixedFaults
    num_nodes: int,
    key: jax.Array,
    fault_kind: int,
    payload_slots: int,
) -> Emits:
    """Compile the campaign into a fault event stream a model splices into
    its initial event set. Payload layout: ``(action, victim, t_lo, t_hi)``
    with ``t = t_hi << 31 | t_lo`` the exact scheduled deadline (both
    halves non-negative int32, so no sign-wrap ambiguity)."""
    if payload_slots < 4:
        raise ValueError(
            f"fault events need 4 payload slots (action, victim, t_lo, "
            f"t_hi); the workload has {payload_slots}"
        )
    times, actions, victims = schedule_events(spec, num_nodes, key)
    e = int(times.shape[0])
    pays = jnp.zeros((e, payload_slots), jnp.int32)
    if e:
        pays = pays.at[:, 0].set(actions)
        pays = pays.at[:, 1].set(victims)
        pays = pays.at[:, 2].set((times & 0x7FFF_FFFF).astype(jnp.int32))
        pays = pays.at[:, 3].set((times >> 31).astype(jnp.int32))
    return Emits(
        times=times,
        kinds=jnp.full((e,), fault_kind, jnp.int32),
        pays=pays,
        enables=jnp.ones((e,), bool),
    )


def decode_time(t_lo, t_hi):
    """Recover the scheduled deadline from a fault event payload."""
    return (jnp.asarray(t_hi, jnp.int64) << 31) | jnp.asarray(t_lo, jnp.int64)


class NetBase(NamedTuple):
    """The model's base network parameters (static python ints) — what a
    burst's "off" transition restores, so no runtime save is needed."""

    lat_lo_ns: int
    lat_hi_ns: int
    loss_q32: int


class FaultState(NamedTuple):
    """Per-seed interpreter state for the compiled campaign — the shared
    piece of every model's workload state.

    Partition refcounts are PER DIRECTION: a symmetric ``partition``
    holds both of its victim's directions, an asymmetric ``part_in`` /
    ``part_out`` holds exactly one — so a symmetric heal can never
    un-clog a direction an overlapping asymmetric window still holds
    (and vice versa). A direction is clogged iff its count is > 0."""

    alive: jnp.ndarray  # bool[N]
    paused: jnp.ndarray  # bool[N]
    part_in_cnt: jnp.ndarray  # int32[N] inbound-clog refcount
    part_out_cnt: jnp.ndarray  # int32[N] outbound-clog refcount
    fsync_cnt: jnp.ndarray  # int32[N] slow-disk (fsync-stall) refcount
    skew_cnt: jnp.ndarray  # int32[N] clock-skew refcount
    spike_cnt: jnp.ndarray  # int32 latency-burst refcount
    loss_cnt: jnp.ndarray  # int32 loss-burst refcount


class FaultEdges(NamedTuple):
    """The transitions one fault event ACTUALLY caused, gated exactly the
    way the host supervisor gates its ``Handle`` calls
    (``faults.apply_schedule``): killing a dead node, restarting a live
    one, and pausing/resuming a dead or already-paused/unpaused node are
    all no-edges. Models key their model-specific consequences (state
    wipes, timer-chain re-arms) off these booleans instead of re-deriving
    them, so the host-mirror semantics stay single-sourced."""

    crashed: jnp.ndarray  # bool: a live victim died (crash OR power_fail;
    # both roll durable state back to the synced frontier — models with a
    # durability plane key the rollback off this edge)
    restarted: jnp.ndarray  # bool: a dead victim revived
    paused: jnp.ndarray  # bool: a live, running victim paused
    resumed: jnp.ndarray  # bool: a live, paused victim resumed


def init_state(num_nodes: int) -> FaultState:
    return FaultState(
        alive=jnp.ones((num_nodes,), bool),
        paused=jnp.zeros((num_nodes,), bool),
        part_in_cnt=jnp.zeros((num_nodes,), jnp.int32),
        part_out_cnt=jnp.zeros((num_nodes,), jnp.int32),
        fsync_cnt=jnp.zeros((num_nodes,), jnp.int32),
        skew_cnt=jnp.zeros((num_nodes,), jnp.int32),
        spike_cnt=jnp.zeros((), jnp.int32),
        loss_cnt=jnp.zeros((), jnp.int32),
    )


def up(f: FaultState) -> jnp.ndarray:
    """bool[N]: node is processing events (alive and not paused)."""
    return f.alive & ~f.paused


def stalled(f: FaultState) -> jnp.ndarray:
    """bool[N]: node's disk is inside a slow-disk window (fsync defers).
    Models gate their durability plane on this: while True, the synced
    shadow of durable state freezes; the window's end catches it up."""
    return f.fsync_cnt > 0


def can_skew(spec) -> bool:
    """Whether the (static, trace-time) spec can ever open a skew
    window. Gates ``skewed_delay`` off entirely for skew-free specs."""
    if isinstance(spec, FixedFaults):
        return any(a in ("skew_on", "skew_off") for _, a, _ in spec.events)
    return spec.skews > 0


def skewed_delay(spec, f: FaultState, node, delay_ns):
    """A timer interval as the (possibly skewed) victim's clock measures
    it: while ``node`` is inside a clock-skew window its timers stretch
    by ``spec.skew_num / spec.skew_den`` (both ``FaultSpec`` and
    ``FixedFaults`` carry the ratio). Models route every node-owned
    timer re-arm through this — the device half of the host tier's
    ``time.node_skew`` (docs/faults.md gray failures). Statically an
    identity when the spec draws no skew windows (``skew_cnt`` is
    provably zero then), so the common case pays nothing."""
    d = jnp.asarray(delay_ns, jnp.int64)
    if not can_skew(spec):
        return d
    slow = get1(f.skew_cnt, node) > 0
    return jnp.where(slow, d * spec.skew_num // spec.skew_den, d)


def on_event(
    spec,  # FaultSpec | FixedFaults (both carry the burst override fields)
    base: NetBase,
    links: enet.LinkState,
    f: FaultState,
    action: jnp.ndarray,
    victim: jnp.ndarray,
):
    """Apply one fault event to the shared state; returns ``(links,
    fstate, edges)``. Model-specific consequences (wiping volatile state
    on crash, re-arming timer chains on restart/resume) stay in the
    model's fault handler, keyed off the returned ``FaultEdges``.

    Partition and burst transitions are refcounted so overlapping windows
    compose exactly: only the 0→1 edge applies and only the 1→0 edge
    restores (same discipline the etcd model used for its private
    partition plan)."""
    is_crash = (action == F_CRASH) | (action == F_POWER_FAIL)
    is_restart = action == F_RESTART
    is_part = action == F_PART
    is_heal = action == F_HEAL
    is_spike_on = action == F_SPIKE_ON
    is_spike_off = action == F_SPIKE_OFF
    is_loss_on = action == F_LOSS_ON
    is_loss_off = action == F_LOSS_OFF
    is_pause = action == F_PAUSE
    is_resume = action == F_RESUME

    was_alive = get1(f.alive, victim)
    was_paused = get1(f.paused, victim)
    edges = FaultEdges(
        crashed=is_crash & was_alive,
        restarted=is_restart & ~was_alive,
        paused=is_pause & was_alive & ~was_paused,
        resumed=is_resume & was_alive & was_paused,
    )
    alive = set1(f.alive, victim, False, is_crash)
    alive = set1(alive, victim, True, is_restart)
    # mirror the host supervisor exactly (faults.apply_schedule): a kill
    # clears a pause (the node's tasks are gone — its restart revives it
    # running), and pausing/resuming a dead node is a no-op
    paused = set1(f.paused, victim, False, is_crash)
    paused = set1(paused, victim, True, is_pause & was_alive)
    paused = set1(paused, victim, False, is_resume & was_alive)

    # partitions, per direction (ref NetSim::clog_node_in/out): a
    # symmetric partition holds BOTH of the victim's directions, an
    # asymmetric window exactly one. The clog matrix is DERIVED from the
    # refcounts — clog[s, d] iff s's outbound or d's inbound count is
    # held — so overlapping symmetric/asymmetric windows of the same OR
    # different victims compose exactly (a heal can never un-clog a cell
    # any other live window still holds; the old incremental clog_node
    # masks could, for two victims sharing a link cell)
    inc_in = is_part | (action == F_PART_IN)
    dec_in = is_heal | (action == F_HEAL_IN)
    inc_out = is_part | (action == F_PART_OUT)
    dec_out = is_heal | (action == F_HEAL_OUT)
    in_cnt = get1(f.part_in_cnt, victim)
    out_cnt = get1(f.part_out_cnt, victim)
    part_in_cnt = set1(f.part_in_cnt, victim, in_cnt + 1, inc_in)
    part_in_cnt = set1(part_in_cnt, victim, jnp.maximum(in_cnt - 1, 0), dec_in)
    part_out_cnt = set1(f.part_out_cnt, victim, out_cnt + 1, inc_out)
    part_out_cnt = set1(
        part_out_cnt, victim, jnp.maximum(out_cnt - 1, 0), dec_out
    )
    touched = inc_in | dec_in | inc_out | dec_out
    derived = (part_out_cnt > 0)[:, None] | (part_in_cnt > 0)[None, :]
    links = links._replace(clog=jnp.where(touched, derived, links.clog))

    # slow-disk and clock-skew windows: plain per-victim refcounts; the
    # consequences live in the models (durability shadows gated on
    # ``stalled``, timer arming through ``skewed_delay``)
    fs_cnt = get1(f.fsync_cnt, victim)
    fsync_cnt = set1(f.fsync_cnt, victim, fs_cnt + 1, action == F_FSYNC_STALL)
    fsync_cnt = set1(
        fsync_cnt, victim, jnp.maximum(fs_cnt - 1, 0), action == F_FSYNC_OK
    )
    sk_cnt = get1(f.skew_cnt, victim)
    skew_cnt = set1(f.skew_cnt, victim, sk_cnt + 1, action == F_SKEW_ON)
    skew_cnt = set1(
        skew_cnt, victim, jnp.maximum(sk_cnt - 1, 0), action == F_SKEW_OFF
    )

    # latency-spike bursts: override the whole link latency range
    spike_apply = is_spike_on & (f.spike_cnt == 0)
    spike_restore = is_spike_off & (f.spike_cnt == 1)
    lat_lo = jnp.where(
        spike_apply,
        jnp.int64(spec.spike_lat_lo_ns),
        jnp.where(spike_restore, jnp.int64(base.lat_lo_ns), links.lat_lo_ns),
    )
    lat_hi = jnp.where(
        spike_apply,
        jnp.int64(spec.spike_lat_hi_ns),
        jnp.where(spike_restore, jnp.int64(base.lat_hi_ns), links.lat_hi_ns),
    )
    spike_cnt = jnp.where(
        is_spike_on,
        f.spike_cnt + 1,
        jnp.where(is_spike_off, jnp.maximum(f.spike_cnt - 1, 0), f.spike_cnt),
    )

    # message-loss bursts: override the loss probability
    loss_apply = is_loss_on & (f.loss_cnt == 0)
    loss_restore = is_loss_off & (f.loss_cnt == 1)
    loss_q32 = jnp.where(
        loss_apply,
        jnp.uint32(spec.burst_loss_q32),
        jnp.where(loss_restore, jnp.uint32(base.loss_q32), links.loss_q32),
    )
    loss_cnt = jnp.where(
        is_loss_on,
        f.loss_cnt + 1,
        jnp.where(is_loss_off, jnp.maximum(f.loss_cnt - 1, 0), f.loss_cnt),
    )

    links = links._replace(lat_lo_ns=lat_lo, lat_hi_ns=lat_hi, loss_q32=loss_q32)
    f2 = FaultState(
        alive=alive,
        paused=paused,
        part_in_cnt=part_in_cnt,
        part_out_cnt=part_out_cnt,
        fsync_cnt=fsync_cnt,
        skew_cnt=skew_cnt,
        spike_cnt=spike_cnt,
        loss_cnt=loss_cnt,
    )
    return links, f2, edges
