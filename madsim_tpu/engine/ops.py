"""One-hot indexing primitives — TPU-friendly dynamic scatter/gather.

Under ``vmap``, ``arr.at[idx].set(v)`` / ``arr[idx]`` with a traced index
lower to batched scatter/gather ops, which the TPU executes ~6-10x slower
than dense vector code (measured on v5e: 0.25-0.5 ms per op over a 16k
batch vs 0.05 ms for the masked equivalent). For the small per-seed tables
this engine manipulates (queues of ~100 slots, node arrays of ~5), the
classic SPMD alternative is strictly better: build a one-hot mask over the
indexed axis and reduce/select densely. Every op below compiles to pure
elementwise + reduction HLO — no scatter, no gather — and fuses with its
neighbours.

All helpers preserve dtype bit-exactly (reductions pick exactly one
element), so replay parity between backends is unaffected.
"""

from __future__ import annotations

import jax.numpy as jnp


def onehot(idx, n: int):
    """bool[n] mask with True at ``idx`` (clamped semantics: out-of-range
    index selects nothing)."""
    return jnp.arange(n, dtype=jnp.int32) == jnp.asarray(idx, jnp.int32)


def _pick(arr, mask, axis):
    """Reduce ``arr`` over ``axis`` picking the single masked element."""
    if arr.dtype == jnp.bool_:
        return jnp.any(arr & mask, axis=axis)
    zero = jnp.zeros((), arr.dtype)
    return jnp.sum(jnp.where(mask, arr, zero), axis=axis, dtype=arr.dtype)


def _expand(mask, ndim: int):
    """Broadcast a leading-axis mask to ``ndim`` dims."""
    return mask.reshape(mask.shape + (1,) * (ndim - mask.ndim))


def get1(arr, idx):
    """``arr[idx]`` along axis 0 (scalar index; works for rows too)."""
    mask = onehot(idx, arr.shape[0])
    return _pick(arr, _expand(mask, arr.ndim), axis=0)


def set1(arr, idx, val, enable=True):
    """``arr[idx] = val`` when ``enable`` (axis 0; ``val`` may be a row)."""
    mask = onehot(idx, arr.shape[0]) & jnp.asarray(enable, bool)
    return jnp.where(_expand(mask, arr.ndim), jnp.asarray(val, arr.dtype), arr)


def geti(arr, idxs):
    """``arr[idxs]`` — gather a vector of scalar indices from a 1-D array."""
    n = arr.shape[0]
    mask = jnp.asarray(idxs, jnp.int32)[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]
    return _pick(arr[None, :], mask, axis=1)


def get2(arr, i, j):
    """``arr[i, j]`` — scalar from a 2-D array."""
    mask = onehot(i, arr.shape[0])[:, None] & onehot(j, arr.shape[1])[None, :]
    return _pick(arr, mask, axis=(0, 1))


def set2(arr, i, j, val, enable=True):
    """``arr[i, j] = val`` when ``enable`` — 2-D point write."""
    mask = (
        onehot(i, arr.shape[0])[:, None]
        & onehot(j, arr.shape[1])[None, :]
        & jnp.asarray(enable, bool)
    )
    return jnp.where(mask, jnp.asarray(val, arr.dtype), arr)


def getrow_i(arr, row, idxs):
    """``arr[row, idxs]`` — gather a vector of columns from one (dynamic)
    row of a 2-D array. Returns shape ``idxs.shape``."""
    r = get1(arr, row)  # [C]
    return geti(r, idxs)
