"""Persistent streaming sweep service: continuous lane refill.

The chunked drivers (core.run_sweep_chunked, checkpoint.run_sweep_pipelined)
run fixed-shape batches to completion: a lane whose seed finishes early —
or violates at t=2s of a 30s horizon — idles as a frozen no-op until the
slowest lane in its chunk retires, and the batch curve sags once the
chunk's loop carry outgrows fast memory (docs/pallas_finding.md §6: both
historical 10x sinks were structural, not micro). This module borrows
continuous batching from LLM serving instead:

- a fixed **lane pool** of ``pool_size`` lanes holds the loop carry at a
  constant, knee-sized working set for the whole sweep;
- each lane carries its own ``(seed, FaultParams, step budget)`` — the
  spec-as-data machinery (engine/faults.py) makes per-lane specs traced
  data, so lanes of one pool may run *different candidates*;
- one compiled **round program** advances every live lane up to
  ``round_steps`` events (``_round`` — the budget-freeze form of
  ``core.drive``'s loop, bit-identical per lane), exiting early once a
  refill quorum of lanes has retired so free slots turn over at the
  retirement flux, not the round boundary;
- retired lanes (done, or per-lane step budget spent) are captured into a
  host-side result buffer and **refilled in flight** from the work queue
  by one jitted fixed-width row re-init (``_refill_rows``: init quorum-many
  fresh lanes, scatter into the pool; the mesh path uses the full-pool
  masked form ``_refill``) — zero XLA compiles after warm-up
  (``engine/compiles.count_compiles`` asserts this in the bench leg and
  tests/test_stream.py).

Determinism contract (docs/streaming.md): a lane's final state is a pure
function of its ``(seed, params, budget)`` — the engine's per-lane masking
makes neighbors invisible — so per-seed results are **bit-identical to the
chunked driver**, and the merged report is **lane-order- and
refill-schedule-invariant**: results are buffered per work item and flushed
as *virtual chunks* in submission order (the same ``chunk_size`` granule,
``summarize``/``host_work``/``merge_summaries`` discipline, and therefore
the same bytes, as ``run_sweep_pipelined``). Two different
``queue_order`` permutations, or an interrupt/resume through a v9 stream
snapshot (``checkpoint.save_stream``), change wall-clock only — never a
report byte.

The budget-freeze trick: ``core.drive`` cuts the whole batch at
``iters < max_steps``, but a live (not-done) lane advances ``ctr`` by
exactly 1 per drive iteration, so the global cut equals a per-lane cut at
``ctr >= max_steps``. ``_round`` applies that cut per lane (temporarily
marking over-budget lanes done for the step, then restoring their true
``done`` bit), which is what lets one pool mix lanes of different ages —
and different per-lane budgets — while staying bit-identical to the
chunked driver for every lane.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache, partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core import EngineConfig, EngineState, Workload, init_sweep, step_batch


def _freeze_step(workload: Workload, cfg: EngineConfig, s: EngineState, budget):
    """One batch step with per-lane budget freeze: an over-budget lane is
    stepped as done (a bit-exact no-op pass-through) and keeps its TRUE
    ``done`` bit — the chunked driver leaves a budget-cut lane not-done
    at ``max_steps`` too, so capture-time states match bit for bit."""
    over = s.ctr >= budget
    s2 = step_batch(workload, cfg, s._replace(done=s.done | over))
    return s2._replace(done=jnp.where(over, s.done, s2.done))


@partial(jax.jit, static_argnums=(0, 1, 2))
def _round(
    workload: Workload, cfg: EngineConfig, round_steps: int,
    state: EngineState, budget, stop_live,
):
    """One device round: up to ``round_steps`` events for every live lane
    of the pool (live = not done AND under its own step budget), exiting
    early once the live count falls to ``stop_live`` — the host sets it a
    refill quorum below the round's starting count while the queue has
    work (so retired lanes hand their slots over promptly instead of
    burning frozen no-op steps to the round boundary) and to 0 for the
    drain. ONE flat while_loop, same shape as ``core.drive`` (a nested
    device loop costs ~9x per step on TPU)."""

    def cond(carry):
        s, i = carry
        live = jnp.sum(~s.done & (s.ctr < budget), dtype=jnp.int32)
        return (live > stop_live) & (i < round_steps)

    def body(carry):
        s, i = carry
        return _freeze_step(workload, cfg, s, budget), i + 1

    state, _ = jax.lax.while_loop(
        cond, body, (state, jnp.zeros((), jnp.int64))
    )
    return state


@lru_cache(maxsize=64)
def _round_sharded(
    workload: Workload, cfg: EngineConfig, round_steps: int, mesh
):
    """The round program shard_map'd over the mesh's seed axis — the
    sharded-variant composition with parallel/mesh.py: per-device stepping
    with one psum'd live count per iteration (the same collective as
    ``mesh._sharded_run``), so all devices leave the round together.
    Cached per (workload, cfg, round_steps, mesh) like every other
    sharded program."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import SEED_AXIS, shard_map_compat

    def device_run(state: EngineState, budget, stop_live):
        def cond(carry):
            s, i = carry
            live = jax.lax.psum(
                jnp.sum(~s.done & (s.ctr < budget), dtype=jnp.int32),
                SEED_AXIS,
            )
            return (live > stop_live[0]) & (i < round_steps)

        def body(carry):
            s, i = carry
            return _freeze_step(workload, cfg, s, budget), i + 1

        state, _ = jax.lax.while_loop(
            cond, body, (state, jnp.zeros((), jnp.int64))
        )
        return state

    return jax.jit(
        shard_map_compat(
            device_run, mesh,
            in_specs=(P(SEED_AXIS), P(SEED_AXIS), P(None)),
            out_specs=P(SEED_AXIS),
        )
    )


def _mask_tree(mask, new, old):
    """Per-leaf ``where(mask, new, old)`` over two EngineStates; typed
    PRNG keys select through their raw uint32 words."""

    def pick(a, b):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            ad, bd = jax.random.key_data(a), jax.random.key_data(b)
            m = mask.reshape(mask.shape + (1,) * (ad.ndim - 1))
            return jax.random.wrap_key_data(jnp.where(m, ad, bd))
        m = mask.reshape(mask.shape + (1,) * (a.ndim - 1))
        return jnp.where(m, a, b)

    return jax.tree.map(pick, new, old)


@partial(jax.jit, static_argnums=(0, 1))
def _refill(
    workload: Workload, cfg: EngineConfig, state: EngineState,
    mask, seeds, params=None,
):
    """The full-pool in-flight refill (mesh path): re-init every lane
    and keep the fresh state only where ``mask`` is set. All inputs are
    traced (fixed shapes), so refilling costs ZERO recompiles — the
    whole point of spec-as-data. Re-initing the unmasked lanes too
    wastes a few vector ops but keeps the program shape independent of
    the retirement pattern (and of the mesh layout)."""
    fresh = init_sweep(workload, cfg, seeds, params)
    return _mask_tree(mask, fresh, state)


@partial(jax.jit, static_argnums=(0, 1))
def _refill_rows(
    workload: Workload, cfg: EngineConfig, state: EngineState,
    lanes, seeds, params=None,
):
    """The fixed-width row refill (local path): init exactly the refill
    quorum's worth of fresh lanes and scatter them into the pool at
    ``lanes``. Init work per stream then totals one init per work item —
    the same as the chunked driver — instead of a full-pool init per
    refill event. Short cohorts pad ``lanes`` with duplicates of their
    first entry; the duplicate rows carry identical (seed, params), so
    the repeated scatter writes are value-identical and the result is
    deterministic."""
    fresh = init_sweep(workload, cfg, seeds, params)

    def put(old, new):
        if jnp.issubdtype(old.dtype, jax.dtypes.prng_key):
            od, nd = jax.random.key_data(old), jax.random.key_data(new)
            return jax.random.wrap_key_data(od.at[lanes].set(nd))
        return old.at[lanes].set(new)

    return jax.tree.map(put, state, fresh)


def _leaf_info(state: EngineState):
    """(treedef, key-leaf mask) of a pool state — computed once per
    stream; rows travel host-side in raw form (key leaves as words)."""
    leaves, treedef = jax.tree.flatten(state)
    keymask = tuple(
        bool(jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key))
        for leaf in leaves
    )
    return treedef, keymask


def _pool_to_host(state: EngineState, keymask):
    """Every pool leaf as a host array (key leaves as raw words)."""
    return [
        np.asarray(jax.random.key_data(leaf) if isk else leaf)
        for isk, leaf in zip(keymask, jax.tree.leaves(state))
    ]


def _buf_state(leaves, treedef, keymask) -> EngineState:
    """A captured chunk buffer (host leaf arrays, submission order) as a
    batched EngineState — what ``summarize`` and ``host_work`` consume
    at flush time."""
    return jax.tree.unflatten(
        treedef,
        [
            jax.random.wrap_key_data(jnp.asarray(b)) if isk else b
            for isk, b in zip(keymask, leaves)
        ],
    )


def stream_sweep(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    summarize,
    *,
    params=None,
    budgets=None,
    chunk_size: Optional[int] = None,
    pool_size: Optional[int] = None,
    round_steps: int = 256,
    host_work: Optional[Callable] = None,
    screen: Optional[Callable] = None,
    mesh=None,
    queue_order=None,
    on_chunk: Optional[Callable] = None,
    stats: Optional[dict] = None,
    ckpt_path: Optional[str] = None,
    stop_after_rounds: Optional[int] = None,
    resume_from: Optional[str] = None,
    feed: Optional[Callable[[], Optional[dict]]] = None,
    reprioritize: Optional[Callable] = None,
    telemetry=None,
) -> dict:
    """Sweep ``seeds`` through a constant-occupancy lane pool; returns
    the merged summary dict, byte-identical to ``run_sweep_pipelined``
    over the same ``(seeds, params, chunk_size)``.

    Work items are ``(seed, params row, budget)`` triples in submission
    order; ``queue_order`` (a permutation of ``range(len(seeds))``)
    reorders only their *dispatch* onto lanes — results are buffered per
    item and flushed as virtual ``chunk_size`` chunks in submission
    order, so the report bytes are refill-schedule-invariant (the
    invariance tests/test_stream.py pins).

    - ``params``: per-item spec-as-data pytree (leading axis = items),
      ``engine.run_sweep``'s contract. Lanes of one pool may carry
      different candidates — this is how a campaign's candidate grid
      feeds the queue instead of chunk boundaries.
    - ``budgets``: optional per-item step budgets (int[n], default
      ``cfg.max_steps``) — the per-lane "horizon" knob.
    - ``screen``: ``final -> bool[S]`` suspect mask (e.g.
      ``oracle.screen.screen_sweep``); runs once per retirement cohort
      on the POOL state, and the per-item bits ride to the flush, where
      ``host_work(final, lo=, n=, seeds=, suspect=, summary=)`` sees
      exactly what the pipelined driver would hand it. A suspect bit is
      a pure per-lane function, so cohort screening == chunk screening.
    - ``mesh``: runs the round/refill/screen programs sharded over the
      mesh's seed axis (``pool_size`` rounds up to mesh divisibility).
    - ``stats``: a caller-owned dict filled with wall-clock-side
      telemetry (``rounds``, ``refills``, ``lanes``, ``occupancy_mean``)
      — kept OUT of the returned totals so the report stays a pure
      function of the work. Updated INCREMENTALLY (after every flush and
      before every snapshot), so an interrupted or crashed run still
      leaves occupancy records behind, not just a completed one.
    - ``telemetry`` (``obs.Telemetry`` or None): per-round occupancy and
      queue-depth gauges, round/refill-quorum/flush latency histograms,
      retirement-flux and drain-tail counters, seeds-done progress, and
      — when the handle carries a trace — "device" round spans with
      "host" flush spans interleaved plus an occupancy counter track
      (the refill-cadence picture). Strictly OUT-OF-BAND: every recorder
      is behind an ``is not None`` guard; the report bytes are identical
      with telemetry on or off.

    Interrupt/resume (checkpoint format v9): ``stop_after_rounds=R``
    snapshots pool + pending results + merged totals to ``ckpt_path``
    after R rounds this call and returns the (partial) totals;
    ``resume_from=path`` continues — flushed chunks never recompute, and
    the final totals are bit-identical to the uninterrupted run.

    In-flight queue feed: ``feed`` is a nullary callable polled whenever
    free lanes outnumber queued items. It returns ``None`` (nothing more
    — the stream drains and returns) or a segment dict
    ``{"seeds": int[m], "params": rows or None, "budgets": int[m] or
    absent}`` appended to the work queue WITHOUT leaving the pool: fed
    lanes enter through the same traced refill programs, so a fleet
    worker's newly leased batches start at zero recompiles. Segments
    (and the initial ``seeds``) must be multiples of ``chunk_size`` —
    fed chunks flush in arrival order with the same virtual-chunk bytes
    as passing the concatenated queue up front (pinned by
    tests/test_stream.py). ``feed`` is incompatible with
    ``queue_order`` and with checkpointing (``ckpt_path``/
    ``resume_from``): the queue is open-ended, so there is no fixed
    submission order to permute or fingerprint.

    Live queue reorder: ``reprioritize`` is a callable polled before
    each dispatch with the UNDISPATCHED item indices (submission
    order positions); it returns a permutation of that array (or None
    to keep it) which replaces the dispatch order of the queued tail —
    the explore scheduler's zero-recompile "jump the queue" knob
    (explore/steer.py). Already-dispatched lanes and the initial pool
    fill are untouched, and because results flush as virtual chunks in
    SUBMISSION order regardless of dispatch order, a reprioritized
    stream changes wall-clock only, never a report byte (the same
    invariance ``queue_order`` pins). Incompatible with checkpointing:
    a mutable dispatch order has no stable ``order_sha`` to fingerprint.
    """
    import time as _time

    from .checkpoint import _sweep_fingerprint, params_digest
    from ..models._common import merge_summaries  # lazy: models import us

    tracer = telemetry.tracer if telemetry is not None else None
    seeds_host = np.asarray(jnp.asarray(seeds, jnp.int64))
    n = int(seeds_host.size)
    if n == 0:
        raise ValueError("seed batch is empty")
    if round_steps < 1:
        raise ValueError(f"round_steps must be >= 1, got {round_steps}")
    if chunk_size is None:
        from .core import pick_chunk_size

        chunk_size = pick_chunk_size(
            workload, cfg,
            params=None
            if params is None
            else jax.tree.map(lambda a: np.asarray(a)[0], params),
        )
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    multiple = 1 if mesh is None else int(mesh.devices.size)
    L = min(pool_size if pool_size is not None else chunk_size, n)
    L = -(-L // multiple) * multiple
    if stop_after_rounds is not None and ckpt_path is None:
        raise ValueError("stop_after_rounds requires ckpt_path")

    budgets_host = (
        np.full(n, cfg.max_steps, np.int32)
        if budgets is None
        else np.asarray(budgets, np.int32)
    )
    if budgets_host.shape != (n,):
        raise ValueError(
            f"budgets must be shape ({n},), got {budgets_host.shape}"
        )
    order = (
        np.arange(n, dtype=np.int64)
        if queue_order is None
        else np.asarray(queue_order, np.int64)
    )
    if not np.array_equal(np.sort(order), np.arange(n)):
        raise ValueError("queue_order must be a permutation of range(n)")
    if feed is not None:
        if queue_order is not None:
            raise ValueError("feed is incompatible with queue_order")
        if resume_from is not None or ckpt_path is not None:
            raise ValueError(
                "feed is incompatible with checkpointing "
                "(ckpt_path/resume_from)"
            )
        if n % chunk_size:
            raise ValueError(
                f"with feed, the initial seeds must be a multiple of "
                f"chunk_size={chunk_size}, got {n}"
            )
    if reprioritize is not None and (
        resume_from is not None or ckpt_path is not None
    ):
        raise ValueError(
            "reprioritize is incompatible with checkpointing "
            "(ckpt_path/resume_from): the dispatch order is mutable"
        )
    params_host = (
        None if params is None else jax.tree.map(np.asarray, params)
    )

    fp = _sweep_fingerprint(workload, cfg)
    if params is not None:
        fp += "|params" + params_digest(params)
    seeds_sha = hashlib.sha256(
        np.ascontiguousarray(seeds_host).tobytes()
    ).hexdigest()
    order_sha = hashlib.sha256(
        np.ascontiguousarray(order).tobytes()
    ).hexdigest()

    def pool_rows(items):
        """Per-lane params rows for an item-index vector."""
        return jax.tree.map(lambda a: a[items].copy(), params_host)

    def place_pool(arr):
        """A [L]-leading pool array, sharded over the mesh when given
        (dtype-preserving — the refill mask is bool)."""
        if mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import SEED_AXIS

        return jax.device_put(
            jnp.asarray(arr), NamedSharding(mesh, P(SEED_AXIS))
        )

    def place_params(tree):
        if tree is None or mesh is None:
            return tree
        from ..parallel.mesh import shard_params

        return shard_params(mesh, tree)

    totals: dict = {}
    # budgeted incremental checking: a host_work advertising the
    # submit/poll/drain protocol (oracle.screen._HostWork) has its WGL
    # work interleaved with the DEVICE rounds — each flush submits its
    # chunk (cheap decode+dedup) and the verdict work is polled right
    # after every round's dispatch, inside the window where the device
    # is crunching and the host would otherwise just block on
    # state.done. The poll budget tracks the round wall time's EMA
    # (minus the poll's own cost), so checking consumes exactly the
    # host idle the rounds create and the pool never stalls on the
    # checker. OFF under checkpointing (ckpt_path/stop_after_rounds/
    # resume_from): snapshots need every flushed chunk's summary
    # finalized at its flush. Reports are byte-identical either way —
    # chunks finalize and merge strictly in submission order no matter
    # how the budget slices the checking.
    incr = (
        host_work is not None
        and getattr(host_work, "incremental", False)
        and ckpt_path is None
        and stop_after_rounds is None
        and resume_from is None
    )
    deferred: dict = {}  # lo -> (k, base summary) awaiting a verdict
    round_ema = 0.0
    # captured-but-unflushed results live in per-chunk host buffers
    # (one preallocated [k_c, ...] array per leaf — captures and flushes
    # are vectorized scatters/reads, never per-row python loops)
    pend: dict = {}  # chunk index -> per-leaf [k_c, ...] buffers
    pend_have: dict = {}  # chunk index -> bool[k_c] captured flags
    sus_buf: dict = {}  # chunk index -> bool[k_c] suspect bits
    resume_pending: dict = {}  # item -> row leaves (v9 load only)
    resume_susp: dict = {}
    rounds = refills = 0
    occ_sum = 0.0
    next_flush_lo = 0

    if resume_from is not None:
        from .checkpoint import load_stream

        pstruct = (
            None
            if params_host is None
            else jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    (L,) + np.shape(a)[1:], np.asarray(a).dtype
                ),
                params_host,
            )
        )
        like = jax.eval_shape(
            partial(init_sweep, workload, cfg),
            jax.ShapeDtypeStruct((L,), jnp.int64),
            pstruct,
        )
        state, resume_pending, resume_susp, meta = load_stream(
            resume_from, like
        )
        for key, want in (
            ("fingerprint", fp), ("seeds_sha", seeds_sha),
            ("order_sha", order_sha), ("chunk_size", int(chunk_size)),
            ("lanes", int(L)),
        ):
            if meta.get(key) != want:
                raise ValueError(
                    f"stream snapshot {resume_from} is from a different "
                    f"stream: {key}={meta.get(key)!r}, expected {want!r}"
                )
        lane_item = np.asarray(meta["lane_item"], np.int64)
        lane_budget = np.asarray(meta["lane_budget"], np.int32)
        next_q = int(meta["next_q"])
        next_flush_lo = int(meta["next_flush_lo"])
        totals = meta["totals"]
        rounds = int(meta["rounds"])
        refills = int(meta["refills"])
        occ_sum = float(meta["occ_sum"])
        pool_seeds = np.asarray(state.seed).copy()
        if params_host is not None:
            pool_params = pool_rows(np.where(lane_item >= 0, lane_item, 0))
        else:
            pool_params = None
        if mesh is not None:
            from ..parallel.mesh import shard_state

            state = shard_state(mesh, state)
    else:
        from .core import _init

        t = min(L, n)
        lane_item = np.full(L, -1, np.int64)
        lane_item[:t] = order[:t]
        next_q = t
        # budget 0 freezes an unassigned lane before its first event —
        # the pool's "live" mask is lane_item >= 0 plus this freeze
        lane_budget = np.zeros(L, np.int32)
        lane_budget[:t] = budgets_host[order[:t]]
        pool_seeds = np.empty(L, np.int64)
        pool_seeds[:t] = seeds_host[order[:t]]
        pool_seeds[t:] = seeds_host[order[0]]
        pool_params = (
            None
            if params_host is None
            else pool_rows(np.where(lane_item >= 0, lane_item, 0))
        )
        state = _init(
            workload, cfg, place_pool(pool_seeds), place_params(pool_params)
        )

    treedef, keymask = _leaf_info(state)

    def capture(items, sub, sus):
        """Scatter a retirement cohort's rows (``sub``: per-leaf
        [cohort, ...] slices, item order matching ``items``) into the
        per-chunk pending buffers — vectorized per (chunk, leaf)."""
        chunks = items // chunk_size
        for c in np.unique(chunks):
            c = int(c)
            lo = c * chunk_size
            k = min(chunk_size, n - lo)
            sel = chunks == c
            pos = items[sel] - lo
            if c not in pend:
                pend[c] = [
                    np.empty((k,) + s.shape[1:], s.dtype) for s in sub
                ]
                pend_have[c] = np.zeros(k, bool)
                sus_buf[c] = np.zeros(k, bool)
            for buf, s in zip(pend[c], sub):
                buf[pos] = s[sel]
            pend_have[c][pos] = True
            if sus is not None:
                sus_buf[c][pos] = sus[sel]

    if resume_pending:
        its = np.fromiter(resume_pending.keys(), np.int64)
        capture(
            its,
            [
                np.stack([resume_pending[int(i)][j] for i in its])
                for j in range(len(keymask))
            ],
            None
            if screen is None
            else np.array(
                [bool(resume_susp.get(int(i), False)) for i in its]
            ),
        )
        resume_pending = resume_susp = {}

    def publish_stats():
        """Surface the stream's internal telemetry NOW — called after
        every flush and before every snapshot (not just at return), so
        an interrupted run still has its occupancy record."""
        if stats is not None:
            stats.update(
                rounds=int(rounds),
                refills=int(refills),
                lanes=int(L),
                round_steps=int(round_steps),
                occupancy_mean=(occ_sum / rounds if rounds else 0.0),
            )

    def absorb(finished):
        """Merge finished incremental reports — ``(lo, extra)`` pairs
        in submission order, the only order ``_HostWork.poll`` ever
        returns them in, so the totals merge exactly as the sync path's
        would."""
        for flo, extra in finished:
            fk, summary = deferred.pop(flo)
            if extra:
                summary = {**summary, **extra}
            merge_summaries(totals, summary)
            if telemetry is not None:
                telemetry.count(
                    "stream_seeds_done_total", fk,
                    help="seeds flushed into the merged report",
                )
                telemetry.event_mix(summary)
                telemetry.event("flush", lo=flo, k=fk)
            if on_chunk is not None:
                on_chunk(lo=flo, k=fk, summary=summary)
            publish_stats()

    def flush_ready():
        nonlocal next_flush_lo
        while next_flush_lo < n:
            c = next_flush_lo // chunk_size
            k = min(chunk_size, n - next_flush_lo)
            if c not in pend or not pend_have[c].all():
                return
            if telemetry is not None:
                t_flush = _time.perf_counter()
                f0 = tracer._now_us() if tracer is not None else 0.0
            chunk_state = _buf_state(pend.pop(c), treedef, keymask)
            pend_have.pop(c)
            sus = sus_buf.pop(c)
            summary = summarize(chunk_state)
            if incr:
                # defer the verdict: submit runs decode+dedup now, the
                # WGL slices run from the per-round polls, and absorb()
                # merges when the chunk's report is final
                host_work.submit(
                    chunk_state,
                    lo=next_flush_lo,
                    n=k,
                    seeds=seeds_host[next_flush_lo : next_flush_lo + k],
                    suspect=None if screen is None else sus,
                    summary=summary,
                )
                deferred[next_flush_lo] = (k, summary)
                if telemetry is not None:
                    dt = _time.perf_counter() - t_flush
                    telemetry.observe(
                        "stream_flush_seconds", dt,
                        help="virtual-chunk flush (summary+host work)",
                    )
                    if tracer is not None:
                        tracer.complete(
                            f"flush lo={next_flush_lo}", f0,
                            tracer._now_us() - f0, track="host",
                            args={"lo": next_flush_lo, "k": k},
                        )
                next_flush_lo += k
                continue
            if host_work is not None:
                extra = host_work(
                    chunk_state,
                    lo=next_flush_lo,
                    n=k,
                    seeds=seeds_host[next_flush_lo : next_flush_lo + k],
                    suspect=None if screen is None else sus,
                    summary=summary,
                )
                if extra:
                    summary = {**summary, **extra}
            merge_summaries(totals, summary)
            if telemetry is not None:
                dt = _time.perf_counter() - t_flush
                telemetry.observe(
                    "stream_flush_seconds", dt,
                    help="virtual-chunk flush (summary+host work)",
                )
                telemetry.count(
                    "stream_seeds_done_total", k,
                    help="seeds flushed into the merged report",
                )
                telemetry.event_mix(summary)
                telemetry.event(
                    "flush", lo=next_flush_lo, k=k, wall_s=round(dt, 6)
                )
                if tracer is not None:
                    tracer.complete(
                        f"flush lo={next_flush_lo}", f0,
                        tracer._now_us() - f0, track="host",
                        args={"lo": next_flush_lo, "k": k},
                    )
            if on_chunk is not None:
                on_chunk(lo=next_flush_lo, k=k, summary=summary)
            next_flush_lo += k
            publish_stats()

    def poll_feed():
        """One feed poll: extend the open-ended work queue with a fed
        segment. False when feed is absent or dry — the stream then
        drains and returns as usual. Growing the host-side queue arrays
        never touches the pool: fed items reach lanes through the same
        traced refill programs, at zero recompiles."""
        nonlocal n, seeds_host, budgets_host, order, params_host
        if feed is None:
            return False
        seg = feed()
        if seg is None:
            return False
        new_seeds = np.asarray(jnp.asarray(seg["seeds"], jnp.int64)).ravel()
        m = int(new_seeds.size)
        if m == 0 or m % chunk_size:
            raise ValueError(
                f"fed segment must be a non-empty multiple of "
                f"chunk_size={chunk_size}, got {m} seeds"
            )
        if (seg.get("params") is None) != (params_host is None):
            raise ValueError(
                "fed segment params presence must match the stream's"
            )
        nb = seg.get("budgets")
        nb = (
            np.full(m, cfg.max_steps, np.int32)
            if nb is None
            else np.asarray(nb, np.int32)
        )
        if nb.shape != (m,):
            raise ValueError(
                f"fed budgets must be shape ({m},), got {nb.shape}"
            )
        seeds_host = np.concatenate([seeds_host, new_seeds])
        budgets_host = np.concatenate([budgets_host, nb])
        order = np.concatenate(
            [order, np.arange(n, n + m, dtype=np.int64)]
        )
        if params_host is not None:
            params_host = jax.tree.map(
                lambda a, b: np.concatenate([a, np.asarray(b)]),
                params_host, seg["params"],
            )
        n += m
        if telemetry is not None:
            telemetry.count(
                "stream_feed_segments_total",
                help="work segments fed into the running stream",
            )
            telemetry.count(
                "stream_feed_items_total", m,
                help="work items fed into the running stream",
            )
        return True

    def dispatch_free():
        """Assign free lanes from the queue, polling ``feed`` for more
        whenever the queue runs dry while lanes sit free — the point
        where a fleet worker's newly leased batches enter the running
        pool, mid-flight."""
        nonlocal next_q, refills, state
        if reprioritize is not None and next_q < n:
            # the live reorder: hand the scheduler the undispatched
            # tail, let it permute the DISPATCH order only (results
            # still flush in submission order — bytes cannot move)
            tail = order[next_q:].copy()
            new = reprioritize(tail)
            if new is not None:
                new = np.asarray(new, np.int64)
                if new.shape != tail.shape or not np.array_equal(
                    np.sort(new), np.sort(tail)
                ):
                    raise ValueError(
                        "reprioritize must return a permutation of the "
                        "undispatched item indices it was given"
                    )
                order[next_q:] = new
        while True:
            free = np.nonzero(lane_item < 0)[0]
            if free.size == 0:
                return
            if next_q >= n and not poll_feed():
                return
            take = min(int(free.size), n - next_q)
            if take == 0:
                return
            lanes_t = free[:take]
            items_t = order[next_q : next_q + take]
            next_q += take
            refills += take
            if telemetry is not None:
                telemetry.count(
                    "stream_refills_total", take,
                    help="lanes refilled from the work queue",
                )
            lane_item[lanes_t] = items_t
            lane_budget[lanes_t] = budgets_host[items_t]
            pool_seeds[lanes_t] = seeds_host[items_t]
            if pool_params is not None:
                for p, s in zip(
                    jax.tree.leaves(pool_params),
                    jax.tree.leaves(params_host),
                ):
                    p[lanes_t] = s[items_t]
            if mesh is None:
                # fixed-width row refill: init exactly quorum-many
                # fresh lanes per event (padding short cohorts with
                # duplicates of their first lane), so total init
                # work is one init per item — same as chunked
                w = max(1, L // 8)
                for off in range(0, take, w):
                    sub = lanes_t[off : off + w]
                    idx = np.concatenate(
                        [sub, np.full(w - sub.size, sub[0], sub.dtype)]
                    )
                    state = _refill_rows(
                        workload, cfg, state,
                        jnp.asarray(idx, jnp.int32),
                        jnp.asarray(pool_seeds[idx]),
                        None
                        if pool_params is None
                        else jax.tree.map(
                            lambda a: jnp.asarray(a[idx]), pool_params
                        ),
                    )
            else:
                # mesh path: full-pool masked re-init keeps the
                # refill shape independent of the mesh layout
                mask = np.zeros(L, bool)
                mask[lanes_t] = True
                state = _refill(
                    workload, cfg, state,
                    place_pool(mask),
                    place_pool(pool_seeds),
                    place_params(pool_params),
                )

    rounds_this_call = 0
    while True:
        flush_ready()
        if next_flush_lo >= n:
            # everything queued so far is flushed; only a fed segment
            # can extend the stream now (all lanes are free, so the
            # dispatch below must land work or we are done)
            if not poll_feed():
                break
            dispatch_free()
            continue
        assigned = int(np.count_nonzero(lane_item >= 0))
        occ_sum += assigned / L
        if telemetry is not None:
            t_round = _time.perf_counter()
            r0 = tracer._now_us() if tracer is not None else 0.0
            telemetry.gauge(
                "stream_occupancy", assigned / L,
                help="assigned lanes / pool size at round start",
            )
            telemetry.gauge(
                "stream_queue_depth", n - next_q,
                help="work items not yet dispatched onto lanes",
            )
            if next_q >= n:
                telemetry.count(
                    "stream_drain_rounds_total",
                    help="rounds run after the queue went dry (drain tail)",
                )
            telemetry.sample(
                "stream occupancy",
                occupancy=assigned / L, queue_depth=n - next_q,
            )
        # while the queue still has work, exit the round as soon as a
        # refill quorum (L/8 lanes) retires — retired lanes hand their
        # slots over instead of burning frozen steps to the round
        # boundary; once the queue is dry, drain to the end (with a
        # feed, quorum exits persist: more work may arrive at any
        # retirement, so slots keep turning over)
        stop = (
            max(assigned - max(1, L // 8), 0)
            if (next_q < n or feed is not None)
            else 0
        )
        budget_dev = jnp.asarray(lane_budget)
        stop_dev = jnp.asarray([stop], jnp.int32)
        if incr:
            t_disp = _time.perf_counter()
        if mesh is None:
            state = _round(
                workload, cfg, round_steps, state, budget_dev, stop_dev[0]
            )
        else:
            state = _round_sharded(workload, cfg, round_steps, mesh)(
                state, budget_dev, stop_dev
            )
        rounds += 1
        rounds_this_call += 1

        if incr:
            # the round program is dispatched but not synced: this is
            # the host's idle window, so burn it on deferred WGL work
            # under the round-time EMA budget (its own cost excluded —
            # the feedback otherwise inflates the budget it measures)
            t_poll = _time.perf_counter()
            absorb(host_work.poll(round_ema))
            poll_s = _time.perf_counter() - t_poll
        done = np.asarray(state.done)  # syncs on the round program
        if incr:
            dt = max(0.0, _time.perf_counter() - t_disp - poll_s)
            round_ema = dt if round_ema == 0.0 else (
                0.5 * round_ema + 0.5 * dt
            )
        if telemetry is not None:
            telemetry.observe(
                "stream_round_seconds", _time.perf_counter() - t_round,
                help="device round (dispatch -> pool state on host)",
            )
            telemetry.count("stream_rounds_total")
            if tracer is not None:
                tracer.complete(
                    f"round {rounds}", r0, tracer._now_us() - r0,
                    track="device",
                    args={"occupancy": assigned / L, "queue": n - next_q},
                )
        ctr = np.asarray(state.ctr)
        retired = (lane_item >= 0) & (done | (ctr >= lane_budget))
        if retired.any():
            if telemetry is not None:
                telemetry.count(
                    "stream_retired_total", int(retired.sum()),
                    help="lanes retired (retirement flux)",
                )
                telemetry.observe(
                    "stream_refill_quorum_seconds",
                    _time.perf_counter() - t_round,
                    help="round dispatch -> retirement cohort on host "
                    "(refill quorum latency)",
                )
            # one screen per retirement cohort, on the pool state; the
            # suspect bit is a pure per-lane function, so these bits are
            # exactly what a per-chunk screen would produce
            susp = None if screen is None else np.asarray(screen(state))
            host_leaves = _pool_to_host(state, keymask)
            idx = np.nonzero(retired)[0]
            capture(
                lane_item[idx],
                [leaf[idx] for leaf in host_leaves],
                None if susp is None else susp[idx],
            )
            lane_item[idx] = -1
            lane_budget[idx] = 0  # freeze until refilled
            dispatch_free()

        if (
            stop_after_rounds is not None
            and rounds_this_call >= stop_after_rounds
        ):
            flush_ready()
            if next_flush_lo >= n:
                break
            publish_stats()  # snapshot leaves a current occupancy record
            if telemetry is not None:
                telemetry.event(
                    "snapshot", rounds=int(rounds),
                    next_flush_lo=int(next_flush_lo),
                )
            from .checkpoint import save_stream

            # the v9 row format: item -> per-leaf rows (views into the
            # pending chunk buffers)
            pending_rows: dict = {}
            susp_rows: dict = {}
            for c, bufs in pend.items():
                lo = c * chunk_size
                for p in np.nonzero(pend_have[c])[0]:
                    it = lo + int(p)
                    pending_rows[it] = [b[p] for b in bufs]
                    if screen is not None:
                        susp_rows[it] = bool(sus_buf[c][p])
            save_stream(
                ckpt_path, state,
                pending=pending_rows, susp=susp_rows,
                meta={
                    "fingerprint": fp,
                    "seeds_sha": seeds_sha,
                    "order_sha": order_sha,
                    "chunk_size": int(chunk_size),
                    "lanes": int(L),
                    "lane_item": [int(x) for x in lane_item],
                    "lane_budget": [int(x) for x in lane_budget],
                    "next_q": int(next_q),
                    "next_flush_lo": int(next_flush_lo),
                    "totals": totals,
                    "rounds": int(rounds),
                    "refills": int(refills),
                    "occ_sum": float(occ_sum),
                },
            )
            break

    if incr:
        # settle any WGL work still pending after the last flush so the
        # returned totals are complete (drain preserves submission order,
        # so the merged summary is byte-for-byte the sync path's).
        absorb(host_work.drain())

    publish_stats()
    return totals
