"""TPU tier: the batched struct-of-arrays simulation engine.

This is the re-design of the reference's inner simulation loop
(pop-min-event / advance-clock / RNG-draw / deliver-message — see
madsim/src/sim/task/mod.rs:220-317 and SURVEY.md §3.1) as a JAX engine that
steps **thousands of seeds in lockstep**:

- every piece of per-seed simulator state (virtual clock, event queue,
  workload actor state, link-state network tables) is a leading-batch-axis
  array (struct-of-arrays);
- one jitted ``step`` pops the minimum-time event, advances the clock,
  draws counter-based randomness keyed by ``(seed, event_index)`` and
  dispatches to the workload's pure handler — vmapped over the seed batch;
- seeds that finish are masked out (``done`` flag) so divergent control
  flow never breaks lockstep;
- everything is integer math (times are int64 nanoseconds, randomness is
  threefry bits), so a sweep is **bit-exact across CPU and TPU backends**:
  any failure found in a TPU batch replays byte-identically with
  ``run_traced`` on CPU.

Scale-out is pure data parallelism over seeds (SURVEY.md §2.3): shard the
seed batch over a ``jax.sharding.Mesh`` — see ``madsim_tpu.parallel``.

64-bit note: virtual time is int64 nanoseconds (the bit-exactness rule of
SURVEY.md §7 forbids float time math), so importing this package enables
``jax_enable_x64``. XLA:TPU emulates int64 with 32-bit pairs; the engine's
hot comparisons are cheap relative to event dispatch.
"""

import jax

jax.config.update("jax_enable_x64", True)
# the engine's draw stream (and the native C++ replay of it,
# madsim_tpu/native) is defined by the partitionable threefry counter
# scheme — pin it against future default changes
jax.config.update("jax_threefry_partitionable", True)

from .core import (  # noqa: E402
    EngineConfig,
    EngineState,
    Emits,
    Workload,
    init_sweep,
    run_sweep,
    run_traced,
    step_batch,
)
from .queue import EventQueue  # noqa: E402
from .stream import stream_sweep  # noqa: E402

__all__ = [
    "EngineConfig",
    "EngineState",
    "Emits",
    "EventQueue",
    "Workload",
    "init_sweep",
    "run_sweep",
    "run_traced",
    "step_batch",
    "stream_sweep",
]
