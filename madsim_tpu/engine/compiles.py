"""XLA compile counting: the honest program-reuse measurement.

``count_compiles()`` wraps a code region in ``jax.log_compiles`` and
counts "Finished XLA compilation" log records — the ground truth for
every zero-recompile claim in this repo (a ragged tail, a mutated
campaign candidate, or a differential-grid spec that recompiles anything
shows up here; self-reported shape bookkeeping does not count).

Grew out of scripts/sweep_million.py's one-script hack; now a first-class
metric shared by the explore demo, the campaign bench leg, and the
spec-as-data tests (tests/test_fault_params.py), so "compiles in the
timed region" is reported the same way everywhere.
"""

from __future__ import annotations

import logging
from contextlib import contextmanager

import jax


class CompileCounter(logging.Handler):
    """Counts finished XLA compilations surfaced by ``jax.log_compiles``."""

    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.count = 0

    def emit(self, record):
        if "Finished XLA compilation" in record.getMessage():
            self.count += 1


@contextmanager
def count_compiles():
    """``with count_compiles() as c:`` ... ``c.count`` is the number of
    XLA compilations the region performed (0 after a proper warm-up is
    the spec-as-data contract — docs/faults.md)."""
    handler = CompileCounter()
    logger = logging.getLogger("jax")
    logger.addHandler(handler)
    try:
        with jax.log_compiles(True):
            yield handler
    finally:
        logger.removeHandler(handler)
