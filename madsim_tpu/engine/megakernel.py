"""VMEM-resident multi-step megakernel — the round-3 headroom probe.

``docs/pallas_finding.md`` §3 measured the flat-loop sweep at ~300 GB/s of
~820 GB/s HBM and attributed the gap to the loop carry round-tripping HBM
every event; the named fix was a *full-step megakernel* that keeps a
seed-tile's whole state resident in VMEM across many steps. This module
builds that kernel and measures it honestly.

Scope: the kernel implements the engine's COMPLETE per-event step — counter
RNG (threefry, bit-identical to ``jax.random``), ``pop_min`` with the
murmur tie-break, 64-bit virtual-time arithmetic (int64 emulated as
sign-biased (hi, lo) int32 planes — TPU vector units have no int64 lanes),
the done/time-limit masking of ``core.step_one``, the handler, and the
rank-select push — for a *probe workload* (``probe_workload``) with the
same structural shape as the MadRaft model: Q=58 queue, 8 payload slots,
15 draws/event, 7-wide emit batch, a [5, 32] log-like state plane. The
workload is defined once as ordinary engine code, so the XLA path runs it
via ``run_sweep``'s machinery and the kernel's final state must match
**bit-exactly** (asserted in tests and in the bench).

Why a probe workload and not the raft model itself: the megakernel
hypothesis is about *memory residency*, not about raft — a structurally
faithful step (same queue, same RNG cost, same masked-write pattern, same
state footprint) measures the residency effect at ~1/4 of the kernel
surface. If the probe shows a win, porting the raft handler is mechanical
follow-up; if it shows none, the headroom claim is closed for every
workload of this shape.

Reference analogy: the ref's hot loop is compiled and cache-resident by
construction (madsim/src/sim/task/mod.rs:220-317); this is the TPU-tier
equivalent question — can the event loop live in fast memory?
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import queue as equeue
from .core import Emits, EngineConfig, EngineState, Workload
from .queue import INVALID_TIME, _HASH_MULT
from .rng import bounded

# -- probe workload (runs on BOTH paths) -----------------------------------

_N = 5  # nodes (raft parity)
_L = 32  # log slots per node
_Q = 58  # queue capacity (raft config #3)
_P = 8  # payload slots
_NUM_RAND = 13  # raft: 2N+3
_MAX_EMITS = 7  # raft: N+2
_DELAY_LO = 1_000_000  # 1 ms
_DELAY_HI = 20_000_001  # 20 ms


class _ProbeW(NamedTuple):
    ring: jnp.ndarray  # int32[N, L] — the raft log-write analogue
    acc: jnp.ndarray  # int32 rolling mix of draws
    nsent: jnp.ndarray  # int32 events handled


def _probe_init(key) -> Tuple[_ProbeW, Emits]:
    del key  # deterministic init: the A/B needs no extra draw stream
    w = _ProbeW(
        ring=jnp.zeros((_N, _L), jnp.int32),
        acc=jnp.zeros((), jnp.int32),
        nsent=jnp.zeros((), jnp.int32),
    )
    e = jnp.arange(_MAX_EMITS, dtype=jnp.int64)
    times = (e + 1) * 1_000_000
    kinds = jnp.zeros((_MAX_EMITS,), jnp.int32)
    pays = jnp.zeros((_MAX_EMITS, _P), jnp.int32)
    pays = pays.at[:, 0].set(jnp.arange(_MAX_EMITS, dtype=jnp.int32) % _N)
    enables = e < _N  # N live timers, one per node
    return w, Emits(times=times, kinds=kinds, pays=pays, enables=enables)


def _probe_handle(w: _ProbeW, now, kind, pay, rand) -> Tuple[_ProbeW, Emits]:
    """One event: mix draws into state, one masked log write, re-arm one
    timer on a random node — every arithmetic op integer, so the kernel
    can reproduce it bit-for-bit."""
    del kind
    node = pay[0]
    acc = (w.acc + (rand[0] ^ rand[1]).astype(jnp.int32)).astype(jnp.int32)
    idx = jnp.bitwise_and(acc, _L - 1)
    flat = jnp.arange(_N * _L, dtype=jnp.int32).reshape(_N, _L)
    mask = flat == (node * _L + idx)
    ring = jnp.where(mask, rand[2].astype(jnp.int32), w.ring)
    nsent = w.nsent + 1

    delay = bounded(rand[3], _DELAY_LO, _DELAY_HI)
    next_node = bounded(rand[4], 0, _N).astype(jnp.int32)

    times = jnp.full((_MAX_EMITS,), now, jnp.int64).at[0].set(now + delay)
    kinds = jnp.zeros((_MAX_EMITS,), jnp.int32)
    pays = jnp.zeros((_MAX_EMITS, _P), jnp.int32)
    pays = pays.at[0, 0].set(next_node)
    pays = pays.at[0, 1].set(rand[5].astype(jnp.int32))
    enables = jnp.arange(_MAX_EMITS) < 1  # exactly the re-arm event
    return _ProbeW(ring=ring, acc=acc, nsent=nsent), Emits(
        times=times, kinds=kinds, pays=pays, enables=enables
    )


def probe_workload() -> Workload:
    return Workload(
        init=_probe_init,
        handle=_probe_handle,
        num_rand=_NUM_RAND,
        payload_slots=_P,
        max_emits=_MAX_EMITS,
    )


def probe_config(max_steps: int) -> EngineConfig:
    # horizon far beyond max_steps * 20 ms so no seed ever finishes: both
    # paths run exactly max_steps real events per seed
    return EngineConfig(
        queue_capacity=_Q,
        time_limit_ns=1 << 62,
        max_steps=max_steps,
    )


# -- 64-bit (hi, lo) int32-plane helpers (kernel side) ---------------------

_SIGN = 0x80000000
_INV_HI = int(INVALID_TIME) >> 32  # 0x7fffffff
_INV_LO_B = 0x7FFFFFFF  # sign-biased lo half of INVALID_TIME


def _u(x):
    return x.astype(jnp.uint32)


def _i(x):
    return x.astype(jnp.int32)


def _split64(t: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """int64 -> (hi int32, lo sign-biased int32): lexicographic signed
    compare on the planes == int64 compare."""
    hi = (t >> 32).astype(jnp.int32)
    lo = ((t & 0xFFFFFFFF).astype(jnp.uint32) ^ jnp.uint32(_SIGN)).astype(jnp.int32)
    return hi, lo


def _join64(hi: jnp.ndarray, lob: jnp.ndarray) -> jnp.ndarray:
    lo_u = (_u(lob) ^ jnp.uint32(_SIGN)).astype(jnp.int64)
    return (hi.astype(jnp.int64) << 32) | lo_u


def _add64_u32(hi, lob, delta_u32):
    """(hi, lob) + delta (a uint32 < 2^31); returns (hi', lob')."""
    lo_u = _u(lob) ^ jnp.uint32(_SIGN)
    s = lo_u + _u(delta_u32)
    carry = (s < lo_u).astype(jnp.int32)
    return hi + carry, _i(s ^ jnp.uint32(_SIGN))


def _gt64(ahi, alob, bhi, blob):
    return (ahi > bhi) | ((ahi == bhi) & (alob > blob))


def _max64(ahi, alob, bhi, blob):
    agt = _gt64(ahi, alob, bhi, blob)
    return jnp.where(agt, ahi, bhi), jnp.where(agt, alob, blob)


def _mulhi32(x_u32, c: int):
    """floor(x * c / 2**32) for a static c < 2**32, via 16-bit limbs —
    the ``bounded`` reduction without int64 lanes."""
    ch, cl = (c >> 16) & 0xFFFF, c & 0xFFFF
    xh = _u(x_u32) >> 16
    xl = _u(x_u32) & jnp.uint32(0xFFFF)
    low = xl * cl
    mid1 = xh * cl
    mid2 = xl * ch
    s = mid1 + mid2
    c1 = (s < mid1).astype(jnp.uint32)
    s2 = s + (low >> 16)
    c2 = (s2 < s).astype(jnp.uint32)
    return xh * ch + (s2 >> 16) + ((c1 + c2) << 16)


# -- threefry2x32 (bit-identical to jax.random's stream) -------------------

_ROT = (13, 15, 26, 6, 17, 29, 16, 24)


def _rotl(x, d: int):
    return (x << d) | (x >> (32 - d))


def _threefry2x32(k0, k1, c0, c1):
    """One threefry-2x32 block (20 rounds) on uint32 vectors — the same
    math as native/simcore.cpp:threefry2x32 and jax.random."""
    ks2 = k0 ^ k1 ^ jnp.uint32(0x1BD11BDA)
    ks = (k0, k1, ks2)
    x0 = c0 + ks[0]
    x1 = c1 + ks[1]
    for block in range(5):
        r = _ROT[4:] if block % 2 else _ROT[:4]
        for i in range(4):
            x0 = x0 + x1
            x1 = _rotl(x1, r[i])
            x1 = x1 ^ x0
        s = block + 1
        x0 = x0 + ks[s % 3]
        x1 = x1 + ks[(s + 1) % 3] + jnp.uint32(s)
    return x0, x1


def _event_words(k0, k1, ctr_u32, n: int):
    """``event_bits(key, ctr, n)`` in-kernel: fold_in then n counter
    draws, each word the XOR of the output pair.  Shapes: k0/k1/ctr are
    [T, 1] uint32; returns [T, n] uint32."""
    f0, f1 = _threefry2x32(k0, k1, jnp.zeros_like(ctr_u32), ctr_u32)
    zeros = jnp.zeros((k0.shape[0], n), jnp.uint32)
    idx = jax.lax.broadcasted_iota(jnp.uint32, (k0.shape[0], n), 1)
    o0, o1 = _threefry2x32(f0, f1, zeros, idx)  # broadcasts [T,1] keys
    return o0 ^ o1


def _murmur_prio(iota_u32, tie_u32):
    x = iota_u32 * jnp.uint32(_HASH_MULT) ^ tie_u32
    x ^= x >> 16
    x *= jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x *= jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


# -- the megakernel --------------------------------------------------------


def _mega_kernel(steps: int, time_limit: int, qp: int,
                 # inputs (aliased to outputs)
                 qthi_r, qtlo_r, qkind_r, qpay_r,
                 key_r, now_r, ctr_r, done_r, ov_r, qmax_r,
                 ring_r, acc_r, nsent_r,
                 # outputs
                 qthi_o, qtlo_o, qkind_o, qpay_o,
                 key_o, now_o, ctr_o, done_o, ov_o, qmax_o,
                 ring_o, acc_o, nsent_o):
    """``steps`` engine events for one [T]-seed tile, all state in VMEM."""
    lim_hi = time_limit >> 32
    lim_lob = (time_limit & 0xFFFFFFFF) ^ _SIGN
    if lim_lob >= 1 << 31:  # spell the biased lo half in int32 range
        lim_lob -= 1 << 32

    qthi = qthi_r[:]
    qtlo = qtlo_r[:]
    qkind = qkind_r[:]
    qpay = qpay_r[:]  # int32[T, P, qp] — payload slot-major
    k0 = _u(key_r[:, 0:1])
    k1 = _u(key_r[:, 1:2])
    now_hi = now_r[:, 0:1]
    now_lob = now_r[:, 1:2]
    ctr = ctr_r[:]
    done = done_r[:]
    ov = ov_r[:]
    qmax = qmax_r[:]
    ring = ring_r[:]
    acc = acc_r[:]
    nsent = nsent_r[:]

    T = qthi.shape[0]
    q_iota_u = jax.lax.broadcasted_iota(jnp.uint32, (T, qp), 1)
    q_iota_i = jax.lax.broadcasted_iota(jnp.int32, (T, qp), 1)
    ring_iota = jax.lax.broadcasted_iota(jnp.int32, (T, _N * _L), 1)

    def body(_, carry):
        (qthi, qtlo, qkind, qpay, now_hi, now_lob, ctr, done, ov, qmax,
         ring, acc, nsent) = carry
        active = done == 0

        # draws (rand[0] jitter, rand[1] tie, rand[2:] handler)
        w = _event_words(k0, k1, _u(ctr), _NUM_RAND + 2)

        # ---- pop_min (lexicographic min + murmur tie-break) ----
        mh = jnp.min(qthi, axis=1, keepdims=True)
        c1m = qthi == mh
        ml = jnp.min(jnp.where(c1m, qtlo, jnp.int32(0x7FFFFFFF)), axis=1,
                     keepdims=True)
        cand = c1m & (qtlo == ml)
        prio = _murmur_prio(q_iota_u, w[:, 1:2])
        pb = _i(prio ^ jnp.uint32(_SIGN))
        mp = jnp.min(jnp.where(cand, pb, jnp.int32(0x7FFFFFFF)), axis=1,
                     keepdims=True)
        winner = cand & (pb == mp)
        first = jnp.min(jnp.where(winner, q_iota_i, jnp.int32(qp)), axis=1,
                        keepdims=True)
        sel = q_iota_i == first  # one-hot popped slot [T, qp]
        found = ~((mh == _INV_HI) & (ml == _INV_LO_B))  # [T,1]

        # one-hot extraction via MAX, not sum: under x64 jnp.sum(int32)
        # inserts an int64 convert that Mosaic cannot lower (and its
        # _convert_helper recurses on). sel is always exactly one slot, so
        # max-over-masked == the selected value. Downstream uses are
        # take-gated exactly like the XLA path, so the !found garbage
        # values never reach state.
        imin = jnp.int32(-0x80000000)
        kind = jnp.max(jnp.where(sel, qkind, imin), axis=1, keepdims=True)
        pay = jnp.max(jnp.where(sel[:, None, :], qpay, imin), axis=2)  # [T,P]

        # ---- clock: now' = max(now, t) + jitter ----
        jitter = jnp.uint32(50) + _mulhi32(w[:, 0:1], 51)
        nh, nl = _max64(now_hi, now_lob, mh, ml)
        nh, nl = _add64_u32(nh, nl, jitter)
        time_up = _gt64(nh, nl, jnp.int32(lim_hi), jnp.int32(lim_lob))
        dispatch = found & ~time_up
        take = active & dispatch  # [T,1]

        # remove the popped slot — gated like the XLA pop (enable=active):
        # a budget-cut event is still consumed even though nothing else
        # is written (core.step_one pops with enable=active, not take)
        rm = sel & (active & found)
        qthi = jnp.where(rm, jnp.int32(_INV_HI), qthi)
        qtlo = jnp.where(rm, jnp.int32(_INV_LO_B), qtlo)

        # ---- handler (probe workload, bit-identical to _probe_handle) ----
        node = pay[:, 0:1]
        acc_n = _i(_u(acc) + (w[:, 2:3] ^ w[:, 3:4]))
        idx = acc_n & jnp.int32(_L - 1)
        rmask = (ring_iota == node * _L + idx) & take
        ring_n = jnp.where(rmask, _i(w[:, 4:5]), ring)
        nsent_n = jnp.where(take, nsent + 1, nsent)

        delay = _mulhi32(w[:, 5:6], _DELAY_HI - _DELAY_LO) + jnp.uint32(_DELAY_LO)
        next_node = _i(_mulhi32(w[:, 6:7], _N))
        eth, etl = _add64_u32(nh, nl, delay)

        # ---- push the re-arm event at the first free slot ----
        free = (qthi == _INV_HI) & (qtlo == _INV_LO_B)
        ffirst = jnp.min(jnp.where(free, q_iota_i, jnp.int32(qp)), axis=1,
                         keepdims=True)
        wmask = (q_iota_i == ffirst) & take  # first-free one-hot
        qthi = jnp.where(wmask, eth, qthi)
        qtlo = jnp.where(wmask, etl, qtlo)
        qkind = jnp.where(wmask, jnp.int32(0), qkind)
        # payload write without .at[].set (Mosaic has no scatter): select
        # the new [P]-column by plane-index iota
        p_iota = jax.lax.broadcasted_iota(jnp.int32, qpay.shape, 1)
        newpay = jnp.where(
            p_iota == 0, next_node[:, None, :],
            jnp.where(p_iota == 1, _i(w[:, 7:8])[:, None, :], jnp.int32(0)),
        )
        qpay = jnp.where(wmask[:, None, :], newpay, qpay)
        # any(free) via the first-free index (jnp.any's reduce_or crashes
        # this Mosaic backend); ffirst == qp means no free slot
        have_room = ffirst < jnp.int32(qp)
        ov_n = ov | (take & ~have_room)

        # occupancy count as a float32 sum (exact for <= 2^24 slots; the
        # int32 sum would hit the same Mosaic int64 promotion)
        qsize = jnp.sum(
            (~((qthi == _INV_HI) & (qtlo == _INV_LO_B))).astype(jnp.float32),
            axis=1, keepdims=True,
        ).astype(jnp.int32)
        qmax_n = jnp.maximum(qmax, qsize)

        now_hi2 = jnp.where(take, nh, now_hi)
        now_lob2 = jnp.where(take, nl, now_lob)
        ctr_n = jnp.where(take, ctr + 1, ctr)
        done_n = done | (active & (~found | time_up)).astype(jnp.int32)
        ring2 = ring_n
        acc2 = jnp.where(take, acc_n, acc)

        return (qthi, qtlo, qkind, qpay, now_hi2, now_lob2, ctr_n, done_n,
                ov_n, qmax_n, ring2, acc2, nsent_n)

    carry = (qthi, qtlo, qkind, qpay, now_hi, now_lob, ctr, done, ov, qmax,
             ring, acc, nsent)
    carry = jax.lax.fori_loop(0, steps, body, carry)
    (qthi, qtlo, qkind, qpay, now_hi, now_lob, ctr, done, ov, qmax,
     ring, acc, nsent) = carry

    qthi_o[:] = qthi
    qtlo_o[:] = qtlo
    qkind_o[:] = qkind
    qpay_o[:] = qpay
    key_o[:] = key_r[:]
    now_o[:, 0:1] = now_hi
    now_o[:, 1:2] = now_lob
    ctr_o[:] = ctr
    done_o[:] = done
    ov_o[:] = ov
    qmax_o[:] = qmax
    ring_o[:] = ring
    acc_o[:] = acc
    nsent_o[:] = nsent


@partial(jax.jit, static_argnames=("steps", "time_limit", "tile", "interpret"))
def run_megasweep(state: EngineState, steps: int,
                  time_limit: int = 1 << 62, tile: int = 256,
                  interpret: bool = False) -> EngineState:
    """Advance a batched probe-workload state ``steps`` events per seed
    entirely inside the megakernel; returns the same ``EngineState``
    structure as the XLA driver (bit-identical, asserted by the tests)."""
    from jax.experimental import pallas as pl

    S = state.seed.shape[0]
    if S % tile:
        raise ValueError(f"batch {S} must be a multiple of tile {tile}")
    if state.cover.shape[1]:
        raise ValueError(
            "run_megasweep does not fold coverage bits (the probe "
            "workload defines none); a cover-enabled workload would "
            "silently report all-zero coverage"
        )
    if state.hist_rec.shape[1]:
        raise ValueError(
            "run_megasweep does not append op-history records (the probe "
            "workload records none); a record-enabled workload would "
            "silently report an empty history — and downstream, the "
            "device history screen (oracle/screen.py) would clear every "
            "seed as boring. Checked sweeps go through the XLA driver "
            "(engine/checkpoint.run_sweep_pipelined)"
        )
    qn = state.queue.time.shape[1]
    qp = qn  # Mosaic pads lanes internally; keep logical width

    qthi, qtlo = _split64(state.queue.time)
    key = jax.random.key_data(state.key).astype(jnp.uint32).astype(jnp.int32)
    nh, nl = _split64(state.now_ns)
    now2 = jnp.stack([nh, nl], axis=1)
    w: _ProbeW = state.wstate

    ins = [
        qthi, qtlo, state.queue.kind,
        jnp.swapaxes(state.queue.pay, 1, 2),  # [S, P, Q] slot-major
        key, now2,
        state.ctr.astype(jnp.int32).reshape(S, 1),
        state.done.astype(jnp.int32).reshape(S, 1),
        state.overflow.astype(jnp.int32).reshape(S, 1),
        # qmax is int64 in the XLA state (x64 sum); values fit int32
        state.qmax.astype(jnp.int32).reshape(S, 1),
        w.ring.reshape(S, _N * _L),
        w.acc.reshape(S, 1),
        w.nsent.reshape(S, 1),
    ]
    row2 = lambda i: (i, jnp.int32(0))  # noqa: E731
    row3 = lambda i: (i, jnp.int32(0), jnp.int32(0))  # noqa: E731

    # one tile per pallas_call: XLA stages each call's operand AND result
    # tuples in scoped VMEM (~2x the tile state; a 4096-seed call OOMs the
    # 16 MB budget), which is exactly the residency the megakernel wants —
    # the tile lives in VMEM for all `steps` events, and the HBM round
    # trip happens once per call, not per event. lax.map sequences tiles
    # through ONE compiled kernel instance.
    chunk = min(S, tile)

    def spec(a):
        if a.ndim == 3:
            return pl.BlockSpec((tile, a.shape[1], a.shape[2]), row3)
        return pl.BlockSpec((tile, a.shape[1]), row2)

    def call(chunk_ins):
        in_specs = [spec(a) for a in chunk_ins]
        out_shape = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in chunk_ins]
        return pl.pallas_call(
            partial(_mega_kernel, steps, time_limit, qp),
            grid=(chunk // tile,),
            in_specs=in_specs,
            out_specs=in_specs,
            out_shape=out_shape,
            input_output_aliases={i: i for i in range(len(chunk_ins))},
            interpret=interpret,
        )(*chunk_ins)

    if S == chunk:
        outs = call(ins)
    else:
        stacked = [a.reshape(S // chunk, chunk, *a.shape[1:]) for a in ins]
        outs = jax.lax.map(lambda xs: tuple(call(list(xs))), tuple(stacked))
        outs = [a.reshape(S, *a.shape[2:]) for a in outs]

    (qthi, qtlo, qkind, qpay, key_o, now2, ctr, done, ov, qmax,
     ring, acc, nsent) = outs
    return EngineState(
        seed=state.seed,
        key=state.key,
        now_ns=_join64(now2[:, 0], now2[:, 1]),
        ctr=ctr[:, 0].astype(state.ctr.dtype),
        done=done[:, 0].astype(bool),
        overflow=ov[:, 0].astype(bool),
        qmax=qmax[:, 0].astype(state.qmax.dtype),
        # the probe workload defines no coverage signal (cover_bits=0) and
        # no history recording (hist_slots=0), so the width-0 planes pass
        # through untouched on both paths
        cover=state.cover,
        hist_rec=state.hist_rec,
        hist_t=state.hist_t,
        hist_len=state.hist_len,
        hist_overflow=state.hist_overflow,
        queue=equeue.EventQueue(
            time=_join64(qthi, qtlo),
            kind=qkind,
            pay=jnp.swapaxes(qpay, 1, 2),
        ),
        wstate=_ProbeW(
            ring=ring.reshape(S, _N, _L),
            acc=acc[:, 0],
            nsent=nsent[:, 0],
        ),
        # probe workload defines no event-mix plane (event_mix_kinds=0)
        evmix=state.evmix,
    )
