"""Pallas TPU kernel for the batched pop-min (random tie-break) phase.

SURVEY.md §7 stage 5 reserves Pallas for the event-queue inner loop if the
jit path bottlenecks. Round-3 profiling (see docs/pallas_finding.md)
showed the real 10x levers were loop structure, not op kernels — this
module exists to *prove* the remaining headroom claim with a measured
A/B rather than assert it: ``scripts/bench_pallas.py`` races this kernel
against the XLA path that ``engine.queue.pop_min`` compiles to, asserting
bit-identical pop decisions.

Kernel design notes (TPU constraints):
- TPU vector units have no int64 lanes, so the int64 deadline array is
  split into (hi, lo) int32 planes and the min is lexicographic; unsigned
  order for the lo half (and for the tie-break priorities) is recovered
  by XOR-ing the sign bit before signed compares.
- The whole [block, Q] tile lives in VMEM; min/tie-break/index-select are
  a handful of VPU reductions. Q is lane-padded to 128 with INVALID
  deadlines, seed blocks ride the sublane axis.
- The tie-break priority hash is bit-identical to ``queue.pop_min``
  (same murmur3 finalizer over slot iota XOR draw), and the
  winner-selection order (min priority, then min slot index among
  candidates) matches XLA ``argmin`` semantics exactly — the kernel can
  substitute without breaking replay parity.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .queue import _HASH_MULT, INVALID_TIME, EventQueue

_LANE = 128
_BLOCK = 128  # seeds per grid step

# python ints (a jnp scalar would be captured as a traced kernel constant)
_INV_HI = int(INVALID_TIME) >> 32  # 0x7fffffff
_SIGN = 0x80000000
_INV_LO_BIASED = 0x7FFFFFFF  # sign-biased lo half of INVALID_TIME as signed int32


def _murmur_prio(iota_u32, tie_u32):
    """The queue.pop_min priority hash, verbatim (uint32 ops)."""
    x = iota_u32 * _HASH_MULT ^ tie_u32
    x ^= x >> 16
    x *= 0x85EBCA6B
    x ^= x >> 13
    x *= 0xC2B2AE35
    return x ^ (x >> 16)


def _kernel(thi_ref, tlo_ref, tie_ref, slot_ref, found_ref):
    thi = thi_ref[:]  # int32[B, Qp]
    tlo = tlo_ref[:]  # int32[B, Qp], sign-biased unsigned lo half
    tie = tie_ref[:]  # int32[B, 1] raw tie draw bits

    # lexicographic min over slots: min hi, then min (unsigned) lo there
    mh = jnp.min(thi, axis=1, keepdims=True)
    c1 = thi == mh
    ml = jnp.min(
        jnp.where(c1, tlo, jnp.int32(0x7FFFFFFF)), axis=1, keepdims=True
    )
    cand = c1 & (tlo == ml)

    # random tie-break: minimal murmur priority among candidates, then
    # minimal slot index — exactly argmin(where(cand, prio, BIG)) order
    q_iota = jax.lax.broadcasted_iota(jnp.uint32, thi.shape, 1)
    prio = _murmur_prio(q_iota, tie.astype(jnp.uint32))
    pb = (prio ^ _SIGN).astype(jnp.int32)  # unsigned order, signed compare
    mp = jnp.min(
        jnp.where(cand, pb, jnp.int32(0x7FFFFFFF)), axis=1, keepdims=True
    )
    winner = cand & (pb == mp)
    qp = thi.shape[1]
    idx = jax.lax.broadcasted_iota(jnp.int32, thi.shape, 1)
    slot = jnp.min(jnp.where(winner, idx, jnp.int32(qp)), axis=1)

    found = ~((mh[:, 0] == _INV_HI) & (ml[:, 0] == _INV_LO_BIASED))
    slot_ref[:, 0] = slot
    found_ref[:, 0] = found.astype(jnp.int32)


@partial(jax.jit, static_argnames=("interpret",))
def pop_min_pallas(q: EventQueue, tie_u32: jnp.ndarray, interpret: bool = False):
    """Batched pop decision via the Pallas kernel.

    ``q`` holds a LEADING seed axis on every leaf ([S, Q] / [S, Q, P]);
    ``tie_u32`` is uint32[S]. Returns ``(slot int32[S], found bool[S])``
    — bit-identical to what ``vmap(queue.pop_min)`` selects. Boundary
    costs (int64 split, lane padding) are inside this function on
    purpose: any honest A/B must pay them.
    """
    from jax.experimental import pallas as pl

    t = q.time  # int64[S, Q]
    s, qn = t.shape
    qp = -(-qn // _LANE) * _LANE
    thi = (t >> 32).astype(jnp.int32)
    tlo_u = (t & 0xFFFFFFFF).astype(jnp.uint32)
    tlo = (tlo_u ^ jnp.uint32(_SIGN)).astype(jnp.int32)
    if qp != qn:
        pad_hi = jnp.full((s, qp - qn), _INV_HI, jnp.int32)
        pad_lo = jnp.full((s, qp - qn), _INV_LO_BIASED, jnp.int32)
        thi = jnp.concatenate([thi, pad_hi], axis=1)
        tlo = jnp.concatenate([tlo, pad_lo], axis=1)
    tie = tie_u32.astype(jnp.uint32).astype(jnp.int32).reshape(s, 1)

    # index maps return an int32 zero explicitly: under jax_enable_x64
    # (which this engine forces) a literal 0 promotes to i64 and Mosaic
    # rejects the mixed (i32, i64) index tuple
    row = lambda i: (i, jnp.int32(0))  # noqa: E731
    grid = (s // _BLOCK,) if s % _BLOCK == 0 else (-(-s // _BLOCK),)
    slot, found = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK, qp), row),
            pl.BlockSpec((_BLOCK, qp), row),
            pl.BlockSpec((_BLOCK, 1), row),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK, 1), row),
            pl.BlockSpec((_BLOCK, 1), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, 1), jnp.int32),
            jax.ShapeDtypeStruct((s, 1), jnp.int32),
        ],
        interpret=interpret,
    )(thi, tlo, tie)
    return slot[:, 0], found[:, 0].astype(bool)


@jax.jit
def pop_min_xla(q: EventQueue, tie_u32: jnp.ndarray):
    """The production path's pop decision, reduced to (slot, found) for
    the A/B: same math ``queue.pop_min`` runs inside the fused step."""
    from .queue import pop_min

    def one(qi, tie):
        _, t, _, _, found = pop_min(qi, tie_u32=tie)
        # recover the chosen slot the same way pop_min's mask does
        iota = jnp.arange(qi.time.shape[0], dtype=jnp.uint32)
        prio = _murmur_prio(iota, jnp.asarray(tie, jnp.uint32))
        cand = qi.time == jnp.min(qi.time)
        slot = jnp.argmin(
            jnp.where(cand, prio.astype(jnp.int64), jnp.int64(1) << 33)
        ).astype(jnp.int32)
        return slot, found

    return jax.vmap(one)(q, tie_u32)
