"""Bounded per-seed event queue as fixed-shape arrays.

The reference's timer queue is a binary heap of boxed callbacks
(madsim/src/sim/time/mod.rs:21-230, naive-timer). Heaps don't vectorize:
pointer chasing and data-dependent shapes defeat XLA. The device engine uses
the classic SoA alternative (SURVEY.md §7 "hard parts" #2): a fixed-capacity
slot table per seed —

    time  : int64[Q]   absolute deadline, ns (INVALID_TIME when free)
    kind  : int32[Q]   event discriminant (workload-defined)
    pay   : int32[Q,P] payload slots
    valid : bool[Q]

``pop_min`` = masked argmin over Q; ``push`` = write at first free slot.
Both are O(Q) dense vector ops — for Q ≲ 256 that is a handful of VPU
lanes, far cheaper than the host round-trip it replaces. Ties on time break
by slot index (deterministic; schedule randomization comes from the jitter
every inserted event carries, not from pop order).

Overflow sets a sticky flag instead of corrupting state; the sweep driver
surfaces it per seed so the run can be retried with a larger Q.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

INVALID_TIME = jnp.iinfo(jnp.int64).max


class EventQueue(NamedTuple):
    time: jnp.ndarray  # int64[Q]
    kind: jnp.ndarray  # int32[Q]
    pay: jnp.ndarray  # int32[Q, P]
    valid: jnp.ndarray  # bool[Q]


def make(capacity: int, payload_slots: int) -> EventQueue:
    return EventQueue(
        time=jnp.full((capacity,), INVALID_TIME, jnp.int64),
        kind=jnp.zeros((capacity,), jnp.int32),
        pay=jnp.zeros((capacity, payload_slots), jnp.int32),
        valid=jnp.zeros((capacity,), bool),
    )


def push(
    q: EventQueue,
    time: jnp.ndarray,
    kind: jnp.ndarray,
    pay: jnp.ndarray,
    enable: jnp.ndarray,
) -> Tuple[EventQueue, jnp.ndarray]:
    """Insert one event at the first free slot (no-op when ``enable`` is
    False). Returns ``(queue', overflowed)``."""
    free = ~q.valid
    slot = jnp.argmax(free)  # first free slot index
    have_room = jnp.any(free)
    do = enable & have_room
    overflow = enable & ~have_room
    return (
        EventQueue(
            time=q.time.at[slot].set(jnp.where(do, time, q.time[slot])),
            kind=q.kind.at[slot].set(jnp.where(do, kind, q.kind[slot])),
            pay=q.pay.at[slot].set(jnp.where(do, pay, q.pay[slot])),
            valid=q.valid.at[slot].set(q.valid[slot] | do),
        ),
        overflow,
    )


import jax


def push_many(
    q: EventQueue,
    times: jnp.ndarray,  # int64[E]
    kinds: jnp.ndarray,  # int32[E]
    pays: jnp.ndarray,  # int32[E, P]
    enables: jnp.ndarray,  # bool[E]
) -> Tuple[EventQueue, jnp.ndarray]:
    """Insert up to E events in ONE pass: the first E free slots come from
    a single top_k over the free mask, and each queue array takes a single
    batched scatter (events map to distinct slots, so no collisions).

    This replaces E sequential (argmax + 4 scatters) rounds — each of
    which forces a full pass over the [Q]-sized arrays — with 1 top_k +
    4 scatters; the difference dominates step cost on large seed batches.
    """
    E = times.shape[0]
    capacity = q.valid.shape[0]
    free = ~q.valid
    idx = jnp.arange(capacity, dtype=jnp.int32)
    # first-free-first scoring: free slot i gets capacity - i, taken get 0
    score = jnp.where(free, capacity - idx, 0)
    _, slots = jax.lax.top_k(score, E)
    slot_free = jnp.take(free, slots)
    ok = slot_free & enables
    overflow = jnp.any(enables & ~slot_free)
    return (
        EventQueue(
            time=q.time.at[slots].set(jnp.where(ok, times, q.time[slots])),
            kind=q.kind.at[slots].set(jnp.where(ok, kinds, q.kind[slots])),
            pay=q.pay.at[slots].set(jnp.where(ok[:, None], pays, q.pay[slots])),
            valid=q.valid.at[slots].set(q.valid[slots] | ok),
        ),
        overflow,
    )


def pop_min(
    q: EventQueue, enable=True
) -> Tuple[EventQueue, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Remove and return the earliest event.

    Returns ``(queue', time, kind, pay, found)``; when the queue is empty
    ``found`` is False and the popped fields are INVALID_TIME/0. With
    ``enable=False`` the queue is left untouched (lets a masked-out seed
    skip its pop without a whole-array select).
    """
    masked = jnp.where(q.valid, q.time, INVALID_TIME)
    slot = jnp.argmin(masked)
    found = q.valid[slot]
    remove = found & enable
    return (
        EventQueue(
            time=q.time.at[slot].set(jnp.where(remove, INVALID_TIME, q.time[slot])),
            kind=q.kind,
            pay=q.pay,
            valid=q.valid.at[slot].set(q.valid[slot] & ~remove),
        ),
        masked[slot],
        jnp.where(found, q.kind[slot], 0),
        q.pay[slot],
        found,
    )


def size(q: EventQueue) -> jnp.ndarray:
    return jnp.sum(q.valid.astype(jnp.int32))
