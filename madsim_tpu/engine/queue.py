"""Bounded per-seed event queue as fixed-shape arrays.

The reference's timer queue is a binary heap of boxed callbacks
(madsim/src/sim/time/mod.rs:21-230, naive-timer). Heaps don't vectorize:
pointer chasing and data-dependent shapes defeat XLA. The device engine uses
the classic SoA alternative (SURVEY.md §7 "hard parts" #2): a fixed-capacity
slot table per seed —

    time  : int64[Q]   absolute deadline, ns (INVALID_TIME when free)
    kind  : int32[Q]   event discriminant (workload-defined)
    pay   : int32[Q,P] payload slots

``pop_min`` = min + one-hot invalidate; ``push_many`` = rank-select masked
writes. Everything is dense vector code — **no dynamic scatter or gather**,
which on TPU run ~6-10x slower than the masked equivalents (see
engine/ops.py). For Q ≲ 256 each op is a handful of VPU lanes, far cheaper
than the host round-trip it replaces.

Occupancy is encoded in the time plane itself: a slot is free iff its time
is ``INVALID_TIME`` (every constructor and removal maintains this), so no
separate validity plane travels in the loop carry. The pre-round-5 layout
kept an explicit ``bool valid[Q]`` plane; it survives as
``LegacyEventQueue`` behind ``EngineConfig(legacy_queue=1)`` purely so the
two layouts can be A/B-measured interleaved in one process
(scripts/bench_packing.py, docs/pallas_finding.md §5) — both produce
bit-identical schedules by construction.

Equal-time pops break ties *randomly* via a caller-supplied counter-RNG
draw (``tie_u32``), mirroring the reference's uniformly-random ready-queue
pop (madsim/src/sim/utils/mpsc.rs:71-84) — the stated source of schedule
amplification — while staying bit-reproducible per (seed, event index).

Overflow sets a sticky flag instead of corrupting state; the sweep driver
surfaces it per seed so the run can be retried with a larger Q.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple, Union

import jax.numpy as jnp

from .ops import onehot

INVALID_TIME = jnp.iinfo(jnp.int64).max

_HASH_MULT = 2654435761  # Knuth multiplicative hash constant


class EventQueue(NamedTuple):
    time: jnp.ndarray  # int64[Q]; INVALID_TIME == free slot
    kind: jnp.ndarray  # int32[Q]
    pay: jnp.ndarray  # int32[Q, P]


class LegacyEventQueue(NamedTuple):
    """Round-1..4 layout with the redundant validity plane (A/B only)."""

    time: jnp.ndarray  # int64[Q]
    kind: jnp.ndarray  # int32[Q]
    pay: jnp.ndarray  # int32[Q, P]
    valid: jnp.ndarray  # bool[Q]


AnyQueue = Union[EventQueue, LegacyEventQueue]


def make(capacity: int, payload_slots: int, legacy: bool = False) -> AnyQueue:
    time = jnp.full((capacity,), INVALID_TIME, jnp.int64)
    kind = jnp.zeros((capacity,), jnp.int32)
    pay = jnp.zeros((capacity, payload_slots), jnp.int32)
    if legacy:
        return LegacyEventQueue(time, kind, pay, jnp.zeros((capacity,), bool))
    return EventQueue(time, kind, pay)


def _free(q: AnyQueue) -> jnp.ndarray:
    """Free-slot mask; trace-time dispatch on the layout (zero runtime
    cost — both encode the same fact, by the INVALID_TIME invariant)."""
    if isinstance(q, LegacyEventQueue):
        return ~q.valid
    return q.time == INVALID_TIME


def _rebuild(q: AnyQueue, time, kind, pay, occupy=None, vacate=None) -> AnyQueue:
    """New queue with the same layout; legacy also updates its valid plane
    (``occupy``/``vacate`` are slot masks)."""
    if isinstance(q, LegacyEventQueue):
        valid = q.valid
        if occupy is not None:
            valid = valid | occupy
        if vacate is not None:
            valid = valid & ~vacate
        return LegacyEventQueue(time, kind, pay, valid)
    return EventQueue(time, kind, pay)


def push(
    q: AnyQueue,
    time: jnp.ndarray,
    kind: jnp.ndarray,
    pay: jnp.ndarray,
    enable: jnp.ndarray,
) -> Tuple[AnyQueue, jnp.ndarray]:
    """Insert one event at the first free slot (no-op when ``enable`` is
    False). Returns ``(queue', overflowed)``."""
    free = _free(q)
    have_room = jnp.any(free)
    do = jnp.asarray(enable, bool) & have_room
    mask = onehot(jnp.argmax(free), q.time.shape[0]) & do
    overflow = enable & ~have_room
    return (
        _rebuild(
            q,
            jnp.where(mask, jnp.asarray(time, jnp.int64), q.time),
            jnp.where(mask, jnp.asarray(kind, jnp.int32), q.kind),
            jnp.where(mask[:, None], pay, q.pay),
            occupy=mask,
        ),
        overflow,
    )


def push_many(
    q: AnyQueue,
    times: jnp.ndarray,  # int64[E]
    kinds: jnp.ndarray,  # int32[E]
    pays: jnp.ndarray,  # int32[E, P]
    enables: jnp.ndarray,  # bool[E]
) -> Tuple[AnyQueue, jnp.ndarray]:
    """Insert up to E events in ONE dense pass: emit ``e`` maps to the
    e-th free slot (ascending index — the same assignment a sequential
    first-free scan would make), computed via a cumsum rank over the free
    mask and written with masked selects. No sort, no top_k, no scatter.
    """
    E = times.shape[0]
    free = _free(q)
    rank = jnp.cumsum(free.astype(jnp.int32)) - 1  # rank among free slots
    eidx = jnp.arange(E, dtype=jnp.int32)
    sel = free[:, None] & (rank[:, None] == eidx[None, :]) & enables[None, :]  # [Q,E]
    write = jnp.any(sel, axis=1)
    t_new = jnp.sum(jnp.where(sel, times[None, :], jnp.int64(0)), axis=1, dtype=jnp.int64)
    k_new = jnp.sum(jnp.where(sel, kinds[None, :], 0), axis=1, dtype=jnp.int32)
    p_new = jnp.sum(jnp.where(sel[:, :, None], pays[None, :, :], 0), axis=1, dtype=jnp.int32)
    num_free = jnp.sum(free.astype(jnp.int32))
    overflow = jnp.any(enables & (eidx >= num_free))
    return (
        _rebuild(
            q,
            jnp.where(write, t_new, q.time),
            jnp.where(write, k_new, q.kind),
            jnp.where(write[:, None], p_new, q.pay),
            occupy=write,
        ),
        overflow,
    )


def pop_min(
    q: AnyQueue, enable=True, tie_u32=0
) -> Tuple[AnyQueue, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Remove and return the earliest event; equal-time ties break
    uniformly-at-random by ``tie_u32`` (a counter-RNG draw — deterministic
    per seed+event, different across seeds: the reference's random ready-
    queue pop semantics).

    Returns ``(queue', time, kind, pay, found)``; when the queue is empty
    ``found`` is False and time is INVALID_TIME. With ``enable=False`` the
    queue is left untouched (lets a masked-out seed skip its pop without a
    whole-array select).

    Invariant used: free slots always hold ``time == INVALID_TIME`` (make
    + removal maintain it), so no validity masking is needed before min.
    """
    capacity = q.time.shape[0]
    t = jnp.min(q.time)
    found = t != INVALID_TIME
    # pseudo-random per-slot priority; argmin over candidates = random tie
    # pick. murmur3-finalizer avalanche so any bit of the draw reshuffles
    # the order (a plain xor would leave clustered draws order-preserving).
    iota = jnp.arange(capacity, dtype=jnp.uint32)
    x = iota * jnp.uint32(_HASH_MULT) ^ jnp.asarray(tie_u32, jnp.uint32)
    x ^= x >> 16
    x *= jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x *= jnp.uint32(0xC2B2AE35)
    prio = x ^ (x >> 16)
    cand = q.time == t
    # int64 sentinel strictly above any uint32 prio, so a candidate always
    # wins even when its hash happens to be 0xFFFFFFFF
    slot = jnp.argmin(jnp.where(cand, prio.astype(jnp.int64), jnp.int64(1) << 33))
    mask = onehot(slot, capacity)
    rm = mask & found & jnp.asarray(enable, bool)
    kind = jnp.sum(jnp.where(mask & found, q.kind, 0), dtype=jnp.int32)
    pay = jnp.sum(jnp.where(mask[:, None], q.pay, 0), axis=0, dtype=jnp.int32)
    return (
        _rebuild(
            q,
            jnp.where(rm, INVALID_TIME, q.time),
            q.kind,
            q.pay,
            vacate=rm,
        ),
        t,
        kind,
        pay,
        found,
    )


def size(q: AnyQueue) -> jnp.ndarray:
    return jnp.sum((~_free(q)).astype(jnp.int32))
