"""The batched simulation loop: pop-min / advance-clock / draw / dispatch.

This is the reference's hot loop (``Executor::block_on`` →
``advance_to_next_event``, SURVEY.md §3.1) restructured for lockstep
execution over a seed batch:

- ``step_one`` advances ONE seed by ONE event: pop the minimum-time event,
  jump the virtual clock to it plus a random 50-100 ns jitter (the
  amplification analogue of the reference's per-poll advance,
  task/mod.rs:312-315 and +50 ns epsilon, time/mod.rs:45-60), draw
  counter-based randomness, dispatch to the workload's pure handler, and
  push the events it emits.
- ``step_batch`` is ``vmap(step_one)``; finished seeds are masked (their
  state passes through unchanged and their RNG counter freezes), so
  divergent seeds never break lockstep.
- ``run_sweep`` drives ``step_batch`` under ``lax.while_loop`` until every
  seed is done (queue empty = the reference's deadlock condition,
  task/mod.rs:250; or virtual time limit, task/mod.rs:253-258) — one XLA
  program, no host round-trips.
- ``run_traced`` replays a single seed recording every dispatched event —
  the bit-exact CPU replay artifact (run it with JAX's CPU backend; the
  engine is integer-only so the trace matches the TPU batch bit for bit).

The workload is a pair of pure functions over arrays (actors as state
machines), not coroutines: user futures can't run on TPU (SURVEY.md §7
"hard parts" #1), so the device tier targets table-driven workloads
(models/), while arbitrary user code runs on the host tier with the same
simulation semantics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import queue as equeue
from .queue import EventQueue
from .rng import bounded, event_bits, seed_key

# Columns of one fixed-width operation-history record (madsim_tpu/oracle):
# (client, code, key, val, opid) as int32; the engine stamps the record's
# int64 virtual time itself. The oracle decoder owns the field semantics —
# the engine only owns the width and the append discipline.
HIST_COLS = 5


class Emits(NamedTuple):
    """Fixed-size batch of events emitted by one handler invocation."""

    times: jnp.ndarray  # int64[E] absolute deadlines
    kinds: jnp.ndarray  # int32[E]
    pays: jnp.ndarray  # int32[E, P]
    enables: jnp.ndarray  # bool[E]


def no_emits(max_emits: int, payload_slots: int) -> Emits:
    return Emits(
        times=jnp.zeros((max_emits,), jnp.int64),
        kinds=jnp.zeros((max_emits,), jnp.int32),
        pays=jnp.zeros((max_emits, payload_slots), jnp.int32),
        enables=jnp.zeros((max_emits,), bool),
    )


class Workload(NamedTuple):
    """A device-expressible workload: two pure functions + static sizes.

    ``init(key) -> (wstate, Emits)`` builds the per-seed actor state and the
    initial event set (timers, fault plan). ``handle(wstate, now_ns, kind,
    pay, rand_u32) -> (wstate, Emits)`` processes one event; ``rand_u32``
    is ``num_rand`` uint32 draws unique to this (seed, event) pair.
    """

    init: Callable[[jax.Array], Tuple[Any, Emits]]
    handle: Callable[..., Tuple[Any, Emits]]
    num_rand: int
    payload_slots: int
    max_emits: int
    # Optional coverage signal (madsim_tpu/explore): ``cover(wstate_before,
    # wstate_after, now_ns, kind, pay) -> int32`` maps each dispatched
    # event to one bit index in ``[0, cover_bits)`` — typically
    # (event kind x node x state transition). The engine ORs the bit into
    # the per-seed bitmap inside the same step (one extra masked write,
    # no second pass); ``cover_bits == 0`` disables the plane entirely.
    cover: Optional[Callable[..., jnp.ndarray]] = None
    cover_bits: int = 0
    # Optional violation probe: ``probe(wstate) -> int32`` flavor bitmask
    # (0 = no violation). ``run_traced`` records it per step so triage
    # (explore/triage.py) can locate the FIRST violating event.
    probe: Optional[Callable[[Any], jnp.ndarray]] = None
    # Optional operation-history recording (madsim_tpu/oracle):
    # ``record(wstate_before, wstate_after, now_ns, kind, pay) ->
    # (slot_op, enable)`` maps each dispatched event to at most one
    # fixed-width op record — ``slot_op`` is int32[HIST_COLS]
    # (client, code, key, val, opid); the engine stamps the event's
    # virtual time and appends the row to the per-seed history buffer in
    # the same step (one masked write, like the coverage plane). A full
    # buffer latches the sticky ``hist_overflow`` flag and DROPS the row
    # — it never wraps, so the recorded prefix stays a valid history.
    # ``hist_slots == 0`` disables the plane entirely.
    record: Optional[Callable[..., Tuple[jnp.ndarray, jnp.ndarray]]] = None
    hist_slots: int = 0
    # Opt-in device-side event-mix plane (madsim_tpu/obs): per-seed
    # per-event-kind uint32 counters, one masked add per dispatched event
    # (same in-step write discipline as the coverage plane). Kinds >=
    # ``event_mix_kinds`` are simply not counted; 0 disables the plane
    # entirely (width-0 arrays, no loop-carry cost). The chunk summary
    # reduces it into an ``event_mix`` kind-histogram
    # (models/_common.make_sweep_summary) — heartbeat storms, election
    # churn and fault-window activity visible per sweep without host
    # decode.
    event_mix_kinds: int = 0


def cover_words(workload: Workload) -> int:
    """uint32 words of the per-seed coverage bitmap (0 when disabled)."""
    return (workload.cover_bits + 31) // 32


def hist_slots(workload: Workload) -> int:
    """Rows of the per-seed history buffer (0 when recording is off)."""
    return workload.hist_slots if workload.record is not None else 0


class EngineConfig(NamedTuple):
    """Static engine parameters (python ints — part of the jit cache key)."""

    queue_capacity: int = 64
    time_limit_ns: int = 10_000_000_000
    max_steps: int = 100_000
    jitter_lo_ns: int = 50
    jitter_hi_ns: int = 100
    # A/B instrumentation (scripts/bench_packing.py): 1 = the pre-round-5
    # queue layout with its redundant bool valid[Q] plane. Schedules are
    # bit-identical either way; only the loop-carry footprint differs.
    legacy_queue: int = 0
    # HISTORICAL, kept for config compatibility (validated but unused):
    # rounds 1-2 chunked the sweep as while(cond){fori(cond_interval){
    # step}} assuming the termination check was the expensive part. TPU
    # profiling (round 3) showed the opposite — the termination cond is
    # free, while ANY nested device loop costs ~9x per step (measured
    # 4.6 ms/step nested vs 0.43 ms/step flat at a 16k batch on v5e; the
    # nesting forces the ~100 MB loop carry through HBM each inner trip
    # instead of keeping it resident). The sweep is now a single flat
    # while_loop with the cond evaluated every step.
    cond_interval: int = 16


class EngineState(NamedTuple):
    """Per-seed simulator state; ``run_sweep`` holds one with a leading
    seed-batch axis on every leaf (struct-of-arrays)."""

    seed: jnp.ndarray  # int64
    key: jax.Array  # typed PRNG key
    now_ns: jnp.ndarray  # int64 virtual clock
    ctr: jnp.ndarray  # int32 events processed (RNG counter)
    done: jnp.ndarray  # bool
    overflow: jnp.ndarray  # bool sticky queue-overflow flag
    qmax: jnp.ndarray  # int32 queue-occupancy high-water mark
    cover: jnp.ndarray  # uint32[cover_words] per-seed coverage bitmap
    # operation-history plane (madsim_tpu/oracle); all empty-shaped when
    # the workload records no history
    hist_rec: jnp.ndarray  # int32[hist_slots, HIST_COLS] op records
    hist_t: jnp.ndarray  # int64[hist_slots] record virtual times
    hist_len: jnp.ndarray  # int32 rows appended so far
    hist_overflow: jnp.ndarray  # bool sticky history-overflow flag
    queue: EventQueue
    wstate: Any  # workload pytree
    # event-mix plane (uint32[event_mix_kinds], width 0 when disabled).
    # LAST field on purpose: checkpoint leaves are stored positionally
    # (checkpoint.py leaf_{i}), so appending after ``wstate`` keeps every
    # pre-v10 leaf index stable and old snapshots loadable.
    evmix: jnp.ndarray


def _init_one(
    workload: Workload, cfg: EngineConfig, seed: jnp.ndarray, params=None
) -> EngineState:
    if workload.max_emits > cfg.queue_capacity:
        raise ValueError(
            f"workload.max_emits ({workload.max_emits}) exceeds "
            f"queue_capacity ({cfg.queue_capacity}); every handler "
            "invocation must be able to enqueue its full emit batch"
        )
    if cfg.cond_interval < 1:
        raise ValueError(
            f"cond_interval must be >= 1, got {cfg.cond_interval} (the "
            "field is retained for config compatibility only — the sweep "
            "loop now checks termination every step — but a value the old "
            "chunked driver would have rejected is still a config bug)"
        )
    key = seed_key(seed)
    # spec-as-data (engine/faults.py): a params-carrying workload builds
    # its fault schedule from this lane's traced FaultParams instead of a
    # static spec — the jit key stays the envelope shape
    wstate, emits = (
        workload.init(key) if params is None else workload.init(key, params)
    )
    q = equeue.make(
        cfg.queue_capacity, workload.payload_slots,
        legacy=bool(cfg.legacy_queue),
    )
    q, overflow = equeue.push_many(q, emits.times, emits.kinds, emits.pays, emits.enables)
    return EngineState(
        seed=jnp.asarray(seed, jnp.int64),
        key=key,
        now_ns=jnp.zeros((), jnp.int64),
        ctr=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), bool),
        overflow=overflow,
        qmax=equeue.size(q),
        cover=jnp.zeros((cover_words(workload),), jnp.uint32),
        hist_rec=jnp.zeros((hist_slots(workload), HIST_COLS), jnp.int32),
        hist_t=jnp.zeros((hist_slots(workload),), jnp.int64),
        hist_len=jnp.zeros((), jnp.int32),
        hist_overflow=jnp.zeros((), bool),
        queue=q,
        wstate=wstate,
        evmix=jnp.zeros((workload.event_mix_kinds,), jnp.uint32),
    )


def init_sweep(
    workload: Workload, cfg: EngineConfig, seeds: jnp.ndarray, params=None
) -> EngineState:
    """Build the batched state for a seed vector (int64[S]). ``params``
    (optional) is a PER-LANE pytree — leading axis S on every leaf, e.g.
    ``faults.tile_params`` of one candidate or a stacked candidate×seed
    grid — vmapped alongside the seed axis."""
    _procs_child_guard()
    seeds = jnp.asarray(seeds, jnp.int64)
    if params is None:
        return jax.vmap(partial(_init_one, workload, cfg))(seeds)
    return jax.vmap(partial(_init_one, workload, cfg))(seeds, params)


def _procs_child_guard() -> None:
    """Fail by name, not by hang, when the device tier is entered from a
    forked ``Builder(procs=N)`` sweep child (modules created before the
    fork hold real jax references the child's sys.modules poison cannot
    reach, so the engine checks the child's sentinel itself). The
    sentinel carries the child's pid: an exec'd DESCENDANT of a child
    (fresh interpreter, no inherited JAX state) inherits the env var but
    not the pid, and may use the engine legitimately."""
    import os

    if os.environ.get("MADSIM_IN_PROCS_CHILD") == str(os.getpid()):
        from ..builder import ProcsDeviceTierError

        raise ProcsDeviceTierError("madsim_tpu.engine")


def _pop_event(workload: Workload, s: EngineState, enable):
    """Draw this event's randomness and pop the next event.

    Draw layout: ``rand[0]`` clock jitter, ``rand[1]`` pop tie-break,
    ``rand[2:]`` workload handler draws. Shared by the sweep step and the
    traced replay so both consume identical streams.
    """
    rand = event_bits(s.key, s.ctr, workload.num_rand + 2)
    q, t, kind, pay, found = equeue.pop_min(s.queue, enable=enable, tie_u32=rand[1])
    return rand, q, t, kind, pay, found


def step_one(workload: Workload, cfg: EngineConfig, s: EngineState) -> EngineState:
    """Advance one seed by one event (no-op once ``done``).

    Three masks compose: already-done seeds freeze entirely; a
    popped-empty queue or expired clock marks done without dispatching;
    only ``take`` applies the handler's writes. Queue mutations are gated
    at the mask level (pop ``enable`` / push ``enables``) so the big
    [Q]-sized arrays never need a whole-array select; only the workload
    state goes through a select tree."""
    active = ~s.done
    rand, q, t, kind, pay, found = _pop_event(workload, s, active)
    jitter = bounded(rand[0], cfg.jitter_lo_ns, cfg.jitter_hi_ns + 1)
    now = jnp.maximum(s.now_ns, t) + jitter
    time_up = now > cfg.time_limit_ns
    dispatch = found & ~time_up
    take = active & dispatch

    wstate, emits = workload.handle(s.wstate, now, kind, pay, rand[2:])
    q, ov = equeue.push_many(
        q, emits.times, emits.kinds, emits.pays, emits.enables & take
    )

    # coverage: fold this event's bit into the per-seed bitmap — a masked
    # OR in the same step, so the signal costs one extra [W]-sized write,
    # never a second pass over the sweep
    cover = s.cover
    if workload.cover is not None and workload.cover_bits > 0:
        w = cover_words(workload)
        bit = jnp.asarray(
            workload.cover(s.wstate, wstate, now, kind, pay), jnp.uint32
        )
        hit = (jnp.arange(w, dtype=jnp.uint32) == (bit >> 5)) & take
        cover = cover | jnp.where(
            hit, jnp.uint32(1) << (bit & 31), jnp.uint32(0)
        )

    # history: append this event's op record (if any) at the write head —
    # one masked [H]-sized write in the same step, mirroring the coverage
    # plane. A full buffer latches the sticky overflow flag and drops the
    # row; the already-written prefix is never touched (no wrap).
    hist_rec, hist_t = s.hist_rec, s.hist_t
    hist_len, hist_ov = s.hist_len, s.hist_overflow
    if workload.record is not None and workload.hist_slots > 0:
        h = workload.hist_slots
        rec, ren = workload.record(s.wstate, wstate, now, kind, pay)
        want = take & jnp.asarray(ren, bool)
        fits = hist_len < h
        row = (jnp.arange(h, dtype=jnp.int32) == hist_len) & want & fits
        hist_rec = jnp.where(
            row[:, None], jnp.asarray(rec, jnp.int32)[None, :], hist_rec
        )
        hist_t = jnp.where(row, now, hist_t)
        hist_len = hist_len + jnp.where(want & fits, 1, 0)
        hist_ov = hist_ov | (want & ~fits)

    # event mix: count this event's kind — one masked [K]-sized add in
    # the same step, the cheapest of the three opt-in planes (no callback,
    # the popped ``kind`` is the index)
    evmix = s.evmix
    if workload.event_mix_kinds > 0:
        k = workload.event_mix_kinds
        slot = (jnp.arange(k, dtype=jnp.int32) == kind) & take
        evmix = evmix + slot.astype(jnp.uint32)

    def sel(pred, new, old):
        return jax.tree.map(lambda a, b: jnp.where(pred, a, b), new, old)

    return EngineState(
        seed=s.seed,
        key=s.key,
        now_ns=jnp.where(take, now, s.now_ns),
        ctr=jnp.where(take, s.ctr + 1, s.ctr),
        done=s.done | (active & (~found | time_up)),
        overflow=s.overflow | (take & ov),
        qmax=jnp.maximum(s.qmax, equeue.size(q)),
        cover=cover,
        hist_rec=hist_rec,
        hist_t=hist_t,
        hist_len=hist_len,
        hist_overflow=hist_ov,
        queue=q,
        wstate=sel(take, wstate, s.wstate),
        evmix=evmix,
    )


def step_batch(workload: Workload, cfg: EngineConfig, state: EngineState) -> EngineState:
    """One lockstep event for every live seed in the batch."""
    return jax.vmap(partial(step_one, workload, cfg))(state)


def drive(workload: Workload, cfg: EngineConfig, state: EngineState) -> EngineState:
    """Step a batched state until every seed is done or ``max_steps`` is
    hit — the single shared sweep driver (used by ``run_sweep``,
    ``checkpoint.resume_sweep``; the sharded driver in parallel/mesh adds
    a psum but follows the same shape).

    ONE flat ``while_loop``, cond evaluated every step: nesting a second
    device loop inside the body costs ~9x per step on TPU (the loop carry
    round-trips HBM per inner iteration; see ``EngineConfig.cond_interval``
    for the measurements), while the ``any(~done)`` reduction in the cond
    is free. Exactly ``max_steps`` steps can run, keeping the sweep
    bit-identical to ``run_traced``'s ``length=max_steps`` scan for
    budget-cut seeds (finished seeds are frozen no-ops either way).
    """

    def cond(carry):
        state, iters = carry
        return jnp.any(~state.done) & (iters < cfg.max_steps)

    def body(carry):
        state, iters = carry
        return step_batch(workload, cfg, state), iters + 1

    state, _ = jax.lax.while_loop(cond, body, (state, jnp.zeros((), jnp.int64)))
    return state


@partial(jax.jit, static_argnums=(0, 1))
def _init(
    workload: Workload, cfg: EngineConfig, seeds: jnp.ndarray, params=None
) -> EngineState:
    return init_sweep(workload, cfg, seeds, params)


@partial(jax.jit, static_argnums=(0, 1))
def _drive(workload: Workload, cfg: EngineConfig, state: EngineState) -> EngineState:
    return drive(workload, cfg, state)


def _run(
    workload: Workload, cfg: EngineConfig, seeds: jnp.ndarray, params=None
) -> EngineState:
    # init and the sweep loop are SEPARATE XLA programs on purpose: fusing
    # the unrolled per-seed init writes into the loop program pessimizes
    # the loop carry (measured 4.4 ms/step fused vs 0.43 ms/step split at
    # a 16k batch on v5e — layouts chosen for the init scatter leak into
    # every loop iteration). One extra dispatch per sweep is noise.
    return _drive(workload, cfg, _init(workload, cfg, seeds, params))


def run_sweep(workload: Workload, cfg: EngineConfig, seeds, params=None) -> EngineState:
    """Run a whole seed batch to completion; returns the final batched
    state (workload stats live in ``.wstate``). ``params`` carries
    per-lane spec-as-data (see ``init_sweep``); its leaves are traced jit
    arguments, so sweeping a new candidate costs NO recompile as long as
    the envelope (and thus every shape) is unchanged."""
    _procs_child_guard()
    return _run(workload, cfg, jnp.asarray(seeds, jnp.int64), params)


@partial(jax.jit, static_argnums=(0,))
def _concat_finals(total: int, *finals):
    """One program for the whole tree-concat + ragged-tail trim: eager
    per-leaf concatenates/slices are separate dispatches and cost
    seconds through a tunneled device (measured 15 s for 8 chunks x
    ~40 leaves). Module-level so the jit cache persists across calls."""
    return jax.tree.map(
        lambda *ls: jnp.concatenate(ls, axis=0)[:total], *finals
    )


@partial(jax.jit, static_argnums=(1,))
def lane_slice(state, n: int, lo):
    """Lanes ``[lo, lo + n)`` of a batched state tree as ONE compiled
    program for every offset: ``lo`` is a traced scalar (dynamic slice),
    only the window size is static. The (candidate x seed) grid path
    carves its per-candidate summaries out of one flat sweep with this —
    K candidates cost K dispatches of one program, zero recompiles."""
    lo = jnp.asarray(lo, jnp.int32)
    return jax.tree.map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, lo, n, axis=0), state
    )


def _pad_seeds(seeds, pad: int):
    """Append ``pad`` synthetic continuation seeds (max real seed + i +
    1); the padded lanes are sliced off inside ``_concat_finals``."""
    filler = jnp.max(seeds) + 1 + jnp.arange(pad, dtype=jnp.int64)
    return jnp.concatenate([seeds, filler])


def _pad_params(params, pad: int):
    """Edge-replicate per-lane params for ``pad`` synthetic lanes (their
    results are trimmed/masked like the padded seeds'; any valid params
    do — the last lane's are simply already there)."""
    return jax.tree.map(
        lambda a: np.concatenate(
            [np.asarray(a), np.broadcast_to(np.asarray(a)[-1:], (pad,) + np.shape(a)[1:])]
        ),
        params,
    )


def _slice_params(params, lo: int, hi: int):
    """Per-lane params for one chunk's lane slice."""
    return jax.tree.map(lambda a: np.asarray(a)[lo:hi], params)


def run_in_chunks(run_chunk, seeds, chunk_size: int, multiple: int = 1, params=None):
    """Shared chunk/pad/concat driver for large sweeps: run
    ``run_chunk(seed_chunk)`` over sequential ``chunk_size`` slices and
    concatenate the final states (single trim+concat program).

    A ragged final chunk is padded to the full ``chunk_size`` so every
    chunk reuses one compiled program; a batch smaller than one chunk is
    padded only to the next ``multiple`` (divisibility, e.g. a mesh
    size) — there is no program reuse to justify full-chunk padding.

    With per-lane ``params`` (spec-as-data), ``run_chunk(seed_chunk,
    param_chunk)`` receives the matching slice, edge-padded like the
    seeds."""
    seeds = jnp.asarray(seeds, jnp.int64)
    n = int(seeds.shape[0])
    if n == 0:
        raise ValueError("seed batch is empty")

    def _run(chunk, pchunk):
        return run_chunk(chunk) if params is None else run_chunk(chunk, pchunk)

    if n <= chunk_size:
        pad = -n % multiple
        if pad == 0:
            return _run(seeds, params)
        padded = None if params is None else _pad_params(params, pad)
        return _concat_finals(n, _run(_pad_seeds(seeds, pad), padded))
    finals = []
    for lo in range(0, n, chunk_size):
        chunk = seeds[lo : lo + chunk_size]
        pchunk = None if params is None else _slice_params(params, lo, lo + chunk_size)
        pad = chunk_size - chunk.shape[0]
        if pad:
            chunk = _pad_seeds(chunk, pad)
            if pchunk is not None:
                pchunk = _pad_params(pchunk, pad)
        finals.append(_run(chunk, pchunk))
    return _concat_finals(n, *finals)


def state_bytes_per_seed(workload: Workload, cfg: EngineConfig, params=None) -> int:
    """Loop-carry bytes ONE seed lane holds through the sweep loop —
    the quantity whose batch-sized total stops fitting fast memory at
    the occupancy cliff (docs/pallas_finding.md §5). Computed from the
    abstract shapes of ``_init_one`` (no device work, no compile).
    ``params`` is one lane's spec-as-data pytree (unbatched) for
    envelope-keyed workloads, whose carry includes the per-lane
    ``FaultRt`` scalars."""
    pstruct = (
        None
        if params is None
        else jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
            params,
        )
    )
    shapes = jax.eval_shape(
        partial(_init_one, workload, cfg),
        jax.ShapeDtypeStruct((), jnp.int64),
        pstruct,
    )
    total = 0
    for leaf in jax.tree.leaves(shapes):
        try:
            itemsize = leaf.dtype.itemsize
        except (AttributeError, TypeError):
            itemsize = 8  # typed PRNG key: two uint32 words
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            itemsize = 8
        total += int(np.prod(leaf.shape, dtype=np.int64)) * itemsize
    return total


# The batch-occupancy knee, as a loop-carry budget: BENCH r05 measured
# the 16,384-seed MadRaft batch (a ~100 MB carry) at full speed and the
# 65,536-seed batch (~4x) at ~0.75x seeds/s — the marginal per-step cost
# cliffs ~9x once the carry stops fitting fast memory (docs/
# pallas_finding.md §3/§5). 128 MiB keeps the auto-picked chunk at or
# below the measured knee for every bundled model; override with
# MADSIM_CHUNK_BUDGET_BYTES (or the explicit argument) after remeasuring
# bench.py's batch_curve on new hardware.
DEFAULT_CHUNK_BUDGET_BYTES = 128 * 1024 * 1024


def pick_chunk_size(
    workload: Workload,
    cfg: EngineConfig,
    budget_bytes: Optional[int] = None,
    lo: int = 1024,
    hi: int = 65536,
    params=None,
) -> int:
    """Largest power-of-two batch in ``[lo, hi]`` whose loop carry fits
    the fast-memory budget — the measured knee of the batch curve, not a
    guess. This is what ``run_sweep_chunked`` / the pipelined driver use
    when no explicit chunk size is given, so a history-recording
    workload (whose per-seed carry is several times a bare one's)
    automatically sweeps in smaller chunks instead of falling off the
    65k-seed cliff."""
    if budget_bytes is None:
        import os

        budget_bytes = int(
            os.environ.get(
                "MADSIM_CHUNK_BUDGET_BYTES", DEFAULT_CHUNK_BUDGET_BYTES
            )
        )
    per_seed = max(1, state_bytes_per_seed(workload, cfg, params=params))
    size = lo
    while size * 2 <= hi and size * 2 * per_seed <= budget_bytes:
        size *= 2
    return size


def run_sweep_chunked(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    chunk_size: Optional[int] = None,
    params=None,
) -> EngineState:
    """Run a large seed sweep as sequential ``chunk_size`` batches of
    ONE compiled program, concatenating the final states.

    Measured on v5e: per-lane step cost cliffs ~9x somewhere between 16k
    and 32k seeds (0.13 -> 1.2 ms/step marginal; the loop working set
    stops fitting fast memory), so a 100k+ sweep runs several times
    faster as 16k chunks than as one giant batch — and a chunk is also
    the natural checkpoint/restart granule. Bit-identical to one big
    ``run_sweep`` per seed (seeds are independent). ``chunk_size=None``
    auto-picks the knee of the batch curve from the workload's measured
    loop-carry footprint (``pick_chunk_size``).

    The returned state keeps O(total seeds) device memory (per-seed
    event queues included) — fine to a few hundred thousand seeds on one
    chip. At the million-seed scale, don't hold finals at all: merge
    per-chunk ``sweep_summary`` dicts on host per chunk, as bench.py's
    bench_100k does."""
    if chunk_size is None:
        chunk_size = pick_chunk_size(
            workload, cfg,
            params=None
            if params is None
            else jax.tree.map(lambda a: np.asarray(a)[0], params),
        )
    if params is None:
        return run_in_chunks(
            lambda chunk: run_sweep(workload, cfg, chunk), seeds, chunk_size
        )
    return run_in_chunks(
        lambda chunk, pchunk: run_sweep(workload, cfg, chunk, params=pchunk),
        seeds, chunk_size, params=params,
    )


@partial(jax.jit, static_argnums=(0, 1))
def _run_traced(workload: Workload, cfg: EngineConfig, seed: jnp.ndarray, params=None):
    state = _init_one(workload, cfg, seed, params)

    def scan_step(s, _):
        before_ctr = s.ctr
        _, q, t, kind, pay, found = _pop_event(workload, s, jnp.zeros((), bool))
        s2 = step_one(workload, cfg, s)
        fired = s2.ctr > before_ctr
        # probe AFTER the step: entry i is the violation-flavor bitmask
        # once event i has been applied, so the first i where it becomes
        # nonzero is the first violating event (explore/triage.py)
        probe = (
            jnp.asarray(workload.probe(s2.wstate), jnp.int32)
            if workload.probe is not None
            else jnp.zeros((), jnp.int32)
        )
        rec = (
            jnp.where(fired, s2.now_ns, jnp.int64(-1)),
            jnp.where(fired, kind, jnp.int32(-1)),
            jnp.where(fired, pay, jnp.zeros_like(pay)),
            fired,
            probe,
        )
        return s2, rec

    final, (times, kinds, pays, fired, probes) = jax.lax.scan(
        scan_step, state, None, length=cfg.max_steps
    )
    trace = {"time_ns": times, "kind": kinds, "pay": pays, "fired": fired}
    if workload.probe is not None:
        trace["probe"] = probes
    return final, trace


def run_traced(workload: Workload, cfg: EngineConfig, seed: int, params=None):
    """Replay ONE seed, recording every dispatched event in order.

    This is the debugging/bit-exact-replay path (SURVEY.md §7): run it on
    the CPU backend against a failure seed found by a TPU sweep — the
    integer-only engine guarantees the identical event sequence.
    ``params`` is ONE candidate's (unbatched) spec-as-data pytree for
    envelope-keyed workloads — ddmin shrink re-verifications replay
    every candidate schedule through one compiled traced program.
    """
    return _run_traced(workload, cfg, jnp.asarray(seed, jnp.int64), params)
