"""Simulation configuration (ref madsim/src/sim/config.rs:11-42).

TOML-parsable ``Config { net, tcp }`` with a stable content hash so test
failures can report the exact config that produced them.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field, asdict
from typing import Any, Dict, Tuple


@dataclass
class NetConfig:
    """ref sim/net/network.rs:66-97 — defaults: no loss, 1-10 ms latency."""

    packet_loss_rate: float = 0.0
    send_latency: Tuple[float, float] = (0.001, 0.010)  # seconds, [lo, hi)


@dataclass
class TcpConfig:
    """Placeholder, as in the reference (sim/net/tcp/config.rs:6-8)."""


@dataclass
class Config:
    net: NetConfig = field(default_factory=NetConfig)
    tcp: TcpConfig = field(default_factory=TcpConfig)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Config":
        net = d.get("net", {})
        latency = net.get("send_latency", (0.001, 0.010))
        if isinstance(latency, dict):  # TOML range table {start, end}
            latency = (latency["start"], latency["end"])
        return Config(
            net=NetConfig(
                packet_loss_rate=float(net.get("packet_loss_rate", 0.0)),
                send_latency=(float(latency[0]), float(latency[1])),
            ),
            tcp=TcpConfig(),
        )

    @staticmethod
    def from_toml(text: str) -> "Config":
        import tomllib

        return Config.from_dict(tomllib.loads(text))

    def hash(self) -> int:
        """Stable 64-bit content hash (ref config.rs ahash-based hash)."""
        blob = json.dumps(asdict(self), sort_keys=True, default=str)
        return int.from_bytes(
            hashlib.sha256(blob.encode()).digest()[:8], "little"
        )
