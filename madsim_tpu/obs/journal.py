"""The run journal: an append-only JSONL telemetry stream.

One line per event, each carrying a wall-clock timestamp and the run ID
— the ONLY place in the repo where wall-clock time is written to disk
next to sweep results. The journal is explicitly excluded from the
deterministic report bytes: reports (chunk summaries, campaign JSONL,
checked-sweep totals) are pure functions of the work and never read or
embed journal content; ``scripts/check_determinism.sh`` byte-diffs the
reports with the journal enabled vs disabled to pin that invariant.

Line shape (sorted keys)::

    {"kind": "stream_flush", "lo": 0, "k": 32, "run": "9f2c...", "ts": 1722950400.123456}

``kind`` names the event, ``run`` the run ID (one per Telemetry handle),
``ts`` seconds since the epoch. Everything else is the event's own
payload — JSON-able scalars only; the writer rejects nothing and repairs
nothing, so emit clean values.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


def new_run_id() -> str:
    """A fresh 16-hex-char run ID (collision-safe across hosts: random
    bytes, not a timestamp)."""
    return os.urandom(8).hex()


class Journal:
    """Append-only JSONL writer; every ``write`` is one flushed line, so
    an interrupted run keeps every event up to the kill."""

    def __init__(self, path: str, run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id or new_run_id()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()
        self.write("run_start")

    def write(self, kind: str, **fields) -> None:
        rec = dict(fields)
        rec["kind"] = kind
        rec["run"] = self.run_id
        rec["ts"] = round(time.time(), 6)
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._f.closed:
                return  # post-close writes are dropped, not crashes
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.write(
                    json.dumps(
                        {
                            "kind": "run_end",
                            "run": self.run_id,
                            "ts": round(time.time(), 6),
                        },
                        sort_keys=True,
                    )
                    + "\n"
                )
                self._f.close()


class JournalRecords(list):
    """``read_journal``'s return value: a plain list of record dicts,
    plus ``truncated`` — True when the file ended in a torn partial
    line (a writer killed mid-append) whose bytes were dropped. The
    valid prefix is always returned; only the torn tail is lost."""

    truncated: bool = False


def read_journal(path: str) -> JournalRecords:
    """Parse a journal back into a list of dicts (tests, post-mortems).

    Crash-tolerant by design: the journal is an append-only stream whose
    writer may die mid-line (``kill -9`` between ``write`` and
    ``flush`` landing), so a torn/partial FINAL line is normal operating
    data, not corruption — the valid prefix is returned with
    ``.truncated`` set instead of raising ``JSONDecodeError``. A
    malformed line with MORE data after it is genuine corruption (a torn
    line can only be last in an append-only file) and still raises."""
    out = JournalRecords()
    with open(path) as f:
        lines = f.read().split("\n")
    # a well-formed file ends "...}\n" -> a trailing "" entry; anything
    # else in the final slot is a torn partial record
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                out.truncated = True
                return out
            raise
    return out
