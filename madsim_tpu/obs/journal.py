"""The run journal: an append-only JSONL telemetry stream.

One line per event, each carrying a wall-clock timestamp and the run ID
— the ONLY place in the repo where wall-clock time is written to disk
next to sweep results. The journal is explicitly excluded from the
deterministic report bytes: reports (chunk summaries, campaign JSONL,
checked-sweep totals) are pure functions of the work and never read or
embed journal content; ``scripts/check_determinism.sh`` byte-diffs the
reports with the journal enabled vs disabled to pin that invariant.

Line shape (sorted keys)::

    {"kind": "stream_flush", "lo": 0, "k": 32, "run": "9f2c...", "ts": 1722950400.123456}

``kind`` names the event, ``run`` the run ID (one per Telemetry handle),
``ts`` seconds since the epoch. Everything else is the event's own
payload — JSON-able scalars only; the writer rejects nothing and repairs
nothing, so emit clean values.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


def new_run_id() -> str:
    """A fresh 16-hex-char run ID (collision-safe across hosts: random
    bytes, not a timestamp)."""
    return os.urandom(8).hex()


class Journal:
    """Append-only JSONL writer; every ``write`` is one flushed line, so
    an interrupted run keeps every event up to the kill."""

    def __init__(self, path: str, run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id or new_run_id()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()
        self.write("run_start")

    def write(self, kind: str, **fields) -> None:
        rec = dict(fields)
        rec["kind"] = kind
        rec["run"] = self.run_id
        rec["ts"] = round(time.time(), 6)
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._f.closed:
                return  # post-close writes are dropped, not crashes
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.write(
                    json.dumps(
                        {
                            "kind": "run_end",
                            "run": self.run_id,
                            "ts": round(time.time(), 6),
                        },
                        sort_keys=True,
                    )
                    + "\n"
                )
                self._f.close()


def read_journal(path: str):
    """Parse a journal back into a list of dicts (tests, post-mortems)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
