"""Prometheus text exposition + the opt-in local HTTP endpoint.

``render_prometheus(registry)`` produces the text format (v0.0.4) from
an ``obs.metrics.Registry`` snapshot; ``start_http_server`` serves it at
``/metrics`` from a daemon thread for long-running sweeps — opt-in only
(``Telemetry(http_port=...)``), bound to localhost by default, stdlib
``http.server`` (no deps).

``bind_runtime_metrics`` joins the host-tier ``madsim_tpu.metrics
.RuntimeMetrics`` shim to the same exposition path: ``num_tasks_by_node``
and ``num_tasks_by_spawn_site`` become pull-time callback gauges, so a
live sim's task census shows up next to the device-tier series.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import Registry


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(labelnames, key, extra=()) -> str:
    pairs = [
        f'{n}="{_escape_label(v)}"' for n, v in zip(labelnames, key)
    ] + list(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _num(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(registry: Registry) -> str:
    """The registry as Prometheus text exposition format v0.0.4."""
    lines = []
    for name, kind, help, labelnames, series in registry.collect():
        if help:
            lines.append(f"# HELP {name} {_escape_help(help)}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            # registry buckets ride in the series rows:
            # [per-bucket counts..., +Inf count, sum]
            m = registry._metrics.get(name)
            buckets = getattr(m, "buckets", ())
            for key, row in series:
                cum = 0.0
                for b, c in zip(buckets, row):
                    cum += c
                    le = _labels(labelnames, key, (f'le="{_num(b)}"',))
                    lines.append(f"{name}_bucket{le} {_num(cum)}")
                cum += row[len(buckets)]
                le = _labels(labelnames, key, ('le="+Inf"',))
                lines.append(f"{name}_bucket{le} {_num(cum)}")
                lines.append(
                    f"{name}_sum{_labels(labelnames, key)} {_num(row[-1])}"
                )
                lines.append(
                    f"{name}_count{_labels(labelnames, key)} {_num(cum)}"
                )
        else:
            for key, val in series:
                lines.append(f"{name}{_labels(labelnames, key)} {_num(val)}")
    return "\n".join(lines) + "\n"


def bind_runtime_metrics(registry: Registry, metrics) -> None:
    """Expose a host-tier ``RuntimeMetrics`` (madsim_tpu/metrics.py) as
    pull-time gauges: ``madsim_runtime_nodes``, ``madsim_runtime_tasks``,
    ``madsim_runtime_tasks_by_node{node=}``,
    ``madsim_runtime_tasks_by_spawn_site{site=}``."""
    registry.callback_gauge(
        "madsim_runtime_nodes", metrics.num_nodes,
        help="live nodes in the host-tier runtime",
    )
    registry.callback_gauge(
        "madsim_runtime_tasks", metrics.num_tasks,
        help="live tasks in the host-tier runtime",
    )
    registry.callback_gauge(
        "madsim_runtime_tasks_by_node",
        lambda: {str(k): v for k, v in metrics.num_tasks_by_node().items()},
        help="live tasks per node", label="node",
    )
    registry.callback_gauge(
        "madsim_runtime_tasks_by_spawn_site",
        lambda: {
            str(k): v for k, v in metrics.num_tasks_by_spawn_site().items()
        },
        help="live tasks per spawn site", label="site",
    )


class _Handler(BaseHTTPRequestHandler):
    registry: Optional[Registry] = None  # bound per-server subclass

    def do_GET(self):  # noqa: N802 — stdlib API
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_response(404)
            self.end_headers()
            return
        body = render_prometheus(self.registry).encode()
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr spam
        pass


class MetricsServer:
    """The opt-in exposition endpoint: ``/metrics`` on a local port,
    served from a daemon thread. ``port=0`` picks a free port (read it
    back from ``.port``)."""

    def __init__(
        self, registry: Registry, port: int = 0, host: str = "127.0.0.1"
    ):
        handler = type("_BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="obs-metrics-http",
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_http_server(
    registry: Registry, port: int = 0, host: str = "127.0.0.1"
) -> MetricsServer:
    return MetricsServer(registry, port=port, host=host)
