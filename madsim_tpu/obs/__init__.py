"""madsim_tpu.obs — the fleet telemetry subsystem.

One handle, four planes, all strictly OUT-OF-BAND (report bytes are
bit-identical with telemetry on or off — the determinism gate pins it):

- **metrics** (obs/metrics.py): counters/gauges/histograms with labels,
  instrumented in every driver — chunk wall time and device/host phase
  overlap (engine/checkpoint.py), per-round occupancy / refill latency /
  queue depth / retirement flux (engine/stream.py), per-device seeds/s
  (parallel/mesh.py), candidates/s and corpus size (explore/campaign.py),
  suspect/dedup rates (oracle/screen.py), connections and per-API latency
  (the wire servers);
- **journal** (obs/journal.py): append-only JSONL with wall timestamps
  and a run ID;
- **exposition** (obs/export.py): Prometheus text format, served by an
  opt-in localhost HTTP endpoint;
- **trace spans** (tracing.SpanTracer): driver phases as one Chrome/
  Perfetto file — device sweep of chunk N over host check of chunk N−1,
  stream round/refill cadence, checker-pool fan-out.

Drivers take ``telemetry=`` (a :class:`Telemetry` or None); None means
ZERO instrumentation work on the hot path — the baseline the bench
``telemetry`` leg compares against (≤3% overhead gate). See
docs/observability.md.
"""

from __future__ import annotations

import sys
import time
from contextlib import nullcontext
from typing import Optional

from .journal import (  # noqa: F401
    Journal,
    JournalRecords,
    new_run_id,
    read_journal,
)
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
)
from .export import (  # noqa: F401
    bind_runtime_metrics,
    render_prometheus,
    start_http_server,
)


class Telemetry:
    """The handle a driver is given: registry + optional journal, trace
    recorder and exposition endpoint, torn down together by ``close``.

    - ``registry``: an ``obs.metrics.Registry`` (fresh one by default);
    - ``journal``: a path or a ``Journal`` — every ``event()`` appends
      one JSONL line with wall timestamp + run ID;
    - ``trace``: a path — driver phases recorded through a
      ``tracing.SpanTracer`` and saved there on ``close``;
    - ``http_port``: serve ``/metrics`` (Prometheus text) on localhost;
      0 picks a free port (``telemetry.server.url``).

    Convenience recorders (``count``/``gauge``/``observe``/``event``/
    ``span``) are what the drivers call; each is a no-op for the planes
    not enabled, so a metrics-only handle costs dict updates and nothing
    else.
    """

    def __init__(
        self,
        *,
        registry: Optional[Registry] = None,
        journal=None,
        trace: Optional[str] = None,
        http_port: Optional[int] = None,
        run_id: Optional[str] = None,
    ):
        self.registry = registry if registry is not None else Registry()
        self.run_id = run_id or new_run_id()
        if journal is None or isinstance(journal, Journal):
            self.journal = journal
        else:
            self.journal = Journal(str(journal), run_id=self.run_id)
        self._trace_path = trace
        if trace is not None:
            from ..tracing import SpanTracer

            self.tracer = SpanTracer()
        else:
            self.tracer = None
        self.server = (
            start_http_server(self.registry, port=http_port)
            if http_port is not None
            else None
        )

    # -- recorders (driver-facing) -----------------------------------------

    def count(self, name: str, value: float = 1, help: str = "", **labels):
        self.registry.counter(
            name, help, labels=tuple(sorted(labels))
        ).inc(value, **labels)

    def gauge(self, name: str, value: float, help: str = "", **labels):
        self.registry.gauge(
            name, help, labels=tuple(sorted(labels))
        ).set(value, **labels)

    def observe(self, name: str, value: float, help: str = "", **labels):
        self.registry.histogram(
            name, help, labels=tuple(sorted(labels))
        ).observe(value, **labels)

    def event(self, kind: str, **fields) -> None:
        if self.journal is not None:
            self.journal.write(kind, **fields)

    def span(self, name: str, track: str = "host", **args):
        """Context manager: a driver-phase span on the trace (no-op
        without a trace path)."""
        if self.tracer is None:
            return nullcontext()
        return self.tracer.span(name, track=track, args=args or None)

    def sample(self, name: str, **values) -> None:
        """One counter-series sample on the trace timeline (occupancy,
        queue depth) — the refill-cadence view; no-op without a trace."""
        if self.tracer is not None:
            self.tracer.counter(name, **values)

    def event_mix(self, summary: dict, prefix: str = "engine") -> None:
        """Fold a chunk summary's device-side ``event_mix`` histogram
        (engine/core.py opt-in plane) into per-kind counters."""
        mix = summary.get("event_mix")
        if mix:
            c = self.registry.counter(
                f"{prefix}_events_by_kind_total",
                "device-side event-mix plane, per event kind",
                labels=("kind",),
            )
            for i, v in enumerate(mix):
                c.inc(v, kind=str(i))

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self.tracer is not None and self._trace_path is not None:
            self.tracer.save(self._trace_path)
        if self.journal is not None:
            self.journal.close()
        if self.server is not None:
            self.server.close()

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _fmt_eta(seconds: float) -> str:
    if seconds < 0 or seconds != seconds or seconds == float("inf"):
        return "?"
    s = int(seconds)
    if s >= 3600:
        return f"{s // 3600}h{(s % 3600) // 60:02d}m"
    if s >= 60:
        return f"{s // 60}m{s % 60:02d}s"
    return f"{s}s"


class Heartbeat:
    """Progress heartbeat driven by the obs registry (seeds done,
    seeds/s, occupancy, ETA) — what scripts/sweep_million.py and
    scripts/stream_smoke.py print instead of ad-hoc ``perf_counter``
    lines.

    Reads ``<prefix>_seeds_done_total`` (counter) and, when present,
    ``<prefix>_occupancy`` (gauge) from the registry; call ``tick()``
    after progress lands (a chunk merge, a stream flush). Lines go to
    stderr so stdout stays machine-readable (the scripts' JSON lines).
    """

    def __init__(
        self,
        registry: Registry,
        total_seeds: int,
        *,
        prefix: str = "sweep",
        out=None,
        min_interval_s: float = 0.0,
    ):
        self.registry = registry
        self.total = int(total_seeds)
        self.prefix = prefix
        self.out = out if out is not None else sys.stderr
        self.min_interval_s = min_interval_s
        self._t0 = time.perf_counter()
        self._last = 0.0

    def tick(self, force: bool = False) -> Optional[str]:
        now = time.perf_counter()
        if not force and (now - self._last) < self.min_interval_s:
            return None
        self._last = now
        done = self.registry.get(f"{self.prefix}_seeds_done_total") or 0
        rate = done / max(now - self._t0, 1e-9)
        eta = (self.total - done) / rate if rate > 0 else float("inf")
        occ = self.registry.get(f"{self.prefix}_occupancy")
        line = (
            f"[hb] {int(done)}/{self.total} seeds  {rate:,.0f} seeds/s"
            + (f"  occ {occ:.3f}" if occ is not None else "")
            + f"  ETA {_fmt_eta(eta)}"
        )
        print(line, file=self.out, flush=True)
        return line
