"""Fleet metrics registry: counters / gauges / histograms with labels.

The device tier ran as a black box — the streaming service, pipelined
driver, mesh shards, campaign loop and checker pool emitted nothing
until a chunk summary landed. This registry is the substrate every
driver instruments against (``Telemetry`` in ``obs/__init__.py`` wires
it to the run journal, the Prometheus exposition endpoint and the trace
recorder).

Out-of-band BY CONSTRUCTION: nothing here ever feeds ``summarize`` /
``merge_summaries`` / report writing — metric values are wall-clock-side
observations, and the determinism gate byte-diffs reports with telemetry
on vs off (``scripts/check_determinism.sh``). Keep it that way: a metric
read must never influence a report byte.

Stdlib only (threading), no deps — the registry must import on every
tier, including the forked checker-pool children.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# Prometheus-compatible default latency buckets (seconds) — wide enough
# for both a 2 ms stream round and a 60 s pod chunk
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_KINDS = ("counter", "gauge", "histogram")


def _label_key(labelnames: Tuple[str, ...], labels: dict) -> Tuple[str, ...]:
    """The child key of one label assignment — declared names only, in
    declaration order, values coerced to str (Prometheus semantics)."""
    extra = set(labels) - set(labelnames)
    if extra:
        raise ValueError(
            f"undeclared label(s) {sorted(extra)}; declared: {labelnames}"
        )
    return tuple(str(labels.get(name, "")) for name in labelnames)


class Counter:
    """Monotonic counter; ``inc`` only (a decrement is a bug upstream)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({value})")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + value

    def get(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0)

    def series(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            return sorted(self._values.items())


class Gauge:
    """Point-in-time value (pool occupancy, queue depth, corpus size)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = value

    def inc(self, value: float = 1, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + value

    def get(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0)

    def series(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            return sorted(self._values.items())


class Histogram:
    """Cumulative-bucket histogram (per-API latency, round occupancy).

    Each child keeps per-bucket counts plus sum/count, rendered in the
    Prometheus ``_bucket``/``_sum``/``_count`` shape by obs/export.py."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Tuple[str, ...] = (),
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        if tuple(buckets) != tuple(sorted(buckets)):
            raise ValueError(f"buckets must be sorted: {buckets}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self.buckets = tuple(float(b) for b in buckets)
        # child key -> [bucket counts..., +Inf count, sum]
        self._values: Dict[Tuple[str, ...], List[float]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            row = self._values.get(key)
            if row is None:
                row = self._values[key] = [0.0] * (len(self.buckets) + 2)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    row[i] += 1
                    break
            else:
                row[len(self.buckets)] += 1  # +Inf bucket
            row[-1] += value

    def get(self, **labels) -> Tuple[int, float]:
        """(count, sum) of one child."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            row = self._values.get(key)
            if row is None:
                return 0, 0.0
            return int(sum(row[:-1])), row[-1]

    def series(self) -> List[Tuple[Tuple[str, ...], List[float]]]:
        with self._lock:
            return sorted((k, list(v)) for k, v in self._values.items())


class Registry:
    """Named metric families; creation is idempotent per (name, kind).

    ``callback_gauge`` registers a pull-time gauge: the callable runs at
    collect/render time and returns either a scalar or a ``{label value:
    number}`` dict — how the host-tier ``RuntimeMetrics`` shim
    (``num_tasks_by_node``/``by_spawn_site``) joins the exposition path
    without a push loop (obs/export.py ``bind_runtime_metrics``)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._callbacks: Dict[str, Tuple[str, Tuple[str, ...], Callable]] = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name: str, help: str, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested {cls.kind}"
                    )
                return m
            if name in self._callbacks:
                raise ValueError(f"metric {name!r} is a callback gauge")
            m = cls(name, help, tuple(labels), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labels=()) -> Counter:
        return self._get_or_make(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels=()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labels)

    def histogram(
        self, name: str, help: str = "", labels=(),
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_make(
            Histogram, name, help, labels, buckets=tuple(buckets)
        )

    def callback_gauge(
        self, name: str, fn: Callable, help: str = "", label: str = ""
    ) -> None:
        """A gauge whose value(s) are pulled from ``fn()`` at collect
        time. ``fn`` returns a number, or (with ``label`` set) a dict of
        ``{label value: number}``."""
        with self._lock:
            if name in self._metrics:
                raise ValueError(f"metric {name!r} already registered")
            self._callbacks[name] = (help, (label,) if label else (), fn)

    def get(self, name: str, **labels):
        """Convenience read for heartbeats/tests: the child value, or
        None when the family does not exist yet."""
        with self._lock:
            m = self._metrics.get(name)
        if m is None:
            return None
        return m.get(**labels)

    def metric(self, name: str):
        """The metric family object itself (or None) — for callers that
        need ``series()``/``buckets`` rather than one child value."""
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> Iterable[Tuple[str, str, str, Tuple[str, ...], list]]:
        """Snapshot every family: ``(name, kind, help, labelnames,
        series)`` tuples, name-sorted — the renderer's input."""
        with self._lock:
            metrics = sorted(self._metrics.items())
            callbacks = sorted(self._callbacks.items())
        out = []
        for name, m in metrics:
            out.append((name, m.kind, m.help, m.labelnames, m.series()))
        for name, (help, labelnames, fn) in callbacks:
            try:
                val = fn()
            except Exception:  # noqa: BLE001 — exposition must not crash
                continue
            if isinstance(val, dict):
                series = sorted(
                    ((str(k),), float(v)) for k, v in val.items()
                )
            else:
                series = [((), float(val))]
            out.append((name, "gauge", help, labelnames, series))
        return sorted(out)

    def snapshot(self) -> dict:
        """Plain-dict view (journal dumps, heartbeats): ``{name: value}``
        for unlabeled scalars, ``{name: {"label=value,...": v}}`` for
        labeled families, ``{name: {"count": c, "sum": s}}``-style rows
        for histograms."""
        out: dict = {}
        for name, kind, _help, labelnames, series in self.collect():
            fam: dict = {}
            for key, val in series:
                lk = ",".join(f"{n}={v}" for n, v in zip(labelnames, key))
                if kind == "histogram":
                    fam[lk] = {"count": int(sum(val[:-1])), "sum": val[-1]}
                else:
                    fam[lk] = val
            out[name] = fam.get("", fam) if list(fam) == [""] else fam
        return out


# the default registry: scripts and drivers that are not handed an
# explicit Telemetry may still share one process-wide registry
_DEFAULT = Registry()


def default_registry() -> Registry:
    return _DEFAULT
