"""Ambient runtime context (thread-local).

Every public API call (``time.sleep``, ``net.Endpoint.bind``, ``rand.random``)
resolves the ambient handle here, so user code never threads a runtime
reference.  Mirrors the reference's thread-local ``CONTEXT: Handle`` +
``TASK: Arc<TaskInfo>`` (madsim/src/sim/runtime/context.rs:9-80).

One OS thread runs at most one simulation at a time (the seed-sweep driver
spawns one thread per seed, like the reference's builder), so plain
``threading.local`` storage is correct and fast.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import TYPE_CHECKING, Iterator, Optional

if TYPE_CHECKING:
    from .runtime import Handle
    from .task import Task, NodeInfo

_tls = threading.local()


class NoContextError(RuntimeError):
    """Raised when a sim API is used outside a Runtime context."""


def try_current_handle() -> Optional["Handle"]:
    return getattr(_tls, "handle", None)


def current_handle() -> "Handle":
    """The ambient runtime handle (context.rs:14-24 ``context::current``)."""
    h = try_current_handle()
    if h is None:
        raise NoContextError(
            "there is no simulation context; this API must be called "
            "inside Runtime.block_on() (or a @sim_test)"
        )
    return h


def try_current_task() -> Optional["Task"]:
    return getattr(_tls, "task", None)


def current_task() -> "Task":
    t = try_current_task()
    if t is None:
        raise NoContextError("not inside a simulated task")
    return t


def current_node() -> "NodeInfo":
    """Node of the currently running task (context.rs ``current_node``)."""
    return current_task().node


@contextmanager
def enter_handle(handle: "Handle") -> Iterator[None]:
    """Enter a runtime context (context.rs:26-44 ``enter``)."""
    prev = getattr(_tls, "handle", None)
    if prev is not None:
        raise RuntimeError("a simulation runtime is already entered on this thread")
    _tls.handle = handle
    try:
        yield
    finally:
        _tls.handle = prev


@contextmanager
def enter_task(task: "Task") -> Iterator[None]:
    """Enter a task context for one poll (context.rs:58-64 ``enter_task``)."""
    prev = getattr(_tls, "task", None)
    _tls.task = task
    try:
        yield
    finally:
        _tls.task = prev


# Hand-rolled enter/exit pair for the executor's per-poll hot path — the
# @contextmanager generator machinery costs more than the bookkeeping it
# wraps at ~2k polls per simulated seed.

def swap_task(task: "Optional[Task]") -> "Optional[Task]":
    """Set the ambient task, returning the previous one (restore by
    calling again with the return value)."""
    prev = getattr(_tls, "task", None)
    _tls.task = task
    return prev
