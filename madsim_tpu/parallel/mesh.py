"""Mesh construction + sharded sweep driver.

Pure data parallelism over seeds (no cross-seed state exists), expressed
with ``shard_map`` so the collective structure is explicit and auditable:

- per-device: ``vmap``'d engine step over the local seed shard;
- cross-device: one ``psum`` of the local live-seed count per loop
  iteration — the global termination signal (the sharded analogue of the
  batch-level ``jnp.any(~done)`` in ``engine.core._run``).

On a multi-host slice the same code spans DCN automatically (the mesh just
contains all devices); seeds never migrate between devices, so there is no
resharding traffic to place.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.core import EngineConfig, EngineState, Workload, init_sweep, step_one

SEED_AXIS = "seeds"


def seed_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, axis ``"seeds"``."""
    import numpy as np

    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (SEED_AXIS,))


def shard_seeds(mesh: Mesh, seeds: jnp.ndarray) -> jnp.ndarray:
    """Place a seed vector sharded over the mesh's seed axis (the batch
    size must divide the mesh size)."""
    sharding = NamedSharding(mesh, P(SEED_AXIS))
    return jax.device_put(jnp.asarray(seeds, jnp.int64), sharding)


def sharded_step(workload: Workload, cfg: EngineConfig, mesh: Mesh):
    """Build an explicit n-step sharded step: advances every local seed
    ``n_steps`` events and returns the global number of still-live seeds
    via ``psum``.

    Kept as the multichip dryrun/CI entry point (__graft_entry__ calls it
    with a fixed n_steps to demonstrate one sharded step + collective);
    the production sweep path is ``run_sweep_sharded``, whose flat
    per-device loop avoids the ~9x nested-device-loop penalty this
    chunked shape pays on TPU."""

    def local_step(state: EngineState, n_steps):
        # finished seeds are frozen no-ops, so over-stepping is harmless
        state = jax.lax.fori_loop(
            0,
            n_steps,
            lambda _, s: jax.vmap(partial(step_one, workload, cfg))(s),
            state,
        )
        live = jnp.sum(~state.done, dtype=jnp.int32)
        return state, jax.lax.psum(live, SEED_AXIS)

    # check_vma off: lax.switch branches mix mesh-constant and mesh-varying
    # outputs (e.g. a constant event-kind vector vs a data-dependent one),
    # which the varying-manual-axes checker rejects even though the program
    # is replication-safe (communication happens only in the psum below).
    return jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(SEED_AXIS), P()),
        out_specs=(P(SEED_AXIS), P()),
        check_vma=False,
    )


@lru_cache(maxsize=64)
def _sharded_run(workload: Workload, cfg: EngineConfig, mesh: Mesh):
    """Cached jitted whole-sweep program for (workload, cfg, mesh) — a
    fresh wrapper per call would retrace and recompile every invocation."""

    def device_run(state: EngineState) -> EngineState:
        def cond(carry):
            state, iters = carry
            live = jax.lax.psum(
                jnp.sum(~state.done, dtype=jnp.int32), SEED_AXIS
            )
            return (live > 0) & (iters < cfg.max_steps)

        def body(carry):
            state, iters = carry
            return jax.vmap(partial(step_one, workload, cfg))(state), iters + 1

        state, _ = jax.lax.while_loop(
            cond, body, (state, jnp.zeros((), jnp.int64))
        )
        return state

    return jax.jit(
        jax.shard_map(
            device_run,
            mesh=mesh,
            in_specs=P(SEED_AXIS),
            out_specs=P(SEED_AXIS),
            check_vma=False,  # same rationale as sharded_step
        )
    )


def run_sweep_sharded(
    workload: Workload, cfg: EngineConfig, seeds, mesh: Optional[Mesh] = None
) -> EngineState:
    """Run a seed sweep sharded over a device mesh; bit-identical to the
    single-device ``engine.run_sweep`` for the same seeds.

    The whole sweep loop lives INSIDE ``shard_map`` — one flat per-device
    ``while_loop`` whose cond psums the live count every step, so all
    devices terminate together. Flat because a nested device loop costs
    ~9x per step on TPU (engine/core.py ``drive``); the per-step psum
    rides ICI and is noise next to a step."""
    if mesh is None:
        mesh = seed_mesh()
    seeds = shard_seeds(mesh, seeds)
    # init and loop compile as separate programs (same split as
    # engine.core._run: fusing the init writes pessimizes the loop carry);
    # core._init shares run_sweep's trace cache
    from ..engine.core import _init

    state = _init(workload, cfg, seeds)
    return _sharded_run(workload, cfg, mesh)(state)


def run_sweep_sharded_chunked(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    mesh: Optional[Mesh] = None,
    chunk_per_device: int = 16384,
) -> EngineState:
    """Pod-scale composition of the two scaling axes: the seed batch is
    sharded over the mesh AND run as sequential fixed-size chunks of one
    compiled program.

    The ~9x per-lane step-cost cliff above ~16k lanes
    (engine.core.run_sweep_chunked) is a per-chip working-set limit, so
    the right chunk is ``chunk_per_device × mesh size`` lanes. A ragged
    batch is padded with continuation seeds (to the chunk multiple when
    chunking, or just to mesh divisibility for a single small batch) and
    trimmed inside one jitted concat. Bit-identical per seed to
    single-device ``run_sweep``. The returned state keeps O(total seeds)
    device memory — at the million-seed scale merge per-chunk
    ``sweep_summary`` dicts on host instead, as bench.py's bench_100k
    does."""
    from ..engine.core import run_in_chunks

    if mesh is None:
        mesh = seed_mesh()
    n_dev = mesh.devices.size
    return run_in_chunks(
        lambda chunk: run_sweep_sharded(workload, cfg, chunk, mesh),
        seeds,
        chunk_per_device * n_dev,
        multiple=n_dev,
    )
