"""Mesh construction + sharded sweep driver.

Pure data parallelism over seeds (no cross-seed state exists), expressed
with ``shard_map`` so the collective structure is explicit and auditable:

- per-device: ``vmap``'d engine step over the local seed shard;
- cross-device: one ``psum`` of the local live-seed count per loop
  iteration — the global termination signal (the sharded analogue of the
  batch-level ``jnp.any(~done)`` in ``engine.core._run``).

On a multi-host slice the same code spans DCN automatically (the mesh just
contains all devices); seeds never migrate between devices, so there is no
resharding traffic to place.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.core import EngineConfig, EngineState, Workload, init_sweep, step_one

SEED_AXIS = "seeds"


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across the jax versions this repo meets: newer
    releases export it top-level with a ``check_vma`` knob, while 0.4.x
    only has ``jax.experimental.shard_map.shard_map`` with the older
    ``check_rep`` spelling. Both checkers are disabled for the same
    reason (see ``sharded_step``): lax.switch branches mix mesh-constant
    and mesh-varying outputs, which the replication checker rejects even
    though the program is replication-safe."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def seed_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, axis ``"seeds"``."""
    import numpy as np

    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (SEED_AXIS,))


def shard_seeds(mesh: Mesh, seeds: jnp.ndarray) -> jnp.ndarray:
    """Place a seed vector sharded over the mesh's seed axis (the batch
    size must divide the mesh size)."""
    sharding = NamedSharding(mesh, P(SEED_AXIS))
    return jax.device_put(jnp.asarray(seeds, jnp.int64), sharding)


def sharded_step(workload: Workload, cfg: EngineConfig, mesh: Mesh):
    """Build an explicit n-step sharded step: advances every local seed
    ``n_steps`` events and returns the global number of still-live seeds
    via ``psum``.

    Kept as the multichip dryrun/CI entry point (__graft_entry__ calls it
    with a fixed n_steps to demonstrate one sharded step + collective);
    the production sweep path is ``run_sweep_sharded``, whose flat
    per-device loop avoids the ~9x nested-device-loop penalty this
    chunked shape pays on TPU."""

    def local_step(state: EngineState, n_steps):
        # finished seeds are frozen no-ops, so over-stepping is harmless
        state = jax.lax.fori_loop(
            0,
            n_steps,
            lambda _, s: jax.vmap(partial(step_one, workload, cfg))(s),
            state,
        )
        live = jnp.sum(~state.done, dtype=jnp.int32)
        return state, jax.lax.psum(live, SEED_AXIS)

    # replication checking off: lax.switch branches mix mesh-constant and
    # mesh-varying outputs (e.g. a constant event-kind vector vs a
    # data-dependent one), which the varying-manual-axes checker rejects
    # even though the program is replication-safe (communication happens
    # only in the psum below).
    return shard_map_compat(
        local_step,
        mesh,
        in_specs=(P(SEED_AXIS), P()),
        out_specs=(P(SEED_AXIS), P()),
    )


@lru_cache(maxsize=64)
def _sharded_run(workload: Workload, cfg: EngineConfig, mesh: Mesh):
    """Cached jitted whole-sweep program for (workload, cfg, mesh) — a
    fresh wrapper per call would retrace and recompile every invocation."""

    def device_run(state: EngineState) -> EngineState:
        def cond(carry):
            state, iters = carry
            live = jax.lax.psum(
                jnp.sum(~state.done, dtype=jnp.int32), SEED_AXIS
            )
            return (live > 0) & (iters < cfg.max_steps)

        def body(carry):
            state, iters = carry
            return jax.vmap(partial(step_one, workload, cfg))(state), iters + 1

        state, _ = jax.lax.while_loop(
            cond, body, (state, jnp.zeros((), jnp.int64))
        )
        return state

    return jax.jit(
        shard_map_compat(
            device_run,
            mesh,
            in_specs=P(SEED_AXIS),
            out_specs=P(SEED_AXIS),
        )
    )


def shard_params(mesh: Mesh, params):
    """Place a per-lane spec-as-data pytree (engine/faults.py) sharded
    over the mesh's seed axis — every leaf's leading axis is the lane
    batch, exactly like ``shard_state``'s contract."""
    sharding = NamedSharding(mesh, P(SEED_AXIS))
    return jax.device_put(params, sharding)


def run_sweep_sharded(
    workload: Workload, cfg: EngineConfig, seeds, mesh: Optional[Mesh] = None,
    params=None,
) -> EngineState:
    """Run a seed sweep sharded over a device mesh; bit-identical to the
    single-device ``engine.run_sweep`` for the same seeds.

    The whole sweep loop lives INSIDE ``shard_map`` — one flat per-device
    ``while_loop`` whose cond psums the live count every step, so all
    devices terminate together. Flat because a nested device loop costs
    ~9x per step on TPU (engine/core.py ``drive``); the per-step psum
    rides ICI and is noise next to a step.

    ``params`` is per-lane spec-as-data (``engine.run_sweep``'s
    contract), sharded alongside the seed axis — its leaves are traced,
    so sweeping a new candidate reuses the one compiled sharded
    program."""
    if mesh is None:
        mesh = seed_mesh()
    seeds = shard_seeds(mesh, seeds)
    # init and loop compile as separate programs (same split as
    # engine.core._run: fusing the init writes pessimizes the loop carry);
    # core._init shares run_sweep's trace cache
    from ..engine.core import _init

    if params is None:
        state = _init(workload, cfg, seeds)
    else:
        state = _init(workload, cfg, seeds, shard_params(mesh, params))
    return _sharded_run(workload, cfg, mesh)(state)


def run_sweep_sharded_chunked(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    mesh: Optional[Mesh] = None,
    chunk_per_device: int = 16384,
    params=None,
) -> EngineState:
    """Pod-scale composition of the two scaling axes: the seed batch is
    sharded over the mesh AND run as sequential fixed-size chunks of one
    compiled program.

    The ~9x per-lane step-cost cliff above ~16k lanes
    (engine.core.run_sweep_chunked) is a per-chip working-set limit, so
    the right chunk is ``chunk_per_device × mesh size`` lanes. A ragged
    batch is padded with continuation seeds (to the chunk multiple when
    chunking, or just to mesh divisibility for a single small batch) and
    trimmed inside one jitted concat. Bit-identical per seed to
    single-device ``run_sweep``. The returned state keeps O(total seeds)
    device memory — at the million-seed scale merge per-chunk
    ``sweep_summary`` dicts on host instead, as bench.py's bench_100k
    does."""
    from ..engine.core import run_in_chunks

    if mesh is None:
        mesh = seed_mesh()
    n_dev = mesh.devices.size
    if params is None:
        run_chunk = lambda chunk: run_sweep_sharded(  # noqa: E731
            workload, cfg, chunk, mesh
        )
    else:
        run_chunk = lambda chunk, pchunk: run_sweep_sharded(  # noqa: E731
            workload, cfg, chunk, mesh, params=pchunk
        )
    return run_in_chunks(
        run_chunk,
        seeds,
        chunk_per_device * n_dev,
        multiple=n_dev,
        params=params,
    )


def shard_state(mesh: Mesh, state: EngineState) -> EngineState:
    """Place a batched EngineState sharded over the mesh's seed axis
    (every leaf's leading axis is the seed batch, so one PartitionSpec
    covers the whole tree). Used to re-shard a checkpoint-restored state
    onto whatever mesh the resuming process has — the snapshot itself is
    host arrays with no layout, which is what makes a sweep interrupted
    on 8 devices resumable on 1 (checkpoint format v8 carries the
    original layout for chunk-boundary bookkeeping, not for data)."""
    sharding = NamedSharding(mesh, P(SEED_AXIS))
    return jax.device_put(state, sharding)


def resume_sweep_sharded(
    workload: Workload, cfg: EngineConfig, state: EngineState,
    mesh: Optional[Mesh] = None,
) -> EngineState:
    """Continue a (possibly restored) sweep sharded over a mesh until
    every seed finishes — the sharded analogue of
    ``engine.checkpoint.resume_sweep``, bit-identical to it per seed.
    The batch must divide the mesh size."""
    if mesh is None:
        mesh = seed_mesh()
    if int(state.seed.shape[0]) % mesh.devices.size:
        raise ValueError(
            f"cannot resume a {int(state.seed.shape[0])}-lane snapshot on "
            f"a {mesh.devices.size}-device mesh (batch must divide the "
            "mesh; resume on a divisor mesh or unsharded)"
        )
    return _sharded_run(workload, cfg, mesh)(shard_state(mesh, state))


def mesh_layout(mesh: Mesh, chunk_per_device: int) -> dict:
    """The mesh-layout metadata a sharded sweep records in its v8
    checkpoints (``engine.checkpoint.save_sweep(mesh_layout=)``): enough
    to rebuild the GLOBAL chunk boundaries (``chunk_size =
    chunk_per_device × n_dev``) on a resuming process with a different
    device count, so per-chunk checkpoint files keep lining up."""
    return {
        "n_dev": int(mesh.devices.size),
        "chunk_per_device": int(chunk_per_device),
        "chunk_size": int(chunk_per_device) * int(mesh.devices.size),
        "axis": SEED_AXIS,
    }


def run_sweep_sharded_pipelined(
    workload: Workload,
    cfg: EngineConfig,
    seeds,
    summarize,
    *,
    mesh: Optional[Mesh] = None,
    host_work: Optional[Callable] = None,
    screen: Optional[Callable] = None,
    chunk_per_device: Optional[int] = None,
    chunk_size: Optional[int] = None,
    ckpt_dir: Optional[str] = None,
    stop_after: Optional[int] = None,
    resume_from: Optional[Tuple[EngineState, dict]] = None,
    on_chunk: Optional[Callable] = None,
    params=None,
    telemetry=None,
) -> dict:
    """The pipelined checked-sweep driver lifted onto the mesh: chunked
    device sweeps run sharded over all devices (``run_sweep_sharded``),
    the screen/summary programs are enqueued behind each chunk sharded
    the same way, and the host phase (decode, WGL checking, triage) of
    chunk N overlaps the sharded sweep of chunk N+1 exactly as in
    ``engine.checkpoint.run_sweep_pipelined`` — a million-seed checked
    campaign becomes ONE unit of work spanning every chip.

    Chunk sizing: the device-memory knee is PER CHIP, so the global
    chunk is ``chunk_per_device × n_dev`` lanes, with ``chunk_per_device``
    auto-picked from the workload's measured loop-carry footprint
    (``engine.core.pick_chunk_size``) when not given. An explicit
    ``chunk_size`` (global) overrides both; either way the granule is
    rounded up to mesh divisibility.

    Report invariance contract: the merged summary dict is BYTE-IDENTICAL
    across mesh sizes — on 1, 2, 4 and 8 devices — even though the chunk
    boundaries differ (per-chunk summaries are exact integer reductions,
    list fields merge in seed order, and caps compose chunking-invariantly;
    tests/test_parallel.py pins the bytes). Checkpointing composes too:
    per-chunk files carry no mesh identity, and a mid-chunk v8 snapshot
    (``save_sweep(..., inflight=, mesh_layout=mesh_layout(mesh, cpd))``)
    resumes bit-identical on ANY mesh whose size divides the chunk —
    interrupt on 8 devices, resume on 1 (``resume_from=(state, inflight)``,
    with ``chunk_size`` taken from the snapshot's mesh layout).

    ``telemetry`` (``obs.Telemetry`` or None) rides through to the inner
    pipelined driver (chunk/host-phase timing, device/host trace spans)
    and adds the mesh-level view: a ``mesh_devices`` gauge and a
    PER-DEVICE seeds/s gauge sampled at each chunk merge. The per-step
    psum'd live count stays inside the compiled round — surfacing it
    per iteration would put host work on the step path; chunk-granule
    throughput is the out-of-band proxy.
    """
    import time as _time

    from ..engine.checkpoint import run_sweep_pipelined
    from ..engine.core import pick_chunk_size

    if mesh is None:
        mesh = seed_mesh()
    n_dev = int(mesh.devices.size)
    if chunk_size is None:
        if chunk_per_device is None:
            one_lane = (
                None
                if params is None
                else jax.tree.map(lambda a: np.asarray(a)[0], params)
            )
            chunk_per_device = pick_chunk_size(workload, cfg, params=one_lane)
        chunk_size = chunk_per_device * n_dev
    chunk_size = -(-chunk_size // n_dev) * n_dev  # mesh divisibility

    if params is None:
        run_chunk = lambda chunk: run_sweep_sharded(  # noqa: E731
            workload, cfg, chunk, mesh
        )
    else:
        run_chunk = lambda chunk, pchunk: run_sweep_sharded(  # noqa: E731
            workload, cfg, chunk, mesh, params=pchunk
        )
    if telemetry is not None:
        telemetry.gauge(
            "mesh_devices", n_dev, help="devices in the sweep mesh"
        )
        inner_on_chunk = on_chunk
        t_last = [_time.perf_counter()]

        def on_chunk(lo, k, summary):
            now = _time.perf_counter()
            dt, t_last[0] = now - t_last[0], now
            telemetry.gauge(
                "mesh_seeds_per_s_per_device",
                k / max(dt, 1e-9) / n_dev,
                help="chunk-merge throughput divided by device count",
            )
            if inner_on_chunk is not None:
                inner_on_chunk(lo=lo, k=k, summary=summary)

    return run_sweep_pipelined(
        workload,
        cfg,
        seeds,
        summarize,
        host_work=host_work,
        screen=screen,
        chunk_size=chunk_size,
        ckpt_dir=ckpt_dir,
        stop_after=stop_after,
        resume_from=resume_from,
        run_chunk=run_chunk,
        resume_chunk=lambda state: resume_sweep_sharded(
            workload, cfg, state, mesh
        ),
        pad_multiple=n_dev,
        on_chunk=on_chunk,
        params=params,
        telemetry=telemetry,
    )
