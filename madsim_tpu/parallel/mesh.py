"""Mesh construction + sharded sweep driver.

Pure data parallelism over seeds (no cross-seed state exists), expressed
with ``shard_map`` so the collective structure is explicit and auditable:

- per-device: ``vmap``'d engine step over the local seed shard;
- cross-device: one ``psum`` of the local live-seed count per loop
  iteration — the global termination signal (the sharded analogue of the
  batch-level ``jnp.any(~done)`` in ``engine.core._run``).

On a multi-host slice the same code spans DCN automatically (the mesh just
contains all devices); seeds never migrate between devices, so there is no
resharding traffic to place.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.core import EngineConfig, EngineState, Workload, init_sweep, step_one

SEED_AXIS = "seeds"


def seed_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, axis ``"seeds"``."""
    import numpy as np

    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (SEED_AXIS,))


def shard_seeds(mesh: Mesh, seeds: jnp.ndarray) -> jnp.ndarray:
    """Place a seed vector sharded over the mesh's seed axis (the batch
    size must divide the mesh size)."""
    sharding = NamedSharding(mesh, P(SEED_AXIS))
    return jax.device_put(jnp.asarray(seeds, jnp.int64), sharding)


def sharded_step(workload: Workload, cfg: EngineConfig, mesh: Mesh):
    """Build the per-iteration sharded step: advances every local seed one
    event and returns the global number of still-live seeds via ``psum``."""

    def local_step(state: EngineState, n_steps):
        # up to cond_interval engine steps per invocation (finished seeds
        # are frozen no-ops; the caller clamps n_steps so the max_steps
        # budget is exact) — the cross-device psum amortizes over the chunk
        state = jax.lax.fori_loop(
            0,
            n_steps,
            lambda _, s: jax.vmap(partial(step_one, workload, cfg))(s),
            state,
        )
        live = jnp.sum(~state.done, dtype=jnp.int32)
        return state, jax.lax.psum(live, SEED_AXIS)

    # check_vma off: lax.switch branches mix mesh-constant and mesh-varying
    # outputs (e.g. a constant event-kind vector vs a data-dependent one),
    # which the varying-manual-axes checker rejects even though the program
    # is replication-safe (communication happens only in the psum below).
    return jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(SEED_AXIS), P()),
        out_specs=(P(SEED_AXIS), P()),
        check_vma=False,
    )


def run_sweep_sharded(
    workload: Workload, cfg: EngineConfig, seeds, mesh: Optional[Mesh] = None
) -> EngineState:
    """Run a seed sweep sharded over a device mesh; bit-identical to the
    single-device ``engine.run_sweep`` for the same seeds."""
    if mesh is None:
        mesh = seed_mesh()
    seeds = shard_seeds(mesh, seeds)
    step = sharded_step(workload, cfg, mesh)

    @partial(jax.jit, static_argnums=())
    def run(seeds):
        state = init_sweep(workload, cfg, seeds)

        def cond(carry):
            _, live, iters = carry
            return (live > 0) & (iters < cfg.max_steps)

        def body(carry):
            state, _, iters = carry
            n = jnp.minimum(cfg.cond_interval, cfg.max_steps - iters)
            state, live = step(state, n)
            return state, live, iters + n

        state, _, _ = jax.lax.while_loop(
            cond, body, (state, jnp.int32(seeds.shape[0]), jnp.zeros((), jnp.int64))
        )
        return state

    return run(seeds)
