"""Scale-out tier: shard the seed batch over a TPU device mesh.

The reference scales seed sweeps with OS threads — one seed per thread,
``MADSIM_TEST_JOBS`` at a time (madsim/src/sim/runtime/builder.rs:128-149).
The TPU-native axis is the same *logical* axis (seeds are independent —
SURVEY.md §2.3) mapped onto hardware the JAX way: the batched engine state
is sharded over a ``jax.sharding.Mesh`` axis named ``"seeds"`` and the
lockstep step runs under ``shard_map``; the only cross-device communication
is the tiny ``psum`` of live-seed counts that decides sweep termination, so
scaling rides ICI bandwidth-free.
"""

from .mesh import (
    seed_mesh,
    shard_seeds,
    shard_state,
    shard_map_compat,
    mesh_layout,
    run_sweep_sharded,
    run_sweep_sharded_chunked,
    run_sweep_sharded_pipelined,
    resume_sweep_sharded,
    sharded_step,
)

__all__ = [
    "seed_mesh",
    "shard_seeds",
    "shard_state",
    "shard_map_compat",
    "mesh_layout",
    "run_sweep_sharded",
    "run_sweep_sharded_chunked",
    "run_sweep_sharded_pipelined",
    "resume_sweep_sharded",
    "sharded_step",
]
