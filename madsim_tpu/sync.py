"""Async synchronization primitives for the deterministic executor.

The reference keeps real tokio ``sync`` in sim mode (madsim-tokio/src/lib.rs:
38-50) because tokio's channels are runtime-agnostic.  Our executor has its
own Future protocol, so we provide the tokio ``sync`` surface natively:
oneshot, mpsc (bounded/unbounded), watch, broadcast, Notify, Semaphore,
Mutex, RwLock, Barrier.  All waiter queues are FIFO lists — deterministic
wake order, with *scheduling* randomness injected only by the executor's
random ready-queue pop.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Generic, List, Optional, Tuple, TypeVar

from .futures import Future

T = TypeVar("T")


class ChannelClosedError(Exception):
    """Send/recv on a closed channel (tokio ``SendError``/``RecvError``)."""


class LaggedError(Exception):
    """Broadcast receiver fell behind and missed messages."""

    def __init__(self, n: int):
        self.missed = n
        super().__init__(f"broadcast receiver lagged by {n} messages")


# -- oneshot ---------------------------------------------------------------


class OneshotSender(Generic[T]):
    def __init__(self, fut: Future):
        self._fut = fut

    def send(self, value: T) -> None:
        if self._fut.done():
            raise ChannelClosedError("oneshot value already sent")
        self._fut.set_result(value)

    def is_closed(self) -> bool:
        return self._fut.done()


def oneshot() -> Tuple[OneshotSender, Future]:
    """tokio ``oneshot::channel`` — receiver is awaitable directly."""
    fut: Future = Future()
    return OneshotSender(fut), fut


# -- mpsc ------------------------------------------------------------------


class _MpscState(Generic[T]):
    def __init__(self, capacity: Optional[int]):
        self.queue: Deque[T] = deque()
        self.capacity = capacity
        self.closed = False
        self.recv_waiters: List[Future] = []
        self.send_waiters: List[Future] = []

    def wake_one_recv(self) -> None:
        while self.recv_waiters:
            fut = self.recv_waiters.pop(0)
            if not fut.done():
                fut.set_result(None)
                return

    def wake_one_send(self) -> None:
        while self.send_waiters:
            fut = self.send_waiters.pop(0)
            if not fut.done():
                fut.set_result(None)
                return

    def wake_all(self) -> None:
        for fut in self.recv_waiters + self.send_waiters:
            if not fut.done():
                fut.set_result(None)
        self.recv_waiters.clear()
        self.send_waiters.clear()


class Sender(Generic[T]):
    def __init__(self, state: _MpscState[T]):
        self._state = state

    async def send(self, value: T) -> None:
        s = self._state
        while True:
            if s.closed:
                raise ChannelClosedError("channel closed")
            if s.capacity is None or len(s.queue) < s.capacity:
                s.queue.append(value)
                s.wake_one_recv()
                return
            fut: Future = Future()
            s.send_waiters.append(fut)
            await fut

    def try_send(self, value: T) -> None:
        s = self._state
        if s.closed:
            raise ChannelClosedError("channel closed")
        if s.capacity is not None and len(s.queue) >= s.capacity:
            raise ChannelClosedError("channel full")
        s.queue.append(value)
        s.wake_one_recv()

    def send_nowait(self, value: T) -> None:
        """Unbounded-style synchronous send (UnboundedSender::send)."""
        s = self._state
        if s.closed:
            raise ChannelClosedError("channel closed")
        s.queue.append(value)
        s.wake_one_recv()

    def close(self) -> None:
        self._state.closed = True
        self._state.wake_all()

    def is_closed(self) -> bool:
        return self._state.closed


class Receiver(Generic[T]):
    def __init__(self, state: _MpscState[T]):
        self._state = state

    async def recv(self) -> Optional[T]:
        """Next value, or ``None`` once closed and drained (tokio parity)."""
        s = self._state
        while True:
            if s.queue:
                v = s.queue.popleft()
                s.wake_one_send()
                return v
            if s.closed:
                return None
            fut: Future = Future()
            s.recv_waiters.append(fut)
            await fut

    def try_recv(self) -> Optional[T]:
        s = self._state
        if s.queue:
            v = s.queue.popleft()
            s.wake_one_send()
            return v
        if s.closed:
            raise ChannelClosedError("channel closed")
        return None

    def close(self) -> None:
        self._state.closed = True
        self._state.wake_all()

    def __len__(self) -> int:
        return len(self._state.queue)


def channel(capacity: int) -> Tuple[Sender, Receiver]:
    s: _MpscState = _MpscState(capacity)
    return Sender(s), Receiver(s)


def unbounded_channel() -> Tuple[Sender, Receiver]:
    s: _MpscState = _MpscState(None)
    return Sender(s), Receiver(s)


# -- watch -----------------------------------------------------------------


class _WatchState(Generic[T]):
    def __init__(self, value: T):
        self.value = value
        self.version = 0
        self.waiters: List[Future] = []


class WatchSender(Generic[T]):
    def __init__(self, state: _WatchState[T]):
        self._state = state

    def send(self, value: T) -> None:
        s = self._state
        s.value = value
        s.version += 1
        waiters, s.waiters = s.waiters, []
        for fut in waiters:
            fut.set_result(None)

    def borrow(self) -> T:
        return self._state.value


class WatchReceiver(Generic[T]):
    def __init__(self, state: _WatchState[T]):
        self._state = state
        self._seen = state.version

    def borrow(self) -> T:
        return self._state.value

    def borrow_and_update(self) -> T:
        self._seen = self._state.version
        return self._state.value

    async def changed(self) -> None:
        s = self._state
        while s.version == self._seen:
            fut: Future = Future()
            s.waiters.append(fut)
            await fut
        self._seen = s.version

    def clone(self) -> "WatchReceiver[T]":
        r: WatchReceiver[T] = WatchReceiver(self._state)
        r._seen = self._seen
        return r


def watch(initial: T) -> Tuple[WatchSender, WatchReceiver]:
    s: _WatchState = _WatchState(initial)
    return WatchSender(s), WatchReceiver(s)


# -- broadcast -------------------------------------------------------------


class _BroadcastState:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.receivers: List["BroadcastReceiver"] = []
        self.closed = False


class BroadcastSender(Generic[T]):
    def __init__(self, state: _BroadcastState):
        self._state = state

    def send(self, value: T) -> int:
        n = 0
        for r in self._state.receivers:
            r._push(value)
            n += 1
        return n

    def subscribe(self) -> "BroadcastReceiver[T]":
        r: BroadcastReceiver[T] = BroadcastReceiver(self._state)
        self._state.receivers.append(r)
        return r

    def close(self) -> None:
        self._state.closed = True
        for r in self._state.receivers:
            r._wake()


class BroadcastReceiver(Generic[T]):
    def __init__(self, state: _BroadcastState):
        self._state = state
        self._queue: Deque[T] = deque()
        self._lagged = 0
        self._waiters: List[Future] = []

    def _push(self, value: T) -> None:
        if len(self._queue) >= self._state.capacity:
            self._queue.popleft()
            self._lagged += 1
        self._queue.append(value)
        self._wake()

    def _wake(self) -> None:
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)

    async def recv(self) -> T:
        while True:
            if self._lagged:
                n, self._lagged = self._lagged, 0
                raise LaggedError(n)
            if self._queue:
                return self._queue.popleft()
            if self._state.closed:
                raise ChannelClosedError("broadcast channel closed")
            fut: Future = Future()
            self._waiters.append(fut)
            await fut


def broadcast(capacity: int) -> Tuple[BroadcastSender, BroadcastReceiver]:
    s = _BroadcastState(capacity)
    tx: BroadcastSender = BroadcastSender(s)
    return tx, tx.subscribe()


# -- Notify ----------------------------------------------------------------


class Notify:
    def __init__(self) -> None:
        self._permit = False
        self._waiters: List[Future] = []

    async def notified(self) -> None:
        if self._permit:
            self._permit = False
            return
        fut: Future = Future()
        self._waiters.append(fut)
        await fut

    def notify_one(self) -> None:
        while self._waiters:
            fut = self._waiters.pop(0)
            if not fut.done():
                fut.set_result(None)
                return
        self._permit = True

    def notify_waiters(self) -> None:
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)


# -- Semaphore / Mutex / RwLock / Barrier ----------------------------------


class Semaphore:
    def __init__(self, permits: int):
        self._permits = permits
        self._waiters: List[Future] = []

    @property
    def available_permits(self) -> int:
        return self._permits

    async def acquire(self, n: int = 1) -> "SemaphoreGuard":
        while self._permits < n:
            fut: Future = Future()
            self._waiters.append(fut)
            await fut
        self._permits -= n
        return SemaphoreGuard(self, n)

    def try_acquire(self, n: int = 1) -> Optional["SemaphoreGuard"]:
        if self._permits < n:
            return None
        self._permits -= n
        return SemaphoreGuard(self, n)

    def release(self, n: int = 1) -> None:
        self._permits += n
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)


class SemaphoreGuard:
    def __init__(self, sem: Semaphore, n: int):
        self._sem: Optional[Semaphore] = sem
        self._n = n

    def release(self) -> None:
        if self._sem is not None:
            sem, self._sem = self._sem, None
            sem.release(self._n)

    def __enter__(self) -> "SemaphoreGuard":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class Mutex:
    """Async mutex: ``async with mutex: ...``"""

    def __init__(self) -> None:
        self._sem = Semaphore(1)

    async def __aenter__(self) -> None:
        self._guard = await self._sem.acquire()

    async def __aexit__(self, *exc: Any) -> None:
        self._guard.release()

    async def lock(self) -> SemaphoreGuard:
        return await self._sem.acquire()


class RwLock:
    """Write-preferring async RwLock."""

    def __init__(self) -> None:
        self._readers = 0
        self._writer = False
        self._write_waiting = 0
        self._waiters: List[Future] = []

    def _wake_all(self) -> None:
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)

    async def read(self) -> "_RwReadGuard":
        while self._writer or self._write_waiting:
            fut: Future = Future()
            self._waiters.append(fut)
            await fut
        self._readers += 1
        return _RwReadGuard(self)

    async def write(self) -> "_RwWriteGuard":
        self._write_waiting += 1
        try:
            while self._writer or self._readers:
                fut: Future = Future()
                self._waiters.append(fut)
                await fut
        finally:
            self._write_waiting -= 1
        self._writer = True
        return _RwWriteGuard(self)


class _RwReadGuard:
    def __init__(self, lock: RwLock):
        self._lock = lock

    def release(self) -> None:
        if self._lock is not None:
            lock, self._lock = self._lock, None  # type: ignore[assignment]
            lock._readers -= 1
            if lock._readers == 0:
                lock._wake_all()

    def __enter__(self) -> "_RwReadGuard":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class _RwWriteGuard:
    def __init__(self, lock: RwLock):
        self._lock = lock

    def release(self) -> None:
        if self._lock is not None:
            lock, self._lock = self._lock, None  # type: ignore[assignment]
            lock._writer = False
            lock._wake_all()

    def __enter__(self) -> "_RwWriteGuard":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class Barrier:
    def __init__(self, n: int):
        if n < 1:
            raise ValueError("barrier size must be >= 1")
        self._n = n
        self._count = 0
        self._generation = 0
        self._waiters: List[Future] = []

    async def wait(self) -> bool:
        """Returns True for the leader (last arriver), tokio parity."""
        gen = self._generation
        self._count += 1
        if self._count == self._n:
            self._count = 0
            self._generation += 1
            waiters, self._waiters = self._waiters, []
            for fut in waiters:
                fut.set_result(None)
            return True
        while self._generation == gen:
            fut: Future = Future()
            self._waiters.append(fut)
            await fut
        return False
