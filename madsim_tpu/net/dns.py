"""Simulated DNS (ref madsim/src/sim/net/dns.rs:1-38).

A global name→IP map with ``localhost`` pre-seeded; string host resolution
(``lookup_host``) goes through this, mirroring the reference's hook into
``ToSocketAddrs`` (net/addr.rs:255-257).
"""

from __future__ import annotations

from typing import Dict, Optional


class DnsServer:
    def __init__(self) -> None:
        self._records: Dict[str, str] = {"localhost": "127.0.0.1"}

    def add(self, name: str, ip: str) -> None:
        self._records[name] = ip

    def remove(self, name: str) -> None:
        self._records.pop(name, None)

    def lookup(self, name: str) -> Optional[str]:
        return self._records.get(name)
