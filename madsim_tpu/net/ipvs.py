"""IP Virtual Server — simulated L4 load balancer
(ref madsim/src/sim/net/ipvs.rs:10-106).

Virtual services are keyed by ``ServiceAddr`` (protocol + "host:port"
string); each maps to a server list with a round-robin scheduler.  NetSim's
send/connect paths consult :meth:`get_server` to rewrite the destination
(ref net/mod.rs:312-317,345-350).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True)
class ServiceAddr:
    proto: str  # "tcp" | "udp"
    addr: str  # "host:port"

    @staticmethod
    def tcp(addr: str) -> "ServiceAddr":
        return ServiceAddr("tcp", addr)

    @staticmethod
    def udp(addr: str) -> "ServiceAddr":
        return ServiceAddr("udp", addr)


class _Service:
    def __init__(self, scheduler: str):
        self.scheduler = scheduler
        self.servers: List[str] = []
        self.rr_index = 0


class IpVirtualServer:
    def __init__(self) -> None:
        self._services: Dict[ServiceAddr, _Service] = {}

    def add_service(self, svc: ServiceAddr, scheduler: str = "rr") -> None:
        if scheduler not in ("rr",):
            raise ValueError(f"unknown scheduler: {scheduler}")
        self._services.setdefault(svc, _Service(scheduler))

    def del_service(self, svc: ServiceAddr) -> None:
        self._services.pop(svc, None)

    def add_server(self, svc: ServiceAddr, server: str) -> None:
        s = self._services.get(svc)
        if s is None:
            raise KeyError(f"no such service: {svc}")
        if server not in s.servers:
            s.servers.append(server)

    def del_server(self, svc: ServiceAddr, server: str) -> None:
        s = self._services.get(svc)
        if s is not None and server in s.servers:
            s.servers.remove(server)

    def get_server(self, svc: ServiceAddr) -> Optional[str]:
        """Round-robin pick (ref ipvs.rs RoundRobin scheduler)."""
        s = self._services.get(svc)
        if s is None or not s.servers:
            return None
        server = s.servers[s.rr_index % len(s.servers)]
        s.rr_index += 1
        return server

    def has_service(self, svc: ServiceAddr) -> bool:
        return svc in self._services
