"""Simulated Unix domain sockets — implemented, beating the reference's
stubs (madsim/src/sim/net/unix/{stream,datagram}.rs is all ``todo!()``).

Unix sockets are node-local IPC: paths live in a per-node namespace (like
the per-node fs), so two nodes can bind the same path and a connect never
crosses nodes. Streams reuse the reliable ``_Pipe`` machinery that backs
``connect1``/TCP — registered in NetSim's per-node pipe table, so a node
kill breaks live unix connections exactly like TCP ones — and datagrams
get a mailbox with the same rand-delay + latency timer delivery as UDP
(minus link faults: there is no link to clog inside one node).

Surface mirrors tokio's ``net::{UnixStream, UnixListener, UnixDatagram}``,
matching what the reference stubs declare.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, List, Optional, Tuple

from ..context import current_node
from ..futures import Future
from ..plugin import simulator
from ..task import NodeId
from .netsim import NetSim, PipeReceiver, PipeSender, _Pipe
from .tcp import TcpStream


def _netsim() -> NetSim:
    return simulator(NetSim)


def _here() -> NodeId:
    return current_node().id


class UnixStream(TcpStream):
    """Connected byte stream over a path (same read/write surface as the
    simulated TcpStream; addresses are paths)."""

    @staticmethod
    async def connect(path: str) -> "UnixStream":
        ns = _netsim()
        node = _here()
        await ns.rand_delay()
        listener = ns.unix_listeners.get((node, str(path)))
        if listener is None:
            raise ConnectionRefusedError(f"connection refused: {path!r}")
        c2s = _Pipe(ns, node, node)
        s2c = _Pipe(ns, node, node)
        ns._node_pipes.setdefault(node, []).extend((c2s, s2c))
        server_stream = UnixStream(
            PipeSender(s2c), PipeReceiver(c2s), str(path), ""
        )
        latency = ns.network.latency()
        ns.network.stat.msg_count += 1
        ns.time.add_timer(latency, lambda: listener._deliver(server_stream))
        return UnixStream(PipeSender(c2s), PipeReceiver(s2c), "", str(path))


class UnixListener:
    """Accepting socket bound to a node-local path."""

    def __init__(self, node: NodeId, path: str):
        self._node = node
        self._path = path
        self._pending: Deque[UnixStream] = deque()
        self._waiters: List[Future] = []
        self._closed = False
        self._broken = False

    @staticmethod
    async def bind(path: str) -> "UnixListener":
        ns = _netsim()
        node = _here()
        key = (node, str(path))
        if key in ns.unix_listeners or key in ns.unix_dgrams:
            raise OSError(f"address already in use: {path!r}")
        listener = UnixListener(node, str(path))
        ns.unix_listeners[key] = listener
        return listener

    def local_addr(self) -> str:
        return self._path

    def _deliver(self, stream: "UnixStream") -> None:
        if self._closed or self._broken:
            stream.close()
            return
        self._pending.append(stream)
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)

    async def accept(self) -> Tuple["UnixStream", str]:
        while not self._pending:
            if self._closed or self._broken:
                raise ConnectionAbortedError("listener closed")
            fut: Future = Future()
            self._waiters.append(fut)
            await fut
        stream = self._pending.popleft()
        return stream, stream.peer_addr()

    def close(self) -> None:
        self._closed = True
        ns = _netsim()
        if ns.unix_listeners.get((self._node, self._path)) is self:
            del ns.unix_listeners[(self._node, self._path)]
        self.break_all()

    def break_all(self) -> None:
        """Node reset: drop pending connections, wake blocked accepts."""
        self._broken = True
        while self._pending:
            self._pending.popleft().close()
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)

    def __enter__(self) -> "UnixListener":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class UnixDatagram:
    """Connectionless datagrams over node-local paths (lossless within a
    node; delivery still goes through the virtual-time timer so schedules
    stay randomized)."""

    def __init__(self, node: NodeId, path: Optional[str]):
        self._node = node
        self._path = path  # None = unbound (can send, cannot be addressed)
        self._mailbox: Deque[Tuple[bytes, str]] = deque()
        self._waiters: List[Future] = []
        self._peer: Optional[str] = None
        self._closed = False
        self._broken = False

    @staticmethod
    async def bind(path: str) -> "UnixDatagram":
        ns = _netsim()
        node = _here()
        key = (node, str(path))
        if key in ns.unix_dgrams or key in ns.unix_listeners:
            raise OSError(f"address already in use: {path!r}")
        sock = UnixDatagram(node, str(path))
        ns.unix_dgrams[key] = sock
        return sock

    @staticmethod
    def unbound() -> "UnixDatagram":
        return UnixDatagram(_here(), None)

    def local_addr(self) -> Optional[str]:
        return self._path

    def connect(self, path: str) -> None:
        """Set the default destination for ``send``/``recv``."""
        self._peer = str(path)

    async def send_to(self, data: bytes, path: str) -> int:
        ns = _netsim()
        if self._closed:
            raise OSError("socket closed")
        await ns.rand_delay()
        dst = ns.unix_dgrams.get((self._node, str(path)))
        if dst is None:
            # kernel semantics: unix datagrams to a missing path error out
            # (unlike lossy UDP)
            raise ConnectionRefusedError(f"no such socket: {path!r}")
        payload = (bytes(data), self._path or "")
        latency = ns.network.latency()
        ns.network.stat.msg_count += 1
        ns.time.add_timer(latency, lambda: dst._deliver(payload))
        return len(data)

    def _deliver(self, payload: Tuple[bytes, str]) -> None:
        if self._closed or self._broken:
            return
        self._mailbox.append(payload)
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)

    async def recv_from(self) -> Tuple[bytes, str]:
        while not self._mailbox:
            if self._closed or self._broken:
                raise ConnectionResetError("socket closed")
            fut: Future = Future()
            self._waiters.append(fut)
            await fut
        return self._mailbox.popleft()

    async def send(self, data: bytes) -> int:
        if self._peer is None:
            raise OSError("not connected")
        return await self.send_to(data, self._peer)

    async def recv(self) -> bytes:
        data, _src = await self.recv_from()
        return data

    def close(self) -> None:
        self._closed = True
        ns = _netsim()
        if self._path is not None and (
            ns.unix_dgrams.get((self._node, self._path)) is self
        ):
            del ns.unix_dgrams[(self._node, self._path)]
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            fut.set_result(None)

    def __enter__(self) -> "UnixDatagram":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
