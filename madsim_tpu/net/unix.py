"""Unix domain sockets — intentionally unimplemented, matching the
reference's stubs (madsim/src/sim/net/unix/{stream,datagram}.rs, all
methods ``todo!()``)."""

from __future__ import annotations

from typing import Any


class UnixStream:
    @staticmethod
    async def connect(path: str) -> "UnixStream":
        raise NotImplementedError("unix sockets are not simulated (ref parity)")


class UnixListener:
    @staticmethod
    async def bind(path: str) -> "UnixListener":
        raise NotImplementedError("unix sockets are not simulated (ref parity)")


class UnixDatagram:
    @staticmethod
    async def bind(path: str) -> "UnixDatagram":
        raise NotImplementedError("unix sockets are not simulated (ref parity)")

    @staticmethod
    def unbound() -> Any:
        raise NotImplementedError("unix sockets are not simulated (ref parity)")
