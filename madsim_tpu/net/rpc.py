"""Built-in tag-matching RPC (ref madsim/src/sim/net/rpc.rs:73-167) and the
``@service`` class decorator (ref madsim-macros ``#[madsim::service]``,
madsim-macros/src/service.rs:60-109).

A *request type* carries a stable 64-bit ID derived from its qualified name
(the analogue of ``#[derive(Request)]``'s const ``hash_str(module_path +
name)``, madsim-macros/src/request.rs:60-66 + rpc.rs:82-92).  ``call`` sends
``(rsp_tag=random u64, req, data)`` on ``tag=ID`` and awaits ``rsp_tag``
(rpc.rs:108-131); ``add_rpc_handler`` spawns an accept loop plus one task
per request (rpc.rs:134-166).
"""

from __future__ import annotations

import hashlib
from typing import Any, Awaitable, Callable, Optional, Tuple, TYPE_CHECKING

from ..context import current_handle
from ..task import spawn
from ..time import timeout as _timeout

if TYPE_CHECKING:
    from .endpoint import Endpoint
    from .network import Addr


def hash_str(s: str) -> int:
    """Stable 64-bit id from a string (ref const ``hash_str``, rpc.rs:82-92)."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "little")


_REQUEST_TYPES: dict = {}


def request_types() -> dict:
    """Live registry of every defined Request subclass, keyed by qualified
    name — the set of user types the real-mode codec may materialize
    (real/codec.py). Never triggers an import."""
    return _REQUEST_TYPES


class Request:
    """Base class for RPC request types (``#[derive(Request)]`` analogue).

    Subclassing assigns a stable ``RPC_ID`` from the qualified class name
    and registers the type for the real-mode wire codec.
    Set class attr ``Response`` for documentation purposes (untyped here).
    """

    RPC_ID: int = 0
    Response: type = object

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        cls.RPC_ID = hash_str(f"{cls.__module__}::{cls.__qualname__}")
        _REQUEST_TYPES[f"{cls.__module__}::{cls.__qualname__}"] = cls


def request_id(req: Any) -> int:
    rid = getattr(type(req), "RPC_ID", None) or getattr(req, "RPC_ID", None)
    if not rid:
        raise TypeError(
            f"{type(req).__name__} is not a Request (subclass "
            f"madsim_tpu.net.rpc.Request or define RPC_ID)"
        )
    return rid


# -- client side (rpc.rs:108-131) ------------------------------------------


async def call_with_data(
    ep: "Endpoint", dst: "str | Addr", req: Any, data: bytes
) -> Tuple[Any, bytes]:
    rsp_tag = current_handle().rng.next_u64()
    await ep.send_to_raw(
        dst, request_id(req), (rsp_tag, req, data), kind="rpc_req"
    )
    payload, _src = await ep.recv_from_raw(rsp_tag)
    rsp, rsp_data = payload
    return rsp, rsp_data


async def call(ep: "Endpoint", dst: "str | Addr", req: Any) -> Any:
    rsp, _data = await call_with_data(ep, dst, req, b"")
    return rsp


async def call_timeout(
    ep: "Endpoint", dst: "str | Addr", req: Any, timeout_s: float
) -> Any:
    return await _timeout(timeout_s, call(ep, dst, req))


# -- server side (rpc.rs:134-166) ------------------------------------------


def add_rpc_handler_with_data(
    ep: "Endpoint",
    req_type: type,
    handler: Callable[[Any, bytes], Awaitable[Tuple[Any, bytes]]],
) -> None:
    rid = request_id(req_type)

    async def accept_loop() -> None:
        while True:
            payload, src = await ep.recv_from_raw(rid)
            rsp_tag, req, data = payload

            async def handle_one(
                rsp_tag: int = rsp_tag, req: Any = req,
                data: bytes = data, src: "Addr" = src,
            ) -> None:
                rsp, rsp_data = await handler(req, data)
                await ep.send_to_raw(src, rsp_tag, (rsp, rsp_data), kind="rpc_rsp")

            spawn(handle_one(), name=f"rpc-{req_type.__name__}")

    spawn(accept_loop(), name=f"rpc-loop-{req_type.__name__}")


def add_rpc_handler(
    ep: "Endpoint", req_type: type, handler: Callable[[Any], Awaitable[Any]]
) -> None:
    async def with_data(req: Any, _data: bytes) -> Tuple[Any, bytes]:
        return await handler(req), b""

    add_rpc_handler_with_data(ep, req_type, with_data)


# -- @service / @rpc decorators (#[madsim::service] analogue) --------------


def rpc_method(req_type: type) -> Callable:
    """Mark a method as the handler for ``req_type``
    (ref ``#[rpc]``, madsim-macros/src/service.rs)."""

    def deco(method: Callable) -> Callable:
        method._rpc_request_type = req_type  # type: ignore[attr-defined]
        return method

    return deco


#: alias matching the reference's ``#[rpc]`` attribute name; import it from
#: ``madsim_tpu.net.rpc`` (the package re-exports ``rpc_method`` to avoid
#: shadowing this module's name)
rpc = rpc_method


def service(cls: type) -> type:
    """Add ``serve(endpoint)`` registering every ``@rpc`` method
    (ref generated ``serve``/``serve_on``, service.rs:60-109)."""

    handlers = [
        (name, m._rpc_request_type)
        for name, m in vars(cls).items()
        if callable(m) and hasattr(m, "_rpc_request_type")
    ]

    def serve(self: Any, ep: "Endpoint") -> None:
        for name, req_type in handlers:
            bound = getattr(self, name)

            async def h(req: Any, _bound: Callable = bound) -> Any:
                return await _bound(req)

            add_rpc_handler(ep, req_type, h)

    cls.serve = serve  # type: ignore[attr-defined]
    return cls
