"""Simulated UDP socket — thin wrapper over Endpoint tag 0
(ref madsim/src/sim/net/udp.rs:10-73)."""

from __future__ import annotations

from typing import Tuple

from .endpoint import Endpoint
from .network import Addr

_UDP_TAG = 0


class UdpSocket:
    def __init__(self, ep: Endpoint):
        self._ep = ep

    @staticmethod
    async def bind(addr: "str | Addr") -> "UdpSocket":
        return UdpSocket(await Endpoint.bind(addr))

    async def connect(self, addr: "str | Addr") -> None:
        self._ep._peer = self._ep._netsim.resolve_host(addr)

    def local_addr(self) -> Addr:
        return self._ep.local_addr()

    def peer_addr(self) -> Addr:
        return self._ep.peer_addr()

    async def send_to(self, data: bytes, addr: "str | Addr") -> int:
        await self._ep.send_to(addr, _UDP_TAG, data)
        return len(data)

    async def recv_from(self) -> Tuple[bytes, Addr]:
        return await self._ep.recv_from(_UDP_TAG)

    async def send(self, data: bytes) -> int:
        return await self.send_to(data, self._ep.peer_addr())

    async def recv(self) -> bytes:
        data, _ = await self.recv_from()
        return data

    def close(self) -> None:
        self._ep.close()
