"""Simulated TCP (ref madsim/src/sim/net/tcp/{mod,listener,stream}.rs).

``TcpListener::bind/accept`` over an Endpoint accept queue
(listener.rs:35-64); ``TcpStream`` buffers writes locally and ``flush``
sends one message; reads pull from the reliable channel; EOF = channel
closed (stream.rs:133-186).  Streams survive link clogs via the channel's
backoff-retry (netsim.PipeReceiver).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from .endpoint import Endpoint
from .netsim import PipeReceiver, PipeSender
from .network import Addr


class TcpStream:
    def __init__(
        self,
        sender: PipeSender,
        receiver: PipeReceiver,
        local: Addr,
        peer: Addr,
        ep: Optional[Endpoint] = None,
    ):
        self._sender = sender
        self._receiver = receiver
        self._local = local
        self._peer = peer
        self._ep = ep  # keeps the client's ephemeral port alive
        self._wbuf = bytearray()
        self._rbuf = bytearray()
        self._eof = False

    @staticmethod
    async def connect(addr: "str | Addr") -> "TcpStream":
        """ref stream.rs:37-60."""
        ep = await Endpoint.connect(addr)
        sender, receiver = await ep.connect1(addr)
        return TcpStream(sender, receiver, ep.local_addr(), ep.peer_addr(), ep)

    def local_addr(self) -> Addr:
        return self._local

    def peer_addr(self) -> Addr:
        return self._peer

    # -- write side (buffer until flush, stream.rs:133-162) ----------------

    def write(self, data: bytes) -> int:
        self._wbuf += data
        return len(data)

    async def write_all(self, data: bytes) -> None:
        self.write(data)

    async def flush(self) -> None:
        if self._wbuf:
            buf, self._wbuf = bytes(self._wbuf), bytearray()
            await self._sender.send(buf)

    async def write_all_flush(self, data: bytes) -> None:
        self.write(data)
        await self.flush()

    # -- read side (stream.rs:164-186) -------------------------------------

    async def read(self, n: int) -> bytes:
        if not self._rbuf and not self._eof:
            msg = await self._receiver.recv()
            if msg is None:
                self._eof = True
            else:
                self._rbuf += msg
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    async def read_exact(self, n: int) -> bytes:
        while len(self._rbuf) < n and not self._eof:
            msg = await self._receiver.recv()
            if msg is None:
                self._eof = True
                break
            self._rbuf += msg
        if len(self._rbuf) < n:
            raise EOFError(
                f"connection closed with {len(self._rbuf)}/{n} bytes buffered"
            )
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    def shutdown(self) -> None:
        """Half-close the write side (EOF at the peer)."""
        self._sender.close()

    def close(self) -> None:
        self._sender.close()
        self._receiver.close()
        if self._ep is not None:
            self._ep.close()


class TcpListener:
    """ref listener.rs:35-64."""

    def __init__(self, ep: Endpoint):
        self._ep = ep

    @staticmethod
    async def bind(addr: "str | Addr") -> "TcpListener":
        return TcpListener(await Endpoint.bind(addr))

    def local_addr(self) -> Addr:
        return self._ep.local_addr()

    async def accept(self) -> Tuple[TcpStream, Addr]:
        sender, receiver, peer = await self._ep.accept1()
        return TcpStream(sender, receiver, self._ep.local_addr(), peer), peer

    def close(self) -> None:
        self._ep.close()

    def __enter__(self) -> "TcpListener":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
