"""Simulated network stack (ref madsim/src/sim/net/).

Layering (bottom-up): ``network`` (pure link-state model) → ``netsim``
(plugin: fault API + timer-scheduled delivery + reliable channels) →
``endpoint`` (tag-matching messaging) → ``rpc``/``tcp``/``udp`` protocol
shims, with ``dns``/``ipvs`` as auxiliary services.
"""

from .dns import DnsServer
from .endpoint import BindGuard, Endpoint, Mailbox, lookup_host
from .ipvs import IpVirtualServer, ServiceAddr
from .netsim import NetSim, PipeReceiver, PipeSender
from .network import Addr, Network, Stat, format_addr, parse_addr
from .rpc import Request, hash_str, rpc_method, service
from .tcp import TcpListener, TcpStream
from .udp import UdpSocket
from .unix import UnixDatagram, UnixListener, UnixStream

__all__ = [
    "Addr",
    "BindGuard",
    "DnsServer",
    "Endpoint",
    "IpVirtualServer",
    "Mailbox",
    "NetSim",
    "Network",
    "PipeReceiver",
    "PipeSender",
    "Request",
    "ServiceAddr",
    "Stat",
    "TcpListener",
    "TcpStream",
    "UdpSocket",
    "UnixDatagram",
    "UnixListener",
    "UnixStream",
    "format_addr",
    "hash_str",
    "lookup_host",
    "parse_addr",
    "rpc_method",
    "service",
]
