"""Pure link-state network model (ref madsim/src/sim/net/network.rs:20-314).

Owns: node↔IP maps (one IP per node, network.rs:149-160), the socket table
keyed ``(node, ip, port, proto)``, clogged node in/out sets + clogged link
set (network.rs:27-29,162-203), loss/latency draws (``test_link``,
network.rs:261-269), destination resolution incl. 0.0.0.0 wildcard and
loopback (network.rs:272-313), and ephemeral port allocation
(network.rs:226-235).

No timers here: the model only *decides* (drop? latency?); scheduling the
delivery is NetSim's job, which is exactly the split that lets the TPU
engine lift this table as struct-of-arrays state (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Dict, Optional, Protocol, Set, Tuple

from ..config import Config
from ..rand import GlobalRng
from ..task import NodeId

Addr = Tuple[str, int]  # (ip, port)

UDP = "udp"
TCP = "tcp"


def format_addr(addr: Addr) -> str:
    return f"{addr[0]}:{addr[1]}"


def parse_addr(addr: "str | Addr") -> Addr:
    if isinstance(addr, tuple):
        return (str(addr[0]), int(addr[1]))
    host, _, port = addr.rpartition(":")
    return (host, int(port))


def is_loopback(ip: str) -> bool:
    return ip.startswith("127.") or ip == "localhost" or ip == "::1"


class Socket(Protocol):
    """ref ``Socket`` trait (network.rs:50-60)."""

    def deliver(self, src: Addr, dst: Addr, msg: object) -> None: ...


class Stat:
    """ref ``Stat`` (network.rs:99-105)."""

    def __init__(self) -> None:
        self.msg_count = 0


class Network:
    def __init__(self, rng: GlobalRng, config: Config, now_ns=None):
        self.rng = rng
        self.config = config
        self.stat = Stat()
        self.node_ip: Dict[NodeId, str] = {}
        self.ip_node: Dict[str, NodeId] = {}
        # socket table: per-node {(ip, port, proto): Socket}
        self.sockets: Dict[NodeId, Dict[Tuple[str, int, str], Socket]] = {}
        self.clogged_node_in: Set[NodeId] = set()
        self.clogged_node_out: Set[NodeId] = set()
        self.clogged_links: Set[Tuple[NodeId, NodeId]] = set()
        self._next_ephemeral: Dict[NodeId, int] = {}

    # -- topology ----------------------------------------------------------

    def insert_node(self, id: NodeId) -> None:
        self.sockets.setdefault(id, {})
        if id not in self.node_ip:
            # auto-assign a unique IP; NodeBuilder.ip() overrides.  Skip
            # addresses the user already claimed.
            n = int(id)
            while True:
                ip = f"10.{200 + (n >> 16)}.{(n >> 8) & 0xFF}.{n & 0xFF}"
                if ip not in self.ip_node:
                    break
                n += 1
            self.set_ip(id, ip)

    def set_ip(self, id: NodeId, ip: str) -> None:
        old = self.node_ip.get(id)
        if old is not None and self.ip_node.get(old) == id:
            del self.ip_node[old]
        if ip in self.ip_node and self.ip_node[ip] != id:
            raise ValueError(f"IP {ip} is already assigned to node {self.ip_node[ip]}")
        self.node_ip[id] = ip
        self.ip_node[ip] = id

    def get_ip(self, id: NodeId) -> Optional[str]:
        return self.node_ip.get(id)

    def reset_node(self, id: NodeId) -> None:
        """Close all sockets on the node (ref network.rs:142-147)."""
        self.sockets[id] = {}

    # -- fault injection (network.rs:162-203) ------------------------------

    def clog_node_in(self, id: NodeId) -> None:
        self.clogged_node_in.add(id)

    def clog_node_out(self, id: NodeId) -> None:
        self.clogged_node_out.add(id)

    def unclog_node_in(self, id: NodeId) -> None:
        self.clogged_node_in.discard(id)

    def unclog_node_out(self, id: NodeId) -> None:
        self.clogged_node_out.discard(id)

    def clog_link(self, src: NodeId, dst: NodeId) -> None:
        self.clogged_links.add((src, dst))

    def unclog_link(self, src: NodeId, dst: NodeId) -> None:
        self.clogged_links.discard((src, dst))

    def is_clogged(self, src: NodeId, dst: NodeId) -> bool:
        return (
            src in self.clogged_node_out
            or dst in self.clogged_node_in
            or (src, dst) in self.clogged_links
        )

    def test_link(self, src: NodeId, dst: NodeId) -> Optional[float]:
        """None if clogged or lost, else a latency draw in seconds
        (ref network.rs:261-269)."""
        if self.is_clogged(src, dst):
            return None
        if self.rng.random() < self.config.net.packet_loss_rate:
            return None
        lo, hi = self.config.net.send_latency
        return self.rng.uniform(lo, hi)

    def latency(self) -> float:
        lo, hi = self.config.net.send_latency
        return self.rng.uniform(lo, hi)

    # -- sockets -----------------------------------------------------------

    def bind(
        self, node: NodeId, addr: Addr, proto: str, socket: Socket
    ) -> Addr:
        """Bind a socket; port 0 allocates an ephemeral port
        (ref network.rs:226-235)."""
        table = self.sockets.setdefault(node, {})
        ip, port = addr
        if port == 0:
            port = self._alloc_port(node, ip, proto)
        key = (ip, port, proto)
        if key in table:
            raise OSError(f"address already in use: {ip}:{port}/{proto}")
        table[key] = socket
        return (ip, port)

    def _alloc_port(self, node: NodeId, ip: str, proto: str) -> int:
        table = self.sockets.get(node, {})
        port = self._next_ephemeral.get(node, 32768)
        for _ in range(65536):
            if port > 65535:
                port = 32768
            if (ip, port, proto) not in table:
                self._next_ephemeral[node] = port + 1
                return port
            port += 1
        raise OSError("out of ephemeral ports")

    def close_socket(self, node: NodeId, addr: Addr, proto: str) -> None:
        table = self.sockets.get(node)
        if table is not None:
            table.pop((addr[0], addr[1], proto), None)

    def resolve_dest_node(self, src: NodeId, dst_ip: str) -> Optional[NodeId]:
        """ref network.rs:272-290 — loopback resolves to the sender node."""
        if is_loopback(dst_ip):
            return src
        if dst_ip == self.node_ip.get(src):
            return src
        return self.ip_node.get(dst_ip)

    def find_socket(
        self, node: NodeId, dst: Addr, proto: str
    ) -> Optional[Socket]:
        """Exact match, else 0.0.0.0 wildcard (ref network.rs:296-313)."""
        table = self.sockets.get(node)
        if table is None:
            return None
        sock = table.get((dst[0], dst[1], proto))
        if sock is None:
            sock = table.get(("0.0.0.0", dst[1], proto))
        return sock

    def try_send(
        self, src: NodeId, dst: Addr, proto: str
    ) -> Optional[Tuple[NodeId, Socket, float]]:
        """Resolve destination + link test; returns (dst_node, socket,
        latency_s) or None when dropped/unroutable (ref network.rs:296-313)."""
        dst_node = self.resolve_dest_node(src, dst[0])
        if dst_node is None:
            return None
        if dst_node == src:
            latency: Optional[float] = self.latency()  # loopback never drops
        else:
            latency = self.test_link(src, dst_node)
        if latency is None:
            return None
        socket = self.find_socket(dst_node, dst, proto)
        if socket is None:
            return None
        self.stat.msg_count += 1
        return (dst_node, socket, latency)
