"""NetSim plugin: fault-injection API + message scheduling + reliable
connection channels (ref madsim/src/sim/net/mod.rs:82-494).

Per-message path (ref net/mod.rs:287-333): random processing delay 0-5 µs
(buggified to 1-5 s at 10%), RPC drop hooks, IPVS destination rewrite, then
``Network.try_send`` decides drop/latency and the delivery is scheduled as a
virtual-time timer — the node boundary is crossed *only* via timers, which
is the invariant the TPU engine batches.

``connect1`` (ref net/mod.rs:337-405) creates a reliable duplex channel pair
whose receiver re-tests the link per message with exponential backoff
1 ms → 10 s while clogged — TCP-like semantics (no loss, blocked by
partitions, broken by node kill).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..config import Config
from ..futures import Future
from ..plugin import Simulator
from ..rand import GlobalRng
from ..task import NodeId
from ..time import Sleep, TimeHandle, _new_sleep
from .dns import DnsServer
from .ipvs import IpVirtualServer, ServiceAddr
from .network import TCP, UDP, Addr, Network, Socket, Stat, parse_addr

Hook = Callable[[NodeId, Addr, int, Any], bool]  # -> True = drop


class NetSim(Simulator):
    """The network simulator plugin (ref ``NetSim``, net/mod.rs:82-161)."""

    def __init__(self, rng: GlobalRng, time: TimeHandle, config: Config):
        super().__init__(rng, time, config)
        self.network = Network(rng, config)
        self.dns = DnsServer()
        self.ipvs = IpVirtualServer()
        self._rpc_req_hooks: List[Hook] = []
        self._rpc_rsp_hooks: List[Hook] = []
        self._node_pipes: Dict[NodeId, List["_Pipe"]] = {}
        # per-node Unix-domain namespaces (net/unix.py): (node, path) ->
        # listener accept queue / datagram socket. Paths are node-local
        # like the per-node fs, so entries die with the node.
        self.unix_listeners: Dict[Tuple[NodeId, str], Any] = {}
        self.unix_dgrams: Dict[Tuple[NodeId, str], Any] = {}

    # -- plugin lifecycle --------------------------------------------------

    def create_node(self, id: NodeId) -> None:
        self.network.insert_node(id)
        self._node_pipes.setdefault(id, [])

    def reset_node(self, id: NodeId) -> None:
        """Close sockets and break live connections
        (ref net/mod.rs:146-149)."""
        self.network.reset_node(id)
        pipes = self._node_pipes.get(id, [])
        self._node_pipes[id] = []
        for pipe in pipes:
            pipe.break_pipe()
        # unix namespaces are node-local state: drop them with the node
        for key in [k for k in self.unix_listeners if k[0] == id]:
            self.unix_listeners.pop(key).break_all()
        for key in [k for k in self.unix_dgrams if k[0] == id]:
            self.unix_dgrams.pop(key)._broken = True

    # -- config / topology -------------------------------------------------

    def update_config(self, config: Config) -> None:
        """ref net/mod.rs:137-141."""
        self.config = config
        self.network.config = config

    def set_ip(self, id: NodeId, ip: str) -> None:
        self.network.set_ip(id, ip)

    def get_ip(self, id: NodeId) -> Optional[str]:
        return self.network.get_ip(id)

    def add_dns_record(self, name: str, ip: str) -> None:
        self.dns.add(name, ip)

    def global_ipvs(self) -> IpVirtualServer:
        return self.ipvs

    def stat(self) -> Stat:
        return self.network.stat

    # -- fault injection (ref net/mod.rs:163-284) --------------------------

    def clog_node(self, id: NodeId) -> None:
        self.network.clog_node_in(id)
        self.network.clog_node_out(id)

    def unclog_node(self, id: NodeId) -> None:
        self.network.unclog_node_in(id)
        self.network.unclog_node_out(id)

    def clog_node_in(self, id: NodeId) -> None:
        self.network.clog_node_in(id)

    def clog_node_out(self, id: NodeId) -> None:
        self.network.clog_node_out(id)

    def unclog_node_in(self, id: NodeId) -> None:
        self.network.unclog_node_in(id)

    def unclog_node_out(self, id: NodeId) -> None:
        self.network.unclog_node_out(id)

    def clog_link(self, src: NodeId, dst: NodeId) -> None:
        self.network.clog_link(src, dst)

    def unclog_link(self, src: NodeId, dst: NodeId) -> None:
        self.network.unclog_link(src, dst)

    def hook_rpc_req(self, hook: Hook) -> None:
        """Register a request drop hook (ref net/mod.rs:240-284)."""
        self._rpc_req_hooks.append(hook)

    def hook_rpc_rsp(self, hook: Hook) -> None:
        self._rpc_rsp_hooks.append(hook)

    # -- helpers -----------------------------------------------------------

    def _sleep_ns(self, ns: int) -> Sleep:
        """Raw virtual sleep without the 1 ms tokio minimum."""
        return _new_sleep(self.time, self.time.now_ns + max(0, int(ns)))

    def rand_delay(self) -> Sleep:
        """0-5 µs processing delay; buggified to 1-5 s at 10%
        (ref net/mod.rs:287-295).

        Plain function returning the awaitable Sleep (``await
        ns.rand_delay()`` reads the same): an ``async def`` here costs a
        generator frame + an extra send() dispatch on EVERY message hop
        (twice per delivered message — it's the hottest helper in the
        host-tier profile). Draw order is unchanged: the draws run at
        call time, which under the single-threaded executor is the same
        poll in which the returned Sleep is first awaited."""
        if self.rng.buggify_with_prob(0.1):
            delay_ns = self.rng.gen_range(1_000_000_000, 5_000_000_001)
        else:
            delay_ns = self.rng.gen_range(0, 5_001)
        return self._sleep_ns(delay_ns)

    def resolve_host(self, addr: "str | Addr") -> Addr:
        """DNS-resolve a "host:port" string (ref addr.rs:255-257)."""
        ip, port = parse_addr(addr)
        if ip and not ip[0].isdigit() and ip != "localhost":
            resolved = self.dns.lookup(ip)
            if resolved is None:
                raise OSError(f"failed to lookup address information: {ip}")
            ip = resolved
        elif ip == "localhost":
            ip = "127.0.0.1"
        return (ip, port)

    def _ipvs_rewrite(self, dst: Addr, proto: str) -> Addr:
        svc = ServiceAddr(proto, f"{dst[0]}:{dst[1]}")
        if self.ipvs.has_service(svc):
            server = self.ipvs.get_server(svc)
            if server is None:
                raise ConnectionRefusedError(
                    f"virtual service {svc} has no backend servers"
                )
            return parse_addr(server)
        return dst

    # -- datagram send (ref ``NetSim::send``, net/mod.rs:298-333) ----------

    def _normalize_src(self, src_node: NodeId, src_addr: Addr) -> Addr:
        """Rewrite wildcard source IPs to the node's real IP so replies to
        the reported peer address route back (ref network.rs try_send)."""
        if src_addr[0] in ("0.0.0.0", "::", ""):
            ip = self.network.node_ip.get(src_node)
            if ip is not None:
                return (ip, src_addr[1])
        return src_addr

    async def send_raw(
        self,
        src_node: NodeId,
        src_addr: Addr,
        dst_addr: Addr,
        tag: int,
        payload: Any,
        kind: Optional[str] = None,
    ) -> None:
        src_addr = self._normalize_src(src_node, src_addr)
        await self.rand_delay()
        hooks = (
            self._rpc_req_hooks
            if kind == "rpc_req"
            else self._rpc_rsp_hooks if kind == "rpc_rsp" else []
        )
        for hook in hooks:
            if hook(src_node, dst_addr, tag, payload):
                return  # dropped by hook
        dst_addr = self._ipvs_rewrite(dst_addr, UDP)
        res = self.network.try_send(src_node, dst_addr, UDP)
        if res is None:
            return  # dropped: clog/loss/no socket — datagrams are lossy
        _dst_node, socket, latency = res
        self.time.add_timer(
            latency, lambda: socket.deliver(src_addr, dst_addr, (tag, payload))
        )

    # -- reliable connections (ref net/mod.rs:337-405) ---------------------

    async def connect1(
        self, src_node: NodeId, src_addr: Addr, dst_addr: "str | Addr"
    ) -> Tuple["PipeSender", "PipeReceiver"]:
        """Open a reliable duplex connection to an accepting socket;
        returns the client's (sender, receiver) half."""
        src_addr = self._normalize_src(src_node, src_addr)
        await self.rand_delay()
        dst = self.resolve_host(dst_addr)
        dst = self._ipvs_rewrite(dst, TCP)
        backoff_s = 0.001
        while True:
            dst_node = self.network.resolve_dest_node(src_node, dst[0])
            if dst_node is None:
                raise ConnectionRefusedError(f"no route to host {dst[0]}")
            if not self.network.is_clogged(src_node, dst_node):
                break
            await self._sleep_ns(int(backoff_s * 1e9))
            backoff_s = min(backoff_s * 2, 10.0)
        socket = self.network.find_socket(dst_node, dst, UDP)
        accept_conn = getattr(socket, "accept_connection", None)
        if accept_conn is None:
            raise ConnectionRefusedError(f"connection refused: {dst[0]}:{dst[1]}")

        c2s = _Pipe(self, src_node, dst_node)
        s2c = _Pipe(self, dst_node, src_node)
        self._node_pipes.setdefault(src_node, []).append(c2s)
        self._node_pipes.setdefault(src_node, []).append(s2c)
        self._node_pipes.setdefault(dst_node, []).append(c2s)
        self._node_pipes.setdefault(dst_node, []).append(s2c)
        server_half = (PipeSender(s2c), PipeReceiver(c2s))
        latency = self.network.latency()
        self.network.stat.msg_count += 1
        self.time.add_timer(
            latency, lambda: accept_conn(src_addr, dst, server_half)
        )
        return (PipeSender(c2s), PipeReceiver(s2c))


class _Pipe:
    """One direction of a reliable connection."""

    __slots__ = ("netsim", "src_node", "dst_node", "queue", "closed", "broken",
                 "waiters")

    def __init__(self, netsim: NetSim, src_node: NodeId, dst_node: NodeId):
        self.netsim = netsim
        self.src_node = src_node
        self.dst_node = dst_node
        self.queue: Deque[Any] = deque()
        self.closed = False  # clean EOF from sender
        self.broken = False  # node killed / reset
        self.waiters: List[Future] = []

    def _wake(self) -> None:
        waiters, self.waiters = self.waiters, []
        for fut in waiters:
            fut.set_result(None)

    def _unregister(self) -> None:
        """Drop this pipe from the per-node registries so finished
        connections don't accumulate for the life of the simulation."""
        for nid in (self.src_node, self.dst_node):
            lst = self.netsim._node_pipes.get(nid)
            if lst is not None:
                try:
                    lst.remove(self)
                except ValueError:
                    pass

    def push(self, msg: Any) -> None:
        if self.closed or self.broken:
            raise BrokenPipeError("connection closed")
        self.queue.append(msg)
        self.netsim.network.stat.msg_count += 1
        self._wake()

    def close(self) -> None:
        self.closed = True
        self._wake()
        if not self.queue:
            self._unregister()

    def break_pipe(self) -> None:
        self.broken = True
        self.queue.clear()
        self._wake()
        self._unregister()


class PipeSender:
    """ref ``Sender`` (net/endpoint.rs connection half)."""

    def __init__(self, pipe: _Pipe):
        self._pipe = pipe

    async def send(self, msg: Any) -> None:
        self._pipe.push(msg)

    def close(self) -> None:
        self._pipe.close()

    def is_closed(self) -> bool:
        return self._pipe.closed or self._pipe.broken


class PipeReceiver:
    """Receiver half; re-tests the link per message with exponential
    backoff while clogged (ref net/mod.rs:366-405)."""

    def __init__(self, pipe: _Pipe):
        self._pipe = pipe

    async def recv(self) -> Optional[Any]:
        """Next message; None on clean EOF; ConnectionResetError if the
        peer node was killed."""
        pipe = self._pipe
        netsim = pipe.netsim
        while True:
            if pipe.broken:
                raise ConnectionResetError("connection reset by peer")
            if pipe.queue:
                break
            if pipe.closed:
                pipe._unregister()
                return None
            fut: Future = Future()
            pipe.waiters.append(fut)
            await fut
        # link re-test with exponential backoff 1 ms -> 10 s while clogged
        backoff_s = 0.001
        while netsim.network.is_clogged(pipe.src_node, pipe.dst_node):
            await netsim._sleep_ns(int(backoff_s * 1e9))
            backoff_s = min(backoff_s * 2, 10.0)
            if pipe.broken:
                raise ConnectionResetError("connection reset by peer")
        await netsim._sleep_ns(int(netsim.network.latency() * 1e9))
        if pipe.broken:
            raise ConnectionResetError("connection reset by peer")
        if not pipe.queue:
            return None if pipe.closed else await self.recv()
        return pipe.queue.popleft()

    def close(self) -> None:
        self._pipe.close()
