"""Endpoint: tag-matching messaging socket
(ref madsim/src/sim/net/endpoint.rs:13-363).

An Endpoint is the universal simulated socket: a mailbox of ``tag ->
messages`` with registered-recv oneshots + undelivered queues
(endpoint.rs:297-363), a bytes API (``send_to``/``recv_from``) plus a raw
payload API (``*_raw``, the Box<dyn Any> analogue) used by the other
simulators, and connection-oriented ``connect1``/``accept1`` built on
NetSim's reliable channels.  Built-in RPC lives in ``net.rpc`` and is
exposed as Endpoint methods (``call``/``add_rpc_handler``/...).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..context import current_node, current_handle
from ..futures import Future
from ..plugin import simulator
from ..task import NodeId
from .netsim import NetSim, PipeReceiver, PipeSender
from .network import UDP, Addr, parse_addr


class Mailbox:
    """tag -> (pending recv oneshots, undelivered messages)
    (ref ``Mailbox``, endpoint.rs:297-363)."""

    def __init__(self) -> None:
        self.registered: Dict[int, List[Future]] = {}
        self.undelivered: Dict[int, Deque[Tuple[Any, Addr]]] = {}

    def deliver(self, tag: int, payload: Any, src: Addr) -> None:
        waiters = self.registered.get(tag)
        while waiters:
            fut = waiters.pop(0)
            if not fut.done():
                fut.set_result((payload, src))
                return
        self.undelivered.setdefault(tag, deque()).append((payload, src))

    def recv(self, tag: int) -> "Future":
        fut: Future = Future()
        queue = self.undelivered.get(tag)
        if queue:
            payload, src = queue.popleft()
            if not queue:
                del self.undelivered[tag]
            fut.set_result((payload, src))
        else:
            self.registered.setdefault(tag, []).append(fut)
        return fut

    def drop_recv(self, tag: int, fut: "Future") -> None:
        """A receiver was dropped (timeout/kill) before consuming: remove
        its registration; if a message already resolved into the dead
        oneshot, hand it to the next live waiter, else put it back at the
        FRONT of the undelivered queue (it arrived earliest). The ref's
        analogue is Mailbox oneshot-drop semantics (endpoint.rs:297-363:
        a dropped oneshot's send fails and the message is buffered) — a
        dropped recv never swallows a message."""
        waiters = self.registered.get(tag)
        if waiters is not None and fut in waiters:
            waiters.remove(fut)
            if not waiters:
                del self.registered[tag]
            return
        if fut.done() and fut.exception() is None:
            payload, src = fut.result()
            while waiters:
                w = waiters.pop(0)
                if not w.done():
                    w.set_result((payload, src))
                    return
            self.undelivered.setdefault(tag, deque()).appendleft((payload, src))


class BindGuard:
    """RAII-ish port release (ref ``BindGuard``, net/mod.rs:436-494):
    explicit ``release`` or node reset frees the port; release is skipped
    when the node has been killed (its socket table was already reset)."""

    def __init__(self, netsim: NetSim, node: NodeId, addr: Addr, proto: str):
        self.netsim = netsim
        self.node = node
        self.addr = addr
        self.proto = proto
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self.netsim.network.close_socket(self.node, self.addr, self.proto)


class _EndpointSocket:
    """The Socket registered in the network table; delivers datagrams into
    the mailbox and connections into the accept queue
    (ref ``EndpointSocket::deliver``, endpoint.rs:311-351)."""

    def __init__(self) -> None:
        self.mailbox = Mailbox()
        self.accept_queue: Deque[Tuple[Addr, Tuple[PipeSender, PipeReceiver]]] = (
            deque()
        )
        self.accept_waiters: List[Future] = []

    def deliver(self, src: Addr, dst: Addr, msg: Any) -> None:
        tag, payload = msg
        self.mailbox.deliver(tag, payload, src)

    def accept_connection(
        self, src: Addr, dst: Addr, half: Tuple[PipeSender, PipeReceiver]
    ) -> None:
        while self.accept_waiters:
            fut = self.accept_waiters.pop(0)
            if not fut.done():
                fut.set_result((src, half))
                return
        self.accept_queue.append((src, half))


class Endpoint:
    """ref ``Endpoint`` (endpoint.rs:13-295)."""

    def __init__(
        self, netsim: NetSim, node: NodeId, addr: Addr, socket: _EndpointSocket
    ):
        self._netsim = netsim
        self.node = node
        self.addr = addr
        self._socket = socket
        self._guard = BindGuard(netsim, node, addr, UDP)
        self._peer: Optional[Addr] = None

    # -- construction ------------------------------------------------------

    @staticmethod
    async def bind(addr: "str | Addr") -> "Endpoint":
        """Bind on the current node; port 0 = ephemeral
        (ref endpoint.rs:29-42)."""
        netsim = simulator(NetSim)
        node = current_node().id
        ip, port = parse_addr(addr)
        if ip == "localhost":
            ip = "127.0.0.1"
        socket = _EndpointSocket()
        bound = netsim.network.bind(node, (ip, port), UDP, socket)
        return Endpoint(netsim, node, bound, socket)

    @staticmethod
    async def connect(addr: "str | Addr") -> "Endpoint":
        """Bind an ephemeral port with a default peer (endpoint.rs:44-56)."""
        netsim = simulator(NetSim)
        ep = await Endpoint.bind(("0.0.0.0", 0))
        ep._peer = netsim.resolve_host(addr)
        return ep

    def local_addr(self) -> Addr:
        return self.addr

    def peer_addr(self) -> Addr:
        if self._peer is None:
            raise OSError("endpoint is not connected")
        return self._peer

    def close(self) -> None:
        self._guard.release()

    def __enter__(self) -> "Endpoint":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- tag-matching datagram API (endpoint.rs:69-149) --------------------

    async def send_to_raw(
        self,
        dst: "str | Addr",
        tag: int,
        payload: Any,
        kind: Optional[str] = None,
    ) -> None:
        dst_addr = self._netsim.resolve_host(dst)
        await self._netsim.send_raw(
            self.node, self.addr, dst_addr, tag, payload, kind=kind
        )

    async def recv_from_raw(self, tag: int) -> Tuple[Any, Addr]:
        mailbox = self._socket.mailbox
        fut = mailbox.recv(tag)
        try:
            payload, src = await fut
            # rand_delay inside the try: a drop landing between
            # resolution and return must also requeue, not lose
            await self._netsim.rand_delay()
        except BaseException:
            # dropped mid-wait (timeout expiry / task kill closes the
            # coroutine): release the mailbox slot — or requeue an
            # already-resolved message — so nothing is swallowed by a
            # dead receiver
            mailbox.drop_recv(tag, fut)
            raise
        return payload, src

    async def send_to(self, dst: "str | Addr", tag: int, data: bytes) -> None:
        await self.send_to_raw(dst, tag, bytes(data))

    async def recv_from(self, tag: int) -> Tuple[bytes, Addr]:
        payload, src = await self.recv_from_raw(tag)
        return payload, src

    async def send(self, tag: int, data: bytes) -> None:
        await self.send_to(self.peer_addr(), tag, data)

    async def recv(self, tag: int) -> bytes:
        data, _src = await self.recv_from(tag)
        return data

    # -- connection-oriented API (endpoint.rs connect1/accept1) ------------

    async def connect1(
        self, dst: "str | Addr"
    ) -> Tuple[PipeSender, PipeReceiver]:
        return await self._netsim.connect1(self.node, self.addr, dst)

    async def accept1(self) -> Tuple[PipeSender, PipeReceiver, Addr]:
        sock = self._socket
        if sock.accept_queue:
            src, half = sock.accept_queue.popleft()
        else:
            fut: Future = Future()
            sock.accept_waiters.append(fut)
            src, half = await fut
        sender, receiver = half
        return sender, receiver, src

    # -- built-in RPC (implemented in net.rpc; ref net/rpc.rs:73-167) ------

    async def call(self, dst: "str | Addr", req: Any) -> Any:
        from .rpc import call

        return await call(self, dst, req)

    async def call_with_data(
        self, dst: "str | Addr", req: Any, data: bytes
    ) -> Tuple[Any, bytes]:
        from .rpc import call_with_data

        return await call_with_data(self, dst, req, data)

    async def call_timeout(
        self, dst: "str | Addr", req: Any, timeout_s: float
    ) -> Any:
        from .rpc import call_timeout

        return await call_timeout(self, dst, req, timeout_s)

    def add_rpc_handler(self, req_type: type, handler: Any) -> None:
        from .rpc import add_rpc_handler

        add_rpc_handler(self, req_type, handler)

    def add_rpc_handler_with_data(self, req_type: type, handler: Any) -> None:
        from .rpc import add_rpc_handler_with_data

        add_rpc_handler_with_data(self, req_type, handler)


async def connect1_ephemeral(dst: "str | Addr") -> Tuple[PipeSender, PipeReceiver]:
    """Open a reliable connection from an ephemeral port, releasing the
    port as soon as the connection is established (the pipes don't use the
    socket table) — the analogue of the reference's RAII Endpoint drop.
    Shared by the gRPC and etcd clients' call paths."""
    ep = await Endpoint.bind(("0.0.0.0", 0))
    try:
        return await ep.connect1(dst)
    finally:
        ep.close()


async def exchange1(tx: Any, rx: Any, req: Any) -> Any:
    """One request/response over a freshly opened connection pair: send,
    half-close the sender, await the single reply. The receiver half is
    ALWAYS closed — in real mode that frees the socket; in sim it marks
    the pipe closed (harmless). Returns the reply, or ``None`` if the
    peer closed without answering. The one-shot exchange discipline shared
    by the etcd / kafka / s3 client call paths (each maps transport errors
    to its own error type)."""
    try:
        await tx.send(req)
        tx.close()
        return await rx.recv()
    finally:
        rx.close()


async def lookup_host(addr: "str | Addr") -> List[Addr]:
    """Resolve a host:port through simulated DNS
    (ref ``lookup_host``, net/addr.rs:33-360)."""
    netsim = simulator(NetSim)
    return [netsim.resolve_host(addr)]


def _current_netsim() -> NetSim:
    return current_handle().simulator(NetSim)
