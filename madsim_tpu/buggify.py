"""FoundationDB-style cooperative fault injection
(ref madsim/src/sim/buggify.rs:8-32; RNG gate in sim/rand.rs:113-134).

``buggify()`` returns True 25% of the time *when enabled* (disabled by
default); simulator code sprinkles ``if buggify():`` at interesting points
(e.g. the network layer turns a 0-5 µs delay into 1-5 s at 10%,
net/mod.rs:287-295).  Draws flow through the GlobalRng, so they are seeded
and appear in the determinism log.

Scoping: ``enabled()`` is the context-manager form — it turns the gate on
for a ``with`` block and restores the PRIOR state on exit, so a test or an
explore campaign can buggify one section without leaking the gate into
whatever runs next. Re-entrant: each nesting level restores what it saw.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Iterator, Optional

from .context import current_handle


def enable() -> None:
    current_handle().rng.buggify_enabled = True


def disable() -> None:
    current_handle().rng.buggify_enabled = False


@contextmanager
def enabled(prob: Optional[float] = None) -> Iterator[None]:
    """Enable buggify for the scope of a ``with`` block, restoring the
    prior gate (and, when ``prob`` is given, the prior default fire
    rate) on exit — exception-safe and re-entrant, so scoped
    buggification composes and never leaks into later tests.

    ``prob`` overrides the fire rate of bare ``buggify()`` calls inside
    the scope (``buggify_with_prob`` keeps taking its explicit value).
    """
    rng = current_handle().rng
    prev_enabled = rng.buggify_enabled
    prev_prob = rng.buggify_prob
    rng.buggify_enabled = True
    if prob is not None:
        rng.buggify_prob = prob
    try:
        yield
    finally:
        rng.buggify_enabled = prev_enabled
        rng.buggify_prob = prev_prob


def is_enabled() -> bool:
    return current_handle().rng.buggify_enabled


def buggify() -> bool:
    """25% chance when enabled, else False (buggify.rs:8-20)."""
    return current_handle().rng.buggify()


def buggify_with_prob(prob: float) -> bool:
    return current_handle().rng.buggify_with_prob(prob)
