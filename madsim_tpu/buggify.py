"""FoundationDB-style cooperative fault injection
(ref madsim/src/sim/buggify.rs:8-32; RNG gate in sim/rand.rs:113-134).

``buggify()`` returns True 25% of the time *when enabled* (disabled by
default); simulator code sprinkles ``if buggify():`` at interesting points
(e.g. the network layer turns a 0-5 µs delay into 1-5 s at 10%,
net/mod.rs:287-295).  Draws flow through the GlobalRng, so they are seeded
and appear in the determinism log.
"""

from __future__ import annotations

from .context import current_handle


def enable() -> None:
    current_handle().rng.buggify_enabled = True


def disable() -> None:
    current_handle().rng.buggify_enabled = False


def is_enabled() -> bool:
    return current_handle().rng.buggify_enabled


def buggify() -> bool:
    """25% chance when enabled, else False (buggify.rs:8-20)."""
    return current_handle().rng.buggify()


def buggify_with_prob(prob: float) -> bool:
    return current_handle().rng.buggify_with_prob(prob)
