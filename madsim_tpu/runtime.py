"""Runtime shell: composition root + supervisor Handle + NodeBuilder.

Mirrors the reference's ``sim/runtime/`` (madsim/src/sim/runtime/mod.rs:34-449):
``Runtime`` wires rng + virtual time + executor + default simulators (FsSim,
NetSim — runtime/mod.rs:53-69); ``Handle`` is the supervisor façade (seed,
kill, restart, pause, resume, ctrl-c, create_node, metrics —
runtime/mod.rs:237-322); ``NodeBuilder`` configures name/ip/cores/init/
restart_on_panic (runtime/mod.rs:374-418); ``check_determinism`` runs a
workload twice recording/replaying the RNG log (runtime/mod.rs:178-202).
"""

from __future__ import annotations

import inspect
import logging
from typing import (
    Any,
    Callable,
    Coroutine,
    Dict,
    List,
    Optional,
    Type,
    TypeVar,
    Union,
)

import gc as _gc
import threading as _threading

from . import context
from .config import Config

# Relaxed gen-0 cycle-GC threshold while any sim runs: the executor
# allocates mostly-acyclic objects at event rate, and collection timing
# cannot affect schedules (no draws, no sim state), only wall-clock.
# Refcounted so concurrent block_on calls (the one-thread-per-seed sweep
# pattern) don't snapshot each other's raised threshold and leak it; the
# original is restored when the LAST sim exits. Threshold 0 (embedder
# disabled GC) is left alone.
_gc_lock = _threading.Lock()
_gc_depth = 0
_gc_saved: "tuple | None" = None


def _gc_relax() -> None:
    global _gc_depth, _gc_saved
    with _gc_lock:
        _gc_depth += 1
        if _gc_depth == 1:
            t = _gc.get_threshold()
            if t[0] > 0:
                _gc_saved = t
                _gc.set_threshold(max(t[0], 50_000), *t[1:])
            else:
                _gc_saved = None


def _gc_restore() -> None:
    global _gc_depth, _gc_saved
    with _gc_lock:
        _gc_depth -= 1
        if _gc_depth == 0 and _gc_saved is not None:
            _gc.set_threshold(*_gc_saved)
            _gc_saved = None
from .futures import JoinHandle
from .metrics import RuntimeMetrics
from .plugin import Simulator
from .rand import GlobalRng
from .task import Executor, NodeId, NodeInfo, MAIN_NODE_ID
from .time import TimeHandle, make_time_handle

S = TypeVar("S", bound=Simulator)

NodeRef = Union["NodeHandle", NodeInfo, NodeId, int]


def _node_id(node: NodeRef) -> NodeId:
    if isinstance(node, NodeHandle):
        return node.id
    if isinstance(node, NodeInfo):
        return node.id
    return NodeId(int(node))


class Handle:
    """Supervisor façade over a running simulation (runtime/mod.rs:237-322)."""

    def __init__(self, rng: GlobalRng, time: TimeHandle, executor: Executor,
                 config: Config):
        self.rng = rng
        self.time = time
        self.executor = executor
        self.config = config
        self.sims: Dict[Type[Simulator], Simulator] = {}
        executor.reset_node_hook = self._reset_node_sims

    @staticmethod
    def current() -> "Handle":
        return context.current_handle()

    @property
    def seed(self) -> int:
        return self.rng.seed

    # -- simulator registry (ref plugin.rs + runtime/mod.rs:72-83) ---------

    def add_simulator(self, cls: Type[S]) -> S:
        if cls in self.sims:
            return self.sims[cls]  # type: ignore[return-value]
        sim = cls(self.rng, self.time, self.config)
        self.sims[cls] = sim
        # late registration: tell the new simulator about existing nodes
        for nid in self.executor.nodes:
            sim.create_node(nid)
        return sim

    def simulator(self, cls: Type[S]) -> S:
        sim = self.sims.get(cls)
        if sim is None:
            raise KeyError(
                f"simulator {cls.__name__} is not registered on this runtime"
            )
        return sim  # type: ignore[return-value]

    def _reset_node_sims(self, id: NodeId) -> None:
        for sim in self.sims.values():
            sim.reset_node(id)

    # -- supervision (runtime/mod.rs:272-303) ------------------------------

    def kill(self, node: NodeRef) -> None:
        self.executor.kill(_node_id(node))

    def restart(self, node: NodeRef) -> None:
        self.executor.restart(_node_id(node))

    def pause(self, node: NodeRef) -> None:
        self.executor.pause(_node_id(node))

    def resume(self, node: NodeRef) -> None:
        self.executor.resume(_node_id(node))

    def send_ctrl_c(self, node: NodeRef) -> None:
        self.executor.send_ctrl_c(_node_id(node))

    def is_exit(self, node: NodeRef) -> bool:
        return self.executor.is_exit(_node_id(node))

    def create_node(self) -> "NodeBuilder":
        return NodeBuilder(self)

    def get_node(self, node: NodeRef) -> Optional["NodeHandle"]:
        info = self.executor.get_node(_node_id(node))
        return NodeHandle(self, info) if info is not None else None

    def metrics(self) -> RuntimeMetrics:
        return RuntimeMetrics(self.executor)


class NodeHandle:
    """Handle to a simulated node (ref ``NodeHandle``, runtime/mod.rs:389-418)."""

    def __init__(self, handle: Handle, info: NodeInfo):
        self._handle = handle
        self._info = info

    @property
    def id(self) -> NodeId:
        # resolve through the executor so a restarted node's fresh NodeInfo
        # is used for spawns
        return self._info.id

    @property
    def name(self) -> str:
        return self._info.name

    def spawn(self, coro: Coroutine[Any, Any, Any],
              name: Optional[str] = None) -> JoinHandle:
        info = self._handle.executor.get_node(self._info.id)
        if info is None:
            raise RuntimeError(f"node {self._info.id} no longer exists")
        return self._handle.executor.spawn_on(info, coro, name=name)

    def __repr__(self) -> str:
        return f"<NodeHandle {self.id} {self.name!r}>"


class NodeBuilder:
    """Builder for simulated nodes (ref runtime/mod.rs:374-418)."""

    def __init__(self, handle: Handle):
        self._handle = handle
        self._name: Optional[str] = None
        self._ip: Optional[str] = None
        self._cores: int = 1
        self._init: Optional[Callable[[], Coroutine[Any, Any, Any]]] = None
        self._restart_on_panic = False
        self._restart_on_panic_matching: Optional[List[str]] = None

    def name(self, name: str) -> "NodeBuilder":
        self._name = name
        return self

    def ip(self, ip: str) -> "NodeBuilder":
        self._ip = ip
        return self

    def cores(self, cores: int) -> "NodeBuilder":
        if cores < 1:
            raise ValueError("cores must be >= 1")
        self._cores = cores
        return self

    def init(self, f: Callable[[], Coroutine[Any, Any, Any]]) -> "NodeBuilder":
        """Async closure respawned on every (re)start (runtime/mod.rs:395)."""
        self._init = f
        return self

    def restart_on_panic(self, matching: Optional[str] = None) -> "NodeBuilder":
        self._restart_on_panic = True
        if matching is not None:
            pats = self._restart_on_panic_matching or []
            pats.append(matching)
            self._restart_on_panic_matching = pats
        return self

    def build(self) -> NodeHandle:
        ex = self._handle.executor
        info = ex.create_node(
            name=self._name,
            cores=self._cores,
            init=self._init,
            restart_on_panic=self._restart_on_panic,
            restart_on_panic_matching=self._restart_on_panic_matching,
        )
        for sim in self._handle.sims.values():
            sim.create_node(info.id)
        if self._ip is not None:
            from .net import NetSim

            self._handle.simulator(NetSim).set_ip(info.id, self._ip)
        if self._init is not None:
            ex.spawn_on(info, self._init(), name="init", spawn_site="init")
        return NodeHandle(self._handle, info)


class Runtime:
    """The simulation runtime (ref ``Runtime``, runtime/mod.rs:34-230).

    One ``Runtime`` = one seeded, single-threaded, deterministic execution.
    """

    def __init__(self, seed: Optional[int] = None,
                 config: Optional[Config] = None):
        if seed is None:
            import time as _walltime

            seed = _walltime.time_ns()  # ref builder.rs:64-73 default seed
        self.rng = GlobalRng(seed)
        self.time = make_time_handle(self.rng)
        self.config = config or Config()
        self.executor = Executor(self.rng, self.time)
        self.handle = Handle(self.rng, self.time, self.executor, self.config)
        # default device simulators (ref runtime/mod.rs:53-69)
        from .fs import FsSim
        from .net import NetSim

        self.handle.add_simulator(NetSim)
        self.handle.add_simulator(FsSim)

    @property
    def seed(self) -> int:
        return self.rng.seed

    def add_simulator(self, cls: Type[S]) -> S:
        return self.handle.add_simulator(cls)

    def create_node(self) -> NodeBuilder:
        return self.handle.create_node()

    def set_time_limit(self, seconds: float) -> None:
        self.executor.time_limit_ns = int(seconds * 1e9)

    def set_allow_system_thread(self, allow: bool) -> None:
        self._allow_system_thread = allow

    def block_on(self, main: Union[Coroutine[Any, Any, Any],
                                   Callable[[], Coroutine[Any, Any, Any]]]) -> Any:
        """Run the main future to completion inside the sim context
        (runtime/mod.rs:127-130)."""
        from .interpose import interposed

        coro = main() if callable(main) and not inspect.iscoroutine(main) else main
        assert inspect.iscoroutine(coro), "block_on expects a coroutine"
        allow_thread = getattr(self, "_allow_system_thread", False)
        _gc_relax()
        try:
            with context.enter_handle(self.handle), interposed(
                self.handle, allow_system_thread=allow_thread
            ):
                return self.executor.block_on(coro)
        finally:
            _gc_restore()

    @staticmethod
    def check_determinism(
        seed: int,
        f: Callable[[], Coroutine[Any, Any, Any]],
        config: Optional[Config] = None,
    ) -> Any:
        """Run ``f`` twice with the same seed, recording then replaying the
        RNG log; raises NondeterminismError at the first divergence
        (ref runtime/mod.rs:178-202, rand.rs:64-88)."""
        rt1 = Runtime(seed=seed, config=config)
        rt1.rng.enable_log()
        result = rt1.block_on(f())
        log = rt1.rng.take_log()
        assert log is not None
        rt2 = Runtime(seed=seed, config=config)
        rt2.rng.enable_check(log)
        rt2.block_on(f())
        return result


def init_logger(level: int = logging.INFO) -> None:
    """Install a logging config whose lines carry sim identity —
    ``[<sim_time>s <node>/<task>]`` — once (ref runtime/mod.rs:445-449;
    the span-per-node/task analogue lives in madsim_tpu.tracing)."""
    from .tracing import LOG_FORMAT, SimContextFilter

    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(level=level, format=LOG_FORMAT)
        for handler in root.handlers:
            handler.addFilter(SimContextFilter())
