"""Deterministic single-threaded task scheduler with nodes-as-processes.

Mirrors the reference's ``sim/task/`` (madsim/src/sim/task/mod.rs:43-1102):

- **Random-order ready queue**: the executor pops a *uniformly random* element
  from the ready queue each step — the source of schedule randomization
  (ref: sim/utils/mpsc.rs:71-84 ``try_recv_random`` swap_remove).
- **Hot loop** (``Executor::block_on``, task/mod.rs:220-260): drain ready
  queue in random order, poll each task, advance the clock a random 50-100 ns
  per poll (task/mod.rs:312-315), then jump the clock to the next timer event;
  raise the deadlock error when no events remain (task/mod.rs:250).
- **Node model** (task/mod.rs:87-176): a node = simulated process owning a set
  of tasks; kill wakes all tasks so the executor drops their coroutines
  (running ``finally`` blocks — the RAII analogue); restart re-runs the
  node's ``init`` closure on a fresh NodeInfo; pause parks popped tasks.
- **Restart-on-panic** (task/mod.rs:282-309): a panicking task on a flagged
  node kills the node and schedules a restart after a random 1-10 s backoff,
  optionally filtered by panic message.
"""

from __future__ import annotations

from typing import Any, Callable, Coroutine, Dict, List, NewType, Optional

from . import context
from .futures import CancelledError, JoinHandle
from .rand import GlobalRng
from .time import TimeHandle

NodeId = NewType("NodeId", int)

MAIN_NODE_ID = NodeId(0)


class DeadlockError(RuntimeError):
    """No timers pending and every task is blocked (ref task/mod.rs:250)."""


class TimeLimitError(RuntimeError):
    """Virtual time exceeded the configured limit (ref task/mod.rs:253-258)."""


class _TaskExit(BaseException):
    """Control-flow signal for simulated process exit (Spawner::exit)."""


class Task:
    """A spawned coroutine bound to a node (ref ``TaskInfo``/``Runnable``)."""

    __slots__ = (
        "id",
        "node",
        "coro",
        "join",
        "name",
        "spawn_site",
        "scheduled",
        "cancelled",
        "finished",
        "_executor",
        "_ready_items",  # direct list ref for the default queue (fast wake)
    )

    def __init__(
        self,
        executor: "Executor",
        node: "NodeInfo",
        coro: Coroutine[Any, Any, Any],
        name: Optional[str],
        spawn_site: str,
    ):
        self.id = executor._alloc_task_id()
        self.node = node
        self.coro = coro
        self.join = JoinHandle(self)
        self.name = name
        self.spawn_site = spawn_site
        self.scheduled = False
        self.cancelled = False
        self.finished = False
        self._executor = executor
        ready = executor.ready
        self._ready_items = ready._items if type(ready) is _PyReadyQueue else None

    def wake(self) -> None:
        """Enqueue this task for polling (idempotent while scheduled)."""
        if self.finished or self.scheduled:
            return
        self.scheduled = True
        items = self._ready_items
        if items is not None:
            items.append(self)  # default queue: skip two method dispatches
        else:
            self._executor.ready.append(self)

    def abort(self) -> None:
        """tokio ``AbortHandle::abort`` — mark cancelled and wake so the
        executor drops the coroutine."""
        if not self.finished:
            self.cancelled = True
            self.wake()

    def __repr__(self) -> str:
        return f"<Task {self.id} {self.name or ''} node={self.node.id}>"


class NodeInfo:
    """A simulated process (ref ``NodeInfo``, task/mod.rs:87-176)."""

    def __init__(
        self,
        id: NodeId,
        name: str,
        cores: int = 1,
        init: Optional[Callable[[], Coroutine[Any, Any, Any]]] = None,
        restart_on_panic: bool = False,
        restart_on_panic_matching: Optional[List[str]] = None,
    ):
        self.id = id
        self.name = name
        self.cores = cores
        self.init = init
        self.restart_on_panic = restart_on_panic
        self.restart_on_panic_matching = restart_on_panic_matching
        self.killed = False
        self.paused = False
        self.paused_tasks: List[Task] = []
        self.tasks: Dict[int, Task] = {}
        # ctrl-c handling (ref task/mod.rs:106-111,166-175,419-434)
        self.ctrl_c_installed = False
        self.ctrl_c_waiters: List[Any] = []

    def kill(self) -> None:
        """Mark killed and wake every task so the executor drops it
        (ref ``NodeInfo::kill``, task/mod.rs:133-140)."""
        self.killed = True
        self.paused = False
        parked, self.paused_tasks = self.paused_tasks, []
        for t in parked:
            t.scheduled = False
            t.wake()
        for t in list(self.tasks.values()):
            t.wake()

    def __repr__(self) -> str:
        return f"<Node {self.id} {self.name!r}>"


class _PyReadyQueue:
    """Default ready queue: Python list with swap-remove pops."""

    __slots__ = ("_items",)

    def __init__(self) -> None:
        self._items: List[Task] = []

    def append(self, task: "Task") -> None:
        self._items.append(task)

    def swap_remove(self, idx: int) -> "Task":
        items = self._items
        task = items[idx]
        items[idx] = items[-1]
        items.pop()
        return task

    def __len__(self) -> int:
        return len(self._items)


class _NativeReadyQueue:
    """C++ swap-remove queue (madsim_tpu.native.ReadyQueue); pop indices
    still come from the Python GlobalRng, so schedules are identical."""

    __slots__ = ("_q", "_tasks")

    def __init__(self) -> None:
        from .native import ReadyQueue

        self._q = ReadyQueue()
        self._tasks: Dict[int, Task] = {}

    def append(self, task: "Task") -> None:
        self._tasks[task.id] = task
        self._q.push(task.id)

    def swap_remove(self, idx: int) -> "Task":
        return self._tasks.pop(self._q.swap_remove(idx))

    def __len__(self) -> int:
        return len(self._q)


def _make_ready_queue():
    import os

    if os.environ.get("MADSIM_NATIVE"):
        from . import native

        if native.available():
            return _NativeReadyQueue()
    return _PyReadyQueue()


class Executor:
    """The deterministic event loop (ref ``Executor``, task/mod.rs:43-317)."""

    def __init__(self, rng: GlobalRng, time: TimeHandle):
        self.rng = rng
        self.time = time
        self.ready = _make_ready_queue()
        # compiled ready-loop driver (native/simloop.c) — available when
        # the time core is compiled and the default Python queue is in use
        self._cloop = None
        core = getattr(time, "_core", None)
        if core is not None and type(self.ready) is _PyReadyQueue:
            from . import native as _native

            sl = _native.simloop()
            if sl is not None:
                self._cloop = sl.Loop(
                    self, self.ready._items, rng, core, context._tls
                )
        self.nodes: Dict[NodeId, NodeInfo] = {}
        self._next_node_id = 1
        self._next_task_id = 1
        self.time_limit_ns: Optional[int] = None
        # set by Handle: called with node_id on kill/restart so registered
        # simulators reset per-node state (ref task/mod.rs:361-364)
        self.reset_node_hook: Callable[[NodeId], None] = lambda _id: None
        self.main_node = NodeInfo(MAIN_NODE_ID, "main")
        self.nodes[MAIN_NODE_ID] = self.main_node

    # -- ids ---------------------------------------------------------------

    def _alloc_task_id(self) -> int:
        tid = self._next_task_id
        self._next_task_id += 1
        return tid

    def alloc_node_id(self) -> NodeId:
        nid = NodeId(self._next_node_id)
        self._next_node_id += 1
        return nid

    # -- spawning ----------------------------------------------------------

    def spawn_on(
        self,
        node: NodeInfo,
        coro: Coroutine[Any, Any, Any],
        name: Optional[str] = None,
        spawn_site: str = "?",
    ) -> JoinHandle:
        """Spawn a coroutine as a task on ``node`` (ref ``Spawner::spawn``,
        task/mod.rs:575-655; raises on killed node, task/mod.rs:625-627)."""
        if node.killed:
            coro.close()
            raise RuntimeError(f"cannot spawn task: node {node} has been killed")
        task = Task(self, node, coro, name, spawn_site)
        node.tasks[task.id] = task
        task.wake()
        return task.join

    # -- the hot loop ------------------------------------------------------

    def block_on(self, coro: Coroutine[Any, Any, Any]) -> Any:
        """Run ``coro`` as the main task until completion
        (ref ``Executor::block_on``, task/mod.rs:220-260)."""
        main = self.spawn_on(self.main_node, coro, name="main", spawn_site="main")
        if self._cloop is not None:
            # the whole inner loop is compiled (ref task/mod.rs:220-260);
            # it re-reads self.time_limit_ns each iteration and raises via
            # _raise_time_limit, so mid-sim set_time_limit behaves exactly
            # like the Python loop below
            return self._cloop.run(main, DeadlockError, 50)
        while True:
            self.run_all_ready()
            if main.done():
                return main.result()
            if not self.time.advance_to_next_event():
                raise DeadlockError(
                    "deadlock detected: no timers are pending and every task "
                    "is blocked — the simulation can never make progress"
                )
            if (
                self.time_limit_ns is not None
                and self.time.now_ns > self.time_limit_ns
            ):
                raise TimeLimitError(
                    f"simulated time limit exceeded "
                    f"({self.time_limit_ns / 1e9:.3f}s of virtual time)"
                )

    def run_all_ready(self) -> None:
        """Drain the ready queue in random order
        (ref ``run_all_ready``, task/mod.rs:263-316).

        The Python-queue fast path inlines swap_remove and the 50-100 ns
        jitter advance; pop indices and jitter still come from the same
        GlobalRng draws in the same order, so schedules are byte-identical
        with the method-dispatch path (and with MADSIM_NATIVE)."""
        ready = self.ready
        rng_next = self.rng.next_u64
        time = self.time
        items = ready._items if type(ready) is _PyReadyQueue else None
        if items is None:
            self._run_all_ready_generic()
            return
        while items:
            n = len(items)
            # random swap-remove pop (ref sim/utils/mpsc.rs:73-83);
            # inlined gen_range(0, n) — Lemire reduction
            idx = rng_next() * n >> 64
            task = items[idx]
            items[idx] = items[-1]
            items.pop()
            task.scheduled = False
            if task.finished:
                continue
            node = task.node
            if task.cancelled or node.killed:
                self._drop_task(task)
                continue
            if node.paused:
                # park until resume (ref task/mod.rs:271-276)
                node.paused_tasks.append(task)
                continue
            self._poll(task)
            # random 50-100 ns advance per poll (ref task/mod.rs:312-315);
            # inlined gen_range(50, 101)
            time.advance_ns(50 + (rng_next() * 51 >> 64))

    def _run_all_ready_generic(self) -> None:
        """Method-dispatch drain for non-default queue backends
        (MADSIM_NATIVE) — same draws, same order as the fast path."""
        ready = self.ready
        rng = self.rng
        while len(ready):
            idx = rng.gen_range(0, len(ready))
            task = ready.swap_remove(idx)
            task.scheduled = False
            if task.finished:
                continue
            node = task.node
            if task.cancelled or node.killed:
                self._drop_task(task)
                continue
            if node.paused:
                node.paused_tasks.append(task)
                continue
            self._poll(task)
            self.time.advance_ns(rng.gen_range(50, 101))

    def _poll(self, task: Task) -> None:
        prev = context.swap_task(task)
        try:
            pollable = task.coro.send(None)
        except StopIteration as stop:
            self._finish(task)
            task.join.set_result(stop.value)
            return
        except _TaskExit:
            self._finish(task)
            task.join.set_result(None)
            return
        except Exception as exc:  # noqa: BLE001 — the catch_unwind analogue
            self._finish(task)
            self._on_panic(task, exc)
            return
        finally:
            context.swap_task(prev)
        pollable.subscribe(task)

    def _finish(self, task: Task) -> None:
        task.finished = True
        task.node.tasks.pop(task.id, None)

    # -- callbacks for the compiled loop (native/simloop.c) ---------------

    def _complete(self, task: Task, value: Any) -> None:
        """Task coroutine returned ``value`` (the StopIteration branch)."""
        self._finish(task)
        task.join.set_result(value)

    def _raise_time_limit(self) -> None:
        """Raise the TimeLimitError the Python loop would (called by the
        compiled loop when the clock passes ``time_limit_ns``)."""
        raise TimeLimitError(
            f"simulated time limit exceeded "
            f"({self.time_limit_ns / 1e9:.3f}s of virtual time)"
        )

    def _poll_raised(self, task: Task, exc: BaseException) -> bool:
        """Exception out of a poll; returns False to propagate (the
        KeyboardInterrupt/SystemExit path, mirroring ``except Exception``)."""
        if isinstance(exc, _TaskExit):
            self._finish(task)
            task.join.set_result(None)
            return True
        if isinstance(exc, Exception):
            self._finish(task)
            self._on_panic(task, exc)
            return True
        return False

    def _drop_task(self, task: Task) -> None:
        """Drop a cancelled/killed task's coroutine, running its ``finally``
        blocks (the RAII analogue: e.g. BindGuard releases ports)."""
        task.finished = True
        task.node.tasks.pop(task.id, None)
        with context.enter_task(task):
            try:
                task.coro.close()
            except Exception:  # noqa: BLE001 — cleanup must not kill the sim
                pass
        task.join.set_exception(CancelledError(f"{task!r} was cancelled"))

    def _on_panic(self, task: Task, exc: Exception) -> None:
        """ref task/mod.rs:282-309: restart-on-panic or propagate."""
        node = task.node
        matching = node.restart_on_panic_matching
        should_restart = node.restart_on_panic and (
            matching is None or any(pat in str(exc) for pat in matching)
        )
        if should_restart and node.id != MAIN_NODE_ID:
            task.join.set_exception(exc)
            self.kill(node.id)
            # random 1-10 s restart backoff (ref task/mod.rs:291-307)
            delay_ns = self.rng.gen_range(1_000_000_000, 10_000_000_001)
            node_id = node.id
            self.time.add_timer_ns(delay_ns, lambda: self.restart(node_id))
            return
        task.join.set_exception(exc)
        # propagate: abort the whole simulation (resume_unwind analogue)
        raise exc

    # -- node lifecycle (ref TaskHandle, task/mod.rs:347-535) --------------

    def create_node(
        self,
        name: Optional[str] = None,
        cores: int = 1,
        init: Optional[Callable[[], Coroutine[Any, Any, Any]]] = None,
        restart_on_panic: bool = False,
        restart_on_panic_matching: Optional[List[str]] = None,
    ) -> NodeInfo:
        nid = self.alloc_node_id()
        node = NodeInfo(
            nid,
            name if name is not None else f"node-{nid}",
            cores=cores,
            init=init,
            restart_on_panic=restart_on_panic,
            restart_on_panic_matching=restart_on_panic_matching,
        )
        self.nodes[nid] = node
        return node

    def get_node(self, id: NodeId) -> Optional[NodeInfo]:
        return self.nodes.get(id)

    def _node(self, id: NodeId) -> NodeInfo:
        node = self.nodes.get(id)
        if node is None:
            raise KeyError(f"no such node: {id}")
        return node

    def kill(self, id: NodeId) -> None:
        """ref ``TaskHandle::kill_id`` (task/mod.rs:355-364)."""
        node = self._node(id)
        node.kill()
        self.reset_node_hook(id)

    def restart(self, id: NodeId) -> None:
        """Kill then respawn the node's ``init`` closure on a fresh NodeInfo
        (ref task/mod.rs:367-394)."""
        old = self._node(id)
        old.kill()
        self.reset_node_hook(id)
        new = NodeInfo(
            id,
            old.name,
            cores=old.cores,
            init=old.init,
            restart_on_panic=old.restart_on_panic,
            restart_on_panic_matching=old.restart_on_panic_matching,
        )
        self.nodes[id] = new
        if new.init is not None:
            self.spawn_on(new, new.init(), name="init", spawn_site="init")

    def pause(self, id: NodeId) -> None:
        self._node(id).paused = True

    def resume(self, id: NodeId) -> None:
        node = self._node(id)
        node.paused = False
        parked, node.paused_tasks = node.paused_tasks, []
        for t in parked:
            t.wake()

    def send_ctrl_c(self, id: NodeId) -> None:
        """Notify ctrl-c subscribers, or kill if none installed
        (ref task/mod.rs:419-434)."""
        node = self._node(id)
        if node.ctrl_c_installed:
            waiters, node.ctrl_c_waiters = node.ctrl_c_waiters, []
            for fut in waiters:
                fut.set_result(None)
        else:
            self.kill(id)

    def is_exit(self, id: NodeId) -> bool:
        node = self.nodes.get(id)
        return node is None or node.killed

    # -- metrics (ref task/mod.rs:490-534) ---------------------------------

    def num_tasks(self) -> int:
        return sum(len(n.tasks) for n in self.nodes.values())

    def num_tasks_by_node(self) -> Dict[str, int]:
        return {n.name: len(n.tasks) for n in self.nodes.values() if n.tasks}

    def num_tasks_by_spawn_site(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for n in self.nodes.values():
            for t in n.tasks.values():
                out[t.spawn_site] = out.get(t.spawn_site, 0) + 1
        return out


# -- ambient spawning API (task::spawn) ------------------------------------


def _spawn_site(depth: int = 2) -> str:
    import sys

    try:
        frame = sys._getframe(depth)
        return f"{frame.f_code.co_filename}:{frame.f_lineno}"
    except ValueError:
        return "?"


def spawn(coro: Coroutine[Any, Any, Any], name: Optional[str] = None) -> JoinHandle:
    """Spawn a task on the current node (ref ``task::spawn``)."""
    task = context.current_task()
    return task._executor.spawn_on(task.node, coro, name=name, spawn_site=_spawn_site())


def spawn_local(
    coro: Coroutine[Any, Any, Any], name: Optional[str] = None
) -> JoinHandle:
    """Alias of :func:`spawn` — the simulator is single-threaded by design."""
    task = context.current_task()
    return task._executor.spawn_on(task.node, coro, name=name, spawn_site=_spawn_site())


def exit_current_task() -> None:
    """Simulated ``process::exit`` for the current node (Spawner::exit):
    kills the node and unwinds the current task immediately."""
    task = context.current_task()
    task._executor.kill(task.node.id)
    raise _TaskExit()
