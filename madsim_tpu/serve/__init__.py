"""``madsim_tpu.serve`` — the shared async wire-serving core.

One ``asyncio.Protocol``-based event loop (optionally SO_REUSEPORT loop
shards) multiplexes every real-TCP wire tier: framing/reassembly, per-
connection state, bounded-queue backpressure, slow-client eviction,
lifecycle metrics, and gray-failure read-stall injection live here once;
the Kafka/S3/etcd wires are thin adapters (``serve/adapters.py``). The
multi-process load rig (``serve/loadgen.py``, driven by
``scripts/wire_load.py``) pushes ≥1k genuine-protocol clients through it
and gates SLOs on the PR-14 latency histograms. See docs/wire.md
("Async serving core").
"""

from .core import AsyncWireServer, Conn, DropConnection, WireAdapter
from .framing import (
    FramingError,
    HttpRequest,
    HttpRequestFramer,
    LengthPrefixFramer,
    frame,
    render_http_response,
)
from .adapters import (
    ChannelAdapter,
    ChannelReceiver,
    ChannelSender,
    HttpAdapter,
    PureFrameAdapter,
)

__all__ = [
    "AsyncWireServer",
    "ChannelAdapter",
    "ChannelReceiver",
    "ChannelSender",
    "Conn",
    "DropConnection",
    "FramingError",
    "HttpAdapter",
    "HttpRequest",
    "HttpRequestFramer",
    "LengthPrefixFramer",
    "PureFrameAdapter",
    "WireAdapter",
    "frame",
    "render_http_response",
]
