"""Multi-process load rig: ≥1k genuine-protocol clients against one
sim-backed cluster served by the async core.

The rig proves ROADMAP item 3 at production scale rather than demo
scale: a parent process hosts one cluster — the Kafka binary wire, the
S3 REST wire, and the framed etcd wire, all multiplexed by
``serve.core.AsyncWireServer`` over real TCP — while worker *processes*
(``multiprocessing``) run hundreds of asyncio client tasks each,
speaking the real protocols end to end:

- Kafka producers pinned to home partitions + consumer groups (Join/
  Sync/Heartbeat/OffsetCommit) with a late joiner per group forcing a
  live rebalance;
- S3 clients doing PutObject/GetObject/DeleteObject plus the multipart
  lifecycle over keep-alive HTTP/1.1;
- etcd clients doing put/get/delete through the framed request-enum
  tier.

Mid-load gray failure, derived from a compiled ``FaultSpec`` schedule
(``faults.compile_host`` — same host-fault vocabulary as the sim tier):
an **asymmetric partition** (the core stops *reading* half the Kafka
connections while its write half stays live) timed to overlap the
consumer-group rebalance window, and an **fsync stall** on S3 multipart
writes (UploadPart/CompleteMultipartUpload responses withheld without
blocking the loop).

Every client op is recorded through ``oracle.HostRecorder`` rows; the
parent merges per-worker rows into one history per wire and checks them
against ``LogSpec`` (Kafka), ``S3Spec`` (S3), and ``KVSpec`` (etcd).
The standing hard rule holds: the Kafka wire transcript and the S3 REST
transcript are replayed through FRESH engines and must reproduce byte
for byte. SLOs (p50/p99 + throughput per api/op/method) come from the
PR-14 server-side latency histograms — the internal registry is always
on, so the caller's telemetry setting cannot change any report byte.
"""

from __future__ import annotations

import asyncio
import hashlib
import multiprocessing as mp
import time as _walltime
from typing import Dict, List, Optional, Tuple

from ..obs import Telemetry
from ..obs.metrics import Registry
from ..oracle import KVSpec, LogSpec, S3Spec, check_history
from ..oracle.history import (
    OP_DEL,
    OP_FETCH,
    OP_GET,
    OP_PRODUCE,
    OP_PUT,
    decode_rows,
)

TOPIC = "load"
GROUP_PREFIX = "load-group"
BUCKET = "load"

_I31 = 0x7FFF_FFFF  # history columns are int32


def fingerprint(body: bytes) -> int:
    """31-bit content digest — the S3Spec register value of a body."""
    return int.from_bytes(
        hashlib.sha256(body).digest()[:4], "big"
    ) & _I31


def body_for(client: int, n: int) -> bytes:
    """The deterministic object body client ``client`` writes as its
    ``n``-th value (both the writer and the spec know the fingerprint)."""
    return b"o%d.%d" % (client, n) * 3


# ---------------------------------------------------------------------------
# the served cluster (parent process)


class Cluster:
    """One sim-backed cluster: Kafka + S3 + framed etcd on real ports.

    ``server_kind`` selects the serving stack — ``"async"`` (the shared
    core) or ``"legacy"`` (the retired thread-of-control-per-connection
    servers) — with identical protocol bytes either way; the A/B is what
    the determinism gate diffs. The internal telemetry registry is
    always on (it is the SLO source); ``telemetry`` adds nothing to any
    report."""

    def __init__(self, server_kind: str = "async",
                 kafka_clock=None, s3_clock=None, telemetry: bool = True,
                 kafka_advertised=None):
        assert server_kind in ("async", "legacy"), server_kind
        self.kind = server_kind
        self.registry = Registry()
        # the determinism gate runs with telemetry off to prove no
        # report byte depends on it; the full rig always instruments
        self.telemetry = (
            Telemetry(registry=self.registry) if telemetry else None
        )
        self.kafka_clock = kafka_clock
        self.s3_clock = s3_clock
        # determinism legs pin the advertised address so the ephemeral
        # bound port cannot leak into transcript hashes
        self.kafka_advertised = kafka_advertised
        self.kafka = None
        self.s3 = None
        self.etcd = None
        self._tasks: List[asyncio.Task] = []
        self.addrs: Dict[str, Tuple[str, int]] = {}

    async def start(self) -> Dict[str, Tuple[str, int]]:
        from ..etcd.service import EtcdService
        from ..kafka import wire as kwire
        from ..real import etcd as retcd
        from ..s3 import wire as s3wire

        loop = asyncio.get_running_loop()
        if self.kind == "async":
            self.kafka = kwire.WireServer(
                telemetry=self.telemetry, clock_ms=self.kafka_clock,
                advertised=self.kafka_advertised,
            )
            self.s3 = s3wire.WireServer(
                telemetry=self.telemetry, clock_ms=self.s3_clock
            )
            self.etcd = retcd.Server(
                EtcdService(), telemetry=self.telemetry
            )
        else:
            self.kafka = kwire.LegacyWireServer(
                telemetry=self.telemetry, clock_ms=self.kafka_clock,
                advertised=self.kafka_advertised,
            )
            self.s3 = s3wire.LegacyWireServer(
                telemetry=self.telemetry, clock_ms=self.s3_clock
            )
            self.etcd = retcd.LegacyServer(
                EtcdService(), telemetry=self.telemetry
            )
        for name, srv in (("kafka", self.kafka), ("s3", self.s3),
                          ("etcd", self.etcd)):
            self._tasks.append(loop.create_task(srv.serve(("127.0.0.1", 0))))
        while not all(
            getattr(s, "bound_addr", None)
            for s in (self.kafka, self.s3, self.etcd)
        ):
            await asyncio.sleep(0.01)
        # live transcripts for the replay gate
        self.kafka.wire.recorder = []
        self.s3.rest.recorder = []
        self.addrs = {
            "kafka": tuple(self.kafka.bound_addr),
            "s3": tuple(self.s3.bound_addr),
            "etcd": tuple(self.etcd.bound_addr),
        }
        return self.addrs

    async def stop(self) -> None:
        for srv in (self.kafka, self.s3, self.etcd):
            close = getattr(srv, "close", None)
            if close is not None:
                close()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    # -- gray failure -------------------------------------------------------

    def inject_partition(self, duration: float, parity: int) -> int:
        """Asymmetric partition: the core stops reading the Kafka
        connections whose id matches ``parity`` (mod 2) — their inbound
        traffic is blackholed while the server's outbound half stays
        live — for ``duration`` seconds."""
        core = getattr(self.kafka, "_core", None)
        if core is None:  # legacy stack has no core — no injection seam
            return 0
        return core.inject_read_stall(
            duration, match=lambda c: c.id % 2 == parity % 2
        )

    def set_fsync_stall(self, seconds: float) -> None:
        """Fsync stall under S3 multipart: UploadPart and
        CompleteMultipartUpload responses are withheld ``seconds``
        before flushing (0 clears). Core stack only."""
        adapter = getattr(self.s3, "adapter", None)
        if adapter is None:
            return
        if seconds <= 0:
            adapter.stall_hook = None
            return
        adapter.stall_hook = (
            lambda req: seconds
            if ("uploadId" in req.query
                and req.method in ("PUT", "POST"))
            else 0.0
        )

    # -- replay gates -------------------------------------------------------

    def replay_kafka(self) -> Tuple[int, bool]:
        """Re-feed the recorded (frame, clock) transcript through a
        FRESH broker: every response byte must reproduce."""
        from ..kafka.broker import Broker
        from ..kafka.wire import KafkaWire

        transcript = self.kafka.wire.recorder or []
        feed = [clk for _req, clk, _rsp in transcript]
        replay = KafkaWire(
            Broker(), clock_ms=lambda: feed.pop(0),
            advertised=self.kafka.wire.advertised,
        )
        ok = True
        for req, _clk, rsp in transcript:
            try:
                got = replay.handle_frame(req)
            except Exception:  # noqa: BLE001 — divergence is the verdict
                got = None
                ok = False
            if got != rsp:
                ok = False
        return len(transcript), ok

    def replay_s3(self) -> Tuple[int, bool]:
        """Re-dispatch the recorded S3 transcript through a FRESH
        service with the recorded clock feed: (status, body, headers)
        must reproduce exactly."""
        from ..s3.wire import S3Rest

        transcript = self.s3.rest.recorder or []
        feed = [clk for _req, clk, _rsp in transcript]
        replay = S3Rest(clock_ms=lambda: feed.pop(0))
        ok = True
        for req, _clk, (status, body, headers) in transcript:
            try:
                got = replay.handle(req)
            except Exception:  # noqa: BLE001
                got = None
                ok = False
            if got != (status, body, headers):
                ok = False
        return len(transcript), ok

    # -- the SLO report -----------------------------------------------------

    def slo_report(self, elapsed_s: float) -> dict:
        """p50/p99 + throughput per api/op/method from the PR-14
        histograms, plus the core's ``serve_*`` lifecycle counters."""
        out: Dict[str, dict] = {}
        for hist_name, label in (
            ("kafka_api_seconds", "api"),
            ("s3_api_seconds", "method"),
            ("etcd_api_seconds", "op"),
        ):
            hist = self.registry.metric(hist_name)
            if hist is None:
                continue
            legs = {}
            for labelvals, row in hist.series():
                count = int(sum(row[:-1]))
                legs["/".join(labelvals)] = {
                    "count": count,
                    "p50_ms": _quantile_ms(hist.buckets, row, 0.50),
                    "p99_ms": _quantile_ms(hist.buckets, row, 0.99),
                    "rps": round(count / elapsed_s, 2) if elapsed_s else 0.0,
                }
            out[hist_name] = legs
        serve = {}
        for name in (
            "serve_connections_total", "serve_frames_total",
            "serve_bytes_in_total", "serve_bytes_out_total",
            "serve_backpressure_pauses_total",
            "serve_slow_client_drops_total", "serve_chaos_stalls_total",
        ):
            metric = self.registry.metric(name)
            if metric is None:
                continue
            serve[name] = {
                "/".join(k): int(v) for k, v in metric.series()
            }
        out["serve"] = serve
        return out


def _quantile_ms(buckets, row, q: float) -> float:
    """Quantile estimate (ms) from one histogram row by linear
    interpolation inside the landing bucket."""
    counts = row[:-1]  # per-slot counts + the +Inf slot
    total = sum(counts)
    if total == 0:
        return 0.0
    target = q * total
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        hi = buckets[i] if i < len(buckets) else buckets[-1] * 2
        if cum + c >= target and c > 0:
            frac = (target - cum) / c
            return round((lo + (hi - lo) * frac) * 1000.0, 3)
        cum += c
        lo = hi
    return round(lo * 1000.0, 3)


# ---------------------------------------------------------------------------
# worker processes: hundreds of asyncio clients each


class _HttpClient:
    """Minimal keep-alive HTTP/1.1 client for the S3 REST wire."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self.reader = None
        self.writer = None

    async def connect(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port
        )

    async def request(self, method: str, target: str, body: bytes = b"",
                      headers: Optional[Dict[str, str]] = None):
        lines = [f"{method} {target} HTTP/1.1",
                 f"Host: {self.host}:{self.port}",
                 f"Content-Length: {len(body)}"]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        self.writer.write(
            ("\r\n".join(lines) + "\r\n\r\n").encode() + body
        )
        await self.writer.drain()
        head = await self.reader.readuntil(b"\r\n\r\n")
        head_lines = head.decode("latin-1").split("\r\n")
        status = int(head_lines[0].split(" ", 2)[1])
        rsp_headers = {}
        for line in head_lines[1:]:
            if ":" in line:
                k, _, v = line.partition(":")
                rsp_headers[k.strip().lower()] = v.strip()
        length = int(rsp_headers.get("content-length", "0"))
        rsp_body = b""
        if length and method != "HEAD":
            rsp_body = await self.reader.readexactly(length)
        return status, rsp_body, rsp_headers

    def close(self) -> None:
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:  # pragma: no cover
                pass


async def _kafka_producer(cid, addr, cfg, rec, stats) -> None:
    from ..kafka.probe import ProbeClient, RealTransport

    c = ProbeClient(await RealTransport.connect(addr))
    part = cid % cfg["partitions"]
    deadline = cfg["t0"] + cfg["run_secs"]
    gap = cfg["run_secs"] / max(1, cfg["kafka_records"])
    try:
        for r in range(cfg["kafka_records"]):
            seq = (cid * cfg["kafka_records"] + r) & _I31
            opid = rec.invoke(client=cid, op=OP_PRODUCE, key=part, inp=seq)
            err, off = await c.produce(
                TOPIC, part,
                [(int(_walltime.time() * 1000), b"p%d" % cid,
                  b"r%d" % seq)],
            )
            if err:
                stats["errors"] += 1
                continue  # open op: may or may not have happened
            rec.complete(client=cid, opid=opid, out=(off + 1) & _I31)
            stats["kafka_ops"] += 1
            now = _walltime.time()
            if now < deadline:
                await asyncio.sleep(min(gap, deadline - now))
    finally:
        c.close()


async def _kafka_consumer(cid, addr, cfg, rec, stats, group: str,
                          late: bool) -> None:
    from ..kafka import wire as kwire
    from ..kafka.probe import ProbeClient, ProbeError, RealTransport

    if late:
        # joins mid-run — inside the partition window, so the rebalance
        # happens UNDER the asymmetric partition
        await asyncio.sleep(cfg["run_secs"] * cfg["chaos_at"])
    c = ProbeClient(await RealTransport.connect(addr))
    deadline = cfg["t0"] + cfg["run_secs"]
    try:
        member, gen, assignment = await c.group_session(group, [TOPIC])
        positions: Dict[int, int] = {}
        while _walltime.time() < deadline:
            for _topic, p in assignment:
                offset = positions.get(p, 0)
                opid = rec.invoke(client=cid, op=OP_FETCH, key=p, inp=offset)
                err, _high, rows = await c.fetch(TOPIC, p, offset)
                if err:
                    stats["errors"] += 1
                    continue
                rec.complete(client=cid, opid=opid, out=len(rows))
                stats["kafka_ops"] += 1
                if rows:
                    positions[p] = rows[-1][0] + 1
            hb = await c.heartbeat(group, gen, member)
            if hb == kwire.ERR_REBALANCE_IN_PROGRESS:
                # rejoin; `positions` is deliberately NOT pruned — a
                # partition lost and later readopted must resume at its
                # last fetched offset or LogSpec's per-(client,
                # partition) contiguity check trips
                member, gen, assignment = await c.group_session(
                    group, [TOPIC], member_id=member
                )
            elif hb != 0:
                # e.g. kicked for missing heartbeats through the
                # partition window: rejoin as a fresh member
                member, gen, assignment = await c.group_session(
                    group, [TOPIC]
                )
            elif positions:
                await c.offset_commit(
                    group, gen, member,
                    [(TOPIC, p, off)
                     for p, off in sorted(positions.items())],
                )
            await asyncio.sleep(0.05)
        await c.leave_group(group, member)
    except (ProbeError, ConnectionError, asyncio.IncompleteReadError):
        stats["errors"] += 1  # e.g. stalled through the partition window
    finally:
        c.close()


async def _s3_client(cid, addr, cfg, rec, stats) -> None:
    c = _HttpClient(*addr)
    await c.connect()
    deadline = cfg["t0"] + cfg["run_secs"]
    nops = cfg["s3_ops"]
    gap = cfg["run_secs"] / max(1, nops)
    own = f"k{cid}"
    shared = f"shared{cid % cfg['s3_shared_keys']}"
    try:
        for n in range(nops):
            kind = n % 4
            use_shared = (n % 7) == 3
            keyname = shared if use_shared else own
            keyid = (cid % cfg["s3_shared_keys"]) if use_shared \
                else (cfg["s3_shared_keys"] + cid)
            if kind in (0, 2):  # put (multipart every other put)
                body = body_for(cid, n)
                fp = fingerprint(body)
                opid = rec.invoke(client=cid, op=OP_PUT, key=keyid, inp=fp)
                if kind == 2 and not use_shared:
                    ok = await _s3_multipart(c, keyname, body)
                else:
                    status, _b, _h = await c.request(
                        "PUT", f"/{BUCKET}/{keyname}", body
                    )
                    ok = status == 200
                if ok:
                    rec.complete(client=cid, opid=opid, out=fp)
                    stats["s3_ops"] += 1
                else:
                    stats["errors"] += 1
            elif kind == 1:  # get
                opid = rec.invoke(client=cid, op=OP_GET, key=keyid, inp=0)
                status, rsp_body, _h = await c.request(
                    "GET", f"/{BUCKET}/{keyname}"
                )
                if status == 200:
                    rec.complete(client=cid, opid=opid,
                                 out=fingerprint(rsp_body))
                    stats["s3_ops"] += 1
                elif status == 404:
                    rec.complete(client=cid, opid=opid, out=-1)
                    stats["s3_ops"] += 1
                else:
                    stats["errors"] += 1
            else:  # delete (own key only: shared deletes thrash GETs)
                if use_shared:
                    continue
                opid = rec.invoke(client=cid, op=OP_DEL, key=keyid, inp=0)
                status, _b, _h = await c.request(
                    "DELETE", f"/{BUCKET}/{own}"
                )
                if status in (200, 204):
                    rec.complete(client=cid, opid=opid, out=0)
                    stats["s3_ops"] += 1
                else:
                    stats["errors"] += 1
            now = _walltime.time()
            if now < deadline:
                await asyncio.sleep(min(gap, deadline - now))
    except (ConnectionError, asyncio.IncompleteReadError):
        stats["errors"] += 1
    finally:
        c.close()


async def _s3_multipart(c: _HttpClient, key: str, body: bytes) -> bool:
    """The multipart lifecycle: create → 2 parts → complete. The fsync
    stall hits exactly these requests."""
    status, rsp, _h = await c.request("POST", f"/load/{key}?uploads")
    if status != 200:
        return False
    upload_id = rsp.split(b"<UploadId>")[1].split(b"</UploadId>")[0]
    uid = upload_id.decode()
    half = len(body) // 2
    for part, chunk in ((1, body[:half]), (2, body[half:])):
        status, _b, _h = await c.request(
            "PUT", f"/load/{key}?partNumber={part}&uploadId={uid}", chunk
        )
        if status != 200:
            return False
    xml = (
        "<CompleteMultipartUpload>"
        "<Part><PartNumber>1</PartNumber></Part>"
        "<Part><PartNumber>2</PartNumber></Part>"
        "</CompleteMultipartUpload>"
    ).encode()
    status, _b, _h = await c.request(
        "POST", f"/load/{key}?uploadId={uid}", xml
    )
    return status == 200


async def _etcd_client(cid, addr, cfg, rec, stats) -> None:
    from ..real import etcd as retcd

    client = await retcd.Client.connect([f"{addr[0]}:{addr[1]}"])
    deadline = cfg["t0"] + cfg["run_secs"]
    nops = cfg["etcd_ops"]
    gap = cfg["run_secs"] / max(1, nops)
    own_key = 1_000_000 + cid
    shared_key = cid % cfg["etcd_shared_keys"]
    try:
        for n in range(nops):
            use_shared = (n % 5) == 2
            keyid = shared_key if use_shared else own_key
            wkey = b"key%d" % keyid
            if n % 2 == 0:
                val = (cid * 1000 + n) & _I31
                opid = rec.invoke(client=cid, op=OP_PUT, key=keyid, inp=val)
                await client.put(wkey, b"%d" % val)
                rec.complete(client=cid, opid=opid, out=val)
            else:
                opid = rec.invoke(client=cid, op=OP_GET, key=keyid, inp=0)
                rsp = await client.get(wkey)
                kvs = rsp.kvs()
                out = int(kvs[0].value) & _I31 if kvs else -1
                rec.complete(client=cid, opid=opid, out=out)
            stats["etcd_ops"] += 1
            now = _walltime.time()
            if now < deadline:
                await asyncio.sleep(min(gap, deadline - now))
    except Exception:  # noqa: BLE001 — a dropped client is load, not a bug
        stats["errors"] += 1


async def _worker_async(widx: int, cfg: dict, addrs: dict,
                        out: dict) -> None:
    from ..oracle.history import HostRecorder

    clock = _walltime.time_ns
    recs = {w: HostRecorder(clock=clock) for w in ("kafka", "s3", "etcd")}
    stats = {"kafka_ops": 0, "s3_ops": 0, "etcd_ops": 0, "errors": 0}
    tasks = []
    loop = asyncio.get_running_loop()

    for role, cid in cfg["roles"]:
        if role == "kprod":
            coro = _kafka_producer(cid, addrs["kafka"], cfg,
                                   recs["kafka"], stats)
        elif role.startswith("kcons"):
            _, gidx, late = role.split(":")
            coro = _kafka_consumer(
                cid, addrs["kafka"], cfg, recs["kafka"], stats,
                group=f"{GROUP_PREFIX}-{gidx}", late=late == "1",
            )
        elif role == "s3":
            coro = _s3_client(cid, addrs["s3"], cfg, recs["s3"], stats)
        else:
            coro = _etcd_client(cid, addrs["etcd"], cfg,
                                recs["etcd"], stats)
        tasks.append(loop.create_task(coro))
        if len(tasks) % 32 == 0:
            await asyncio.sleep(0)  # stagger the connect surge

    grace = cfg["run_secs"] * 3 + 30
    done, pending = await asyncio.wait(tasks, timeout=grace)
    for t in pending:
        t.cancel()
        stats["errors"] += 1
    for t in done:
        if t.exception() is not None:
            stats["errors"] += 1

    out["rows"] = {w: list(recs[w]._rows) for w in recs}
    out["stats"] = stats
    out["open"] = {
        w: len(recs[w]._open) for w in recs
    }


def _worker_main(widx: int, cfg: dict, addrs: dict, q) -> None:
    # forked from inside the parent's running event loop: clear the
    # inherited thread-local "a loop is running" marker or asyncio.run
    # refuses to start, and drop the inherited loop object
    import asyncio.events as _ev

    _ev._set_running_loop(None)
    asyncio.set_event_loop(None)
    out: dict = {"widx": widx}
    try:
        asyncio.run(_worker_async(widx, cfg, addrs, out))
    except Exception as e:  # noqa: BLE001 — report, don't hang the rig
        out["fatal"] = repr(e)
        out.setdefault("rows", {"kafka": [], "s3": [], "etcd": []})
        out.setdefault("stats", {"kafka_ops": 0, "s3_ops": 0,
                                 "etcd_ops": 0, "errors": 1})
    q.put(out)


# ---------------------------------------------------------------------------
# history assembly + checking (parent)


def merge_history(all_rows: List[tuple], seed: int):
    """Merge per-worker HostRecorder rows — (client, code, key, val,
    opid, t_ns) — into one checkable History. Client ids are globally
    unique, so pairing is safe; rows sort by wall time (one shared
    machine clock), tie-broken deterministically."""
    import numpy as np

    rows = sorted(all_rows, key=lambda r: (r[5], r[0], r[1], r[4]))
    if not rows:
        return decode_rows(
            np.zeros((0, 5), dtype=np.int32),
            np.zeros((0,), dtype=np.int64), 0, False, seed=seed,
        )
    rec = np.asarray([r[:5] for r in rows], dtype=np.int32)
    t = np.asarray([r[5] for r in rows], dtype=np.int64)
    return decode_rows(rec, t, len(rows), False, seed=seed)


def check_wire_histories(histories: dict, max_states: int = 200_000) -> dict:
    """Run each wire's history against its sequential spec."""
    specs = {"kafka": LogSpec(), "s3": S3Spec(), "etcd": KVSpec()}
    out = {}
    for wire, hist in histories.items():
        result = check_history(hist, specs[wire], max_states=max_states)
        out[wire] = {
            "ops": len(hist.ops),
            "ok": bool(result.ok),
            "decided": bool(result.decided),
            "reason": result.reason if not result.ok else "",
        }
    return out


# ---------------------------------------------------------------------------
# the scenario driver


def plan_roles(cfg: dict) -> List[List[Tuple[str, int]]]:
    """Assign (role, client_id) pairs round-robin to workers. Client ids
    are globally unique across every wire and worker."""
    roles: List[Tuple[str, int]] = []
    cid = 0
    for _ in range(cfg["kafka_producers"]):
        roles.append(("kprod", cid)); cid += 1
    for g in range(cfg["kafka_groups"]):
        for m in range(cfg["kafka_members"]):
            late = 1 if m == cfg["kafka_members"] - 1 else 0
            roles.append((f"kcons:{g}:{late}", cid)); cid += 1
    for _ in range(cfg["s3_clients"]):
        roles.append(("s3", cid)); cid += 1
    for _ in range(cfg["etcd_clients"]):
        roles.append(("etcd", cid)); cid += 1
    per: List[List[Tuple[str, int]]] = [
        [] for _ in range(cfg["workers"])
    ]
    for i, rc in enumerate(roles):
        per[i % cfg["workers"]].append(rc)
    return per


def _chaos_child(cfg: dict, q) -> None:
    """``chaos_plan`` in a forked child: ``compile_host`` imports jax,
    and jax's thread pools must never exist in the parent that later
    forks the load workers (fork + threads = deadlock risk)."""
    q.put(chaos_plan(cfg))


def chaos_plan(cfg: dict) -> dict:
    """Derive the gray-failure windows from a compiled FaultSpec
    schedule — the same host-fault vocabulary the sim tier uses
    (``faults.compile_host``), so window times and victims are a pure
    function of the seed."""
    from .. import faults as hfaults
    from ..engine.faults import FaultSpec

    spec = FaultSpec(
        spikes=2,
        spike_window_ns=int(cfg["run_secs"] * 1e9),
        spike_dur_lo_ns=int(cfg["run_secs"] * 0.08e9),
        spike_dur_hi_ns=int(cfg["run_secs"] * 0.2e9),
        spike_lat_lo_ns=1, spike_lat_hi_ns=2,
    )
    events = hfaults.compile_host(spec, num_nodes=2, seed=cfg["seed"])
    window = int(cfg["run_secs"] * 1e9)
    starts = sorted(
        t_ns for t_ns, _a, _v in events
    ) or [window // 3, window // 2]
    victims = [v for _t, _a, v in events] or [0, 1]
    frac = max(0.15, min(0.6, starts[0] / window))
    return {
        "partition_at": frac,
        "partition_dur": max(0.5, cfg["run_secs"] * 0.15),
        "partition_parity": victims[0] % 2,
        "fsync_at": max(0.2, min(0.7, starts[-1] / window)),
        "fsync_dur": max(0.5, cfg["run_secs"] * 0.12),
        "fsync_stall": 0.2,
        "events": len(events),
    }


DEFAULT_SCENARIO = dict(
    kafka_producers=480,
    kafka_groups=9,
    kafka_members=8,
    kafka_records=6,
    partitions=64,
    s3_clients=416,
    s3_ops=8,
    s3_shared_keys=16,
    etcd_clients=88,
    etcd_ops=8,
    etcd_shared_keys=8,
    workers=4,
    run_secs=20.0,
    seed=0,
)

SMOKE_SCENARIO = dict(
    kafka_producers=24,
    kafka_groups=2,
    kafka_members=4,
    kafka_records=4,
    partitions=8,
    s3_clients=20,
    s3_ops=6,
    s3_shared_keys=4,
    etcd_clients=12,
    etcd_ops=6,
    etcd_shared_keys=4,
    workers=2,
    run_secs=4.0,
    seed=0,
)


def total_clients(cfg: dict) -> int:
    return (cfg["kafka_producers"]
            + cfg["kafka_groups"] * cfg["kafka_members"]
            + cfg["s3_clients"] + cfg["etcd_clients"])


async def _run_load_async(cfg: dict, server_kind: str) -> dict:
    from ..kafka.probe import ProbeClient, RealTransport

    cluster = Cluster(server_kind=server_kind)
    addrs = await cluster.start()

    # topic setup before any client connects
    setup = ProbeClient(await RealTransport.connect(addrs["kafka"]))
    await setup.create_topics([(TOPIC, cfg["partitions"])])
    setup.close()
    s3setup = _HttpClient(*addrs["s3"])
    await s3setup.connect()
    await s3setup.request("PUT", f"/{BUCKET}")
    s3setup.close()

    # the chaos schedule compiles in a child process: the parent must
    # stay jax-free so forking the load workers below is safe
    ctx = mp.get_context("fork")
    q0 = ctx.Queue()
    p0 = ctx.Process(target=_chaos_child, args=(cfg, q0), daemon=True)
    p0.start()
    chaos = q0.get(timeout=300)
    p0.join(timeout=10)
    cfg = dict(cfg, t0=_walltime.time(), chaos_at=chaos["partition_at"])

    q = ctx.Queue()
    per_worker = plan_roles(cfg)
    procs = []
    for widx, roles in enumerate(per_worker):
        wcfg = dict(cfg, roles=roles)
        p = ctx.Process(
            target=_worker_main, args=(widx, wcfg, addrs, q), daemon=True
        )
        p.start()
        procs.append(p)

    # chaos scheduler + connection peak sampler in the serving loop
    peak = {"conns": 0}
    stall_counts = {"partition": 0}

    async def sampler():
        while True:
            gauge = cluster.registry.metric("serve_connections_open")
            if gauge is not None:
                open_now = int(sum(v for _k, v in gauge.series()))
                peak["conns"] = max(peak["conns"], open_now)
            await asyncio.sleep(0.05)

    async def chaos_task():
        await asyncio.sleep(cfg["run_secs"] * chaos["partition_at"])
        stall_counts["partition"] = cluster.inject_partition(
            chaos["partition_dur"], chaos["partition_parity"]
        )
        delta = cfg["run_secs"] * (chaos["fsync_at"]
                                   - chaos["partition_at"])
        await asyncio.sleep(max(0.0, delta))
        cluster.set_fsync_stall(chaos["fsync_stall"])
        await asyncio.sleep(chaos["fsync_dur"])
        cluster.set_fsync_stall(0.0)

    sam = asyncio.get_running_loop().create_task(sampler())
    cha = asyncio.get_running_loop().create_task(chaos_task())

    # collect worker results without blocking the serving loop
    results = []
    deadline = _walltime.time() + cfg["run_secs"] * 6 + 60
    while len(results) < len(procs) and _walltime.time() < deadline:
        try:
            results.append(q.get_nowait())
        except Exception:  # queue.Empty
            await asyncio.sleep(0.1)
    for p in procs:
        p.join(timeout=5)
        if p.is_alive():
            p.terminate()
    sam.cancel()
    cha.cancel()
    elapsed = _walltime.time() - cfg["t0"]

    # merge + check histories
    merged = {w: [] for w in ("kafka", "s3", "etcd")}
    stats = {"kafka_ops": 0, "s3_ops": 0, "etcd_ops": 0, "errors": 0}
    fatals = []
    for res in results:
        for w in merged:
            merged[w].extend(res.get("rows", {}).get(w, []))
        for k in stats:
            stats[k] += res.get("stats", {}).get(k, 0)
        if res.get("fatal"):
            fatals.append(res["fatal"])
    histories = {
        w: merge_history(rows, cfg["seed"]) for w, rows in merged.items()
    }
    checks = check_wire_histories(histories)

    kafka_frames, kafka_replay_ok = cluster.replay_kafka()
    s3_frames, s3_replay_ok = cluster.replay_s3()
    slo = cluster.slo_report(elapsed)
    await cluster.stop()

    total_ops = stats["kafka_ops"] + stats["s3_ops"] + stats["etcd_ops"]
    return {
        "server": server_kind,
        "seed": cfg["seed"],
        "clients": total_clients(cfg),
        "workers": cfg["workers"],
        "elapsed_s": round(elapsed, 2),
        "total_ops": total_ops,
        "throughput_ops_s": round(total_ops / elapsed, 2) if elapsed else 0,
        "peak_open_conns": peak["conns"],
        "stats": stats,
        "missing_workers": len(procs) - len(results),
        "fatals": fatals,
        "chaos": dict(chaos, partition_stalled=stall_counts["partition"]),
        "history_checks": checks,
        "histories_ok": all(c["ok"] for c in checks.values()),
        "replay": {
            "kafka_frames": kafka_frames,
            "kafka_ok": kafka_replay_ok,
            "s3_requests": s3_frames,
            "s3_ok": s3_replay_ok,
        },
        "replay_ok": kafka_replay_ok and s3_replay_ok,
        "slo": slo,
    }


def run_load(cfg: Optional[dict] = None, server_kind: str = "async") -> dict:
    """Run the full multi-process load scenario; returns the report."""
    merged = dict(DEFAULT_SCENARIO)
    merged.update(cfg or {})
    return asyncio.run(_run_load_async(merged, server_kind))
