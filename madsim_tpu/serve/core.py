"""The async wire-serving core: one event loop multiplexing thousands
of connections for every real-TCP wire tier.

Before this package each wire owned its own accept loop and spawned one
task (or stream-callback pair) per connection with unbounded write
buffering — fine at demo scale, hopeless at thousands of clients and
impossible to give uniform backpressure or lifecycle metrics. The core
inverts that: **one** ``asyncio.Protocol``-based server owns

- framing/reassembly (pluggable per-wire framer, ``serve/framing.py``),
- per-connection state and lifecycle (``Conn``),
- write-side backpressure: transport-paused output spills into a
  **bounded** per-connection queue; a slow client that exceeds the bound
  is evicted (``serve_slow_client_drops_total``) instead of growing the
  heap,
- read-side backpressure: connections whose output backlog (or whose
  adapter-side inbox) is over the threshold stop being read
  (``transport.pause_reading``) until they drain,
- connection/byte/frame metrics through ``obs.Telemetry`` — strictly
  out-of-band, like every PR-14 plane,
- clean shutdown: stop accepting, let in-flight handlers finish, flush
  write queues, then close.

Wires plug in through a :class:`WireAdapter`: ``on_frame(conn, frame)``
returns response bytes (the pure ``handle_frame`` shape — Kafka, S3) or
a coroutine (dispatched in order per connection — the framed etcd/gRPC
tiers), and may push out-of-order/streamed responses at any time via
``conn.send``. ``serve/adapters.py`` holds the three adapter shapes;
the per-wire modules keep only protocol logic.

Optionally the listener shards across N event loops (``shards=``): each
shard binds its own ``SO_REUSEPORT`` socket on a daemon-thread loop and
the kernel spreads accepts across them. Because the served state
machines (Broker/S3Service/EtcdService) are single-writer, sharded
dispatch serializes ``on_frame`` under one lock — shards parallelize
framing and socket I/O, not state-machine work — and is limited to
adapters whose handlers are synchronous.
"""

from __future__ import annotations

import asyncio
import socket
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .framing import FramingError

__all__ = [
    "AsyncWireServer",
    "Conn",
    "DropConnection",
    "WireAdapter",
]


class DropConnection(Exception):
    """Raised by an adapter to hard-drop the connection (protocol
    violation semantics: the peer sees a reset, not a clean EOF)."""


class WireAdapter:
    """What a wire plugs into the core. Subclasses override:

    - ``name`` — the ``wire=`` metric label;
    - ``new_framer()`` — per-connection framer (``feed(bytes)->list``);
    - ``on_frame(conn, frame)`` — one protocol unit. Return response
      ``bytes`` (written through the bounded queue), ``None`` (no
      response), or a coroutine (awaited in order per connection; its
      return value, if bytes, is written). Raise :class:`DropConnection`
      (or an exception listed in ``drop_errors``) to hard-drop.
    - ``on_connect(conn)`` / ``on_eof(conn)`` / ``on_disconnect(conn,
      exc)`` — lifecycle. Default EOF behavior closes the connection
      after pending responses flush (the task-per-conn servers' shape).
    """

    name = "wire"
    #: exception types from ``on_frame`` that mean "protocol violation:
    #: drop the connection" rather than "bug: log and drop anyway"
    drop_errors: Tuple[type, ...] = ()

    def new_framer(self):
        raise NotImplementedError

    def on_connect(self, conn: "Conn") -> None:
        pass

    def on_frame(self, conn: "Conn", frame) -> Any:
        raise NotImplementedError

    def on_eof(self, conn: "Conn") -> None:
        conn.close()

    def on_disconnect(self, conn: "Conn", exc: Optional[Exception]) -> None:
        pass


class Conn:
    """One live connection: bounded write queue + pause bookkeeping.

    ``send`` never blocks: while the transport is writable it writes
    through; once the transport pauses us, output queues up to
    ``max_queue_bytes`` and an overflowing (slow) client is evicted.
    Adapters needing sender-side backpressure await :meth:`drained`.
    """

    __slots__ = (
        "server", "transport", "wire", "id", "peer", "state",
        "_writable", "_q", "_q_bytes", "_closing", "closed",
        "_pauses", "_drain_waiters", "inflight", "framer", "loop",
    )

    def __init__(self, server: "AsyncWireServer", transport, conn_id: int,
                 loop) -> None:
        self.server = server
        self.transport = transport
        self.wire = server.adapter.name
        self.id = conn_id
        self.peer = (transport.get_extra_info("peername") or ("?", 0))[:2]
        self.state: Any = None  # adapter-owned slot
        self.loop = loop
        self._writable = True
        self._q: List[bytes] = []
        self._q_bytes = 0
        self._closing = False
        self.closed = False
        self._pauses: set = set()
        self._drain_waiters: List[asyncio.Future] = []
        self.inflight = 0  # async handlers pending on this conn
        self.framer = server.adapter.new_framer()

    # -- write side ---------------------------------------------------------

    def send(self, data: bytes) -> None:
        """Queue one response; raises ``BrokenPipeError`` if the
        connection is already gone (matches the pipe-sender contract)."""
        if self.closed or self._closing:
            raise BrokenPipeError("connection closed")
        srv = self.server
        if srv.telemetry is not None:
            srv.telemetry.count(
                "serve_bytes_out_total", len(data),
                help="bytes written by the serving core", wire=self.wire,
            )
        if self._writable and not self._q:
            self.transport.write(data)
            return
        self._q.append(data)
        self._q_bytes += len(data)
        if self._q_bytes > srv.max_queue_bytes:
            if srv.telemetry is not None:
                srv.telemetry.count(
                    "serve_slow_client_drops_total",
                    help="connections evicted for unread output backlog",
                    wire=self.wire,
                )
            self.abort()
            return
        if self._q_bytes > srv.read_pause_bytes:
            self.pause_reading("write-backlog")

    def _flush(self) -> None:
        """Drain the queue into a resumed transport."""
        while self._q and self._writable:
            self.transport.write(self._q.pop(0))
        if not self._q:
            if self._q_bytes:
                self._q_bytes = 0
            self.resume_reading("write-backlog")
            for f in self._drain_waiters:
                if not f.done():
                    f.set_result(None)
            self._drain_waiters.clear()
            if self._closing and not self.closed:
                self.transport.close()
        else:
            self._q_bytes = sum(len(b) for b in self._q)

    async def drained(self) -> None:
        """Resolve once the bounded queue is empty (sender-side
        backpressure for streaming adapters)."""
        if not self._q or self.closed:
            return
        f = self.loop.create_future()
        self._drain_waiters.append(f)
        await f

    # -- read-side pause bookkeeping ---------------------------------------

    def pause_reading(self, reason: str) -> None:
        if self.closed:
            return
        first = not self._pauses
        self._pauses.add(reason)
        if first:
            try:
                self.transport.pause_reading()
            except RuntimeError:  # pragma: no cover - transport closing
                return
            if self.server.telemetry is not None:
                self.server.telemetry.count(
                    "serve_backpressure_pauses_total",
                    help="read pauses applied by the serving core",
                    wire=self.wire,
                )

    def resume_reading(self, reason: str) -> None:
        if reason not in self._pauses:
            return
        self._pauses.discard(reason)
        if not self._pauses and not self.closed:
            try:
                self.transport.resume_reading()
            except RuntimeError:  # pragma: no cover - transport closing
                pass

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Flush queued output, then close (clean EOF to the peer)."""
        if self.closed or self._closing:
            return
        self._closing = True
        if not self._q:
            self.transport.close()

    def abort(self) -> None:
        """Hard-drop: the peer sees a reset; queued output is gone."""
        if self.closed:
            return
        self._closing = True
        self._q.clear()
        self._q_bytes = 0
        try:
            self.transport.abort()
        except Exception:  # pragma: no cover - transport already detached
            pass


class _WireProtocol(asyncio.Protocol):
    """The one protocol class every core-served connection runs."""

    def __init__(self, server: "AsyncWireServer", loop) -> None:
        self.server = server
        self.loop = loop
        self.conn: Optional[Conn] = None
        self._tasks: List = []  # pending coroutines (ordered)
        self._drainer: Optional[asyncio.Task] = None

    # -- transport callbacks ------------------------------------------------

    def connection_made(self, transport) -> None:
        srv = self.server
        self.conn = conn = Conn(srv, transport, srv._next_conn_id(), self.loop)
        srv._register(conn)
        if srv.telemetry is not None:
            srv.telemetry.count(
                "serve_connections_total",
                help="connections accepted by the serving core",
                wire=conn.wire,
            )
            srv.telemetry.gauge(
                "serve_connections_open", srv.open_conns(),
                help="currently open connections", wire=conn.wire,
            )
        try:
            srv.adapter.on_connect(conn)
        except Exception:
            conn.abort()

    def data_received(self, data: bytes) -> None:
        conn = self.conn
        srv = self.server
        if conn is None or conn.closed:
            return
        if srv.telemetry is not None:
            srv.telemetry.count(
                "serve_bytes_in_total", len(data),
                help="bytes read by the serving core", wire=conn.wire,
            )
        try:
            frames = conn.framer.feed(data)
        except FramingError:
            conn.abort()
            return
        for f in frames:
            if conn.closed:
                return
            self._dispatch(f)

    def _dispatch(self, frame) -> None:
        conn = self.conn
        srv = self.server
        if srv.telemetry is not None:
            srv.telemetry.count(
                "serve_frames_total",
                help="protocol units dispatched by the serving core",
                wire=conn.wire,
            )
        try:
            if srv._dispatch_lock is not None:
                with srv._dispatch_lock:
                    result = srv.adapter.on_frame(conn, frame)
            else:
                result = srv.adapter.on_frame(conn, frame)
        except DropConnection:
            conn.abort()
            return
        except srv.adapter.drop_errors:
            conn.abort()
            return
        if result is None:
            return
        if isinstance(result, (bytes, bytearray, memoryview)):
            try:
                conn.send(bytes(result))
            except BrokenPipeError:
                pass
            return
        # a coroutine: run in arrival order on this connection
        self._tasks.append(result)
        conn.inflight += 1
        srv._inflight_inc()
        if len(self._tasks) > srv.max_inflight_frames:
            conn.pause_reading("handler-backlog")
        if self._drainer is None or self._drainer.done():
            self._drainer = self.loop.create_task(self._drain_tasks())

    async def _drain_tasks(self) -> None:
        conn = self.conn
        srv = self.server
        while self._tasks:
            coro = self._tasks.pop(0)
            try:
                result = await coro
                if isinstance(result, (bytes, bytearray, memoryview)):
                    conn.send(bytes(result))
            except DropConnection:
                conn.abort()
            except srv.adapter.drop_errors:
                conn.abort()
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                conn.inflight -= 1
                srv._inflight_dec()
            if len(self._tasks) <= srv.max_inflight_frames:
                conn.resume_reading("handler-backlog")

    def eof_received(self) -> Optional[bool]:
        if self.conn is not None and not self.conn.closed:
            try:
                self.server.adapter.on_eof(self.conn)
            except Exception:
                self.conn.abort()
        return True  # keep the write half open until we flush

    def connection_lost(self, exc: Optional[Exception]) -> None:
        conn = self.conn
        if conn is None:
            return
        conn.closed = True
        srv = self.server
        srv._unregister(conn)
        for f in conn._drain_waiters:
            if not f.done():
                f.set_result(None)
        conn._drain_waiters.clear()
        for coro in self._tasks:  # never awaited: close, do not leak
            coro.close()
            conn.inflight -= 1
            srv._inflight_dec()
        self._tasks.clear()
        if srv.telemetry is not None:
            srv.telemetry.gauge(
                "serve_connections_open", srv.open_conns(),
                help="currently open connections", wire=conn.wire,
            )
        try:
            srv.adapter.on_disconnect(conn, exc)
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass

    def pause_writing(self) -> None:
        if self.conn is not None:
            self.conn._writable = False

    def resume_writing(self) -> None:
        if self.conn is not None:
            self.conn._writable = True
            self.conn._flush()


class _Shard:
    """One extra listener loop on a daemon thread (SO_REUSEPORT)."""

    def __init__(self, server: "AsyncWireServer", sock: socket.socket):
        self.server = server
        self.sock = sock
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self._run, name=f"serve-shard-{server.adapter.name}",
            daemon=True,
        )
        self._srv: Optional[asyncio.AbstractServer] = None

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)

        async def _bind():
            self._srv = await self.loop.create_server(
                lambda: _WireProtocol(self.server, self.loop), sock=self.sock
            )

        self.loop.run_until_complete(_bind())
        self.loop.run_forever()
        # drain callbacks queued by stop(), then close
        self.loop.run_until_complete(asyncio.sleep(0))
        self.loop.close()

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        def _close():
            if self._srv is not None:
                self._srv.close()
            self.loop.stop()

        try:
            self.loop.call_soon_threadsafe(_close)
        except RuntimeError:  # pragma: no cover - loop already closed
            pass
        self.thread.join(timeout=5)


def _reuseport_socket(host: str, port: int) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind((host, port))
    s.listen(1024)
    s.setblocking(False)
    return s


class AsyncWireServer:
    """The shared serving core: one adapter, one (optionally sharded)
    listener, uniform backpressure/lifecycle/metrics."""

    def __init__(
        self,
        adapter: WireAdapter,
        *,
        telemetry=None,
        shards: int = 1,
        max_queue_bytes: int = 8 * 1024 * 1024,
        read_pause_bytes: int = 1 * 1024 * 1024,
        max_inflight_frames: int = 64,
    ):
        if shards > 1 and getattr(adapter, "async_handlers", False):
            raise ValueError(
                "loop shards require synchronous adapter handlers (the "
                "dispatch lock cannot serialize coroutines across loops)"
            )
        self.adapter = adapter
        self.telemetry = telemetry
        self.shards = max(1, int(shards))
        self.max_queue_bytes = max_queue_bytes
        self.read_pause_bytes = read_pause_bytes
        self.max_inflight_frames = max_inflight_frames
        self.bound_addr: Optional[Tuple[str, int]] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._shards: List[_Shard] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stopped: Optional[asyncio.Event] = None
        self._conns: Dict[int, Conn] = {}
        self._conn_lock = threading.Lock()
        self._conn_seq = 0
        self._inflight = 0
        self._dispatch_lock: Optional[threading.Lock] = None

    # -- registry (thread-safe: shards touch it too) ------------------------

    def _next_conn_id(self) -> int:
        with self._conn_lock:
            self._conn_seq += 1
            return self._conn_seq

    def _register(self, conn: Conn) -> None:
        with self._conn_lock:
            self._conns[conn.id] = conn

    def _unregister(self, conn: Conn) -> None:
        with self._conn_lock:
            self._conns.pop(conn.id, None)

    def _inflight_inc(self) -> None:
        with self._conn_lock:
            self._inflight += 1

    def _inflight_dec(self) -> None:
        with self._conn_lock:
            self._inflight -= 1

    def open_conns(self) -> int:
        with self._conn_lock:
            return len(self._conns)

    def connections(self) -> List[Conn]:
        with self._conn_lock:
            return list(self._conns.values())

    # -- lifecycle ----------------------------------------------------------

    async def start(self, addr: "str | tuple") -> Tuple[str, int]:
        from ..real.stream import parse_addr

        host, port = parse_addr(addr)
        self._loop = asyncio.get_running_loop()
        self._stopped = asyncio.Event()
        if self.shards > 1:
            self._dispatch_lock = threading.Lock()
            sock0 = _reuseport_socket(host, port)
            self.bound_addr = sock0.getsockname()[:2]
            self._server = await self._loop.create_server(
                lambda: _WireProtocol(self, self._loop), sock=sock0
            )
            for _ in range(self.shards - 1):
                shard = _Shard(
                    self, _reuseport_socket(*self.bound_addr)
                )
                self._shards.append(shard)
                shard.start()
        else:
            self._server = await self._loop.create_server(
                lambda: _WireProtocol(self, self._loop), host, port
            )
            self.bound_addr = self._server.sockets[0].getsockname()[:2]
        return self.bound_addr

    async def serve(self, addr: "str | tuple") -> None:
        """Bind and serve until :meth:`close` — the drop-in shape the
        per-wire servers expose."""
        await self.start(addr)
        try:
            await self._stopped.wait()
        finally:
            self._teardown()

    def close(self) -> None:
        """Stop accepting and wake :meth:`serve`; open connections are
        dropped by the serve task's teardown (call :meth:`aclose` for a
        draining shutdown instead)."""
        if self._server is not None:
            self._server.close()
        for shard in self._shards:
            shard.stop()
        self._shards = []
        if self._stopped is not None and self._loop is not None:
            if self._loop.is_running():
                self._loop.call_soon_threadsafe(self._stopped.set)
            else:  # pragma: no cover - loop already torn down
                self._stopped.set()

    def _teardown(self) -> None:
        for conn in self.connections():
            conn.abort()

    async def aclose(self, drain_timeout: float = 5.0) -> None:
        """Graceful shutdown: stop accepting, wait for in-flight frame
        handlers, flush write queues, then close every connection."""
        if self._server is not None:
            self._server.close()
        for shard in self._shards:
            shard.stop()
        self._shards = []
        deadline = self._loop.time() + drain_timeout
        while self._loop.time() < deadline:
            with self._conn_lock:
                busy = self._inflight > 0 or any(
                    c._q for c in self._conns.values()
                )
            if not busy:
                break
            await asyncio.sleep(0.01)
        for conn in self.connections():
            conn.close()
        if self._stopped is not None:
            self._stopped.set()

    # -- gray-failure injection --------------------------------------------

    def inject_read_stall(
        self,
        duration: float,
        match: Optional[Callable[[Conn], bool]] = None,
    ) -> int:
        """Asymmetric-partition chaos: stop READING the matched
        connections for ``duration`` seconds while their write half
        stays live (the server can still talk to them — inbound is
        blackholed, the gray-failure shape). Returns how many
        connections were stalled."""
        stalled = [
            c for c in self.connections()
            if not c.closed and (match is None or match(c))
        ]
        for c in stalled:
            c.pause_reading("chaos")
        if self.telemetry is not None and stalled:
            self.telemetry.count(
                "serve_chaos_stalls_total", len(stalled),
                help="connections read-stalled by fault injection",
                wire=self.adapter.name,
            )

        def _heal() -> None:
            for c in stalled:
                c.resume_reading("chaos")

        self._loop.call_later(duration, _heal)
        return len(stalled)
