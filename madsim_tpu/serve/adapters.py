"""The three adapter shapes that put every wire tier on the core.

- :class:`PureFrameAdapter` — 4-byte length-prefixed frames in, a pure
  ``handle_frame(bytes) -> bytes|None`` out (the Kafka binary wire; any
  framed request/response codec).
- :class:`HttpAdapter` — incremental HTTP/1.1 requests in, rendered
  response bytes out (the S3 REST wire), with an optional per-request
  stall hook for gray-failure injection (fsync stall: the handler's
  response is withheld for N seconds without blocking the loop).
- :class:`ChannelAdapter` — re-creates the sim tier's pull-style
  ``(tx, rx)`` pipe surface per connection and spawns the wire's
  existing ``conn_handler(tx, rx)`` coroutine over it, so dispatchers
  written against ``PipeSender``/``PipeReceiver`` semantics (the etcd
  request-enum server, framed gRPC) ride the core unchanged.

Adapters carry no I/O of their own: the core owns sockets, framing
buffers, bounded queues, and metrics; adapters own protocol meaning.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional, Tuple

from .core import Conn, WireAdapter
from .framing import (
    HttpRequest,
    HttpRequestFramer,
    LengthPrefixFramer,
    frame as _frame,
    render_http_response,
)

__all__ = [
    "ChannelAdapter",
    "ChannelReceiver",
    "ChannelSender",
    "HttpAdapter",
    "PureFrameAdapter",
]


class PureFrameAdapter(WireAdapter):
    """Length-prefixed frames dispatched to a pure sync handler.

    ``handler(frame: bytes) -> Optional[bytes]`` — the ``handle_frame``
    shape. The response body is length-prefixed on the way out.
    ``drop_errors`` lists handler exceptions meaning protocol violation
    (hard-drop, like a real broker). ``connect_hook`` lets the wire keep
    its legacy per-wire connection counter.
    """

    def __init__(
        self,
        handler: Callable[[bytes], Optional[bytes]],
        name: str = "frame",
        drop_errors: Tuple[type, ...] = (),
        connect_hook: Optional[Callable[[Conn], None]] = None,
    ):
        self.handler = handler
        self.name = name
        self.drop_errors = drop_errors
        self._connect_hook = connect_hook

    def new_framer(self) -> LengthPrefixFramer:
        return LengthPrefixFramer()

    def on_connect(self, conn: Conn) -> None:
        if self._connect_hook is not None:
            self._connect_hook(conn)

    def on_frame(self, conn: Conn, frame: bytes) -> Optional[bytes]:
        rsp = self.handler(frame)
        return None if rsp is None else _frame(rsp)


class HttpAdapter(WireAdapter):
    """HTTP/1.1 requests dispatched to a sync handler returning a
    rendered response.

    ``handler(req: HttpRequest) -> (status, body, headers)`` — rendering
    (Content-Length, HEAD body suppression) happens here so handlers
    stay pure. ``stall_hook(req) -> float`` seconds (0 = none) lets the
    load rig inject an fsync-style stall: the response is computed at
    its deterministic position in the request order but withheld without
    blocking other connections.
    """

    def __init__(
        self,
        handler: Callable[[HttpRequest], Tuple[int, bytes, dict]],
        name: str = "http",
        drop_errors: Tuple[type, ...] = (),
        connect_hook: Optional[Callable[[Conn], None]] = None,
    ):
        self.handler = handler
        self.name = name
        self.drop_errors = drop_errors
        self._connect_hook = connect_hook
        self.stall_hook: Optional[Callable[[HttpRequest], float]] = None

    def new_framer(self) -> HttpRequestFramer:
        return HttpRequestFramer()

    def on_connect(self, conn: Conn) -> None:
        if self._connect_hook is not None:
            self._connect_hook(conn)

    def on_frame(self, conn: Conn, req: HttpRequest) -> Any:
        status, body, headers = self.handler(req)
        rendered = render_http_response(
            status, body, headers, head_only=req.method == "HEAD"
        )
        delay = self.stall_hook(req) if self.stall_hook is not None else 0.0
        if delay and delay > 0:
            async def _stalled(data=rendered, d=delay):
                await asyncio.sleep(d)
                return data

            return _stalled()
        return rendered


# ---------------------------------------------------------------------------
# pull-style channel surface over a core connection


class ChannelSender:
    """``PipeSender``/``StreamSender`` semantics over a core ``Conn``."""

    __slots__ = ("_conn", "_codec", "_closed")

    def __init__(self, conn: Conn, codec):
        self._conn = conn
        self._codec = codec
        self._closed = False

    async def send(self, msg: object) -> None:
        if self._closed or self._conn.closed:
            raise BrokenPipeError("connection closed")
        self._conn.send(_frame(self._codec.dumps(msg)))
        # bounded-queue backpressure: a streaming sender waits for a
        # slow client instead of growing the heap (or being evicted)
        await self._conn.drained()

    def close(self) -> None:
        """Clean EOF: pending frames flush, then the peer sees FIN."""
        if self._closed:
            return
        self._closed = True
        self._conn.close()

    def is_closed(self) -> bool:
        return self._closed or self._conn.closed


class ChannelReceiver:
    """``PipeReceiver``/``StreamReceiver`` semantics over a core
    ``Conn``: ``None`` on clean EOF, ``ConnectionResetError`` on abort,
    ``close()`` hard-drops."""

    _EOF = object()
    _RESET = object()

    __slots__ = ("_conn", "_q", "_done")

    def __init__(self, conn: Conn):
        self._conn = conn
        self._q: "asyncio.Queue" = asyncio.Queue()
        self._done = False

    async def recv(self) -> Optional[object]:
        if self._done:
            return None
        item = await self._q.get()
        if self._q.qsize() <= ChannelAdapter.MAX_INBOX:
            self._conn.resume_reading("handler-backlog")
        if item is ChannelReceiver._EOF:
            self._done = True
            return None
        if item is ChannelReceiver._RESET:
            self._done = True
            raise ConnectionResetError("connection reset")
        return item

    def close(self) -> None:
        self._done = True
        self._conn.abort()


class ChannelAdapter(WireAdapter):
    """Run a pull-style ``conn_handler(tx, rx)`` per connection.

    ``conn_handler`` is the wire's existing dispatcher coroutine (e.g.
    ``etcd.server.SimServer._serve_conn``); ``codec`` provides
    ``dumps``/``loads`` (``real/codec.py``) and a decode failure drops
    the connection like any protocol violation.
    """

    #: decoded-but-unclaimed inbox bound before the read side pauses
    MAX_INBOX = 32

    def __init__(
        self,
        conn_handler: Callable[..., Any],
        codec,
        name: str = "channel",
        connect_hook: Optional[Callable[[Conn], None]] = None,
    ):
        self.conn_handler = conn_handler
        self.codec = codec
        self.name = name
        self._connect_hook = connect_hook

    def new_framer(self) -> LengthPrefixFramer:
        return LengthPrefixFramer()

    def on_connect(self, conn: Conn) -> None:
        if self._connect_hook is not None:
            self._connect_hook(conn)
        tx = ChannelSender(conn, self.codec)
        rx = ChannelReceiver(conn)
        task = conn.loop.create_task(self._run(conn, tx, rx))
        conn.state = (rx, task)

    async def _run(self, conn: Conn, tx: ChannelSender,
                   rx: ChannelReceiver) -> None:
        try:
            await self.conn_handler(tx, rx)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception:  # noqa: BLE001 — a handler bug drops one conn
            conn.abort()

    def on_frame(self, conn: Conn, frame: bytes) -> None:
        rx, _task = conn.state
        try:
            obj = self.codec.loads(frame)
        except Exception:
            # protocol violation: kill the connection, like StreamReceiver
            conn.abort()
            return
        rx._q.put_nowait(obj)
        if rx._q.qsize() > ChannelAdapter.MAX_INBOX:
            conn.pause_reading("handler-backlog")

    def on_eof(self, conn: Conn) -> None:
        rx, _task = conn.state
        rx._q.put_nowait(ChannelReceiver._EOF)
        # the write half stays open: the handler may still be streaming

    def on_disconnect(self, conn: Conn, exc) -> None:
        if conn.state is None:
            return
        rx, _task = conn.state
        rx._q.put_nowait(ChannelReceiver._RESET)
