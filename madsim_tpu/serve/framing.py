"""Wire framing for the async serving core: incremental reassembly.

A framer owns one connection's read-side byte stream and turns arbitrary
chunk boundaries back into protocol units — the core calls ``feed`` with
whatever the transport delivered and dispatches each completed unit to
the wire adapter. Two framings cover every wire this repo serves:

- :class:`LengthPrefixFramer` — the repo-wide 4-byte big-endian length
  convention (``real/stream.py``), which is also exactly Kafka's binary
  framing, so the genuine Kafka wire and the framed-codec transports
  (etcd request enums, framed gRPC) share one parser;
- :class:`HttpRequestFramer` — a minimal incremental HTTP/1.1 request
  parser (request line + headers + Content-Length body, keep-alive),
  the S3 REST wire's transport.

Both are pure per-connection state machines: no I/O, no clocks — which
is what keeps the served responses a function of (request bytes, clock)
and the live-vs-replay byte-identity gate meaningful through the core.
"""

from __future__ import annotations

import struct
import urllib.parse
from typing import Dict, List, Optional

_LEN = struct.Struct(">I")

#: sanity ceiling shared with real/stream.py — not a protocol limit
MAX_FRAME = 64 * 1024 * 1024


class FramingError(Exception):
    """Bytes this framer refuses to parse — the connection is dropped
    hard, like a protocol violation on a real wire."""


class LengthPrefixFramer:
    """Reassemble 4-byte big-endian length-prefixed frames from
    arbitrary byte chunks (a pipe may deliver a frame whole; TCP may
    split it anywhere)."""

    __slots__ = ("_buf", "max_frame")

    def __init__(self, max_frame: int = MAX_FRAME):
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed(self, chunk: bytes) -> List[bytes]:
        self._buf += chunk
        out: List[bytes] = []
        while len(self._buf) >= 4:
            (n,) = _LEN.unpack(self._buf[:4])
            if not 0 <= n <= self.max_frame:
                raise FramingError(f"insane frame length {n}")
            if len(self._buf) < 4 + n:
                break
            out.append(bytes(self._buf[4 : 4 + n]))
            del self._buf[: 4 + n]
        return out

    def pending(self) -> int:
        """Buffered bytes of an incomplete frame (tests/diagnostics)."""
        return len(self._buf)


def frame(body: bytes) -> bytes:
    """Length-prefix one frame body for the wire."""
    if len(body) > MAX_FRAME:
        raise FramingError(f"frame of {len(body)} bytes exceeds bound")
    return _LEN.pack(len(body)) + body


class HttpRequest:
    """One parsed HTTP/1.1 request — the unit the S3 adapter consumes.
    Field shape matches what ``s3/wire.py`` dispatches on."""

    __slots__ = ("method", "path", "query", "headers", "body")

    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body


_MAX_HEAD = 64 * 1024  # request line + headers sanity bound


class HttpRequestFramer:
    """Incremental HTTP/1.1 request parser: head (request line +
    headers, terminated ``\\r\\n\\r\\n``) then a Content-Length body.
    Keep-alive: yields every complete request in the stream. No chunked
    transfer encoding (stock S3 SDK PUTs carry Content-Length)."""

    __slots__ = ("_buf", "_head", "_need", "max_body")

    def __init__(self, max_body: int = MAX_FRAME):
        self._buf = bytearray()
        self._head: Optional[HttpRequest] = None  # parsed, awaiting body
        self._need = 0  # body bytes still missing
        self.max_body = max_body

    def feed(self, chunk: bytes) -> List[HttpRequest]:
        self._buf += chunk
        out: List[HttpRequest] = []
        while True:
            if self._head is None:
                end = self._buf.find(b"\r\n\r\n")
                if end < 0:
                    if len(self._buf) > _MAX_HEAD:
                        raise FramingError("oversized request head")
                    break
                self._head, self._need = self._parse_head(
                    bytes(self._buf[: end + 4])
                )
                del self._buf[: end + 4]
            if len(self._buf) < self._need:
                break
            req = self._head
            req.body = bytes(self._buf[: self._need])
            del self._buf[: self._need]
            self._head, self._need = None, 0
            out.append(req)
        return out

    def _parse_head(self, head: bytes):
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, target, _version = lines[0].split(" ", 2)
        except ValueError:
            raise FramingError(f"bad request line {lines[0]!r}") from None
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if ":" in line:
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
        parsed = urllib.parse.urlsplit(target)
        query = {
            k: v[0] if v else ""
            for k, v in urllib.parse.parse_qs(
                parsed.query, keep_blank_values=True
            ).items()
        }
        try:
            need = int(headers.get("content-length", "0"))
        except ValueError:
            raise FramingError("unparseable Content-Length") from None
        if not 0 <= need <= self.max_body:
            raise FramingError(f"insane Content-Length {need}")
        req = HttpRequest(
            method, urllib.parse.unquote(parsed.path), query, headers, b""
        )
        return req, need

    def pending(self) -> int:
        return len(self._buf)


_REASON = {200: "OK", 204: "No Content", 400: "Bad Request",
           404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
           500: "Internal Server Error", 503: "Service Unavailable"}


def render_http_response(status: int, body: bytes,
                         headers: Dict[str, str],
                         head_only: bool = False) -> bytes:
    """Render one HTTP/1.1 response. ``head_only`` (a HEAD request)
    advertises the real entity length but sends no body."""
    sent = b"" if head_only else body
    lines = [f"HTTP/1.1 {status} {_REASON.get(status, 'OK')}"]
    hdrs = dict(headers)
    hdrs["Content-Length"] = str(len(body))
    hdrs.setdefault("Server", "madsim-s3-wire")
    for k, v in hdrs.items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode() + sent
