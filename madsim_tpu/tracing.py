"""Tracing: node/task-scoped logging + a chrome-trace exporter.

The reference enters a tracing span per node and per task on every poll so
log lines carry simulation identity (madsim/src/sim/task/mod.rs:121,193;
runtime/context.rs:58-64). Python's analogue: a logging.Filter that stamps
records with ``sim_time`` / ``node`` / ``task`` from the ambient context —
installed by ``runtime.init_logger`` — plus helpers to log through.

Beyond the reference (which has no trace exporter), ``Tracer`` records
per-task poll spans and emits the Chrome trace-event JSON format
(chrome://tracing / Perfetto), with virtual time as the timeline — a
practical way to *see* a schedule when debugging a failing seed.

``SpanTracer`` scales the same exporter from one seed's polls to the
FLEET drivers (madsim_tpu/obs): wall-clock phase spans on named tracks
("device", "host", "stream", "checkers"), so one trace file shows the
device sweep of chunk N overlapping the host decode/check of chunk N−1,
the stream pool's round/refill cadence, and the checker-pool fan-out.
Same JSON shape, same viewers; only the clock differs (virtual ns for
``Tracer``, wall µs since construction for ``SpanTracer``).
"""

from __future__ import annotations

import json
import logging
import threading
import time as _walltime
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from . import context


class SimContextFilter(logging.Filter):
    """Stamps every record with the ambient sim identity."""

    def filter(self, record: logging.LogRecord) -> bool:
        task = context.try_current_task()
        handle = context.try_current_handle()
        record.sim_time = (
            f"{handle.time.elapsed():.6f}" if handle is not None else "-"
        )
        record.node = task.node.name if task is not None else "-"
        record.task = (task.name or str(task.id)) if task is not None else "-"
        return True


LOG_FORMAT = "%(levelname)s [%(sim_time)ss %(node)s/%(task)s] %(name)s: %(message)s"


class Tracer:
    """Chrome-trace recorder for one simulation run.

    Register with ``tracer.install(runtime)`` before ``block_on``; every
    task poll becomes a complete event ("X") on the node's row, with
    virtual microseconds as the timeline. ``save(path)`` writes JSON
    loadable in chrome://tracing or Perfetto.
    """

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []
        self._runtime: Optional[Any] = None

    def install(self, runtime: Any) -> "Tracer":
        executor = runtime.executor
        tracer = self
        # tracing instruments every poll, so the run must take the Python
        # loop — the compiled core (native/simloop.c) steps coroutines in
        # C and would bypass the _poll wrapper below. Schedules are
        # byte-identical either way; only wall-clock differs.
        executor._cloop = None
        original_poll = executor._poll

        def traced_poll(task: Any) -> None:
            time = executor.time
            start_ns = time.now_ns
            original_poll(task)
            tracer.events.append(
                {
                    "name": task.name or f"task-{task.id}",
                    "cat": "poll",
                    "ph": "X",
                    "pid": int(task.node.id),
                    "tid": int(task.id),
                    "ts": start_ns / 1000.0,  # chrome uses microseconds
                    "dur": max((time.now_ns - start_ns) / 1000.0, 0.001),
                }
            )

        executor._poll = traced_poll
        self._runtime = runtime
        for node in executor.nodes.values():
            self._name_node(node)
        return self

    def _name_node(self, node: Any) -> None:
        self.events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": int(node.id),
                "args": {"name": node.name},
            }
        )

    def to_json(self) -> str:
        # name any nodes created after install
        if self._runtime is not None:
            named = {e["pid"] for e in self.events if e.get("ph") == "M"}
            for node in self._runtime.executor.nodes.values():
                if int(node.id) not in named:
                    self._name_node(node)
        return json.dumps({"traceEvents": self.events})

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())


class SpanTracer:
    """Chrome-trace recorder for DRIVER phases: wall-clock complete
    events ("X") on named tracks, plus counter events ("C") for series
    like pool occupancy — the fleet-scale sibling of :class:`Tracer`.

    Tracks are lazily numbered in first-use order and named through "M"
    ``thread_name`` metadata, so Perfetto shows "device" / "host" /
    "stream" rows instead of bare thread ids. Timestamps are wall
    microseconds since construction (Chrome's unit). Thread-safe: the
    checker pool and the HTTP exporter may emit concurrently.
    """

    PID = 0  # one logical process: the driver

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.PID,
                "args": {"name": "madsim_tpu driver"},
            }
        ]
        self._t0 = _walltime.perf_counter_ns()
        self._tracks: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _now_us(self) -> float:
        return (_walltime.perf_counter_ns() - self._t0) / 1000.0

    def _tid(self, track: str) -> int:
        tid = self._tracks.get(track)
        if tid is None:
            tid = self._tracks[track] = len(self._tracks)
            self.events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self.PID,
                    "tid": tid,
                    "args": {"name": track},
                }
            )
        return tid

    def complete(
        self,
        name: str,
        start_us: float,
        dur_us: float,
        track: str = "host",
        cat: str = "phase",
        args: Optional[dict] = None,
    ) -> None:
        """One finished span from precomputed times (µs since t0)."""
        with self._lock:
            ev = {
                "name": name,
                "cat": cat,
                "ph": "X",
                "pid": self.PID,
                "tid": self._tid(track),
                "ts": start_us,
                "dur": max(dur_us, 0.001),
            }
            if args:
                ev["args"] = args
            self.events.append(ev)

    @contextmanager
    def span(
        self,
        name: str,
        track: str = "host",
        cat: str = "phase",
        args: Optional[dict] = None,
    ):
        """Record the wrapped block as one complete event on ``track``."""
        start = self._now_us()
        try:
            yield self
        finally:
            self.complete(
                name, start, self._now_us() - start, track, cat, args
            )

    def instant(self, name: str, track: str = "host", args=None) -> None:
        with self._lock:
            ev = {
                "name": name,
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "pid": self.PID,
                "tid": self._tid(track),
                "ts": self._now_us(),
            }
            if args:
                ev["args"] = args
            self.events.append(ev)

    def counter(self, name: str, **values: float) -> None:
        """One sample of a counter series (occupancy, queue depth) —
        Perfetto renders these as a step chart over the trace."""
        with self._lock:
            self.events.append(
                {
                    "name": name,
                    "ph": "C",
                    "pid": self.PID,
                    "ts": self._now_us(),
                    "args": {k: float(v) for k, v in values.items()},
                }
            )

    def to_json(self) -> str:
        with self._lock:
            return json.dumps({"traceEvents": list(self.events)})

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())


def instrument(logger: Optional[logging.Logger] = None):
    """Decorator: log entry/exit of an async op with sim identity (the
    ``#[instrument]`` analogue on net/fs ops)."""
    log = logger or logging.getLogger("madsim")

    def deco(fn):
        import functools

        @functools.wraps(fn)
        async def wrapper(*args: Any, **kwargs: Any):
            log.debug("enter %s", fn.__qualname__)
            try:
                return await fn(*args, **kwargs)
            finally:
                log.debug("exit %s", fn.__qualname__)

        return wrapper

    return deco
