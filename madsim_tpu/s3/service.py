"""The S3 state machine (madsim-aws-sdk-s3/src/server/service.rs).

``ServiceInner`` — per-bucket ordered maps of objects plus in-progress
multipart uploads and bucket lifecycle configuration. Pure deterministic
state; the server node wraps it.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class S3Error(Exception):
    """AWS-style coded error (NoSuchBucket / NoSuchKey / ...)."""

    def __init__(self, code: str, message: str):
        self.code = code
        self.message = message
        super().__init__(f"{code}: {message}")


def _etag(body: bytes) -> str:
    return '"' + hashlib.md5(body).hexdigest() + '"'


@dataclass
class S3Object:
    body: bytes
    e_tag: str
    last_modified_ms: int


@dataclass
class MultipartUpload:
    key: str
    parts: Dict[int, bytes] = field(default_factory=dict)


@dataclass
class Bucket:
    objects: Dict[str, S3Object] = field(default_factory=dict)
    uploads: Dict[str, MultipartUpload] = field(default_factory=dict)
    lifecycle: Optional[Any] = None
    next_upload: int = 1


class S3Service:
    def __init__(self) -> None:
        self.buckets: Dict[str, Bucket] = {}

    def _bucket(self, name: str) -> Bucket:
        b = self.buckets.get(name)
        if b is None:
            raise S3Error("NoSuchBucket", f"The specified bucket does not exist: {name}")
        return b

    # -- bucket lifecycle ---------------------------------------------------

    def create_bucket(self, name: str) -> None:
        if name in self.buckets:
            raise S3Error("BucketAlreadyExists", name)
        self.buckets[name] = Bucket()

    def delete_bucket(self, name: str) -> None:
        b = self._bucket(name)
        if b.objects:
            raise S3Error("BucketNotEmpty", name)
        del self.buckets[name]

    def list_buckets(self) -> List[str]:
        return sorted(self.buckets)

    def head_bucket(self, name: str) -> None:
        """Existence probe (S3 HeadBucket); raises NoSuchBucket."""
        self._bucket(name)

    # -- objects ------------------------------------------------------------

    def put_object(self, bucket: str, key: str, body: bytes, now_ms: int) -> str:
        b = self._bucket(bucket)
        obj = S3Object(body=body, e_tag=_etag(body), last_modified_ms=now_ms)
        b.objects[key] = obj
        return obj.e_tag

    def get_object(self, bucket: str, key: str) -> S3Object:
        b = self._bucket(bucket)
        obj = b.objects.get(key)
        if obj is None:
            raise S3Error("NoSuchKey", f"The specified key does not exist: {key}")
        return obj

    def head_object(self, bucket: str, key: str) -> Tuple[int, str, int]:
        obj = self.get_object(bucket, key)
        return len(obj.body), obj.e_tag, obj.last_modified_ms

    def delete_object(self, bucket: str, key: str) -> None:
        self._bucket(bucket).objects.pop(key, None)  # S3 delete is idempotent

    def delete_objects(self, bucket: str, keys: List[str]) -> List[str]:
        b = self._bucket(bucket)
        deleted = []
        for key in keys:
            b.objects.pop(key, None)
            deleted.append(key)
        return deleted

    def list_objects_v2(
        self,
        bucket: str,
        prefix: str,
        continuation_token: Optional[str],
        max_keys: int,
    ) -> Tuple[List[Tuple[str, int, str]], Optional[str], bool]:
        """Returns ([(key, size, etag)], next_token, is_truncated) in
        lexicographic key order (the BTreeMap semantics of the reference)."""
        b = self._bucket(bucket)
        if max_keys <= 0:
            return [], None, False
        keys = sorted(k for k in b.objects if k.startswith(prefix))
        if continuation_token:
            keys = [k for k in keys if k > continuation_token]
        page, rest = keys[:max_keys], keys[max_keys:]
        contents = [
            (k, len(b.objects[k].body), b.objects[k].e_tag) for k in page
        ]
        next_token = page[-1] if rest else None
        return contents, next_token, bool(rest)

    # -- multipart upload lifecycle -----------------------------------------

    def create_multipart_upload(self, bucket: str, key: str) -> str:
        b = self._bucket(bucket)
        upload_id = f"upload-{b.next_upload}"
        b.next_upload += 1
        b.uploads[upload_id] = MultipartUpload(key=key)
        return upload_id

    def _upload(self, bucket: str, upload_id: str) -> MultipartUpload:
        up = self._bucket(bucket).uploads.get(upload_id)
        if up is None:
            raise S3Error("NoSuchUpload", upload_id)
        return up

    def upload_part(
        self, bucket: str, upload_id: str, part_number: int, body: bytes
    ) -> str:
        if part_number < 1:
            raise S3Error("InvalidArgument", "part numbers start at 1")
        self._upload(bucket, upload_id).parts[part_number] = body
        return _etag(body)

    def complete_multipart_upload(
        self, bucket: str, upload_id: str, part_numbers: List[int], now_ms: int
    ) -> str:
        up = self._upload(bucket, upload_id)
        missing = [n for n in part_numbers if n not in up.parts]
        if missing:
            raise S3Error("InvalidPart", f"missing parts: {missing}")
        if part_numbers != sorted(part_numbers):
            raise S3Error(
                "InvalidPartOrder",
                "the list of parts was not in ascending order",
            )
        body = b"".join(up.parts[n] for n in part_numbers)
        etag = self.put_object(bucket, up.key, body, now_ms)
        del self._bucket(bucket).uploads[upload_id]
        return etag

    def abort_multipart_upload(self, bucket: str, upload_id: str) -> None:
        self._upload(bucket, upload_id)
        del self._bucket(bucket).uploads[upload_id]

    # -- bucket lifecycle configuration --------------------------------------

    def put_bucket_lifecycle_configuration(self, bucket: str, config: Any) -> None:
        self._bucket(bucket).lifecycle = config

    def get_bucket_lifecycle_configuration(self, bucket: str) -> Any:
        lc = self._bucket(bucket).lifecycle
        if lc is None:
            raise S3Error(
                "NoSuchLifecycleConfiguration", "the lifecycle configuration does not exist"
            )
        return lc
