"""The S3 client — fluent builders mirroring the AWS SDK surface
(madsim-aws-sdk-s3/src/operation/*.rs, client.rs:29-57).

Every operation is a builder (``client.put_object().bucket(..).key(..)
.body(..).send()``) whose ``send`` performs one request exchange with the
SimServer. Output objects expose the SDK's accessor methods.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..net.endpoint import connect1_ephemeral, exchange1
from .service import S3Error


# -- model types ------------------------------------------------------------


@dataclass
class ObjectIdentifier:
    _key: str

    @staticmethod
    def builder() -> "ObjectIdentifierBuilder":
        return ObjectIdentifierBuilder()

    def key(self) -> str:
        return self._key


class ObjectIdentifierBuilder:
    def __init__(self) -> None:
        self._key: Optional[str] = None

    def key(self, key: str) -> "ObjectIdentifierBuilder":
        self._key = key
        return self

    def build(self) -> ObjectIdentifier:
        assert self._key is not None
        return ObjectIdentifier(self._key)


@dataclass
class Delete:
    _objects: List[ObjectIdentifier] = field(default_factory=list)

    @staticmethod
    def builder() -> "DeleteBuilder":
        return DeleteBuilder()

    def objects(self) -> List[ObjectIdentifier]:
        return self._objects


class DeleteBuilder:
    def __init__(self) -> None:
        self._objects: List[ObjectIdentifier] = []

    def objects(self, obj: ObjectIdentifier) -> "DeleteBuilder":
        self._objects.append(obj)
        return self

    def build(self) -> Delete:
        return Delete(self._objects)


@dataclass
class CompletedPart:
    _part_number: int
    _e_tag: Optional[str] = None

    @staticmethod
    def builder() -> "CompletedPartBuilder":
        return CompletedPartBuilder()

    def part_number(self) -> int:
        return self._part_number


class CompletedPartBuilder:
    def __init__(self) -> None:
        self._part_number: Optional[int] = None
        self._e_tag: Optional[str] = None

    def part_number(self, n: int) -> "CompletedPartBuilder":
        self._part_number = n
        return self

    def e_tag(self, tag: str) -> "CompletedPartBuilder":
        self._e_tag = tag
        return self

    def build(self) -> CompletedPart:
        assert self._part_number is not None
        return CompletedPart(self._part_number, self._e_tag)


@dataclass
class CompletedMultipartUpload:
    _parts: List[CompletedPart] = field(default_factory=list)

    @staticmethod
    def builder() -> "CompletedMultipartUploadBuilder":
        return CompletedMultipartUploadBuilder()

    def parts(self) -> List[CompletedPart]:
        return self._parts


class CompletedMultipartUploadBuilder:
    def __init__(self) -> None:
        self._parts: List[CompletedPart] = []

    def parts(self, part: CompletedPart) -> "CompletedMultipartUploadBuilder":
        self._parts.append(part)
        return self

    def build(self) -> CompletedMultipartUpload:
        return CompletedMultipartUpload(self._parts)


class ByteStream:
    """The SDK body type: ``await out.body.collect()`` → bytes."""

    def __init__(self, data: bytes):
        self._data = data

    async def collect(self) -> "ByteStream":
        return self

    def into_bytes(self) -> bytes:
        return self._data

    def to_bytes(self) -> bytes:
        return self._data

    @staticmethod
    def from_static(data: bytes) -> "ByteStream":
        return ByteStream(data)


# -- outputs ----------------------------------------------------------------


class _Output:
    def __init__(self, **kw: Any):
        self._kw = kw

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._kw:
            value = self._kw[name]
            return lambda: value
        raise AttributeError(name)


@dataclass
class S3ListedObject:
    _key: str
    _size: int
    _e_tag: str

    def key(self) -> str:
        return self._key

    def size(self) -> int:
        return self._size

    def e_tag(self) -> str:
        return self._e_tag


# -- the client -------------------------------------------------------------


class _OpBuilder:
    """Generic fluent builder: setter per field, ``send`` runs the op."""

    _FIELDS: tuple = ()

    def __init__(self, client: "Client"):
        self._client = client
        self._args: Dict[str, Any] = {}

    def __getattr__(self, name: str):
        if name in type(self)._FIELDS:
            def setter(value: Any):
                self._args[name] = value
                return self

            return setter
        raise AttributeError(name)

    async def _call(self, req: tuple) -> Any:
        return await self._client._call(req)


def _op(name: str, fields: tuple, send):
    """Define one operation builder class."""
    cls = type(name, (_OpBuilder,), {"_FIELDS": fields, "send": send})
    return cls


async def _send_create_bucket(self):
    await self._call(("create_bucket", self._args["bucket"]))
    return _Output(bucket=self._args["bucket"])


async def _send_delete_bucket(self):
    await self._call(("delete_bucket", self._args["bucket"]))
    return _Output()


async def _send_list_buckets(self):
    names = await self._call(("list_buckets",))
    return _Output(buckets=[_Output(name=n) for n in names])


async def _send_put_object(self):
    body = self._args.get("body", b"")
    if isinstance(body, ByteStream):
        body = body.into_bytes()
    etag = await self._call(
        ("put_object", self._args["bucket"], self._args["key"], bytes(body))
    )
    return _Output(e_tag=etag)


async def _send_get_object(self):
    body, etag, modified = await self._call(
        ("get_object", self._args["bucket"], self._args["key"])
    )
    out = _Output(e_tag=etag, last_modified=modified, content_length=len(body))
    out.body = ByteStream(body)
    return out


async def _send_head_object(self):
    length, etag, modified = await self._call(
        ("head_object", self._args["bucket"], self._args["key"])
    )
    return _Output(content_length=length, e_tag=etag, last_modified=modified)


async def _send_delete_object(self):
    await self._call(("delete_object", self._args["bucket"], self._args["key"]))
    return _Output()


async def _send_delete_objects(self):
    delete: Delete = self._args["delete"]
    keys = [o.key() for o in delete.objects()]
    deleted = await self._call(("delete_objects", self._args["bucket"], keys))
    return _Output(deleted=[_Output(key=k) for k in deleted])


async def _send_list_objects_v2(self):
    contents, next_token, truncated = await self._call(
        (
            "list_objects_v2",
            self._args["bucket"],
            self._args.get("prefix", ""),
            self._args.get("continuation_token"),
            self._args.get("max_keys", 1000),
        )
    )
    return _Output(
        contents=[S3ListedObject(k, size, etag) for k, size, etag in contents],
        next_continuation_token=next_token,
        is_truncated=truncated,
        key_count=len(contents),
    )


async def _send_create_multipart_upload(self):
    upload_id = await self._call(
        ("create_multipart_upload", self._args["bucket"], self._args["key"])
    )
    return _Output(upload_id=upload_id)


async def _send_upload_part(self):
    body = self._args.get("body", b"")
    if isinstance(body, ByteStream):
        body = body.into_bytes()
    etag = await self._call(
        (
            "upload_part",
            self._args["bucket"],
            self._args["upload_id"],
            self._args["part_number"],
            bytes(body),
        )
    )
    return _Output(e_tag=etag)


async def _send_complete_multipart_upload(self):
    mp: CompletedMultipartUpload = self._args["multipart_upload"]
    part_numbers = [p.part_number() for p in mp.parts()]
    etag = await self._call(
        (
            "complete_multipart_upload",
            self._args["bucket"],
            self._args["upload_id"],
            part_numbers,
        )
    )
    return _Output(e_tag=etag, key=self._args.get("key"))


async def _send_abort_multipart_upload(self):
    await self._call(
        ("abort_multipart_upload", self._args["bucket"], self._args["upload_id"])
    )
    return _Output()


async def _send_put_lifecycle(self):
    await self._call(
        (
            "put_bucket_lifecycle_configuration",
            self._args["bucket"],
            self._args["lifecycle_configuration"],
        )
    )
    return _Output()


async def _send_get_lifecycle(self):
    config = await self._call(
        ("get_bucket_lifecycle_configuration", self._args["bucket"])
    )
    return _Output(rules=config)


_OPS = {
    "create_bucket": _op("CreateBucket", ("bucket",), _send_create_bucket),
    "delete_bucket": _op("DeleteBucket", ("bucket",), _send_delete_bucket),
    "list_buckets": _op("ListBuckets", (), _send_list_buckets),
    "put_object": _op("PutObject", ("bucket", "key", "body"), _send_put_object),
    "get_object": _op("GetObject", ("bucket", "key"), _send_get_object),
    "head_object": _op("HeadObject", ("bucket", "key"), _send_head_object),
    "delete_object": _op("DeleteObject", ("bucket", "key"), _send_delete_object),
    "delete_objects": _op("DeleteObjects", ("bucket", "delete"), _send_delete_objects),
    "list_objects_v2": _op(
        "ListObjectsV2",
        ("bucket", "prefix", "continuation_token", "max_keys"),
        _send_list_objects_v2,
    ),
    "create_multipart_upload": _op(
        "CreateMultipartUpload", ("bucket", "key"), _send_create_multipart_upload
    ),
    "upload_part": _op(
        "UploadPart",
        ("bucket", "key", "upload_id", "part_number", "body"),
        _send_upload_part,
    ),
    "complete_multipart_upload": _op(
        "CompleteMultipartUpload",
        ("bucket", "key", "upload_id", "multipart_upload"),
        _send_complete_multipart_upload,
    ),
    "abort_multipart_upload": _op(
        "AbortMultipartUpload",
        ("bucket", "key", "upload_id"),
        _send_abort_multipart_upload,
    ),
    "put_bucket_lifecycle_configuration": _op(
        "PutBucketLifecycleConfiguration",
        ("bucket", "lifecycle_configuration"),
        _send_put_lifecycle,
    ),
    "get_bucket_lifecycle_configuration": _op(
        "GetBucketLifecycleConfiguration", ("bucket",), _send_get_lifecycle
    ),
}


class Client:
    """``Client::send_request`` = one connect1 exchange per op
    (client.rs:29-57)."""

    def __init__(self, addr: str):
        self._addr = addr

    @classmethod
    def from_addr(cls, addr: str) -> "Client":
        return cls(addr)

    @classmethod
    def from_conf(cls, conf: Dict[str, Any]) -> "Client":
        return cls(conf["endpoint"])

    # transport hook — real/s3.py dials framed TCP instead
    _connect = staticmethod(connect1_ephemeral)

    async def _call(self, req: tuple) -> Any:
        try:
            tx, rx = await self._connect(self._addr)
            rsp = await exchange1(tx, rx, req)
        except (ConnectionError, OSError) as e:
            raise S3Error("TransportError", str(e)) from None
        if rsp is None:
            raise S3Error("TransportError", "connection closed")
        kind, payload = rsp
        if kind == "err":
            code, message = payload
            raise S3Error(code, message)
        return payload

    def __getattr__(self, name: str):
        op = _OPS.get(name)
        if op is None:
            raise AttributeError(name)
        return lambda: op(self)
