"""The S3 sim server node (madsim-aws-sdk-s3/src/server/rpc_server.rs).

One request tuple per ``connect1`` exchange, dispatched over the service
operations (rpc_server.rs:24-76).
"""

from __future__ import annotations

from typing import Any

from .. import task as mstask
from ..context import current_handle
from ..net.endpoint import Endpoint as NetEndpoint
from .service import S3Error, S3Service


class SimServer:
    # executor/clock bindings as class attributes so the real-mode twin
    # (real/s3.py) rebinds them to asyncio + the wall clock while reusing
    # the dispatcher (the sim/std split of madsim-aws-sdk-s3/src/lib.rs)
    _spawn = staticmethod(mstask.spawn)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await NetEndpoint.bind(addr)

    def __init__(self, service: "S3Service | None" = None) -> None:
        self.service = service or S3Service()
        #: set once the listener is bound (port-0 discovery, real mode)
        self.bound_addr: "tuple | None" = None

    async def serve(self, addr: "str | tuple") -> None:
        ep = await self._bind(addr)
        local = getattr(ep, "local_addr", None)
        self.bound_addr = local() if callable(local) else None
        while True:
            tx, rx, _src = await ep.accept1()
            self._spawn(self._serve_conn(tx, rx), name="s3-conn")

    async def _serve_conn(self, tx: Any, rx: Any) -> None:
        try:
            req = await rx.recv()
            if req is None:
                return
            try:
                await tx.send(("ok", self._handle(req)))
            except S3Error as e:
                await tx.send(("err", (e.code, e.message)))
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            tx.close()

    def _now_ms(self) -> int:
        return current_handle().time.now_time_ns() // 1_000_000

    def _handle(self, req: tuple) -> Any:
        s = self.service
        op, args = req[0], req[1:]
        if op == "create_bucket":
            return s.create_bucket(*args)
        if op == "delete_bucket":
            return s.delete_bucket(*args)
        if op == "list_buckets":
            return s.list_buckets()
        if op == "put_object":
            bucket, key, body = args
            return s.put_object(bucket, key, body, self._now_ms())
        if op == "get_object":
            obj = s.get_object(*args)
            return (obj.body, obj.e_tag, obj.last_modified_ms)
        if op == "head_object":
            return s.head_object(*args)
        if op == "delete_object":
            return s.delete_object(*args)
        if op == "delete_objects":
            return s.delete_objects(*args)
        if op == "list_objects_v2":
            return s.list_objects_v2(*args)
        if op == "create_multipart_upload":
            return s.create_multipart_upload(*args)
        if op == "upload_part":
            return s.upload_part(*args)
        if op == "complete_multipart_upload":
            bucket, upload_id, part_numbers = args
            return s.complete_multipart_upload(
                bucket, upload_id, part_numbers, self._now_ms()
            )
        if op == "abort_multipart_upload":
            return s.abort_multipart_upload(*args)
        if op == "put_bucket_lifecycle_configuration":
            return s.put_bucket_lifecycle_configuration(*args)
        if op == "get_bucket_lifecycle_configuration":
            return s.get_bucket_lifecycle_configuration(*args)
        raise S3Error("NotImplemented", f"unknown op {op!r}")
