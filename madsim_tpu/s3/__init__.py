"""S3 simulation — the madsim-aws-sdk-s3 analogue.

An in-memory S3 served over sim connections: the client sends one request
enum per ``connect1`` exchange (madsim-aws-sdk-s3/src/client.rs:29-57) to a
``SimServer`` dispatching the object/multipart/lifecycle operations
(server/rpc_server.rs:24-76) against per-bucket ordered maps
(``ServiceInner``). The client mirrors the AWS SDK's fluent-builder shape
(src/operation/*.rs):

    client = s3.Client.from_addr("10.0.0.1:9000")
    await client.put_object().bucket("b").key("k").body(b"...").send()
    out = await (await client.get_object().bucket("b").key("k").send()).body()
"""

from .client import (
    ByteStream,
    Client,
    CompletedMultipartUpload,
    CompletedPart,
    Delete,
    ObjectIdentifier,
)
from .server import SimServer
from .service import S3Error, S3Service

__all__ = [
    "ByteStream",
    "Client",
    "CompletedMultipartUpload",
    "CompletedPart",
    "Delete",
    "ObjectIdentifier",
    "S3Error",
    "S3Service",
    "SimServer",
]
