"""S3 REST wire: the framework's S3 state machine served over the REAL
S3 protocol (path-style REST + XML), so any stock S3 HTTP client can
create buckets, put/get/head/delete objects, page ListObjectsV2, and run
the multipart-upload lifecycle against it.

The reference's madsim-aws-sdk-s3 compiles to the *real* AWS SDK outside
the sim — its std mode speaks actual S3 REST. No AWS SDK is installed in
this image to point at this server, but the protocol itself is held:
``tests/test_s3_wire.py`` drives every operation with a stock HTTP
client, asserting S3's status codes, headers (ETag, Content-Length), and
XML shapes (ListBucketResult, InitiateMultipartUploadResult, Error).

Transport: a minimal HTTP/1.1 server on asyncio streams (keep-alive,
Content-Length bodies) — no web framework, mirroring how the repo's
other wire tiers stay dependency-light. Auth/signature headers are
accepted and ignored (the sim trusts its caller, like the reference
sim). XML parsing uses the stdlib ElementTree; this server is a test
double, not an internet-facing endpoint.

Scope: listing is **ListObjectsV2 only** — ``GET /bucket`` without
``list-type=2`` (ListObjects v1, the default for several stock SDK code
paths) is rejected with ``InvalidArgument`` rather than served with
Marker/NextMarker pagination; configure clients for v2 listing. Ranged
reads are **not supported** either: ``GetObject`` ignores a ``Range``
header and always returns the full body with 200 (no 206/Content-Range).
Both are deliberate test-double boundaries, not oversights (README
"ecosystem shims" scope note).

Operation map (path-style):
  PUT    /bucket                         CreateBucket
  DELETE /bucket                         DeleteBucket
  GET    /                               ListBuckets
  GET    /bucket?list-type=2&...         ListObjectsV2
  POST   /bucket?delete                  DeleteObjects (XML body)
  PUT    /bucket/key                     PutObject
  GET    /bucket/key                     GetObject
  HEAD   /bucket/key                     HeadObject
  DELETE /bucket/key                     DeleteObject
  POST   /bucket/key?uploads             CreateMultipartUpload
  PUT    /bucket/key?partNumber&uploadId UploadPart
  POST   /bucket/key?uploadId            CompleteMultipartUpload (XML)
  DELETE /bucket/key?uploadId            AbortMultipartUpload
"""

from __future__ import annotations

import asyncio
import time as _walltime
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate
from typing import Dict, Optional, Tuple

from .service import S3Error, S3Service

_ERROR_STATUS = {
    "NoSuchBucket": 404,
    "NoSuchKey": 404,
    "NoSuchUpload": 404,
    "NoSuchLifecycleConfiguration": 404,
    "BucketAlreadyExists": 409,
    "BucketNotEmpty": 409,
    "InvalidPart": 400,
    "InvalidPartOrder": 400,
    "InvalidArgument": 400,
}


def _xml(tag: str, children: str) -> bytes:
    return (
        f'<?xml version="1.0" encoding="UTF-8"?>\n<{tag}>{children}</{tag}>'
    ).encode()


def _esc(s: str) -> str:
    return (
        s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
    )


def _local(tag: str) -> str:
    """Element local name: real S3 SDKs send namespaced bodies
    (xmlns="http://s3.amazonaws.com/doc/2006-03-01/"), so every lookup
    must match '{ns}Key' as well as bare 'Key'."""
    return tag.rsplit("}", 1)[-1]


def _elements(root: ET.Element, name: str):
    return [el for el in root.iter() if _local(el.tag) == name]


def _child_text(el: ET.Element, name: str, default: str = "") -> str:
    for child in el:
        if _local(child.tag) == name:
            return child.text or default
    return default


class _Request:
    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body


class _Response:
    def __init__(self, status: int = 200, body: bytes = b"",
                 headers: Optional[Dict[str, str]] = None,
                 content_type: str = "application/xml"):
        self.status = status
        self.body = body
        self.headers = dict(headers or {})
        if body and "Content-Type" not in self.headers:
            self.headers["Content-Type"] = content_type


_REASON = {200: "OK", 204: "No Content", 400: "Bad Request",
           404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
           500: "Internal Server Error"}


class S3Rest:
    """The S3 REST engine: one parsed HTTP request in, one response out.

    Pure protocol meaning — no sockets, no buffering. Both servers (the
    shared-core :class:`WireServer` and the thread-per-connection
    :class:`LegacyWireServer`) dispatch through this one engine, which
    is what makes their response bytes identical by construction.
    ``clock_ms`` injects the timestamp source so the determinism leg can
    feed a seeded clock instead of wall time.
    """

    def __init__(self, service: Optional[S3Service] = None, telemetry=None,
                 clock_ms=None):
        self.service = service or S3Service()
        self.telemetry = telemetry
        self.clock_ms = clock_ms or (lambda: int(_walltime.time() * 1000))
        #: optional list of (request, clock_ms, (status, body, headers))
        #: — the live-vs-replay transcript, like ``KafkaWire.recorder``
        self.recorder = None
        self._now = 0

    def handle(self, req) -> Tuple[int, bytes, Dict[str, str]]:
        """Dispatch one request (any object with ``method``/``path``/
        ``query``/``headers``/``body``) → ``(status, body, headers)``.

        The clock is sampled exactly ONCE per request, up front — the
        same purity contract as ``KafkaWire.handle_frame``: the response
        is a pure function of (request, clock sample), which is what the
        recorded transcript replays against a fresh engine."""
        self._now = self.clock_ms()
        t0 = (_walltime.perf_counter()
              if self.telemetry is not None else 0.0)
        try:
            rsp = self._dispatch(req)
        except S3Error as e:
            rsp = _Response(
                _ERROR_STATUS.get(e.code, 400),
                _xml("Error",
                     f"<Code>{_esc(e.code)}</Code>"
                     f"<Message>{_esc(e.message)}</Message>"),
            )
        except Exception as e:  # noqa: BLE001 — wire boundary
            rsp = _Response(
                500,
                _xml("Error",
                     "<Code>InternalError</Code>"
                     f"<Message>{_esc(str(e))}</Message>"),
            )
        if self.telemetry is not None:
            self.telemetry.count(
                "s3_requests_total", help="requests served",
                method=req.method,
            )
            self.telemetry.observe(
                "s3_api_seconds",
                _walltime.perf_counter() - t0,
                help="per-request handling latency",
                method=req.method,
            )
        if self.recorder is not None:
            self.recorder.append(
                (req, self._now, (rsp.status, rsp.body, dict(rsp.headers)))
            )
        return rsp.status, rsp.body, rsp.headers

    # -- the S3 operation map -----------------------------------------------

    def _dispatch(self, req: _Request) -> _Response:
        bucket, _, key = req.path.lstrip("/").partition("/")
        if not bucket:
            if req.method == "GET":
                return self._list_buckets()
            raise S3Error("InvalidArgument", f"{req.method} on service root")
        if not key:
            return self._bucket_op(req, bucket)
        return self._object_op(req, bucket, key)

    def _list_buckets(self) -> _Response:
        names = "".join(
            f"<Bucket><Name>{_esc(n)}</Name></Bucket>"
            for n in self.service.list_buckets()
        )
        return _Response(
            200, _xml("ListAllMyBucketsResult", f"<Buckets>{names}</Buckets>")
        )

    def _bucket_op(self, req: _Request, bucket: str) -> _Response:
        svc = self.service
        if req.method == "PUT":
            svc.create_bucket(bucket)
            return _Response(200)
        if req.method == "HEAD":
            # HeadBucket: SDKs probe bucket existence with it
            svc.head_bucket(bucket)  # raises NoSuchBucket -> 404
            return _Response(200)
        if req.method == "DELETE":
            svc.delete_bucket(bucket)
            return _Response(204)
        if req.method == "GET" and req.query.get("list-type") == "2":
            contents, next_token, truncated = svc.list_objects_v2(
                bucket,
                req.query.get("prefix", ""),
                req.query.get("continuation-token") or None,
                int(req.query.get("max-keys", "1000")),
            )
            inner = "".join(
                f"<Contents><Key>{_esc(k)}</Key><Size>{size}</Size>"
                f"<ETag>{_esc(etag)}</ETag></Contents>"
                for k, size, etag in contents
            )
            inner += (
                f"<KeyCount>{len(contents)}</KeyCount>"
                f"<Prefix>{_esc(req.query.get('prefix', ''))}</Prefix>"
                f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            )
            if next_token:
                inner += (
                    "<NextContinuationToken>"
                    f"{_esc(next_token)}</NextContinuationToken>"
                )
            return _Response(200, _xml("ListBucketResult", inner))
        if req.method == "POST" and "delete" in req.query:
            root = ET.fromstring(req.body.decode())
            keys = [
                _child_text(el, "Key") for el in _elements(root, "Object")
            ]
            deleted = self.service.delete_objects(bucket, keys)
            inner = "".join(
                f"<Deleted><Key>{_esc(k)}</Key></Deleted>" for k in deleted
            )
            return _Response(200, _xml("DeleteResult", inner))
        raise S3Error("InvalidArgument", f"{req.method} /{bucket}")

    def _object_op(self, req: _Request, bucket: str, key: str) -> _Response:
        svc = self.service
        now_ms = self._now  # the one per-request clock sample (handle())
        if req.method == "PUT" and "uploadId" in req.query:
            if "x-amz-copy-source" in req.headers:
                # UploadPartCopy: the part body comes from an existing
                # object, answered with a CopyPartResult document
                src = urllib.parse.unquote(req.headers["x-amz-copy-source"])
                src_bucket, _, src_key = src.lstrip("/").partition("/")
                body = svc.get_object(src_bucket, src_key).body
            else:
                body = req.body
            etag = svc.upload_part(
                bucket,
                req.query["uploadId"],
                int(req.query.get("partNumber", "0")),
                body,
            )
            if "x-amz-copy-source" in req.headers:
                return _Response(
                    200,
                    _xml(
                        "CopyPartResult",
                        f"<ETag>{_esc(etag)}</ETag>"
                        f"<LastModified>"
                        f"{_esc(formatdate(now_ms / 1000, usegmt=True))}"
                        f"</LastModified>",
                    ),
                )
            return _Response(200, headers={"ETag": etag})
        if req.method == "PUT" and "x-amz-copy-source" in req.headers:
            # CopyObject: source is "/bucket/key" (optionally URL-encoded)
            src = urllib.parse.unquote(req.headers["x-amz-copy-source"])
            src_bucket, _, src_key = src.lstrip("/").partition("/")
            obj = svc.get_object(src_bucket, src_key)
            etag = svc.put_object(bucket, key, obj.body, now_ms)
            return _Response(
                200,
                _xml(
                    "CopyObjectResult",
                    f"<ETag>{_esc(etag)}</ETag>"
                    f"<LastModified>{_esc(formatdate(now_ms / 1000, usegmt=True))}"
                    f"</LastModified>",
                ),
            )
        if req.method == "PUT":
            etag = svc.put_object(bucket, key, req.body, now_ms)
            return _Response(200, headers={"ETag": etag})
        if req.method in ("GET", "HEAD"):
            obj = svc.get_object(bucket, key)
            return _Response(
                200,
                obj.body,
                headers={
                    "ETag": obj.e_tag,
                    "Last-Modified": formatdate(
                        obj.last_modified_ms / 1000, usegmt=True
                    ),
                },
                content_type="application/octet-stream",
            )
        if req.method == "DELETE" and "uploadId" in req.query:
            svc.abort_multipart_upload(bucket, req.query["uploadId"])
            return _Response(204)
        if req.method == "DELETE":
            svc.delete_object(bucket, key)
            return _Response(204)
        if req.method == "POST" and "uploads" in req.query:
            upload_id = svc.create_multipart_upload(bucket, key)
            return _Response(
                200,
                _xml(
                    "InitiateMultipartUploadResult",
                    f"<Bucket>{_esc(bucket)}</Bucket><Key>{_esc(key)}</Key>"
                    f"<UploadId>{_esc(upload_id)}</UploadId>",
                ),
            )
        if req.method == "POST" and "uploadId" in req.query:
            root = ET.fromstring(req.body.decode())
            part_numbers = [
                int(_child_text(el, "PartNumber", "0"))
                for el in _elements(root, "Part")
            ]
            etag = svc.complete_multipart_upload(
                bucket, req.query["uploadId"], part_numbers, now_ms
            )
            return _Response(
                200,
                _xml(
                    "CompleteMultipartUploadResult",
                    f"<Bucket>{_esc(bucket)}</Bucket><Key>{_esc(key)}</Key>"
                    f"<ETag>{_esc(etag)}</ETag>",
                ),
            )
        raise S3Error("InvalidArgument", f"{req.method} /{bucket}/{key}")


class WireServer:
    """Serve an :class:`S3Service` over S3 REST on a real TCP port,
    multiplexed by the shared serving core (``madsim_tpu/serve/``):
    incremental HTTP parsing, bounded write queues, slow-client
    eviction, and ``serve_*`` metrics come from the core; this class
    owns only the S3 meaning via :class:`S3Rest`."""

    def __init__(self, service: Optional[S3Service] = None, telemetry=None,
                 clock_ms=None, shards: int = 1):
        self.rest = S3Rest(service, telemetry=telemetry, clock_ms=clock_ms)
        self.service = self.rest.service
        self.telemetry = telemetry
        self.bound_addr: Optional[Tuple[str, int]] = None
        self._shards = shards
        self._core = None
        self.adapter = None  # set at start; carries the stall hook

    def _count_conn(self, _conn) -> None:
        if self.telemetry is not None:
            self.telemetry.count(
                "s3_connections_total", help="accepted connections"
            )

    async def start(self, addr: "str | tuple") -> None:
        from ..serve import AsyncWireServer, HttpAdapter

        self.adapter = HttpAdapter(
            self.rest.handle, name="s3", connect_hook=self._count_conn
        )
        self._core = AsyncWireServer(
            self.adapter, telemetry=self.telemetry, shards=self._shards
        )
        self.bound_addr = await self._core.start(addr)

    async def serve(self, addr: "str | tuple") -> None:
        await self.start(addr)
        try:
            await self._core._stopped.wait()
        finally:
            self._core._teardown()

    def close(self) -> None:
        if self._core is not None:
            self._core.close()

    async def aclose(self, drain_timeout: float = 5.0) -> None:
        if self._core is not None:
            await self._core.aclose(drain_timeout)


class LegacyWireServer:
    """The pre-core transport: one asyncio-streams task per connection,
    unbounded write buffering. Kept as the A/B baseline for parity and
    determinism gates; deprecated for serving — see docs/wire.md.
    Dispatch goes through the same :class:`S3Rest` engine, so response
    bytes match the core-backed server exactly."""

    def __init__(self, service: Optional[S3Service] = None, telemetry=None,
                 clock_ms=None):
        self.rest = S3Rest(service, telemetry=telemetry, clock_ms=clock_ms)
        self.service = self.rest.service
        self.telemetry = telemetry
        self.bound_addr: Optional[Tuple[str, int]] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def serve(self, addr: "str | tuple") -> None:
        host, port = addr if isinstance(addr, tuple) else addr.rsplit(":", 1)
        self._server = await asyncio.start_server(self._conn, host, int(port))
        self.bound_addr = self._server.sockets[0].getsockname()[:2]
        async with self._server:
            await self._server.serve_forever()

    def close(self) -> None:
        if self._server is not None:
            self._server.close()

    # -- HTTP/1.1 plumbing --------------------------------------------------

    async def _conn(self, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter) -> None:
        if self.telemetry is not None:
            self.telemetry.count(
                "s3_connections_total", help="accepted connections"
            )
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    return
                status, body, headers = self.rest.handle(req)
                await self._write_response(
                    writer, req, _Response(status, body, headers)
                )
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    @staticmethod
    async def _read_request(reader: asyncio.StreamReader) -> Optional[_Request]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        lines = head.decode("latin-1").split("\r\n")
        method, target, _version = lines[0].split(" ", 2)
        headers = {}
        for line in lines[1:]:
            if ":" in line:
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
        parsed = urllib.parse.urlsplit(target)
        query = {
            k: v[0] if v else ""
            for k, v in urllib.parse.parse_qs(
                parsed.query, keep_blank_values=True
            ).items()
        }
        length = int(headers.get("content-length", "0"))
        body = await reader.readexactly(length) if length else b""
        return _Request(
            method, urllib.parse.unquote(parsed.path), query, headers, body
        )

    @staticmethod
    async def _write_response(writer: asyncio.StreamWriter, req: _Request,
                              rsp: _Response) -> None:
        head_only = req.method == "HEAD"
        body = b"" if head_only else rsp.body
        lines = [f"HTTP/1.1 {rsp.status} {_REASON.get(rsp.status, 'OK')}"]
        headers = dict(rsp.headers)
        # HEAD advertises the real entity length; the others, the sent one
        headers["Content-Length"] = str(len(rsp.body))
        headers.setdefault("Server", "madsim-s3-wire")
        for k, v in headers.items():
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
        await writer.drain()
