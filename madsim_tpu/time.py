"""Virtual time: mock clock + timer heap + sleep/timeout/interval futures.

Mirrors the reference's ``sim/time/`` tree:
- ``TimeHandle`` / clock-jump loop        -> madsim/src/sim/time/mod.rs:21-230
- base wall time randomized "around 2022" -> time/mod.rs:27-32
- ``advance_to_next_event`` (+50ns eps)   -> time/mod.rs:45-60
- minimum 1 ms sleep (tokio parity)       -> time/mod.rs:110-124
- Sleep future (lazy timer registration)  -> sim/time/sleep.rs:20-55
- Interval + MissedTickBehavior           -> sim/time/interval.rs:38-192
- clock_gettime interposition equivalent  -> madsim_tpu.interpose
                                             (ref: sim/time/system_time.rs)

All internal arithmetic is integer nanoseconds (no float time math — this is
also the invariant that keeps the TPU engine bit-exact, SURVEY.md §7).
Public APIs take float seconds, converted once at the boundary.
"""

from __future__ import annotations

import heapq
from enum import Enum
from typing import Any, Callable, Generator, List, Optional, Tuple

from .context import _tls as _ctx_tls, current_handle
from .futures import Future
from .rand import GlobalRng

NANOS_PER_SEC = 1_000_000_000
MIN_SLEEP_NS = 1_000_000  # 1 ms, tokio parity (time/mod.rs:110-124)
_JUMP_EPSILON_NS = 50  # time/mod.rs:45-60
_EPOCH_2022_S = 1_640_995_200  # 2022-01-01T00:00:00Z


class TimeoutError(Exception):
    """Elapsed deadline from :func:`timeout` (tokio ``Elapsed``)."""


def _to_ns(seconds: float) -> int:
    if seconds < 0:
        raise ValueError("duration must be non-negative")
    return int(round(seconds * NANOS_PER_SEC))


class Instant:
    """Monotonic sim-time point; subtraction gives float seconds."""

    __slots__ = ("ns",)

    def __init__(self, ns: int):
        self.ns = ns

    def __sub__(self, other: "Instant") -> float:
        return (self.ns - other.ns) / NANOS_PER_SEC

    def __add__(self, seconds: float) -> "Instant":
        return Instant(self.ns + _to_ns(seconds))

    def elapsed(self) -> float:
        return current_handle().time.now_instant() - self

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Instant) and self.ns == other.ns

    def __lt__(self, other: "Instant") -> bool:
        return self.ns < other.ns

    def __le__(self, other: "Instant") -> bool:
        return self.ns <= other.ns

    # explicit so `a >= b` doesn't pay Python's reflected-dispatch fallback
    def __gt__(self, other: "Instant") -> bool:
        return self.ns > other.ns

    def __ge__(self, other: "Instant") -> bool:
        return self.ns >= other.ns

    def __hash__(self) -> int:
        return hash(("Instant", self.ns))

    def __repr__(self) -> str:
        return f"Instant({self.ns}ns)"


class _TimerEntry:
    __slots__ = ("deadline_ns", "callback", "cancelled")

    def __init__(self, deadline_ns: int, callback: Callable[[], None]):
        self.deadline_ns = deadline_ns
        self.callback = callback
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class _PyTimerQueue:
    """Default timer queue: heapq of (deadline, seq, entry)."""

    __slots__ = ("_heap", "_seq")

    def __init__(self) -> None:
        self._heap: List[Tuple[int, int, _TimerEntry]] = []
        self._seq = 0

    def push(self, entry: _TimerEntry) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (entry.deadline_ns, self._seq, entry))

    def peek(self) -> Optional[_TimerEntry]:
        while self._heap:
            _d, _s, entry = self._heap[0]
            if entry.cancelled:
                heapq.heappop(self._heap)
                continue
            return entry
        return None

    def pop(self) -> Optional[_TimerEntry]:
        entry = self.peek()
        if entry is not None:
            heapq.heappop(self._heap)
        return entry


class _NativeTimerQueue:
    """Native C++ heap backend (madsim_tpu.native.TimerHeap) — identical
    (deadline, insertion-seq) ordering, selected with MADSIM_NATIVE=1."""

    __slots__ = ("_heap", "_entries", "_next_id")

    def __init__(self) -> None:
        from .native import TimerHeap

        self._heap = TimerHeap()
        self._entries: dict = {}
        self._next_id = 0

    def push(self, entry: _TimerEntry) -> None:
        self._next_id += 1
        self._entries[self._next_id] = entry
        self._heap.push(entry.deadline_ns, self._next_id)

    def peek(self) -> Optional[_TimerEntry]:
        while True:
            top = self._heap.peek()
            if top is None:
                return None
            entry = self._entries[top[1]]
            if entry.cancelled:
                self._heap.pop()
                del self._entries[top[1]]
                continue
            return entry

    def pop(self) -> Optional[_TimerEntry]:
        if self.peek() is None:
            return None
        _d, id = self._heap.pop()
        return self._entries.pop(id)


def _make_timer_queue():
    import os

    if os.environ.get("MADSIM_NATIVE"):
        from . import native

        if native.available():
            return _NativeTimerQueue()
    return _PyTimerQueue()


class TimeHandle:
    """Virtual clock + binary-heap timer queue (time/mod.rs:21-230)."""

    def __init__(self, rng: GlobalRng):
        # Base wall-clock randomized around 2022 (time/mod.rs:27-32) so no
        # workload can depend on the absolute date.
        self._epoch_ns = (
            _EPOCH_2022_S * NANOS_PER_SEC
            + rng.gen_range(0, 365 * 24 * 3600) * NANOS_PER_SEC
        )
        self._clock_ns = 0  # monotonic ns since sim start
        self._q = _make_timer_queue()
        self._skew = {}  # node id -> (num, den) clock-skew ratio
        rng._now_ns = lambda: self._clock_ns

    # -- per-node clock skew (gray failures, docs/faults.md) --------------
    # The fault supervisor (madsim_tpu/faults.apply_schedule) registers a
    # skew ratio while a victim's clock-skew window is open; ``sleep``
    # stretches that node's relative waits by num/den, and user code that
    # computes its own deadlines consults ``node_skew()``. The device
    # tier's counterpart is ``engine.faults.skewed_delay``.

    def set_node_skew(self, node_id, num: int, den: int) -> None:
        self._skew[node_id] = (int(num), int(den))

    def clear_node_skew(self, node_id) -> None:
        self._skew.pop(node_id, None)

    def node_skew_of(self, node_id) -> Tuple[int, int]:
        return self._skew.get(node_id, (1, 1))

    # -- clocks -----------------------------------------------------------

    @property
    def now_ns(self) -> int:
        return self._clock_ns

    def now_instant(self) -> Instant:
        return Instant(self._clock_ns)

    def now_time_ns(self) -> int:
        """Simulated wall-clock (UNIX epoch ns) — SystemTime equivalent."""
        return self._epoch_ns + self._clock_ns

    def elapsed(self) -> float:
        return self._clock_ns / NANOS_PER_SEC

    # -- timers -----------------------------------------------------------

    def add_timer_at_ns(
        self, deadline_ns: int, callback: Callable[[], None]
    ) -> _TimerEntry:
        """Register a callback at an absolute monotonic deadline
        (``TimeHandle::add_timer_at``, time/mod.rs:142-153)."""
        entry = _TimerEntry(deadline_ns, callback)
        self._q.push(entry)
        return entry

    def add_timer_ns(self, delay_ns: int, callback: Callable[[], None]) -> _TimerEntry:
        return self.add_timer_at_ns(self._clock_ns + max(0, delay_ns), callback)

    def add_timer(self, delay_s: float, callback: Callable[[], None]) -> _TimerEntry:
        return self.add_timer_ns(_to_ns(delay_s), callback)

    def next_deadline_ns(self) -> Optional[int]:
        entry = self._q.peek()
        return entry.deadline_ns if entry is not None else None

    def _fire_due(self) -> int:
        fired = 0
        while True:
            entry = self._q.peek()
            if entry is None or entry.deadline_ns > self._clock_ns:
                break
            self._q.pop()
            entry.callback()
            fired += 1
        return fired

    def advance_ns(self, delta_ns: int) -> None:
        """Jump the clock forward, firing any timers that become due
        (``time::advance`` / per-poll 50-100ns advance)."""
        clock = self._clock_ns = self._clock_ns + delta_ns
        # fast path: nothing due (runs once per executor poll) — a
        # cancelled head entry compares the same, so skipping is correct
        heap = getattr(self._q, "_heap", None)
        if type(heap) is list:
            if not heap or heap[0][0] > clock:
                return
        self._fire_due()

    def advance(self, seconds: float) -> None:
        self.advance_ns(_to_ns(seconds))

    def advance_to_next_event(self) -> bool:
        """Pop the earliest timer and jump the clock to it (+50 ns epsilon);
        returns False when no timers remain — the deadlock signal
        (time/mod.rs:45-60)."""
        deadline = self.next_deadline_ns()
        if deadline is None:
            return False
        self._clock_ns = max(self._clock_ns, deadline + _JUMP_EPSILON_NS)
        self._fire_due()
        return True


# -- compiled time core (native/simloop.c) ---------------------------------

try:
    from . import native as _native

    _simloop = _native.simloop()
except Exception:  # pragma: no cover - native tier is always optional
    _simloop = None
if _simloop is not None:
    _simloop._configure(Instant)  # lets the C Sleep build .deadline Instants


class _NativeTimeHandle(TimeHandle):
    """TimeHandle over the compiled clock + timer heap (native/simloop.c).

    Identical (deadline, insertion-seq) ordering and jump semantics as the
    Python heapq path — schedules are byte-identical with the core on or
    off (MADSIM_NO_NATIVE=1)."""

    def __init__(self, rng: GlobalRng):
        # same epoch draw as the base class, so the RNG stream is identical
        self._epoch_ns = (
            _EPOCH_2022_S * NANOS_PER_SEC
            + rng.gen_range(0, 365 * 24 * 3600) * NANOS_PER_SEC
        )
        self._core = core = _simloop.Timers()
        self._q = None  # the heap lives in the core
        self._skew = {}  # node id -> (num, den) clock-skew ratio
        rng._now_ns = lambda: core.clock

    @property
    def now_ns(self) -> int:
        return self._core.clock

    def now_instant(self) -> Instant:
        return Instant(self._core.clock)

    def now_time_ns(self) -> int:
        return self._epoch_ns + self._core.clock

    def elapsed(self) -> float:
        return self._core.clock / NANOS_PER_SEC

    def add_timer_at_ns(self, deadline_ns: int, callback: Callable[[], None]):
        return self._core.push(deadline_ns, callback)

    def add_timer_ns(self, delay_ns: int, callback: Callable[[], None]):
        core = self._core
        return core.push(core.clock + max(0, delay_ns), callback)

    def next_deadline_ns(self) -> Optional[int]:
        return self._core.peek_deadline()

    def _fire_due(self) -> int:
        return self._core.fire_due()

    def advance_ns(self, delta_ns: int) -> None:
        self._core.advance_ns(delta_ns)

    def advance_to_next_event(self) -> bool:
        return self._core.advance_to_next_event(_JUMP_EPSILON_NS)


def make_time_handle(rng: GlobalRng) -> TimeHandle:
    """The runtime's TimeHandle factory: compiled core by default,
    pure Python under MADSIM_NO_NATIVE=1 (or MADSIM_NATIVE=1, which
    selects the older ctypes heap instead)."""
    import os

    if _simloop is not None and not os.environ.get("MADSIM_NATIVE"):
        return _NativeTimeHandle(rng)
    return TimeHandle(rng)


# -- Sleep future (sim/time/sleep.rs:20-55) --------------------------------


class Sleep(Future):
    """Resolves when the virtual clock reaches ``deadline``.

    The timer is registered lazily on first poll (subscribe), matching the
    reference's poll-registered waker (sleep.rs:30-44).
    """

    __slots__ = ("_time", "_deadline_ns", "_timer")

    def __init__(self, time: TimeHandle, deadline_ns: int):
        super().__init__()
        self._time = time
        self._deadline_ns = deadline_ns
        self._timer: Optional[_TimerEntry] = None

    @property
    def deadline(self) -> Instant:
        return Instant(self._deadline_ns)

    def is_elapsed(self) -> bool:
        return self.done()

    def subscribe(self, task: Any) -> None:
        if not self.done() and self._timer is None:
            if self._deadline_ns <= self._time.now_ns:
                self.set_result(None)
            else:
                self._timer = self._time.add_timer_at_ns(
                    self._deadline_ns, lambda: self.set_result(None)
                )
        super().subscribe(task)

    def reset(self, deadline: Instant) -> None:
        """Move the deadline (``Sleep::reset``, sleep.rs:47-55)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._reset()
        self._deadline_ns = deadline.ns
        if self._wakers:
            # tasks are already awaiting: re-arm immediately — they won't be
            # polled again (and so won't re-subscribe) until we fire
            if self._deadline_ns <= self._time.now_ns:
                self.set_result(None)
            else:
                self._timer = self._time.add_timer_at_ns(
                    self._deadline_ns, lambda: self.set_result(None)
                )


def _new_sleep(t: TimeHandle, deadline_ns: int):
    """Sleep factory: the C Sleep on the compiled core, else the Python
    one — same lazy first-subscribe timer arming either way."""
    core = getattr(t, "_core", None)
    if core is not None:
        return _simloop.Sleep(core, deadline_ns)
    return Sleep(t, deadline_ns)


_ns_cache: dict = {}  # duration float -> clamped ns (workloads reuse a few constants)


def sleep(seconds: float) -> Sleep:
    """Sleep for a virtual duration (min 1 ms, tokio parity).

    While the calling task's node is inside a clock-skew window
    (docs/faults.md gray failures), the wait stretches by the registered
    num/den ratio — the node's slow clock measures the duration."""
    # hand-inlined ambient lookup + _to_ns: this is the hottest API call
    # in a typical workload (one per task loop iteration)
    h = getattr(_ctx_tls, "handle", None)
    if h is None:
        current_handle()  # raises NoContextError with the standard message
    ns = _ns_cache.get(seconds)
    if ns is None:
        if seconds < 0:
            raise ValueError("duration must be non-negative")
        ns = int(round(seconds * NANOS_PER_SEC))
        if ns < MIN_SLEEP_NS:
            ns = MIN_SLEEP_NS
        if len(_ns_cache) < 4096:
            _ns_cache[seconds] = ns
    t = h.time
    if t._skew:  # empty dict outside skew windows: one falsy check
        task = getattr(_ctx_tls, "task", None)
        if task is not None:
            f = t._skew.get(task.node.id)
            if f is not None:
                ns = ns * f[0] // f[1]
    core = getattr(t, "_core", None)
    if core is not None:
        return _simloop.Sleep(core, core.clock + ns)
    return Sleep(t, t.now_ns + ns)


def sleep_until(deadline: Instant) -> Sleep:
    t = current_handle().time
    return _new_sleep(t, deadline.ns)


class _InlineTimeout:
    """Drive a coroutine to completion WITHIN the current task, bounded
    by a deadline.

    The reference's ``time::timeout`` polls the inner future inline
    (time/mod.rs:183-196) — it does not spawn it. That matters for error
    flow: an exception raised by the timed coroutine must propagate to
    the awaiter (where a ``try``/``except`` can catch it), not take down
    a separate task (the executor treats an unhandled task exception as
    a panic and aborts the simulation). On expiry the coroutine is
    closed — ``finally`` blocks run, the drop analogue — and
    :class:`TimeoutError` is raised.
    """

    __slots__ = ("_coro", "_sleep", "_cur", "_seconds")

    def __init__(self, coro, sleep_fut: Sleep, seconds: float):
        self._coro = coro
        self._sleep = sleep_fut
        self._cur = None  # pollable the inner coroutine is blocked on
        self._seconds = seconds

    def subscribe(self, task: Any) -> None:
        self._sleep.subscribe(task)
        if self._cur is not None:
            self._cur.subscribe(task)

    def __await__(self):
        # the finally closes the inner coroutine on EVERY exit — timeout,
        # and cancellation (GeneratorExit thrown at the yield when the
        # awaiting task is killed/aborted) — so drop cleanup (finally
        # blocks, BindGuard releases) runs deterministically, not at GC
        # time; close() after normal completion is a no-op
        try:
            while True:
                try:
                    # poll the inner coroutine FIRST (tokio's Timeout
                    # polls the future before the deadline, so an answer
                    # that lands on the deadline instant wins; spurious
                    # re-polls are fine — inner __await__ loops re-yield
                    # while pending)
                    self._cur = self._coro.send(None)
                except StopIteration as stop:
                    return stop.value
                if self._sleep.done():
                    raise TimeoutError(
                        f"deadline has elapsed after {self._seconds}s"
                    )
                yield self
        finally:
            self._coro.close()


async def timeout(seconds: float, awaitable: Any) -> Any:
    """Await ``awaitable`` with a virtual-time deadline.

    Coroutines are polled inline in the current task and closed on
    expiry (the drop analogue; exceptions propagate to the awaiter —
    ``time::timeout``, time/mod.rs:183-196); Future-likes are raced
    directly. Raises :class:`TimeoutError` on expiry.
    """
    import inspect

    from .futures import select

    if inspect.iscoroutine(awaitable):
        return await _InlineTimeout(awaitable, sleep(seconds), seconds)
    idx, value = await select(awaitable, sleep(seconds))
    if idx == 0:
        return value
    raise TimeoutError(f"deadline has elapsed after {seconds}s")


# -- Interval (sim/time/interval.rs:38-192) --------------------------------


class MissedTickBehavior(Enum):
    BURST = "burst"
    DELAY = "delay"
    SKIP = "skip"


class Interval:
    """Periodic ticks with tokio ``MissedTickBehavior`` semantics."""

    def __init__(self, time: TimeHandle, start_ns: int, period_ns: int):
        if period_ns <= 0:
            raise ValueError("interval period must be positive")
        self._time = time
        self._period_ns = period_ns
        self._deadline_ns = start_ns
        self.missed_tick_behavior = MissedTickBehavior.BURST

    @property
    def period(self) -> float:
        return self._period_ns / NANOS_PER_SEC

    async def tick(self) -> Instant:
        await _new_sleep(self._time, self._deadline_ns)
        scheduled = self._deadline_ns
        now = self._time.now_ns
        b = self.missed_tick_behavior
        if b is MissedTickBehavior.BURST:
            self._deadline_ns = scheduled + self._period_ns
        elif b is MissedTickBehavior.DELAY:
            self._deadline_ns = now + self._period_ns
        else:  # SKIP: next multiple of period after now
            missed = (now - scheduled) // self._period_ns + 1
            self._deadline_ns = scheduled + missed * self._period_ns
        return Instant(scheduled)

    def reset(self) -> None:
        self._deadline_ns = self._time.now_ns + self._period_ns


def interval(period: float) -> Interval:
    """First tick completes immediately (tokio ``interval``)."""
    t = current_handle().time
    return Interval(t, t.now_ns, _to_ns(period))


def interval_at(start: Instant, period: float) -> Interval:
    t = current_handle().time
    return Interval(t, start.ns, _to_ns(period))


# -- ambient conveniences --------------------------------------------------


def now_instant() -> Instant:
    h = getattr(_ctx_tls, "handle", None)
    if h is None:
        current_handle()  # raises NoContextError
    t = h.time
    core = getattr(t, "_core", None)
    return Instant(core.clock if core is not None else t._clock_ns)


def now() -> float:
    """Simulated wall-clock time as float UNIX seconds (SystemTime::now)."""
    return current_handle().time.now_time_ns() / NANOS_PER_SEC


def elapsed() -> float:
    """Seconds of virtual time since the simulation started."""
    return current_handle().time.elapsed()


def node_skew() -> "Tuple[int, int]":
    """The current task's node clock-skew ratio ``(num, den)`` — ``(1,
    1)`` outside a skew window. User code that computes its own
    deadlines (rather than sleeping the full duration) applies this to
    the duration, mirroring what ``sleep`` does automatically; see
    ``examples/raft_host.py`` election deadlines."""
    h = getattr(_ctx_tls, "handle", None)
    if h is None:
        current_handle()  # raises NoContextError
    if not h.time._skew:
        return (1, 1)
    task = getattr(_ctx_tls, "task", None)
    if task is None:
        return (1, 1)
    return h.time._skew.get(task.node.id, (1, 1))


def advance(seconds: float) -> None:
    """Manually advance the virtual clock (``time::advance``)."""
    current_handle().time.advance(seconds)
