"""The etcd service state machine (madsim-etcd-client/src/service.rs).

Pure deterministic state: ``ServiceInner { revision, kv: BTreeMap, lease:
HashMap, watcher: EventBus }`` (service.rs:189-198) with full
put/get(prefix)/delete/txn(compare+ops, recursive)/compact, leases whose
TTLs tick down in simulated seconds, and elections built on prefix
watches. No I/O here — the server wraps this in a node (server.py).
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from ..futures import Future
from ..grpc.status import Status

MAX_REQUEST_SIZE = int(1.5 * 1024 * 1024)  # service.rs:36


def _b(x: "str | bytes") -> bytes:
    return x.encode() if isinstance(x, str) else bytes(x)


@dataclass
class KeyValue:
    """etcd mvccpb.KeyValue."""

    key: bytes
    value: bytes
    create_revision: int
    mod_revision: int
    version: int = 1
    lease: int = 0

    def key_str(self) -> str:
        return self.key.decode()

    def value_str(self) -> str:
        return self.value.decode()


class EventType(Enum):
    PUT = "put"
    DELETE = "delete"


@dataclass
class Event:
    type: EventType
    kv: KeyValue
    prev_kv: Optional[KeyValue] = None


# -- options (fluent mirrors of etcd-client's *Options) ---------------------


@dataclass
class PutOptions:
    lease: int = 0
    prev_kv: bool = False

    def with_lease(self, lease: int) -> "PutOptions":
        self.lease = lease
        return self

    def with_prev_key(self) -> "PutOptions":
        self.prev_kv = True
        return self


@dataclass
class GetOptions:
    prefix: bool = False
    range_end: Optional[bytes] = None
    limit: int = 0
    revision: int = 0
    count_only: bool = False
    keys_only: bool = False
    #: etcd's from-key convention (range_end = "\0"): every key >= key
    from_key: bool = False

    def with_prefix(self) -> "GetOptions":
        self.prefix = True
        return self

    def with_range(self, end: "str | bytes") -> "GetOptions":
        self.range_end = _b(end)
        return self

    def with_limit(self, n: int) -> "GetOptions":
        self.limit = n
        return self

    def with_count_only(self) -> "GetOptions":
        self.count_only = True
        return self

    def with_keys_only(self) -> "GetOptions":
        self.keys_only = True
        return self


@dataclass
class DeleteOptions:
    prefix: bool = False
    range_end: Optional[bytes] = None
    prev_kv: bool = False
    #: etcd's from-key convention (range_end = "\0"): every key >= key
    from_key: bool = False

    def with_prefix(self) -> "DeleteOptions":
        self.prefix = True
        return self

    def with_range(self, end: "str | bytes") -> "DeleteOptions":
        self.range_end = _b(end)
        return self

    def with_prev_key(self) -> "DeleteOptions":
        self.prev_kv = True
        return self


class CompareOp(Enum):
    EQUAL = "="
    GREATER = ">"
    LESS = "<"
    NOT_EQUAL = "!="


@dataclass
class Compare:
    """Txn guard: compare a key's value/revision/version/lease.

    With ``range_end`` (or ``from_key``) set this is a RANGE compare
    (etcd >= 3.3): the predicate must hold for EVERY key in the range;
    an empty range is evaluated against the missing-key defaults (so the
    "no key in range exists" idiom — version == 0 — holds vacuously)."""

    key: bytes
    target: str  # "value" | "version" | "create_revision" | "mod_revision" | "lease"
    op: CompareOp
    operand: Any
    range_end: Optional[bytes] = None
    from_key: bool = False

    @staticmethod
    def value(key: "str | bytes", op: CompareOp, v: "str | bytes") -> "Compare":
        return Compare(_b(key), "value", op, _b(v))

    @staticmethod
    def version(key: "str | bytes", op: CompareOp, v: int) -> "Compare":
        return Compare(_b(key), "version", op, v)

    @staticmethod
    def create_revision(key: "str | bytes", op: CompareOp, v: int) -> "Compare":
        return Compare(_b(key), "create_revision", op, v)

    @staticmethod
    def mod_revision(key: "str | bytes", op: CompareOp, v: int) -> "Compare":
        return Compare(_b(key), "mod_revision", op, v)

    @staticmethod
    def lease(key: "str | bytes", op: CompareOp, v: int) -> "Compare":
        return Compare(_b(key), "lease", op, v)


@dataclass
class TxnOp:
    """One op inside a txn branch (put/get/delete/nested txn)."""

    kind: str
    args: Tuple = ()

    @staticmethod
    def put(key: "str | bytes", value: "str | bytes",
            options: Optional[PutOptions] = None) -> "TxnOp":
        return TxnOp("put", (_b(key), _b(value), options or PutOptions()))

    @staticmethod
    def get(key: "str | bytes", options: Optional[GetOptions] = None) -> "TxnOp":
        return TxnOp("get", (_b(key), options or GetOptions()))

    @staticmethod
    def delete(key: "str | bytes", options: Optional[DeleteOptions] = None) -> "TxnOp":
        return TxnOp("delete", (_b(key), options or DeleteOptions()))

    @staticmethod
    def txn(txn: "Txn") -> "TxnOp":
        return TxnOp("txn", (txn,))


@dataclass
class Txn:
    """compare-and-ops transaction (recursive — service.rs txn handling)."""

    compares: List[Compare] = field(default_factory=list)
    success: List[TxnOp] = field(default_factory=list)
    failure: List[TxnOp] = field(default_factory=list)

    def when(self, compares: List[Compare]) -> "Txn":
        self.compares = list(compares)
        return self

    def and_then(self, ops: List[TxnOp]) -> "Txn":
        self.success = list(ops)
        return self

    def or_else(self, ops: List[TxnOp]) -> "Txn":
        self.failure = list(ops)
        return self


@dataclass
class Lease:
    id: int
    ttl: int  # granted TTL seconds
    remaining: int  # seconds until expiry (ticked down)
    keys: set = field(default_factory=set)


class EventBus:
    """Prefix-watch pub/sub (the reference's watcher EventBus).

    ``future_factory`` produces the one-shot wakeup cell watchers block on;
    the default is the sim Future, and real mode (real/etcd.py) swaps in
    ``asyncio`` futures so the same service runs on a real event loop."""

    def __init__(self) -> None:
        self._watchers: List[Tuple[bytes, bool, List[Event], List[Future]]] = []
        self.future_factory = Future

    def subscribe(self, key: bytes, prefix: bool) -> "Watcher":
        entry = (key, prefix, [], [])
        self._watchers.append(entry)
        return Watcher(self, entry)

    def publish(self, event: Event) -> None:
        for key, prefix, queue, futs in self._watchers:
            match = (
                event.kv.key.startswith(key) if prefix else event.kv.key == key
            )
            if match:
                queue.append(event)
                waiters, futs[:] = futs[:], []
                for f in waiters:
                    if not f.done():  # asyncio futures raise if cancelled
                        f.set_result(None)


class Watcher:
    def __init__(self, bus: EventBus, entry: Tuple):
        self._bus = bus
        self._entry = entry

    async def next(self) -> Event:
        _key, _prefix, queue, futs = self._entry
        while not queue:
            fut = self._bus.future_factory()
            futs.append(fut)
            await fut
        return queue.pop(0)

    def cancel(self) -> None:
        try:
            self._bus._watchers.remove(self._entry)
        except ValueError:
            pass


class EtcdService:
    """``ServiceInner`` (service.rs:189-198) — the whole etcd state."""

    def __init__(self) -> None:
        self.revision = 0
        self.kv: Dict[bytes, KeyValue] = {}
        self.leases: Dict[int, Lease] = {}
        self.bus = EventBus()
        self._next_lease_id = 0x70000000

    # -- kv ----------------------------------------------------------------

    def _select(
        self,
        key: bytes,
        prefix: bool,
        range_end: Optional[bytes],
        from_key: bool = False,
    ) -> List[KeyValue]:
        if from_key:
            items = [kv for k, kv in self.kv.items() if k >= key]
        elif range_end is not None:
            items = [kv for k, kv in self.kv.items() if key <= k < range_end]
        elif prefix:
            items = [kv for k, kv in self.kv.items() if k.startswith(key)]
        else:
            items = [self.kv[key]] if key in self.kv else []
        return sorted(items, key=lambda kv: kv.key)

    def put(self, key: bytes, value: bytes, options: PutOptions) -> Tuple[int, Optional[KeyValue]]:
        if len(key) + len(value) > MAX_REQUEST_SIZE:
            raise Status.invalid_argument("etcdserver: request is too large")
        if options.lease and options.lease not in self.leases:
            raise Status.not_found("etcdserver: requested lease not found")
        self.revision += 1
        prev = self.kv.get(key)
        kv = KeyValue(
            key=key,
            value=value,
            create_revision=prev.create_revision if prev else self.revision,
            mod_revision=self.revision,
            version=prev.version + 1 if prev else 1,
            lease=options.lease,
        )
        self.kv[key] = kv
        if options.lease:
            self.leases[options.lease].keys.add(key)
        if prev and prev.lease and prev.lease != options.lease:
            lease = self.leases.get(prev.lease)
            if lease:
                lease.keys.discard(key)
        self.bus.publish(Event(EventType.PUT, kv, prev))
        return self.revision, prev if options.prev_kv else None

    def get(self, key: bytes, options: GetOptions) -> Tuple[int, List[KeyValue], int]:
        items = self._select(
            key, options.prefix, options.range_end, options.from_key
        )
        count = len(items)
        if options.limit:
            items = items[: options.limit]
        if options.count_only:
            items = []
        if options.keys_only:
            items = [
                KeyValue(kv.key, b"", kv.create_revision, kv.mod_revision,
                         kv.version, kv.lease)
                for kv in items
            ]
        return self.revision, items, count

    def delete(self, key: bytes, options: DeleteOptions) -> Tuple[int, int, List[KeyValue]]:
        items = self._select(
            key, options.prefix, options.range_end, options.from_key
        )
        if items:
            self.revision += 1
        for kv in items:
            del self.kv[kv.key]
            if kv.lease:
                lease = self.leases.get(kv.lease)
                if lease:
                    lease.keys.discard(kv.key)
            tomb = KeyValue(kv.key, b"", kv.create_revision, self.revision, 0, 0)
            self.bus.publish(Event(EventType.DELETE, tomb, kv))
        return self.revision, len(items), items if options.prev_kv else []

    def txn(self, txn: Txn) -> Tuple[int, bool, List[Any]]:
        succeeded = all(self._check(c) for c in txn.compares)
        results = [
            self._apply(op) for op in (txn.success if succeeded else txn.failure)
        ]
        return self.revision, succeeded, results

    def _check(self, c: Compare) -> bool:
        if c.range_end is not None or c.from_key:
            # range compare: must hold for every key in the range; empty
            # range -> evaluate once against missing-key defaults
            items = self._select(c.key, False, c.range_end, c.from_key)
            if not items:
                return self._check_one(None, c)
            return all(self._check_one(kv, c) for kv in items)
        return self._check_one(self.kv.get(c.key), c)

    def _check_one(self, kv: Optional[KeyValue], c: Compare) -> bool:
        if c.target == "value":
            actual: Any = kv.value if kv else b""
        elif kv is None:
            actual = 0
        else:
            actual = getattr(kv, c.target)
        op = c.op
        if op is CompareOp.EQUAL:
            return actual == c.operand
        if op is CompareOp.NOT_EQUAL:
            return actual != c.operand
        if op is CompareOp.GREATER:
            return actual > c.operand
        return actual < c.operand

    def _apply(self, op: TxnOp) -> Tuple[str, Any]:
        if op.kind == "put":
            key, value, options = op.args
            rev, prev = self.put(key, value, options)
            return ("put", (rev, prev))
        if op.kind == "get":
            key, options = op.args
            return ("get", self.get(key, options))
        if op.kind == "delete":
            key, options = op.args
            return ("delete", self.delete(key, options))
        return ("txn", self.txn(op.args[0]))

    def compact(self, revision: int) -> int:
        if revision > self.revision:
            raise Status.out_of_range(
                "etcdserver: mvcc: required revision is a future revision"
            )
        return self.revision

    # -- lease (service.rs:27-33,466-485) ----------------------------------

    def lease_grant(self, ttl: int, lease_id: int = 0) -> Tuple[int, int]:
        if lease_id == 0:
            self._next_lease_id += 1
            lease_id = self._next_lease_id
        if lease_id in self.leases:
            raise Status.failed_precondition("etcdserver: lease already exists")
        self.leases[lease_id] = Lease(id=lease_id, ttl=ttl, remaining=ttl)
        return lease_id, ttl

    def lease_revoke(self, lease_id: int) -> None:
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            raise Status.not_found("etcdserver: requested lease not found")
        for key in sorted(lease.keys):
            self.delete(key, DeleteOptions())

    def lease_keep_alive(self, lease_id: int) -> Tuple[int, int]:
        lease = self.leases.get(lease_id)
        if lease is None:
            raise Status.not_found("etcdserver: requested lease not found")
        lease.remaining = lease.ttl
        return lease_id, lease.ttl

    def lease_time_to_live(self, lease_id: int) -> Tuple[int, int, int, List[bytes]]:
        lease = self.leases.get(lease_id)
        if lease is None:
            raise Status.not_found("etcdserver: requested lease not found")
        return lease_id, lease.remaining, lease.ttl, sorted(lease.keys)

    def lease_leases(self) -> List[int]:
        return sorted(self.leases)

    def tick(self) -> None:
        """One simulated second: expire leases (the reference's per-second
        tick task, service.rs:27-33)."""
        expired = []
        for lease in self.leases.values():
            lease.remaining -= 1
            if lease.remaining < 0:
                expired.append(lease.id)
        for lid in expired:
            self.lease_revoke(lid)

    # -- election (service.rs:487-583) --------------------------------------

    def election_key(self, name: bytes, lease_id: int) -> bytes:
        return name + b"/" + format(lease_id, "x").encode()

    def campaign_try(self, name: bytes, value: bytes, lease_id: int) -> Optional[bytes]:
        """Write our candidacy key; return the key if we are now leader
        (lowest create_revision under the election prefix), else None."""
        if lease_id not in self.leases:
            raise Status.not_found("etcdserver: requested lease not found")
        key = self.election_key(name, lease_id)
        if key not in self.kv:
            self.put(key, value, PutOptions(lease=lease_id))
        leader = self.election_leader(name)
        return key if leader is not None and leader.key == key else None

    def election_leader(self, name: bytes) -> Optional[KeyValue]:
        _rev, items, _n = self.get(name + b"/", GetOptions(prefix=True))
        if not items:
            return None
        return min(items, key=lambda kv: kv.create_revision)

    def proclaim(self, key: bytes, value: bytes) -> None:
        kv = self.kv.get(key)
        if kv is None:
            raise Status.failed_precondition("election: session expired")
        self.put(key, value, PutOptions(lease=kv.lease))

    def resign(self, key: bytes) -> None:
        self.delete(key, DeleteOptions())

    # -- snapshot (dump/load — service.rs:160-163) --------------------------

    def dump(self) -> str:
        def enc(b: bytes) -> str:
            return base64.b64encode(b).decode()

        return json.dumps(
            {
                "revision": self.revision,
                "next_lease_id": self._next_lease_id,
                "kv": [
                    {
                        "key": enc(kv.key),
                        "value": enc(kv.value),
                        "create_revision": kv.create_revision,
                        "mod_revision": kv.mod_revision,
                        "version": kv.version,
                        "lease": kv.lease,
                    }
                    for kv in sorted(self.kv.values(), key=lambda kv: kv.key)
                ],
                "leases": [
                    {
                        "id": l.id,
                        "ttl": l.ttl,
                        "remaining": l.remaining,
                        "keys": [enc(k) for k in sorted(l.keys)],
                    }
                    for l in sorted(self.leases.values(), key=lambda l: l.id)
                ],
            },
            indent=2,
        )

    def load(self, dump: str) -> None:
        def dec(s: str) -> bytes:
            return base64.b64decode(s)

        data = json.loads(dump)
        self.revision = data["revision"]
        self._next_lease_id = data["next_lease_id"]
        self.kv = {
            dec(e["key"]): KeyValue(
                dec(e["key"]), dec(e["value"]), e["create_revision"],
                e["mod_revision"], e["version"], e["lease"]
            )
            for e in data["kv"]
        }
        self.leases = {
            e["id"]: Lease(
                id=e["id"], ttl=e["ttl"], remaining=e["remaining"],
                keys={dec(k) for k in e["keys"]},
            )
            for e in data["leases"]
        }
