"""etcd v3 gRPC wire: the framework's etcd state machine served over the
REAL etcd protocol (``/etcdserverpb.KV/*``, ``/etcdserverpb.Lease/*``).

The reference's madsim-etcd-client compiles to the *real* etcd-client
crate outside the sim — its std mode speaks actual etcd gRPC. This image
has no etcd server or client library to link against, but it does have
grpcio + protoc, so this module holds the same property from the server
side: ``WireServer`` serves :class:`~madsim_tpu.etcd.service.EtcdService`
(the exact state machine the simulator uses, ref service.rs:189-198)
over genuine gRPC with the etcd v3 message schema, so any stock etcd v3
client — in any language — can Put/Range/DeleteRange/Txn/Compact and
Grant/Revoke/KeepAlive leases against it.

Schema notes: the message/field layout below is transcribed from etcd's
public ``rpc.proto``/``kv.proto`` (field numbers and types must match for
wire compatibility; message *names* need not — a peer never sees this
descriptor). ``mvccpb.KeyValue`` is declared inside the ``etcdserverpb``
package here because one .proto holds one package; the wire bytes are
identical. Scope: the KV, Lease, Watch, and Maintenance services
(Status/Alarm/Defragment/Hash/Snapshot — the surface health tooling
touches; the snapshot blob is this server's JSON dump, see
``_make_maintenance_service``).
Watches deliver current changes only: a FUTURE ``start_revision`` (the
read-then-watch-from-R+1 pattern) is served, with events below the start
suppressed; a PAST one — which would need MVCC history this server does
not keep — is answered with an immediate cancel naming the reason.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ..grpc import protogen
from .service import (
    Compare,
    CompareOp,
    DeleteOptions,
    EtcdService,
    GetOptions,
    KeyValue,
    PutOptions,
)

ETCD_PROTO = """
syntax = "proto3";
package etcdserverpb;

// mvccpb.KeyValue, inlined (same field numbers; see module docstring)
message KeyValue {
  bytes key = 1;
  int64 create_revision = 2;
  int64 mod_revision = 3;
  int64 version = 4;
  bytes value = 5;
  int64 lease = 6;
}

message ResponseHeader {
  uint64 cluster_id = 1;
  uint64 member_id = 2;
  int64 revision = 3;
  uint64 raft_term = 4;
}

message RangeRequest {
  enum SortOrder { NONE = 0; ASCEND = 1; DESCEND = 2; }
  enum SortTarget { KEY = 0; VERSION = 1; CREATE = 2; MOD = 3; VALUE = 4; }
  bytes key = 1;
  bytes range_end = 2;
  int64 limit = 3;
  int64 revision = 4;
  SortOrder sort_order = 5;
  SortTarget sort_target = 6;
  bool serializable = 7;
  bool keys_only = 8;
  bool count_only = 9;
  int64 min_mod_revision = 10;
  int64 max_mod_revision = 11;
  int64 min_create_revision = 12;
  int64 max_create_revision = 13;
}

message RangeResponse {
  ResponseHeader header = 1;
  repeated KeyValue kvs = 2;
  bool more = 3;
  int64 count = 4;
}

message PutRequest {
  bytes key = 1;
  bytes value = 2;
  int64 lease = 3;
  bool prev_kv = 4;
  bool ignore_value = 5;
  bool ignore_lease = 6;
}

message PutResponse {
  ResponseHeader header = 1;
  KeyValue prev_kv = 2;
}

message DeleteRangeRequest {
  bytes key = 1;
  bytes range_end = 2;
  bool prev_kv = 3;
}

message DeleteRangeResponse {
  ResponseHeader header = 1;
  int64 deleted = 2;
  repeated KeyValue prev_kvs = 3;
}

message RequestOp {
  oneof request {
    RangeRequest request_range = 1;
    PutRequest request_put = 2;
    DeleteRangeRequest request_delete_range = 3;
    TxnRequest request_txn = 4;
  }
}

message ResponseOp {
  oneof response {
    RangeResponse response_range = 1;
    PutResponse response_put = 2;
    DeleteRangeResponse response_delete_range = 3;
    TxnResponse response_txn = 4;
  }
}

message Compare {
  enum CompareResult { EQUAL = 0; GREATER = 1; LESS = 2; NOT_EQUAL = 3; }
  enum CompareTarget { VERSION = 0; CREATE = 1; MOD = 2; VALUE = 3; LEASE = 4; }
  CompareResult result = 1;
  CompareTarget target = 2;
  bytes key = 3;
  oneof target_union {
    int64 version = 4;
    int64 create_revision = 5;
    int64 mod_revision = 6;
    bytes value = 7;
    int64 lease = 8;
  }
  bytes range_end = 64;
}

message TxnRequest {
  repeated Compare compare = 1;
  repeated RequestOp success = 2;
  repeated RequestOp failure = 3;
}

message TxnResponse {
  ResponseHeader header = 1;
  bool succeeded = 2;
  repeated ResponseOp responses = 3;
}

message CompactionRequest {
  int64 revision = 1;
  bool physical = 2;
}

message CompactionResponse {
  ResponseHeader header = 1;
}

message LeaseGrantRequest {
  int64 TTL = 1;
  int64 ID = 2;
}

message LeaseGrantResponse {
  ResponseHeader header = 1;
  int64 ID = 2;
  int64 TTL = 3;
  string error = 4;
}

message LeaseRevokeRequest { int64 ID = 1; }
message LeaseRevokeResponse { ResponseHeader header = 1; }

message LeaseKeepAliveRequest { int64 ID = 1; }
message LeaseKeepAliveResponse {
  ResponseHeader header = 1;
  int64 ID = 2;
  int64 TTL = 3;
}

message LeaseTimeToLiveRequest {
  int64 ID = 1;
  bool keys = 2;
}
message LeaseTimeToLiveResponse {
  ResponseHeader header = 1;
  int64 ID = 2;
  int64 TTL = 3;
  int64 grantedTTL = 4;
  repeated bytes keys = 5;
}

message LeaseLeasesRequest {}
message LeaseStatus { int64 ID = 1; }
message LeaseLeasesResponse {
  ResponseHeader header = 1;
  repeated LeaseStatus leases = 2;
}

// mvccpb.Event, inlined like KeyValue
message Event {
  enum EventType { PUT = 0; DELETE = 1; }
  EventType type = 1;
  KeyValue kv = 2;
  KeyValue prev_kv = 3;
}

message WatchCreateRequest {
  enum FilterType { NOPUT = 0; NODELETE = 1; }
  bytes key = 1;
  bytes range_end = 2;
  int64 start_revision = 3;
  bool progress_notify = 4;
  repeated FilterType filters = 5;
  bool prev_kv = 6;
  int64 watch_id = 7;
  bool fragment = 8;
}

message WatchCancelRequest { int64 watch_id = 1; }
message WatchProgressRequest {}

message WatchRequest {
  oneof request_union {
    WatchCreateRequest create_request = 1;
    WatchCancelRequest cancel_request = 2;
    WatchProgressRequest progress_request = 3;
  }
}

message WatchResponse {
  ResponseHeader header = 1;
  int64 watch_id = 2;
  bool created = 3;
  bool canceled = 4;
  int64 compact_revision = 5;
  string cancel_reason = 6;
  bool fragment = 7;
  repeated Event events = 11;
}

service KV {
  rpc Range (RangeRequest) returns (RangeResponse);
  rpc Put (PutRequest) returns (PutResponse);
  rpc DeleteRange (DeleteRangeRequest) returns (DeleteRangeResponse);
  rpc Txn (TxnRequest) returns (TxnResponse);
  rpc Compact (CompactionRequest) returns (CompactionResponse);
}

service Lease {
  rpc LeaseGrant (LeaseGrantRequest) returns (LeaseGrantResponse);
  rpc LeaseRevoke (LeaseRevokeRequest) returns (LeaseRevokeResponse);
  rpc LeaseKeepAlive (stream LeaseKeepAliveRequest)
      returns (stream LeaseKeepAliveResponse);
  rpc LeaseTimeToLive (LeaseTimeToLiveRequest)
      returns (LeaseTimeToLiveResponse);
  rpc LeaseLeases (LeaseLeasesRequest) returns (LeaseLeasesResponse);
}

service Watch {
  rpc Watch (stream WatchRequest) returns (stream WatchResponse);
}

message StatusRequest {}
message StatusResponse {
  ResponseHeader header = 1;
  string version = 2;
  int64 dbSize = 3;
  uint64 leader = 4;
  uint64 raftIndex = 5;
  uint64 raftTerm = 6;
  uint64 raftAppliedIndex = 7;
  repeated string errors = 8;
  int64 dbSizeInUse = 9;
  bool isLearner = 10;
}

message AlarmRequest {
  enum AlarmAction { GET = 0; ACTIVATE = 1; DEACTIVATE = 2; }
  AlarmAction action = 1;
  uint64 memberID = 2;
  AlarmType alarm = 3;
}
enum AlarmType { NONE = 0; NOSPACE = 1; CORRUPT = 2; }
message AlarmMember {
  uint64 memberID = 1;
  AlarmType alarm = 2;
}
message AlarmResponse {
  ResponseHeader header = 1;
  repeated AlarmMember alarms = 2;
}

message DefragmentRequest {}
message DefragmentResponse { ResponseHeader header = 1; }

message HashRequest {}
message HashResponse {
  ResponseHeader header = 1;
  uint32 hash = 2;
}

message SnapshotRequest {}
message SnapshotResponse {
  ResponseHeader header = 1;
  uint64 remaining_bytes = 2;
  bytes blob = 3;
}

service Maintenance {
  rpc Alarm (AlarmRequest) returns (AlarmResponse);
  rpc Status (StatusRequest) returns (StatusResponse);
  rpc Defragment (DefragmentRequest) returns (DefragmentResponse);
  rpc Hash (HashRequest) returns (HashResponse);
  rpc Snapshot (SnapshotRequest) returns (stream SnapshotResponse);
}
"""

# etcd's election/lock "concurrency" services live in their own proto
# packages (server/etcdserver/api/v3election, v3lock) and their own
# files here — one .proto holds one package — importing the shared
# header/KeyValue messages from the main schema.
ELECTION_PROTO = """
syntax = "proto3";
package v3electionpb;

import "etcd_wire.proto";

message CampaignRequest {
  bytes name = 1;
  int64 lease = 2;
  bytes value = 3;
}

message LeaderKey {
  bytes name = 1;
  bytes key = 2;
  int64 rev = 3;
  int64 lease = 4;
}

message CampaignResponse {
  etcdserverpb.ResponseHeader header = 1;
  LeaderKey leader = 2;
}

message LeaderRequest { bytes name = 1; }
message LeaderResponse {
  etcdserverpb.ResponseHeader header = 1;
  etcdserverpb.KeyValue kv = 2;
}

message ProclaimRequest {
  LeaderKey leader = 1;
  bytes value = 2;
}
message ProclaimResponse { etcdserverpb.ResponseHeader header = 1; }

message ResignRequest { LeaderKey leader = 1; }
message ResignResponse { etcdserverpb.ResponseHeader header = 1; }

service Election {
  rpc Campaign (CampaignRequest) returns (CampaignResponse);
  rpc Proclaim (ProclaimRequest) returns (ProclaimResponse);
  rpc Leader (LeaderRequest) returns (LeaderResponse);
  rpc Observe (LeaderRequest) returns (stream LeaderResponse);
  rpc Resign (ResignRequest) returns (ResignResponse);
}
"""

LOCK_PROTO = """
syntax = "proto3";
package v3lockpb;

import "etcd_wire.proto";

message LockRequest {
  bytes name = 1;
  int64 lease = 2;
}

message LockResponse {
  etcdserverpb.ResponseHeader header = 1;
  bytes key = 2;
}

message UnlockRequest { bytes key = 1; }
message UnlockResponse { etcdserverpb.ResponseHeader header = 1; }

service Lock {
  rpc Lock (LockRequest) returns (LockResponse);
  rpc Unlock (UnlockRequest) returns (UnlockResponse);
}
"""

_pkg_cache: dict = {}


def wire_pkg() -> protogen.ProtoPackage:
    """The compiled etcd v3 wire schema (once per process — protobuf's
    descriptor pool cannot hold two versions of one file)."""
    if "pkg" not in _pkg_cache:
        d = tempfile.mkdtemp(prefix="etcd_wire_proto")
        paths = []
        for name, text in (
            ("etcd_wire.proto", ETCD_PROTO),
            ("etcd_election.proto", ELECTION_PROTO),
            ("etcd_lock.proto", LOCK_PROTO),
        ):
            path = os.path.join(d, name)
            with open(path, "w") as f:
                f.write(text)
            paths.append(path)
        _pkg_cache["pkg"] = protogen.compile_protos(*paths)
    return _pkg_cache["pkg"]


# -- adapters: protobuf messages <-> the EtcdService surface ----------------

_FROM_END = b"\x00"  # etcd convention: range_end="\0" = every key >= key


def _mk_classes(pkg):
    m = {name.rsplit(".", 1)[-1]: cls for name, cls in pkg.messages.items()}
    return m


def _header(m, svc: EtcdService):
    return m["ResponseHeader"](
        cluster_id=1, member_id=1, revision=svc.revision, raft_term=1
    )


def _wire_kv(m, kv: KeyValue):
    return m["KeyValue"](
        key=kv.key,
        create_revision=kv.create_revision,
        mod_revision=kv.mod_revision,
        version=kv.version,
        value=kv.value,
        lease=kv.lease,
    )


def _get_options(range_end: bytes, **kw) -> GetOptions:
    """The etcd range_end conventions -> GetOptions: empty = single key,
    "\\0" = every key >= key, anything else = half-open [key, range_end)."""
    if range_end == _FROM_END:
        return GetOptions(from_key=True, **kw)
    return GetOptions(range_end=range_end or None, **kw)


_SORT_KEYS = {
    0: lambda kv: kv.key,  # KEY
    1: lambda kv: kv.version,  # VERSION
    2: lambda kv: kv.create_revision,  # CREATE
    3: lambda kv: kv.mod_revision,  # MOD
    4: lambda kv: kv.value,  # VALUE
}


def _guard_range(req) -> None:
    """The one source of truth for unsupported RangeRequest shapes —
    called by the top-level handler AND txn pre-validation, so the two
    can never drift (drift would reintroduce non-atomic txns)."""
    from ..grpc.status import Status

    if req.revision or req.min_mod_revision or req.max_mod_revision or (
        req.min_create_revision or req.max_create_revision
    ):
        # the state machine keeps no MVCC history (current state only,
        # like the reference sim) — fail loudly rather than hand back
        # current data dressed up as a pinned-revision snapshot
        raise Status.unimplemented(
            "etcdserver: historical reads (revision / revision filters) "
            "are not supported by this server; it keeps current state only"
        )


def _guard_put(svc: EtcdService, req) -> None:
    """The one source of truth for PutRequest rejection (see
    _guard_range); mirrors every raise path ``svc.put`` itself has so a
    txn can validate before applying anything."""
    from ..grpc.status import Status
    from .service import MAX_REQUEST_SIZE

    if req.ignore_value or req.ignore_lease:
        raise Status.unimplemented(
            "etcdserver: ignore_value/ignore_lease are not supported here"
        )
    if len(req.key) + len(req.value) > MAX_REQUEST_SIZE:
        raise Status.invalid_argument("etcdserver: request is too large")
    if req.lease and req.lease not in svc.leases:
        raise Status.not_found("etcdserver: requested lease not found")


def _range(m, svc: EtcdService, req):
    _guard_range(req)
    # fetch the FULL range, then sort -> limit -> count_only -> keys_only
    # in etcd's order (sorting after limiting would return the wrong page
    # for descending "latest N" queries)
    _rev, items, count = svc.get(req.key, _get_options(req.range_end))
    if req.sort_order != m["RangeRequest"].SortOrder.NONE:
        items = sorted(
            items,
            key=_SORT_KEYS[int(req.sort_target)],
            reverse=(req.sort_order == m["RangeRequest"].SortOrder.DESCEND),
        )
    if req.limit:
        items = items[: req.limit]
    more = bool(req.limit) and count > len(items)
    if req.count_only:
        items = []
        more = False  # etcd: count_only answers are never "truncated"
    if req.keys_only:
        items = [
            KeyValue(kv.key, b"", kv.create_revision, kv.mod_revision,
                     kv.version, kv.lease)
            for kv in items
        ]
    return m["RangeResponse"](
        header=_header(m, svc),
        kvs=[_wire_kv(m, kv) for kv in items],
        more=more,
        count=count,
    )


def _put(m, svc: EtcdService, req):
    _guard_put(svc, req)
    opts = PutOptions(lease=req.lease, prev_kv=req.prev_kv)
    _rev, prev = svc.put(req.key, req.value, opts)
    out = m["PutResponse"](header=_header(m, svc))
    if prev is not None:
        out.prev_kv.CopyFrom(_wire_kv(m, prev))
    return out


def _delete_options(range_end: bytes, prev_kv: bool) -> DeleteOptions:
    if range_end == _FROM_END:
        return DeleteOptions(from_key=True, prev_kv=prev_kv)
    return DeleteOptions(range_end=range_end or None, prev_kv=prev_kv)


def _delete(m, svc: EtcdService, req):
    # one service.delete whatever the range shape: the whole DeleteRange
    # is one revision, as in etcd
    _rev, deleted, prevs = svc.delete(
        req.key, _delete_options(req.range_end, req.prev_kv)
    )
    return m["DeleteRangeResponse"](
        header=_header(m, svc),
        deleted=deleted,
        prev_kvs=[_wire_kv(m, kv) for kv in prevs],
    )


_CMP_OP = {
    0: CompareOp.EQUAL,
    1: CompareOp.GREATER,
    2: CompareOp.LESS,
    3: CompareOp.NOT_EQUAL,
}
_CMP_TARGET = {
    0: ("version", "version"),
    1: ("create_revision", "create_revision"),
    2: ("mod_revision", "mod_revision"),
    3: ("value", "value"),
    4: ("lease", "lease"),
}


def _compare(req) -> Compare:
    target, operand_field = _CMP_TARGET[req.target]
    return Compare(
        key=req.key,
        target=target,
        op=_CMP_OP[req.result],
        operand=getattr(req, operand_field),
        # range compare (etcd >= 3.3): same range_end conventions
        range_end=(None if req.range_end in (b"", _FROM_END) else req.range_end),
        from_key=req.range_end == _FROM_END,
    )


def _validate_txn(svc: EtcdService, req) -> None:
    """Reject an invalid TxnRequest BEFORE any op applies (etcd validates
    the whole request first; raising mid-branch would leave earlier ops
    committed behind an RPC error — a non-atomic txn on the wire). Covers
    every error path the op handlers can raise: empty ops, unsupported
    revision reads, put guards, oversized puts, and missing leases."""
    from ..grpc.status import Status

    for op in list(req.success) + list(req.failure):
        which = op.WhichOneof("request")
        if which is None:
            raise Status.invalid_argument("etcdserver: missing request op")
        if which == "request_range":
            _guard_range(op.request_range)
        elif which == "request_put":
            _guard_put(svc, op.request_put)
        elif which == "request_txn":
            _validate_txn(svc, op.request_txn)


def _run_txn(m, svc: EtcdService, req, validated: bool = False):
    """Run a TxnRequest by routing each branch op through the SAME wire
    handlers the top-level RPCs use — so sort/limit/more, the from-key
    convention, keys_only, one-revision deletes, and the put guards hold
    identically inside transactions. Atomic: the whole request (both
    branches, recursively) is validated before anything applies, and the
    application itself is synchronous single-threaded code, no awaits."""
    if not validated:
        _validate_txn(svc, req)
    succeeded = all(svc._check(_compare(c)) for c in req.compare)
    return m["TxnResponse"](
        header=_header(m, svc),
        succeeded=succeeded,
        responses=[
            _apply_wire_op(m, svc, op)
            for op in (req.success if succeeded else req.failure)
        ],
    )


def _apply_wire_op(m, svc: EtcdService, op):
    from ..grpc.status import Status

    which = op.WhichOneof("request")
    rop = m["ResponseOp"]()
    if which == "request_range":
        rop.response_range.CopyFrom(_range(m, svc, op.request_range))
    elif which == "request_put":
        rop.response_put.CopyFrom(_put(m, svc, op.request_put))
    elif which == "request_delete_range":
        rop.response_delete_range.CopyFrom(
            _delete(m, svc, op.request_delete_range)
        )
    elif which == "request_txn":
        # already validated recursively by the outermost _run_txn
        rop.response_txn.CopyFrom(
            _run_txn(m, svc, op.request_txn, validated=True)
        )
    else:
        # unreachable after _validate_txn, kept as a hard backstop
        raise Status.invalid_argument("etcdserver: missing request op")
    return rop


def _make_services(pkg, svc: EtcdService):
    """The KV + Lease wire service classes bound to one EtcdService."""
    m = _mk_classes(pkg)

    @pkg.implement("etcdserverpb.KV")
    class KVWire:
        async def range(self, request):
            return _range(m, svc, request.message)

        async def put(self, request):
            return _put(m, svc, request.message)

        async def delete_range(self, request):
            return _delete(m, svc, request.message)

        async def txn(self, request):
            return _run_txn(m, svc, request.message)

        async def compact(self, request):
            svc.compact(request.message.revision)
            return m["CompactionResponse"](header=_header(m, svc))

    @pkg.implement("etcdserverpb.Lease")
    class LeaseWire:
        async def lease_grant(self, request):
            req = request.message
            lease_id, ttl = svc.lease_grant(req.TTL, req.ID)
            return m["LeaseGrantResponse"](
                header=_header(m, svc), ID=lease_id, TTL=ttl
            )

        async def lease_revoke(self, request):
            svc.lease_revoke(request.message.ID)
            return m["LeaseRevokeResponse"](header=_header(m, svc))

        async def lease_keep_alive(self, stream):
            from ..grpc.status import Status

            async for req in stream:
                try:
                    lease_id, ttl = svc.lease_keep_alive(req.ID)
                except Status:
                    # real etcd answers an expired/unknown lease with
                    # TTL=-1 and KEEPS the stream alive (clients read
                    # TTL<=0 as "lease gone"; a stream error would look
                    # like a retryable transport failure instead)
                    yield m["LeaseKeepAliveResponse"](
                        header=_header(m, svc), ID=req.ID, TTL=-1
                    )
                    continue
                yield m["LeaseKeepAliveResponse"](
                    header=_header(m, svc), ID=lease_id, TTL=ttl
                )

        async def lease_time_to_live(self, request):
            req = request.message
            lease_id, remaining, granted, keys = svc.lease_time_to_live(req.ID)
            return m["LeaseTimeToLiveResponse"](
                header=_header(m, svc),
                ID=lease_id,
                TTL=remaining,
                grantedTTL=granted,
                keys=list(keys) if req.keys else [],
            )

        async def lease_leases(self, request):
            return m["LeaseLeasesResponse"](
                header=_header(m, svc),
                leases=[m["LeaseStatus"](ID=i) for i in svc.lease_leases()],
            )

    return KVWire(), LeaseWire()


def _make_maintenance_service(pkg, svc: EtcdService):
    """The Maintenance surface health tooling touches (``etcdctl endpoint
    status``, clientv3 health checks): Status, Alarm (always clear),
    Defragment (a no-op on an in-memory store), Hash (over the state
    dump), and Snapshot. The snapshot BLOB is this server's own JSON dump
    (restorable via ``EtcdService.load``), not a bbolt database — the
    stream protocol is etcd's, the payload format is declared here."""
    import zlib

    m = _mk_classes(pkg)

    def _kv_hash() -> int:
        """A function of KV state ONLY — the dump also carries live
        leases' decaying ``remaining`` counters, which would make the
        hash drift every wall-clock second and defeat its purpose
        (comparing across calls/members to detect divergence)."""
        acc = 0
        for key in sorted(svc.kv):
            kv = svc.kv[key]
            acc = zlib.crc32(
                b"%b\x00%b\x00%d\x00%d\x00%d\x00%d" % (
                    kv.key, kv.value, kv.create_revision, kv.mod_revision,
                    kv.version, kv.lease,
                ),
                acc,
            )
        return zlib.crc32(str(svc.revision).encode(), acc)

    @pkg.implement("etcdserverpb.Maintenance")
    class MaintenanceWire:
        async def status(self, request):
            dump = svc.dump().encode()
            return m["StatusResponse"](
                header=_header(m, svc),
                version="3.5.0-madsim",
                dbSize=len(dump),
                dbSizeInUse=len(dump),
                leader=1,
                raftIndex=max(svc.revision, 1),
                raftTerm=1,
                raftAppliedIndex=max(svc.revision, 1),
            )

        async def alarm(self, request):
            # an in-memory store never raises NOSPACE/CORRUPT; every
            # action observes (and "clears") an empty alarm list
            return m["AlarmResponse"](header=_header(m, svc), alarms=[])

        async def defragment(self, request):
            return m["DefragmentResponse"](header=_header(m, svc))

        async def hash(self, request):
            return m["HashResponse"](
                header=_header(m, svc), hash=_kv_hash()
            )

        async def snapshot(self, request):
            blob = svc.dump().encode()
            chunk = 32 * 1024
            for i in range(0, max(len(blob), 1), chunk):
                part = blob[i:i + chunk]
                yield m["SnapshotResponse"](
                    header=_header(m, svc),
                    remaining_bytes=max(0, len(blob) - (i + len(part))),
                    blob=part,
                )

    return MaintenanceWire()


def _make_watch_service(pkg, svc: EtcdService):
    """The Watch bidi service: multiplexes create/cancel control messages
    with event delivery on one response stream, as etcd does. Each watch
    subscribes to the service EventBus (everything) and filters by its
    own key range — range watches work even though the bus itself only
    knows exact/prefix subscriptions."""
    import asyncio

    from .service import EventType

    m = _mk_classes(pkg)

    def _matches(create, key: bytes) -> bool:
        if create.range_end == b"":
            return key == create.key
        if create.range_end == _FROM_END:
            return key >= create.key
        return create.key <= key < create.range_end

    @pkg.implement("etcdserverpb.Watch")
    class WatchWire:
        async def watch(self, stream):
            out: asyncio.Queue = asyncio.Queue()
            pumps: dict = {}  # watch_id -> (bus watcher, pump task)
            next_id = [1]
            loop = asyncio.get_running_loop()

            async def pump(wid: int, create, watcher,
                           min_rev: int = 0) -> None:
                nofilter = set(int(f) for f in create.filters)
                while True:
                    ev = await watcher.next()
                    if not _matches(create, ev.kv.key):
                        continue
                    if min_rev and ev.kv.mod_revision < min_rev:
                        # future start_revision: suppress events below it
                        # (the read-then-watch-from-R+1 pattern expects
                        # exactly the events at revision >= R+1)
                        continue
                    is_put = ev.type == EventType.PUT
                    if (is_put and 0 in nofilter) or (
                        not is_put and 1 in nofilter
                    ):
                        continue  # FilterType NOPUT=0 / NODELETE=1
                    wev = m["Event"](
                        type=(m["Event"].EventType.PUT if is_put
                              else m["Event"].EventType.DELETE),
                        kv=_wire_kv(m, ev.kv),
                    )
                    if create.prev_kv and ev.prev_kv is not None:
                        wev.prev_kv.CopyFrom(_wire_kv(m, ev.prev_kv))
                    await out.put(m["WatchResponse"](
                        header=_header(m, svc), watch_id=wid, events=[wev]
                    ))

            async def reader() -> None:
                try:
                    async for req in stream:
                        which = req.WhichOneof("request_union")
                        if which == "create_request":
                            c = req.create_request
                            wid = c.watch_id or next_id[0]
                            next_id[0] = max(next_id[0], wid) + 1
                            if wid in pumps:
                                # etcd rejects duplicate explicit ids; a
                                # silent overwrite would leak the old bus
                                # subscription and deliver events twice
                                await out.put(m["WatchResponse"](
                                    header=_header(m, svc), watch_id=wid,
                                    canceled=True,
                                    cancel_reason=(
                                        "duplicated watch_id provided"
                                    ),
                                ))
                                continue
                            if 0 < c.start_revision <= svc.revision:
                                # past revisions need MVCC history we do
                                # not keep; a FUTURE start_revision (the
                                # canonical read-then-watch-from-R+1
                                # pattern) needs none and is served below
                                await out.put(m["WatchResponse"](
                                    header=_header(m, svc), watch_id=wid,
                                    created=True, canceled=True,
                                    cancel_reason=(
                                        "historical watch is not supported "
                                        "by this server (no MVCC history)"
                                    ),
                                ))
                                continue
                            watcher = svc.bus.subscribe(b"", True)
                            pumps[wid] = (
                                watcher,
                                loop.create_task(
                                    pump(wid, c, watcher,
                                         min_rev=c.start_revision)
                                ),
                            )
                            await out.put(m["WatchResponse"](
                                header=_header(m, svc), watch_id=wid,
                                created=True,
                            ))
                        elif which == "cancel_request":
                            wid = req.cancel_request.watch_id
                            entry = pumps.pop(wid, None)
                            if entry is not None:
                                entry[0].cancel()
                                entry[1].cancel()
                            await out.put(m["WatchResponse"](
                                header=_header(m, svc), watch_id=wid,
                                canceled=True,
                            ))
                        else:  # progress request
                            await out.put(m["WatchResponse"](
                                header=_header(m, svc), watch_id=-1
                            ))
                finally:
                    await out.put(None)  # client closed its request side

            rtask = loop.create_task(reader())
            try:
                while True:
                    item = await out.get()
                    if item is None:
                        return
                    yield item
            finally:
                rtask.cancel()
                for watcher, task in pumps.values():
                    watcher.cancel()
                    task.cancel()

    return WatchWire()


async def acquire_candidacy(
    svc: EtcdService, name: bytes, value: bytes, lease: int
) -> bytes:
    """The blocking half of Campaign/Lock: write our candidacy key and
    wait until it is the OLDEST (lowest create_revision) under the
    prefix. Subscribes BEFORE each try so a delete landing between the
    try and the wait cannot be missed; only deletions (resign, unlock,
    lease expiry) can change who is oldest, so only they wake the loop.
    Module-level (not closed over a compiled proto package) so the
    recipe's semantics are testable without protoc."""
    from .service import EventType

    while True:
        watcher = svc.bus.subscribe(name + b"/", True)
        try:
            key = svc.campaign_try(name, value, lease)
            if key is not None:
                return key
            while True:
                ev = await watcher.next()
                if ev.type == EventType.DELETE:
                    break  # a candidate left — re-evaluate leadership
        finally:
            watcher.cancel()


def _make_concurrency_services(pkg, svc: EtcdService):
    """The v3election/v3lock "concurrency" services, on the exact recipe
    real etcd's run on: a candidate key ``name + "/" + hex(lease)`` under
    the election prefix, leadership to the LOWEST create_revision, and
    blocking by watching the prefix for deletions (resign, unlock, or
    lease expiry) before re-trying. ``EtcdService`` already holds the
    primitives (campaign_try/election_leader/proclaim/resign,
    service.rs:487-583); these classes put them on the wire."""
    from ..grpc.status import Status
    from .service import DeleteOptions

    m = _mk_classes(pkg)

    async def _acquire(name: bytes, value: bytes, lease: int) -> bytes:
        return await acquire_candidacy(svc, name, value, lease)

    @pkg.implement("v3electionpb.Election")
    class ElectionWire:
        async def campaign(self, request):
            req = request.message
            key = await _acquire(req.name, req.value, req.lease)
            kv = svc.kv[key]
            return m["CampaignResponse"](
                header=_header(m, svc),
                leader=m["LeaderKey"](
                    name=req.name, key=key,
                    rev=kv.create_revision, lease=req.lease,
                ),
            )

        async def proclaim(self, request):
            req = request.message
            svc.proclaim(req.leader.key, req.value)  # gone key -> error
            return m["ProclaimResponse"](header=_header(m, svc))

        async def leader(self, request):
            kv = svc.election_leader(request.message.name)
            if kv is None:
                raise Status.not_found("election: no leader")
            return m["LeaderResponse"](
                header=_header(m, svc), kv=_wire_kv(m, kv)
            )

        async def observe(self, request):
            name = request.message.name
            watcher = svc.bus.subscribe(name + b"/", True)
            last = None
            try:
                while True:
                    kv = svc.election_leader(name)
                    if kv is not None and (kv.key, kv.mod_revision) != last:
                        last = (kv.key, kv.mod_revision)
                        yield m["LeaderResponse"](
                            header=_header(m, svc), kv=_wire_kv(m, kv)
                        )
                    await watcher.next()
            finally:
                watcher.cancel()

        async def resign(self, request):
            # resigning a key that is already gone is a no-op, as in etcd
            svc.resign(request.message.leader.key)
            return m["ResignResponse"](header=_header(m, svc))

    @pkg.implement("v3lockpb.Lock")
    class LockWire:
        async def lock(self, request):
            req = request.message
            key = await _acquire(req.name, b"", req.lease)
            return m["LockResponse"](header=_header(m, svc), key=key)

        async def unlock(self, request):
            svc.delete(request.message.key, DeleteOptions())
            return m["UnlockResponse"](header=_header(m, svc))

    return ElectionWire(), LockWire()


class WireServer:
    """Serve an :class:`EtcdService` over genuine etcd v3 gRPC wire
    (real mode: grpc.aio transport + wall-clock lease ticks).

    Deliberately NOT on the shared serving core (``madsim_tpu/serve/``):
    grpc.aio owns its HTTP/2 accept loop, flow control, and framing
    end-to-end, so there is no seam to plug an adapter into. The framed
    etcd tier (``real/etcd.py``) — same EtcdService, same dispatcher —
    is the one the core multiplexes; see docs/wire.md.
    """

    def __init__(self, service: Optional[EtcdService] = None):
        self.service = service or EtcdService()
        self.bound_addr: "tuple | None" = None

    async def serve(self, addr: "str | tuple") -> None:
        from ..real import time as rtime
        from ..real.grpc import GrpcioServer
        from ..real.runtime import spawn

        import asyncio

        # watchers block on asyncio futures here, not sim futures
        self.service.bus.future_factory = (
            lambda: asyncio.get_running_loop().create_future()
        )
        pkg = wire_pkg()
        kv, lease = _make_services(pkg, self.service)
        election, lock = _make_concurrency_services(pkg, self.service)
        router = (
            GrpcioServer.builder()
            .add_service(kv)
            .add_service(lease)
            .add_service(_make_watch_service(pkg, self.service))
            .add_service(_make_maintenance_service(pkg, self.service))
            .add_service(election)
            .add_service(lock)
        )

        async def tick_loop() -> None:
            while True:
                await rtime.sleep(1.0)
                self.service.tick()

        tick = spawn(tick_loop(), name="etcd-wire-tick")
        serve_task = spawn(router.serve(addr), name="etcd-wire-serve")
        try:
            while router.bound_addr is None:
                if serve_task.done():
                    serve_task.result()
                await rtime.sleep(0.005)
            self.bound_addr = router.bound_addr
            await serve_task
        finally:
            tick.abort()
            serve_task.abort()
