"""etcd v3 simulation — the madsim-etcd-client analogue.

A deterministic in-sim etcd: the client issues one ``connect1`` exchange
per operation against a ``SimServer`` node holding the whole service state
(madsim-etcd-client/src/{sim.rs,server.rs,service.rs}):

- **kv**: put / range-get (prefix) / delete / txn (compares + nested ops) /
  compact, with etcd's revision bookkeeping (global revision,
  create_revision / mod_revision per key)
- **lease**: grant / revoke / keep-alive / time-to-live, with a TTL tick
  task expiring leases (and their attached keys) every simulated second
  (service.rs:27-33,466-485)
- **election**: campaign / proclaim / leader / observe / resign built on a
  prefix-watch event bus (service.rs:487-583)
- **watch**: prefix watch streams (the event bus made public)
- **maintenance**: status, and the state **dump/load** snapshot-restore
  the reference exposes for checkpointing (service.rs:160-163)
- fault injection: ``timeout_rate`` — a random 5-15 s delay then
  Unavailable on any request (service.rs:165-176)
- 1.5 MiB max request size (service.rs:36)

Errors are ``grpc.Status`` values, matching the reference's use of tonic
``Status`` as the etcd error surface.
"""

from .client import (
    CampaignResponse,
    Client,
    ConnectOptions,
    DeleteResponse,
    ElectionClient,
    GetResponse,
    KvClient,
    LeaderKey,
    LeaderResponse,
    LeaseClient,
    LeaseGrantResponse,
    LeaseKeepAliveResponse,
    LeaseTimeToLiveResponse,
    MaintenanceClient,
    ObserveStream,
    PutResponse,
    ResponseHeader,
    StatusResponse,
    TxnResponse,
    WatchClient,
    WatchStream,
)
from .server import SimServer
from .service import (
    Compare,
    CompareOp,
    DeleteOptions,
    Event,
    EventType,
    GetOptions,
    KeyValue,
    PutOptions,
    Txn,
    TxnOp,
)

__all__ = [
    "CampaignResponse",
    "Client",
    "Compare",
    "CompareOp",
    "ConnectOptions",
    "DeleteOptions",
    "DeleteResponse",
    "ElectionClient",
    "Event",
    "EventType",
    "GetOptions",
    "GetResponse",
    "KeyValue",
    "KvClient",
    "LeaderKey",
    "LeaderResponse",
    "LeaseClient",
    "LeaseGrantResponse",
    "LeaseKeepAliveResponse",
    "LeaseTimeToLiveResponse",
    "MaintenanceClient",
    "ObserveStream",
    "PutOptions",
    "PutResponse",
    "ResponseHeader",
    "SimServer",
    "StatusResponse",
    "Txn",
    "TxnOp",
    "TxnResponse",
    "WatchClient",
    "WatchStream",
]
