"""The etcd sim server node (madsim-etcd-client/src/server.rs).

``SimServer.builder().timeout_rate(p).serve(addr)`` binds an Endpoint and
answers one request enum per ``connect1`` exchange (server.rs:104-167).
Streaming ops (watch, observe, blocking campaign) keep their connection
open. A per-simulated-second tick task drives lease expiry, and
``timeout_rate`` injects random 5-15 s delays followed by Unavailable
(service.rs:165-176).
"""

from __future__ import annotations

from typing import Any, Optional

from .. import rand as msrand
from .. import task as mstask
from .. import time as mstime
from ..grpc.status import Status
from ..net.endpoint import Endpoint as NetEndpoint
from .service import (
    DeleteOptions,
    EtcdService,
    GetOptions,
    PutOptions,
    Txn,
)


class SimServerBuilder:
    _server_cls: "type | None" = None  # real/etcd.py overrides

    def __init__(self) -> None:
        self._timeout_rate = 0.0
        self._service: Optional[EtcdService] = None
        self._telemetry = None

    def timeout_rate(self, rate: float) -> "SimServerBuilder":
        """Fraction of requests that hang 5-15 s then fail Unavailable
        (server.rs:20-25)."""
        self._timeout_rate = rate
        return self

    def telemetry(self, telemetry) -> "SimServerBuilder":
        """Attach an ``obs.Telemetry`` handle for wire-level metrics."""
        self._telemetry = telemetry
        return self

    def load(self, dump: str) -> "SimServerBuilder":
        """Start from a dumped snapshot (server.rs:27-31)."""
        svc = EtcdService()
        svc.load(dump)
        self._service = svc
        return self

    async def serve(self, addr: "str | tuple") -> None:
        server = (self._server_cls or SimServer)(
            self._service or EtcdService(), self._timeout_rate,
            telemetry=self._telemetry,
        )
        await server.serve(addr)


class SimServer:
    @staticmethod
    def builder() -> SimServerBuilder:
        return SimServerBuilder()

    # executor bindings as class attributes so the real-mode twin
    # (real/etcd.py) can rebind them to asyncio + real randomness while
    # reusing the whole request dispatcher — the sim/std split of
    # madsim-etcd-client/src/lib.rs
    _spawn = staticmethod(mstask.spawn)
    _sleep = staticmethod(mstime.sleep)
    _rand01 = staticmethod(msrand.random)
    _uniform = staticmethod(msrand.uniform)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await NetEndpoint.bind(addr)

    def __init__(self, service: EtcdService, timeout_rate: float = 0.0,
                 telemetry=None):
        self.service = service
        self.timeout_rate = timeout_rate
        self.telemetry = telemetry
        #: set once the listener is bound (port-0 discovery, real mode)
        self.bound_addr: "Optional[tuple]" = None

    async def serve(self, addr: "str | tuple") -> None:
        ep = await self._bind(addr)
        local = getattr(ep, "local_addr", None)
        self.bound_addr = local() if callable(local) else None
        self._spawn(self._tick_loop(), name="etcd-tick")
        while True:
            tx, rx, _src = await ep.accept1()
            self._spawn(self._serve_conn(tx, rx), name="etcd-conn")

    async def _tick_loop(self) -> None:
        while True:
            await self._sleep(1.0)
            self.service.tick()

    async def _serve_conn(self, tx: Any, rx: Any) -> None:
        if self.telemetry is not None:
            self.telemetry.count(
                "etcd_connections_total", help="accepted connections"
            )
        try:
            req = await rx.recv()
            if req is None:
                return
            if self.timeout_rate > 0 and self._rand01() < self.timeout_rate:
                await self._sleep(self._uniform(5.0, 15.0))
                await tx.send(("err", Status.unavailable("etcdserver: request timed out")))
                return
            await self._handle(req, tx, rx)
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            tx.close()

    async def _handle(self, req: tuple, tx: Any, rx: Any) -> None:
        if self.telemetry is None:
            return await self._handle_op(req, tx, rx)
        import time as _walltime

        t0 = _walltime.perf_counter()
        op = str(req[0]) if req else "?"
        try:
            return await self._handle_op(req, tx, rx)
        finally:
            self.telemetry.count(
                "etcd_requests_total", help="requests served", op=op
            )
            self.telemetry.observe(
                "etcd_api_seconds", _walltime.perf_counter() - t0,
                help="per-op handling latency", op=op,
            )

    async def _handle_op(self, req: tuple, tx: Any, rx: Any) -> None:
        svc = self.service
        op = req[0]
        try:
            if op == "put":
                _, key, value, options = req
                rev, prev = svc.put(key, value, options or PutOptions())
                await tx.send(("ok", (rev, prev)))
            elif op == "get":
                _, key, options = req
                await tx.send(("ok", svc.get(key, options or GetOptions())))
            elif op == "delete":
                _, key, options = req
                await tx.send(("ok", svc.delete(key, options or DeleteOptions())))
            elif op == "txn":
                _, txn = req
                assert isinstance(txn, Txn)
                await tx.send(("ok", svc.txn(txn)))
            elif op == "compact":
                _, revision = req
                await tx.send(("ok", svc.compact(revision)))
            elif op == "lease_grant":
                _, ttl, lease_id = req
                await tx.send(("ok", svc.lease_grant(ttl, lease_id)))
            elif op == "lease_revoke":
                _, lease_id = req
                svc.lease_revoke(lease_id)
                await tx.send(("ok", None))
            elif op == "lease_keep_alive":
                _, lease_id = req
                await tx.send(("ok", svc.lease_keep_alive(lease_id)))
            elif op == "lease_time_to_live":
                _, lease_id = req
                await tx.send(("ok", svc.lease_time_to_live(lease_id)))
            elif op == "lease_leases":
                await tx.send(("ok", svc.lease_leases()))
            elif op == "campaign":
                # blocks until leadership (service.rs:487-527): retry on
                # every change under the election prefix
                _, name, value, lease_id = req
                while True:
                    key = svc.campaign_try(name, value, lease_id)
                    if key is not None:
                        kv = svc.kv[key]
                        await tx.send(("ok", (name, key, kv.create_revision, lease_id)))
                        break
                    watcher = svc.bus.subscribe(name + b"/", prefix=True)
                    try:
                        await watcher.next()
                    finally:
                        watcher.cancel()
            elif op == "proclaim":
                _, key, value = req
                svc.proclaim(key, value)
                await tx.send(("ok", None))
            elif op == "leader":
                _, name = req
                kv = svc.election_leader(name)
                if kv is None:
                    await tx.send(("err", Status.not_found("election: no leader")))
                else:
                    await tx.send(("ok", kv))
            elif op == "observe":
                # stream of leader kvs (service.rs:553-583)
                _, name = req
                watcher = svc.bus.subscribe(name + b"/", prefix=True)
                try:
                    leader = svc.election_leader(name)
                    if leader is not None:
                        await tx.send(leader)
                    while True:
                        await watcher.next()
                        leader = svc.election_leader(name)
                        if leader is not None:
                            await tx.send(leader)
                finally:
                    watcher.cancel()
            elif op == "resign":
                _, key = req
                svc.resign(key)
                await tx.send(("ok", None))
            elif op == "watch":
                _, key, prefix = req
                watcher = svc.bus.subscribe(key, prefix=prefix)
                try:
                    await tx.send(("ok", None))
                    while True:
                        event = await watcher.next()
                        await tx.send(event)
                finally:
                    watcher.cancel()
            elif op == "status":
                await tx.send(("ok", (svc.revision, len(svc.kv))))
            elif op == "dump":
                await tx.send(("ok", svc.dump()))
            elif op == "load":
                _, dump = req
                svc.load(dump)
                await tx.send(("ok", None))
            else:
                await tx.send(("err", Status.unimplemented(f"unknown op {op!r}")))
        except Status as st:
            await tx.send(("err", st))
