"""etcd client handles (madsim-etcd-client/src/sim.rs:33-77).

``Client.connect([addr], options)`` + ``{kv, lease, election, maintenance,
watch}_client()`` views; every operation is one ``connect1`` exchange with
the SimServer (server.rs:104-167). Response objects mirror the etcd-client
Rust API shape (``resp.kvs()``, ``resp.header().revision()``, …).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

from .. import rand as msrand
from ..grpc.status import Status
from ..net.endpoint import connect1_ephemeral, exchange1
from .service import (
    DeleteOptions,
    Event,
    GetOptions,
    KeyValue,
    PutOptions,
    Txn,
    _b,
)


@dataclass
class ResponseHeader:
    _revision: int

    def revision(self) -> int:
        return self._revision


@dataclass
class PutResponse:
    _header: ResponseHeader
    _prev_kv: Optional[KeyValue]

    def header(self) -> ResponseHeader:
        return self._header

    def prev_key(self) -> Optional[KeyValue]:
        return self._prev_kv


@dataclass
class GetResponse:
    _header: ResponseHeader
    _kvs: List[KeyValue]
    _count: int

    def header(self) -> ResponseHeader:
        return self._header

    def kvs(self) -> List[KeyValue]:
        return self._kvs

    def count(self) -> int:
        return self._count


@dataclass
class DeleteResponse:
    _header: ResponseHeader
    _deleted: int
    _prev_kvs: List[KeyValue]

    def header(self) -> ResponseHeader:
        return self._header

    def deleted(self) -> int:
        return self._deleted

    def prev_kvs(self) -> List[KeyValue]:
        return self._prev_kvs


@dataclass
class TxnResponse:
    _header: ResponseHeader
    _succeeded: bool
    _responses: List[Any]

    def header(self) -> ResponseHeader:
        return self._header

    def succeeded(self) -> bool:
        return self._succeeded

    def op_responses(self) -> List[Any]:
        return self._responses


@dataclass
class LeaseGrantResponse:
    _id: int
    _ttl: int

    def id(self) -> int:
        return self._id

    def ttl(self) -> int:
        return self._ttl


@dataclass
class LeaseKeepAliveResponse:
    _id: int
    _ttl: int

    def id(self) -> int:
        return self._id

    def ttl(self) -> int:
        return self._ttl


@dataclass
class LeaseTimeToLiveResponse:
    _id: int
    _ttl: int
    _granted_ttl: int
    _keys: List[bytes]

    def id(self) -> int:
        return self._id

    def ttl(self) -> int:
        return self._ttl

    def granted_ttl(self) -> int:
        return self._granted_ttl

    def keys(self) -> List[bytes]:
        return self._keys


@dataclass
class LeaderKey:
    _name: bytes
    _key: bytes
    _rev: int
    _lease: int

    def name(self) -> bytes:
        return self._name

    def key(self) -> bytes:
        return self._key

    def rev(self) -> int:
        return self._rev

    def lease(self) -> int:
        return self._lease


@dataclass
class CampaignResponse:
    _leader: LeaderKey

    def leader(self) -> LeaderKey:
        return self._leader


@dataclass
class LeaderResponse:
    _kv: Optional[KeyValue]

    def kv(self) -> Optional[KeyValue]:
        return self._kv


@dataclass
class StatusResponse:
    _revision: int
    _num_keys: int

    def revision(self) -> int:
        return self._revision


class ConnectOptions:
    """Accepted for API parity (auth/timeouts are sim-irrelevant)."""

    def __init__(self) -> None:
        pass

    def with_user(self, _name: str, _password: str) -> "ConnectOptions":
        return self

    def with_timeout(self, _seconds: float) -> "ConnectOptions":
        return self

    def with_connect_timeout(self, _seconds: float) -> "ConnectOptions":
        return self


class Client:
    """The top-level handle (sim.rs:33-77)."""

    def __init__(self, endpoints: List[str]):
        self._endpoints = endpoints

    @classmethod
    async def connect(
        cls,
        endpoints: "str | Sequence[str]",
        options: Optional[ConnectOptions] = None,
    ) -> "Client":
        if isinstance(endpoints, str):
            endpoints = [endpoints]
        return cls(list(endpoints))

    # -- plumbing ----------------------------------------------------------

    @staticmethod
    def _randint(n: int) -> int:
        """Endpoint-balance draw — sim RNG; real/etcd.py overrides."""
        return msrand.gen_range(0, n)

    def _pick(self) -> str:
        eps = self._endpoints
        return eps[self._randint(len(eps))] if len(eps) > 1 else eps[0]

    async def _open(self):
        return await connect1_ephemeral(self._pick())

    async def _call(self, req: tuple) -> Any:
        tx, rx = await self._open()
        try:
            rsp = await exchange1(tx, rx, req)
        except (BrokenPipeError, ConnectionResetError) as e:
            raise Status.unavailable(f"etcd transport error: {e}") from None
        if rsp is None:
            raise Status.unavailable("etcd connection closed")
        kind, payload = rsp
        if kind == "err":
            raise payload
        return payload

    async def _stream(self, req: tuple) -> Tuple[Any, Any]:
        tx, rx = await self._open()
        await tx.send(req)
        return tx, rx

    # -- sub-clients -------------------------------------------------------

    def kv_client(self) -> "KvClient":
        return KvClient(self)

    def lease_client(self) -> "LeaseClient":
        return LeaseClient(self)

    def election_client(self) -> "ElectionClient":
        return ElectionClient(self)

    def maintenance_client(self) -> "MaintenanceClient":
        return MaintenanceClient(self)

    def watch_client(self) -> "WatchClient":
        return WatchClient(self)

    # convenience passthroughs (etcd-client has these on Client too)

    async def put(self, key, value, options: Optional[PutOptions] = None) -> PutResponse:
        return await self.kv_client().put(key, value, options)

    async def get(self, key, options: Optional[GetOptions] = None) -> GetResponse:
        return await self.kv_client().get(key, options)

    async def delete(self, key, options: Optional[DeleteOptions] = None) -> DeleteResponse:
        return await self.kv_client().delete(key, options)

    async def txn(self, txn: Txn) -> TxnResponse:
        return await self.kv_client().txn(txn)

    # snapshot-restore (sim.rs:70-77)

    async def dump(self) -> str:
        return await self._call(("dump",))

    async def load(self, dump: str) -> None:
        await self._call(("load", dump))


class KvClient:
    def __init__(self, client: Client):
        self._c = client

    async def put(self, key, value, options: Optional[PutOptions] = None) -> PutResponse:
        rev, prev = await self._c._call(("put", _b(key), _b(value), options))
        return PutResponse(ResponseHeader(rev), prev)

    async def get(self, key, options: Optional[GetOptions] = None) -> GetResponse:
        rev, kvs, count = await self._c._call(("get", _b(key), options))
        return GetResponse(ResponseHeader(rev), kvs, count)

    async def delete(self, key, options: Optional[DeleteOptions] = None) -> DeleteResponse:
        rev, deleted, prev = await self._c._call(("delete", _b(key), options))
        return DeleteResponse(ResponseHeader(rev), deleted, prev)

    async def txn(self, txn: Txn) -> TxnResponse:
        rev, ok, results = await self._c._call(("txn", txn))
        return TxnResponse(ResponseHeader(rev), ok, results)

    async def compact(self, revision: int) -> None:
        await self._c._call(("compact", revision))


class LeaseClient:
    def __init__(self, client: Client):
        self._c = client

    async def grant(self, ttl: int, lease_id: int = 0) -> LeaseGrantResponse:
        lid, ttl = await self._c._call(("lease_grant", ttl, lease_id))
        return LeaseGrantResponse(lid, ttl)

    async def revoke(self, lease_id: int) -> None:
        await self._c._call(("lease_revoke", lease_id))

    async def keep_alive(self, lease_id: int) -> LeaseKeepAliveResponse:
        lid, ttl = await self._c._call(("lease_keep_alive", lease_id))
        return LeaseKeepAliveResponse(lid, ttl)

    async def time_to_live(self, lease_id: int) -> LeaseTimeToLiveResponse:
        lid, ttl, granted, keys = await self._c._call(("lease_time_to_live", lease_id))
        return LeaseTimeToLiveResponse(lid, ttl, granted, keys)

    async def leases(self) -> List[int]:
        return await self._c._call(("lease_leases",))


class ElectionClient:
    """campaign/proclaim/leader/observe/resign (service.rs:487-583)."""

    def __init__(self, client: Client):
        self._c = client

    async def campaign(self, name, value, lease_id: int) -> CampaignResponse:
        tx, rx = await self._c._stream(("campaign", _b(name), _b(value), lease_id))
        try:
            rsp = await rx.recv()
        except ConnectionResetError as e:
            raise Status.unavailable(str(e)) from None
        finally:
            tx.close()
            rx.close()  # exchange complete; frees the real-mode socket
        if rsp is None:
            raise Status.unavailable("etcd connection closed")
        kind, payload = rsp
        if kind == "err":
            raise payload
        name_, key, rev, lease = payload
        return CampaignResponse(LeaderKey(name_, key, rev, lease))

    async def proclaim(self, value, leader: LeaderKey) -> None:
        await self._c._call(("proclaim", leader.key(), _b(value)))

    async def leader(self, name) -> LeaderResponse:
        kv = await self._c._call(("leader", _b(name)))
        return LeaderResponse(kv)

    async def observe(self, name) -> "ObserveStream":
        tx, rx = await self._c._stream(("observe", _b(name)))
        return ObserveStream(tx, rx)

    async def resign(self, leader: LeaderKey) -> None:
        await self._c._call(("resign", leader.key()))


class ObserveStream:
    """Async stream of leader KeyValues."""

    def __init__(self, tx: Any, rx: Any):
        self._tx = tx
        self._rx = rx

    async def next(self) -> Optional[KeyValue]:
        try:
            return await self._rx.recv()
        except ConnectionResetError:
            return None

    def __aiter__(self) -> "ObserveStream":
        return self

    async def __anext__(self) -> KeyValue:
        kv = await self.next()
        if kv is None:
            raise StopAsyncIteration
        return kv

    def cancel(self) -> None:
        # close both halves: closing the receiver makes the server's next
        # send raise BrokenPipeError, tearing down its observe loop
        self._tx.close()
        self._rx.close()


class WatchStream:
    """Async stream of watch Events."""

    def __init__(self, tx: Any, rx: Any):
        self._tx = tx
        self._rx = rx

    async def next(self) -> Optional[Event]:
        try:
            return await self._rx.recv()
        except ConnectionResetError:
            return None

    def __aiter__(self) -> "WatchStream":
        return self

    async def __anext__(self) -> Event:
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev

    def cancel(self) -> None:
        # close both halves so the server's watch loop tears down on its
        # next send instead of queueing events forever
        self._tx.close()
        self._rx.close()


class WatchClient:
    def __init__(self, client: Client):
        self._c = client

    async def watch(self, key, prefix: bool = False) -> WatchStream:
        tx, rx = await self._c._stream(("watch", _b(key), prefix))
        try:
            head = await rx.recv()
            if head is None:
                raise Status.unavailable("etcd connection closed")
            kind, payload = head
            if kind == "err":
                raise payload
        except BaseException:
            tx.close()
            rx.close()  # failed exchange must not leak the real-mode socket
            raise
        return WatchStream(tx, rx)


class MaintenanceClient:
    def __init__(self, client: Client):
        self._c = client

    async def status(self) -> StatusResponse:
        rev, nkeys = await self._c._call(("status",))
        return StatusResponse(rev, nkeys)
