"""madsim_tpu — a TPU-native deterministic simulation testing (DST) framework.

A brand-new framework with the capabilities of madsim (Rust DST in the
FoundationDB tradition): a seeded single-threaded executor with virtual time,
a fault-injecting network simulator, node kill/restart/pause supervision,
drop-in shims for gRPC/etcd/Kafka/S3-style workloads, and a seed-sweep test
driver with bit-exact replay.  On top of the host tier, the inner simulation
loop is re-designed as a JAX/Pallas struct-of-arrays engine
(``madsim_tpu.engine``) that steps thousands of seeds in lockstep on TPU.

Layer map (mirrors reference /root/reference, see SURVEY.md §1):
  L0 determinism core   -> madsim_tpu.rand        (madsim/src/sim/rand.rs)
  L1 virtual time       -> madsim_tpu.time        (madsim/src/sim/time/)
  L2 task scheduler     -> madsim_tpu.task        (madsim/src/sim/task/)
  L3 runtime + plugins  -> madsim_tpu.runtime     (madsim/src/sim/runtime/)
  L4 device simulators  -> madsim_tpu.net, .fs    (madsim/src/sim/{net,fs})
  L5 protocol layer     -> madsim_tpu.net.{endpoint,rpc}
  L6 ecosystem shims    -> madsim_tpu.{grpc,etcd,kafka,s3}
  L7 codegen/macros     -> decorators (@sim_test, @service, @request)
  L8 test driver        -> madsim_tpu.builder
  TPU tier              -> madsim_tpu.{engine,models,parallel,ops}
  correctness tooling   -> madsim_tpu.{explore,oracle,replay,faults}

(The L6 ecosystem shims and the TPU tier are built progressively — check the
package tree for what is present in this revision.)
"""

__version__ = "0.1.0"

from . import buggify as buggify
from . import fs as fs
from . import rand as rand
from . import signal as signal
from . import sync as sync
from . import time as time
from . import tracing as tracing
from .builder import Builder, main, sim_test
from .context import current_handle, current_node, current_task
from .futures import Future, JoinHandle, select, join, pending_forever
from .runtime import Handle, NodeBuilder, Runtime, init_logger
from .task import spawn, spawn_local, NodeId, exit_current_task
from .time import sleep, sleep_until, timeout, interval, Instant, TimeoutError

__all__ = [
    "Builder",
    "Future",
    "Handle",
    "Instant",
    "JoinHandle",
    "NodeBuilder",
    "NodeId",
    "Runtime",
    "TimeoutError",
    "buggify",
    "current_handle",
    "current_node",
    "current_task",
    "exit_current_task",
    "fs",
    "init_logger",
    "interval",
    "join",
    "main",
    "pending_forever",
    "rand",
    "select",
    "signal",
    "sim_test",
    "sleep",
    "sleep_until",
    "spawn",
    "spawn_local",
    "sync",
    "time",
    "timeout",
    "tracing",
]
