"""Cross-tier replay: device-found failure seeds → host-tier user code.

The sweep→replay pipeline (SURVEY.md §7 stage 5 acceptance):

1. a TPU sweep flags violation seeds (``violation_seeds``);
2. ``engine.run_traced`` re-runs one seed on the CPU backend — the
   integer-only engine makes the replay bit-exact, so the violation is
   confirmed and the full event schedule is captured;
3. ``extract_fault_plan`` lifts the *externally injected* schedule — the
   crash/restart fault events the simulator decided — out of the trace;
4. the plan drives a host-tier supervisor (e.g.
   ``examples/raft_host.run_seed_with_plan``) that applies the same
   kills/restarts at the same virtual times to ordinary async user code,
   where a debugger, print statements, or tracing spans can attach.

Step 4 is the semantic bridge the reference gets for free by running one
engine for everything (``MADSIM_TEST_SEED=N`` reruns the same binary,
runtime/mod.rs:205-210). Two engines can't share one RNG stream, so what
transfers is the *fault environment*, not the exact interleaving: the
host tier explores its own schedules under the recorded faults
(``replay_on_host`` scans a few host seeds), and within-tier bit-exact
reproduction stays the job of ``run_traced``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

FaultEvent = Tuple[int, str, int]  # (time_ns, "crash" | "restart", node)


def amnesia_raft_config():
    """The canonical cross-tier demo configuration: a 3-node Raft cluster
    whose crashes wipe durable state — matching ``examples/raft_host.py``
    semantics, where a restart loses everything in memory — under an
    aggressive fault plan so modest sweeps find double-vote violations.

    Returns ``(RaftConfig, EngineConfig)``; shared by ``tests/test_replay``
    and ``scripts/replay_seed.py`` so the two never drift apart.
    """
    from .models import raft

    cfg = raft.RaftConfig(
        num_nodes=3,
        crashes=3,
        commands=0,
        volatile_state=True,
        crash_window_ns=2_000_000_000,
        restart_lo_ns=50_000_000,
        restart_hi_ns=300_000_000,
    )
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    return cfg, ecfg


def violation_seeds(final) -> np.ndarray:
    """Seeds whose workload latched ``violation`` in a finished sweep."""
    return np.asarray(final.seed)[np.asarray(final.wstate.violation)]


def extract_fault_plan(
    trace: Dict, crash_kind: int, restart_kind: int, node_slot: int = 0
) -> List[FaultEvent]:
    """Lift the fired crash/restart events out of a ``run_traced`` trace.

    ``trace`` is the dict returned by ``engine.run_traced``; ``crash_kind``
    / ``restart_kind`` are the workload's event-kind codes (e.g.
    ``models.raft.K_CRASH``); the victim node id sits in payload slot
    ``node_slot``. Returns ``(time_ns, action, node)`` in dispatch order.
    """
    t = np.asarray(trace["time_ns"])
    k = np.asarray(trace["kind"])
    p = np.asarray(trace["pay"])
    fired = np.asarray(trace["fired"])
    plan: List[FaultEvent] = []
    for i in np.nonzero(fired)[0]:
        if k[i] == crash_kind:
            plan.append((int(t[i]), "crash", int(p[i, node_slot])))
        elif k[i] == restart_kind:
            plan.append((int(t[i]), "restart", int(p[i, node_slot])))
    return plan


def replay_on_host(
    run_with_plan: Callable[[int, Sequence[FaultEvent]], Dict],
    plan: Sequence[FaultEvent],
    host_seeds: Sequence[int] = range(8),
    reproduced: Callable[[Dict], bool] = lambda r: r.get("violations", 0) > 0,
) -> Optional[Dict]:
    """Drive host-tier user code under the recorded fault plan.

    ``run_with_plan(seed, plan)`` runs one host simulation (e.g.
    ``examples/raft_host.run_seed_with_plan``); the host tier's own
    schedule randomization varies per seed, so a few seeds are scanned.
    Returns the first result where ``reproduced`` holds, else None.
    """
    for seed in host_seeds:
        result = run_with_plan(int(seed), plan)
        if reproduced(result):
            result["host_seed"] = int(seed)
            return result
    return None
