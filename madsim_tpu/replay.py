"""Cross-tier replay: device-found failure seeds → host-tier user code.

The sweep→replay pipeline (SURVEY.md §7 stage 5 acceptance):

1. a TPU sweep flags violation seeds (``violation_seeds``);
2. ``engine.run_traced`` re-runs one seed on the CPU backend — the
   integer-only engine makes the replay bit-exact, so the violation is
   confirmed and the full event schedule is captured;
3. ``extract_fault_schedule`` lifts the *externally injected* schedule —
   the compiled fault campaign's crash/restart, partition/heal, latency/
   loss-burst and pause/resume events (engine/faults.py) — out of the
   trace, with the exact scheduled deadlines the payloads carry;
4. the schedule drives a host-tier supervisor
   (``madsim_tpu.faults.apply_schedule``, e.g. via
   ``examples/raft_host.run_seed_with_plan``) that applies the same
   faults at the same virtual times to ordinary async user code, where a
   debugger, print statements, or tracing spans can attach. Because both
   tiers compile the same ``FaultSpec`` (madsim_tpu/faults.compile_host
   == the device schedule, tests/test_faults.py), the trace hop is
   optional: the spec plus the violating seed already reproduce the
   fault environment.

Step 4 is the semantic bridge the reference gets for free by running one
engine for everything (``MADSIM_TEST_SEED=N`` reruns the same binary,
runtime/mod.rs:205-210). Two engines can't share one RNG stream, so what
transfers is the *fault environment*, not the exact interleaving: the
host tier explores its own schedules under the recorded faults
(``replay_on_host`` scans a few host seeds), and within-tier bit-exact
reproduction stays the job of ``run_traced``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

FaultEvent = Tuple[int, str, int]  # (time_ns, action name, victim node)


def amnesia_raft_config():
    """The canonical cross-tier demo configuration: a 3-node Raft cluster
    whose crashes wipe durable state — matching ``examples/raft_host.py``
    semantics, where a restart loses everything in memory — under an
    aggressive fault plan so modest sweeps find double-vote violations.

    Returns ``(RaftConfig, EngineConfig)``; shared by ``tests/test_replay``
    and ``scripts/replay_seed.py`` so the two never drift apart.
    """
    from .models import raft

    cfg = raft.RaftConfig(
        num_nodes=3,
        crashes=3,
        commands=0,
        volatile_state=True,
        crash_window_ns=2_000_000_000,
        restart_lo_ns=50_000_000,
        restart_hi_ns=300_000_000,
    )
    ecfg = raft.engine_config(cfg, time_limit_ns=3_000_000_000, max_steps=30_000)
    return cfg, ecfg


def violation_seeds(final) -> np.ndarray:
    """Seeds whose workload latched ``violation`` in a finished sweep."""
    return np.asarray(final.seed)[np.asarray(final.wstate.violation)]


def extract_fault_schedule(trace: Dict, fault_kind: int) -> List[FaultEvent]:
    """Lift the fired fault-campaign events out of a ``run_traced`` trace.

    ``trace`` is the dict returned by ``engine.run_traced``; ``fault_kind``
    is the workload's unified fault event kind (e.g. ``models.raft.
    K_FAULT``). Fault payloads are ``(action, victim, t_lo, t_hi)``
    (engine/faults.compile_device), so the returned times are the *exact
    scheduled deadlines* — free of the engine's 50-100 ns dispatch
    jitter — and compare equal to ``madsim_tpu.faults.compile_host`` for
    the same ``(spec, seed)``, PROVIDED every scheduled event fits the
    engine horizon: only events that actually fired appear in a trace, so
    a fault drawn at or past ``time_limit_ns`` (or beyond ``max_steps``)
    is absent here while ``compile_host`` still lists it. Size campaign
    windows (plus the max restart/heal delay) inside the horizon when the
    full environment must transfer. Returns ``(time_ns, action, victim)``
    sorted by time."""
    from .engine.faults import ACTION_NAMES, decode_time

    k = np.asarray(trace["kind"])
    p = np.asarray(trace["pay"])
    fired = np.asarray(trace["fired"])
    plan: List[FaultEvent] = []
    for i in np.nonzero(fired & (k == fault_kind))[0]:
        t = int(decode_time(p[i, 2], p[i, 3]))
        plan.append((t, ACTION_NAMES[int(p[i, 0])], int(p[i, 1])))
    return sorted(plan)


def extract_history(final, lane: Optional[int] = None):
    """Decode the recorded operation history out of a replay's final
    state (``oracle.History``) — the history-oracle counterpart of
    ``extract_fault_schedule``. ``final`` is ``run_traced``'s final state
    (unbatched), or a batched sweep state with ``lane`` set; either way
    the decoded ops are byte-identical across the two paths for one
    seed (``oracle.history_bytes`` is the canonical encoding the
    determinism gate diffs)."""
    from .oracle.history import decode_seed

    return decode_seed(final, lane)


def history_violation_seeds(final, spec) -> np.ndarray:
    """Seeds of a finished sweep whose decoded history fails the
    linearizability check against ``spec`` — the generic-oracle
    counterpart of ``violation_seeds`` (no hand-coded probe needed)."""
    from .oracle.check import violating_seeds

    return violating_seeds(final, spec)


def replay_on_host(
    run_with_plan: Callable[[int, Sequence[FaultEvent]], Dict],
    plan: Sequence[FaultEvent],
    host_seeds: Sequence[int] = range(8),
    reproduced: Callable[[Dict], bool] = lambda r: r.get("violations", 0) > 0,
) -> Optional[Dict]:
    """Drive host-tier user code under the recorded fault plan.

    ``run_with_plan(seed, plan)`` runs one host simulation (e.g.
    ``examples/raft_host.run_seed_with_plan``); the host tier's own
    schedule randomization varies per seed, so a few seeds are scanned.
    Returns the first result where ``reproduced`` holds, else None.
    """
    for seed in host_seeds:
        result = run_with_plan(int(seed), plan)
        if reproduced(result):
            result["host_seed"] = int(seed)
            return result
    return None
