"""A minimal vendored Kafka wire client ("probe"): enough of the binary
protocol to exercise every API ``kafka/wire.py`` serves, from either
tier.

No kafka-python/librdkafka ships in this image, so the stock-client
round-trip story is held by this probe instead: it speaks the genuine
frame/header/record-batch-v2 encodings (sharing the primitive codec with
the server — the compositions are written independently per API, which
is the same stance the etcd wire tests take with shared protobuf message
classes), negotiates versions via ApiVersions, and raises on every
non-zero error code unless the caller asked for the raw code.

Transports: :class:`RealTransport` dials real TCP (asyncio);
:class:`SimTransport` dials the simulator's ``connect1`` pipes carrying
framed byte chunks; :class:`LoopbackTransport` feeds a ``KafkaWire``
in-process — the pure-codec path the differential fuzz and the
determinism gate lean on.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .wire import (
    API_CREATE_TOPICS,
    API_DELETE_TOPICS,
    API_FETCH,
    API_FIND_COORDINATOR,
    API_HEARTBEAT,
    API_JOIN_GROUP,
    API_LEAVE_GROUP,
    API_LIST_OFFSETS,
    API_METADATA,
    API_OFFSET_COMMIT,
    API_OFFSET_FETCH,
    API_PRODUCE,
    API_SYNC_GROUP,
    API_VERSIONS,
    ERROR_NAMES,
    FrameBuffer,
    KafkaWire,
    Reader,
    Record,
    Writer,
    decode_assignment,
    decode_record_batches,
    encode_record_batch,
    encode_subscription,
    frame,
    is_flexible,
    rnstr,
    rstr,
)


class ProbeError(Exception):
    """A non-zero Kafka error code surfaced by the probe."""

    def __init__(self, code: int, where: str):
        self.code = code
        super().__init__(
            f"{where}: {ERROR_NAMES.get(code, 'error')} ({code})"
        )


def _check(code: int, where: str) -> None:
    if code != 0:
        raise ProbeError(code, where)


# ---------------------------------------------------------------------------
# transports


class RealTransport:
    """One persistent TCP connection (asyncio streams)."""

    def __init__(self, reader, writer):
        self._reader = reader
        self._writer = writer

    @classmethod
    async def connect(cls, addr: "str | tuple") -> "RealTransport":
        import asyncio

        from ..real.stream import parse_addr

        host, port = parse_addr(addr)
        reader, writer = await asyncio.open_connection(host, port)
        return cls(reader, writer)

    async def send_frame(self, body: bytes) -> None:
        from ..real.stream import write_frame_raw

        await write_frame_raw(self._writer, body)

    async def recv_frame(self) -> Optional[bytes]:
        from ..real.stream import read_frame_raw

        return await read_frame_raw(self._reader)

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass


class SimTransport:
    """One persistent sim connection: ``connect1`` pipes carrying framed
    byte chunks (the Endpoint/stream plumbing of the sim tier)."""

    def __init__(self, tx, rx):
        self._tx = tx
        self._rx = rx
        self._buf = FrameBuffer()
        self._ready: List[bytes] = []

    @classmethod
    async def connect(cls, addr: "str | tuple") -> "SimTransport":
        from ..net.endpoint import connect1_ephemeral

        tx, rx = await connect1_ephemeral(addr)
        return cls(tx, rx)

    async def send_frame(self, body: bytes) -> None:
        await self._tx.send(frame(body))

    async def recv_frame(self) -> Optional[bytes]:
        while not self._ready:
            chunk = await self._rx.recv()
            if chunk is None:
                return None
            self._ready.extend(self._buf.feed(chunk))
        return self._ready.pop(0)

    def close(self) -> None:
        self._tx.close()
        self._rx.close()


class LoopbackTransport:
    """Feed a :class:`KafkaWire` directly — no sockets, pure codec. The
    differential-fuzz workhorse: every byte still round-trips through
    the full request/response encodings."""

    def __init__(self, wire: KafkaWire):
        self.wire = wire
        self._ready: List[bytes] = []

    async def send_frame(self, body: bytes) -> None:
        rsp = self.wire.handle_frame(body)
        if rsp is not None:
            self._ready.append(rsp)

    async def recv_frame(self) -> Optional[bytes]:
        if not self._ready:
            raise ProbeError(-1, "loopback: no response pending")
        return self._ready.pop(0)

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# the probe client


class ProbeClient:
    """The vendored wire client. Methods take an explicit ``ver`` so the
    fuzz can sweep the advertised version matrix; defaults are sensible
    mid-range picks."""

    def __init__(self, transport, client_id: str = "madsim-probe"):
        self.t = transport
        self.client_id = client_id
        self._corr = 0

    def close(self) -> None:
        self.t.close()

    # -- plumbing -----------------------------------------------------------

    def _header(self, api: int, ver: int) -> Writer:
        self._corr += 1
        w = Writer()
        w.i16(api).i16(ver).i32(self._corr)
        w.nullable_string(self.client_id)
        if is_flexible(api, ver):
            w.tagged_fields()
        return w

    async def _call(self, api: int, ver: int, w: Writer,
                    expect_response: bool = True) -> Optional[Reader]:
        await self.t.send_frame(w.done())
        if not expect_response:
            return None
        body = await self.t.recv_frame()
        if body is None:
            raise ProbeError(-1, "connection closed mid-call")
        r = Reader(body)
        corr = r.i32()
        if corr != self._corr:
            raise ProbeError(-1, f"correlation mismatch {corr} != {self._corr}")
        if is_flexible(api, ver) and api != API_VERSIONS:
            r.tagged_fields()
        return r

    # -- ApiVersions ---------------------------------------------------------

    async def api_versions(self, ver: int = 0) -> Tuple[int, Dict[int, Tuple[int, int]]]:
        """Returns (error_code, {api: (min, max)})."""
        w = self._header(API_VERSIONS, ver)
        if ver >= 3:
            w.compact_string("madsim-probe").compact_string("1.0")
            w.tagged_fields()
        r = await self._call(API_VERSIONS, ver, w)
        flex = ver >= 3
        err = r.i16()
        out: Dict[int, Tuple[int, int]] = {}

        def one():
            k, lo, hi = r.i16(), r.i16(), r.i16()
            if flex:
                r.tagged_fields()
            out[k] = (lo, hi)

        (r.compact_array if flex else r.array)(one)
        return err, out

    # -- Metadata ------------------------------------------------------------

    async def metadata(self, topics: Optional[List[str]] = None,
                       ver: int = 1) -> Dict[str, "int | None"]:
        """topic -> partition count (None = topic-level error)."""
        w = self._header(API_METADATA, ver)
        if topics is None:
            w.i32(0 if ver == 0 else -1)
        else:
            w.array(topics, lambda ww, t: ww.string(t))
        if ver >= 4:
            w.boolean(False)
        r = await self._call(API_METADATA, ver, w)
        if ver >= 3:
            r.i32()

        def one_broker():
            r.i32(); r.string(); r.i32()
            if ver >= 1:
                r.nullable_string()

        r.array(one_broker)
        if ver >= 2:
            r.nullable_string()
        if ver >= 1:
            r.i32()
        out: Dict[str, "int | None"] = {}

        def one_topic():
            err = r.i16()
            name = r.string()
            if ver >= 1:
                r.boolean()

            def one_part():
                r.i16(); r.i32(); r.i32()
                r.array(r.i32); r.array(r.i32)
                if ver >= 5:
                    r.array(r.i32)

            parts = r.array(one_part)
            out[name] = len(parts or []) if err == 0 else None

        r.array(one_topic)
        return out

    # -- topic admin ----------------------------------------------------------

    async def create_topics(
        self, topics: List[Tuple[str, int]], ver: int = 1
    ) -> List[Tuple[str, int, Optional[str]]]:
        w = self._header(API_CREATE_TOPICS, ver)

        def one(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name).i32(parts).i16(1)
            ww.array([], lambda w2, _x: None)
            ww.array([], lambda w2, _x: None)

        w.array(topics, one)
        w.i32(30_000)
        if ver >= 1:
            w.boolean(False)
        r = await self._call(API_CREATE_TOPICS, ver, w)
        if ver >= 2:
            r.i32()
        out = []

        def one_rsp():
            name = r.string()
            err = r.i16()
            msg = r.nullable_string() if ver >= 1 else None
            out.append((name, err, msg))

        r.array(one_rsp)
        return out

    async def delete_topics(self, names: List[str],
                            ver: int = 1) -> List[Tuple[str, int]]:
        w = self._header(API_DELETE_TOPICS, ver)
        w.array(names, lambda ww, n: ww.string(n))
        w.i32(30_000)
        r = await self._call(API_DELETE_TOPICS, ver, w)
        if ver >= 1:
            r.i32()
        out = []
        r.array(lambda: out.append((r.string(), r.i16())))
        return out

    # -- Produce / Fetch / ListOffsets ----------------------------------------

    async def produce(self, topic: str, partition: int,
                      records: List[Record], ver: int = 5,
                      acks: int = 1) -> Tuple[int, int]:
        """Returns (error_code, base_offset); acks=0 returns (0, -1)
        without waiting (fire-and-forget, as on the real wire)."""
        w = self._header(API_PRODUCE, ver)
        w.nullable_string(None)  # transactional_id
        w.i16(acks).i32(30_000)
        batch = encode_record_batch(0, records)

        def one_topic(ww: Writer, name: str) -> None:
            ww.string(name)
            ww.array([partition],
                     lambda w2, p: w2.i32(p).nullable_bytes(batch))

        w.array([topic], one_topic)
        r = await self._call(API_PRODUCE, ver, w, expect_response=acks != 0)
        if r is None:
            return 0, -1
        result = [0, -1]

        def one_rsp():
            r.string()

            def one_part():
                r.i32()
                result[0] = r.i16()
                result[1] = r.i64()
                if ver >= 2:
                    r.i64()
                if ver >= 5:
                    r.i64()

            r.array(one_part)

        r.array(one_rsp)
        r.i32()  # throttle
        return result[0], result[1]

    async def fetch(self, topic: str, partition: int, offset: int,
                    max_bytes: int = 52_428_800,
                    partition_max_bytes: int = 1_048_576,
                    ver: int = 4) -> Tuple[int, int, List[Tuple[int, int, Optional[bytes], Optional[bytes]]]]:
        """Returns (error_code, high_watermark, [(offset, ts, key, value)])."""
        w = self._header(API_FETCH, ver)
        w.i32(-1).i32(0).i32(1).i32(max_bytes)
        if ver >= 4:
            w.i8(0)
        if ver >= 7:
            w.i32(0).i32(-1)

        def one_topic(ww: Writer, name: str) -> None:
            ww.string(name)

            def one_part(w2: Writer, p: int) -> None:
                w2.i32(p)
                if ver >= 9:
                    w2.i32(-1)
                w2.i64(offset)
                if ver >= 5:
                    w2.i64(-1)
                w2.i32(partition_max_bytes)

            ww.array([partition], one_part)

        w.array([topic], one_topic)
        if ver >= 7:
            w.array([], lambda ww, _x: None)
        r = await self._call(API_FETCH, ver, w)
        r.i32()  # throttle
        if ver >= 7:
            r.i16(); r.i32()
        result: List[Tuple[int, int, List]] = []

        def one_rsp():
            r.string()

            def one_part():
                r.i32()
                err = r.i16()
                high = r.i64()
                r.i64()  # last_stable_offset
                if ver >= 5:
                    r.i64()  # log_start_offset
                r.array(lambda: (r.i64(), r.i64()))  # aborted txns
                if ver >= 11:
                    r.i32()
                blob = r.nullable_bytes() or b""
                result.append((err, high, decode_record_batches(blob)))

            r.array(one_part)

        r.array(one_rsp)
        err, high, rows = result[0]
        return err, high, rows

    async def list_offsets(self, topic: str, partition: int, ts: int,
                           ver: int = 1) -> Tuple[int, int, int]:
        """Returns (error_code, timestamp, offset); ts -1=latest,
        -2=earliest, else first-offset-with-timestamp>=ts."""
        w = self._header(API_LIST_OFFSETS, ver)
        w.i32(-1)
        if ver >= 2:
            w.i8(0)

        def one_topic(ww: Writer, name: str) -> None:
            ww.string(name)

            def one_part(w2: Writer, p: int) -> None:
                w2.i32(p)
                if ver >= 4:
                    w2.i32(-1)
                w2.i64(ts)

            ww.array([partition], one_part)

        w.array([topic], one_topic)
        r = await self._call(API_LIST_OFFSETS, ver, w)
        if ver >= 2:
            r.i32()
        result = [0, -1, -1]

        def one_rsp():
            r.string()

            def one_part():
                r.i32()
                result[0] = r.i16()
                result[1] = r.i64()
                result[2] = r.i64()
                if ver >= 4:
                    r.i32()

            r.array(one_part)

        r.array(one_rsp)
        return result[0], result[1], result[2]

    # -- group coordination ----------------------------------------------------

    async def find_coordinator(self, group: str,
                               ver: int = 0) -> Tuple[int, str, int]:
        flex = is_flexible(API_FIND_COORDINATOR, ver)
        w = self._header(API_FIND_COORDINATOR, ver)
        (w.compact_string if flex else w.string)(group)
        if ver >= 1:
            w.i8(0)
        if flex:
            w.tagged_fields()
        r = await self._call(API_FIND_COORDINATOR, ver, w)
        if ver >= 1:
            r.i32()
        err = r.i16()
        if ver >= 1:
            rnstr(r, flex)
        r.i32()  # node_id
        host = rstr(r, flex)
        port = r.i32()
        if flex:
            r.tagged_fields()
        return err, host, port

    async def join_group(
        self, group: str, member_id: str, topics: List[str], ver: int = 2
    ) -> Tuple[int, int, str, str, List[Tuple[str, bytes]]]:
        """Returns (error, generation, member_id, leader, members)."""
        w = self._header(API_JOIN_GROUP, ver)
        w.string(group).i32(30_000)
        if ver >= 1:
            w.i32(60_000)
        w.string(member_id)
        if ver >= 5:
            w.nullable_string(None)
        w.string("consumer")
        w.array([("range", encode_subscription(topics))],
                lambda ww, p: ww.string(p[0]).bytes32(p[1]))
        r = await self._call(API_JOIN_GROUP, ver, w)
        if ver >= 2:
            r.i32()
        err = r.i16()
        gen = r.i32()
        r.string()  # protocol_name
        leader = r.string()
        member = r.string()
        members: List[Tuple[str, bytes]] = []

        def one():
            mid = r.string()
            if ver >= 5:
                r.nullable_string()
            members.append((mid, r.bytes32()))

        r.array(one)
        return err, gen, member, leader, members

    async def sync_group(
        self, group: str, generation: int, member: str, ver: int = 1,
        assignments: Optional[List[Tuple[str, bytes]]] = None,
    ) -> Tuple[int, List[Tuple[str, int]]]:
        """Returns (error, [(topic, partition)])."""
        w = self._header(API_SYNC_GROUP, ver)
        w.string(group).i32(generation).string(member)
        if ver >= 3:
            w.nullable_string(None)
        w.array(assignments or [],
                lambda ww, p: ww.string(p[0]).bytes32(p[1]))
        r = await self._call(API_SYNC_GROUP, ver, w)
        if ver >= 1:
            r.i32()
        err = r.i16()
        blob = r.bytes32()
        return err, (decode_assignment(blob) if blob else [])

    async def heartbeat(self, group: str, generation: int, member: str,
                        ver: int = 0) -> int:
        flex = is_flexible(API_HEARTBEAT, ver)
        w = self._header(API_HEARTBEAT, ver)
        (w.compact_string if flex else w.string)(group)
        w.i32(generation)
        (w.compact_string if flex else w.string)(member)
        if ver >= 3:
            (w.compact_nullable_string if flex else w.nullable_string)(None)
        if flex:
            w.tagged_fields()
        r = await self._call(API_HEARTBEAT, ver, w)
        if ver >= 1:
            r.i32()
        return r.i16()

    async def leave_group(self, group: str, member: str, ver: int = 1) -> int:
        w = self._header(API_LEAVE_GROUP, ver)
        w.string(group)
        if ver >= 3:
            w.array([(member, None)],
                    lambda ww, p: ww.string(p[0]).nullable_string(p[1]))
        else:
            w.string(member)
        r = await self._call(API_LEAVE_GROUP, ver, w)
        if ver >= 1:
            r.i32()
        err = r.i16()
        if ver >= 3:
            r.array(lambda: (r.string(), r.nullable_string(), r.i16()))
        return err

    async def offset_commit(
        self, group: str, generation: int, member: str,
        offsets: List[Tuple[str, int, int]], ver: int = 2,
    ) -> List[Tuple[str, int, int]]:
        """Returns [(topic, partition, error_code)]."""
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for t, p, o in offsets:
            by_topic.setdefault(t, []).append((p, o))
        w = self._header(API_OFFSET_COMMIT, ver)
        w.string(group).i32(generation).string(member)
        if 2 <= ver <= 4:
            w.i64(-1)  # retention_time_ms

        def one_topic(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name)
            ww.array(parts,
                     lambda w2, p: w2.i32(p[0]).i64(p[1]).nullable_string(None))

        w.array(sorted(by_topic.items()), one_topic)
        r = await self._call(API_OFFSET_COMMIT, ver, w)
        if ver >= 3:
            r.i32()
        out: List[Tuple[str, int, int]] = []

        def one_rsp():
            name = r.string()
            r.array(lambda: out.append((name, r.i32(), r.i16())))

        r.array(one_rsp)
        return out

    async def offset_fetch(
        self, group: str, tps: List[Tuple[str, int]], ver: int = 1
    ) -> List[Tuple[str, int, Optional[int]]]:
        """Returns [(topic, partition, committed offset | None)]."""
        by_topic: Dict[str, List[int]] = {}
        for t, p in tps:
            by_topic.setdefault(t, []).append(p)
        w = self._header(API_OFFSET_FETCH, ver)
        w.string(group)

        def one_topic(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name)
            ww.array(parts, lambda w2, p: w2.i32(p))

        w.array(sorted(by_topic.items()), one_topic)
        r = await self._call(API_OFFSET_FETCH, ver, w)
        if ver >= 3:
            r.i32()
        out: List[Tuple[str, int, Optional[int]]] = []

        def one_rsp():
            name = r.string()

            def one_part():
                index = r.i32()
                off = r.i64()
                if ver >= 5:
                    r.i32()
                r.nullable_string()
                r.i16()
                out.append((name, index, None if off < 0 else off))

            r.array(one_part)

        r.array(one_rsp)
        if ver >= 2:
            r.i16()
        return out

    # -- the canonical session (the acceptance-criteria flow) ------------------

    async def group_session(
        self, group: str, topics: List[str], member_id: str = ""
    ) -> Tuple[str, int, List[Tuple[str, int]]]:
        """Join/Sync to a working assignment: the Join->Sync half of the
        canonical consumer-group session. A concurrent joiner can move
        the generation between our Join and Sync — the coordinator
        answers REBALANCE_IN_PROGRESS and, like a stock client, we
        rejoin (keeping the member id) until a generation holds still.
        Returns (member, generation, assignment)."""
        err, host, port = await self.find_coordinator(group)
        _check(err, "FindCoordinator")
        assert host, "coordinator must name itself"
        member = member_id
        for _attempt in range(50):
            err, gen, member, _leader, _members = await self.join_group(
                group, member, topics
            )
            _check(err, "JoinGroup")
            err, assignment = await self.sync_group(group, gen, member)
            if err in (27, 22):  # REBALANCE_IN_PROGRESS / ILLEGAL_GENERATION
                continue
            _check(err, "SyncGroup")
            return member, gen, assignment
        raise ProbeError(27, "SyncGroup: rebalance never settled")
