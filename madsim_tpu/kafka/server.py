"""The broker server node (madsim-rdkafka/src/sim/sim_broker.rs).

``SimBroker().serve(addr)``: one request enum exchange per ``connect1``
connection — CreateTopic / DeleteTopic / Produce / Fetch / FetchMetadata /
FetchWatermarks / OffsetsForTimes (sim_broker.rs:14-77).
"""

from __future__ import annotations

from typing import Any, Optional

from .. import task as mstask
from ..context import current_handle
from ..net.endpoint import Endpoint as NetEndpoint
from .broker import Broker, KafkaBrokerError


class SimBroker:
    def __init__(self) -> None:
        self.broker = Broker()

    async def serve(self, addr: "str | tuple") -> None:
        ep = await NetEndpoint.bind(addr)
        while True:
            tx, rx, _src = await ep.accept1()
            mstask.spawn(self._serve_conn(tx, rx), name="kafka-conn")

    async def _serve_conn(self, tx: Any, rx: Any) -> None:
        try:
            req = await rx.recv()
            if req is None:
                return
            try:
                await tx.send(("ok", self._handle(req)))
            except KafkaBrokerError as e:
                await tx.send(("err", str(e)))
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            tx.close()

    def _handle(self, req: tuple) -> Any:
        b = self.broker
        op = req[0]
        if op == "create_topic":
            _, name, partitions = req
            b.create_topic(name, partitions)
            return None
        if op == "delete_topic":
            b.delete_topic(req[1])
            return None
        if op == "produce":
            _, topic, partition, key, payload = req
            ts_ms = current_handle().time.now_time_ns() // 1_000_000
            return b.produce(topic, partition, key, payload, ts_ms)
        if op == "fetch":
            _, topic, partition, offset, fmax, pmax = req
            return b.fetch(topic, partition, offset, fmax, pmax)
        if op == "watermarks":
            _, topic, partition = req
            return b.watermarks(topic, partition)
        if op == "offsets_for_times":
            return b.offsets_for_times(req[1])
        if op == "metadata":
            return b.metadata(req[1])
        raise KafkaBrokerError(f"unknown request {op!r}")
