"""The broker server node (madsim-rdkafka/src/sim/sim_broker.rs).

``SimBroker().serve(addr)``: one request enum exchange per ``connect1``
connection — CreateTopic / DeleteTopic / Produce / Fetch / FetchMetadata /
FetchWatermarks / OffsetsForTimes (sim_broker.rs:14-77) — plus the
consumer-group ops (join/leave/heartbeat/commit/committed), which the
reference sim does not model (broker.py ``Group``).
"""

from __future__ import annotations

from typing import Any, Optional

from .. import task as mstask
from ..context import current_handle
from ..net.endpoint import Endpoint as NetEndpoint
from .broker import Broker, KafkaBrokerError


class SimBroker:
    # executor/clock bindings as class attributes so the real-mode twin
    # (real/kafka.py) rebinds them to asyncio + the wall clock while
    # reusing the whole request dispatcher (the sim/std split of
    # madsim-rdkafka/src/lib.rs:3-12)
    _spawn = staticmethod(mstask.spawn)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        return await NetEndpoint.bind(addr)

    @staticmethod
    def _now_ms() -> int:
        return current_handle().time.now_time_ns() // 1_000_000

    def __init__(self) -> None:
        self.broker = Broker()
        #: set once the listener is bound (port-0 discovery, real mode)
        self.bound_addr: "tuple | None" = None

    async def serve(self, addr: "str | tuple") -> None:
        ep = await self._bind(addr)
        local = getattr(ep, "local_addr", None)
        self.bound_addr = local() if callable(local) else None
        while True:
            tx, rx, _src = await ep.accept1()
            self._spawn(self._serve_conn(tx, rx), name="kafka-conn")

    async def _serve_conn(self, tx: Any, rx: Any) -> None:
        try:
            req = await rx.recv()
            if req is None:
                return
            try:
                await tx.send(("ok", self._handle(req)))
            except KafkaBrokerError as e:
                await tx.send(("err", str(e)))
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            tx.close()

    def _handle(self, req: tuple) -> Any:
        b = self.broker
        op = req[0]
        if op == "create_topic":
            _, name, partitions = req
            b.create_topic(name, partitions)
            return None
        if op == "delete_topic":
            b.delete_topic(req[1])
            return None
        if op == "produce":
            _, topic, partition, key, payload = req
            return b.produce(topic, partition, key, payload, self._now_ms())
        if op == "fetch":
            _, topic, partition, offset, fmax, pmax = req
            return b.fetch(topic, partition, offset, fmax, pmax)
        if op == "watermarks":
            _, topic, partition = req
            return b.watermarks(topic, partition)
        if op == "offsets_for_times":
            return b.offsets_for_times(req[1])
        if op == "metadata":
            return b.metadata(req[1])
        if op == "join_group":
            _, group, member, topics = req
            return b.join_group(group, member, topics)
        if op == "leave_group":
            _, group, member = req
            b.leave_group(group, member)
            return None
        if op == "heartbeat":
            _, group, member = req
            return b.group_state(group, member)
        if op == "commit":
            # legacy 3-tuple requests carry no generation (fence skipped)
            _, group, offsets = req[:3]
            b.commit_offsets(group, offsets, req[3] if len(req) > 3 else None)
            return None
        if op == "committed":
            _, group, tps = req
            return b.committed_offsets(group, tps)
        raise KafkaBrokerError(f"unknown request {op!r}")
