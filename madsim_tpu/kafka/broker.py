"""The broker state machine (madsim-rdkafka/src/sim/broker.rs).

Pure deterministic state: topics → partitions → append-only message logs
with log-end-offset/low-watermark bookkeeping, round-robin partition
assignment for keyless produce (broker.rs:80-101), offset-for-timestamp
lookup, and fetch honoring ``fetch_max_bytes`` / ``max_partition_fetch_
bytes`` (broker.rs:104-146).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class KafkaBrokerError(Exception):
    """Broker-side error (serialized back to clients as KafkaError)."""


@dataclass
class OwnedMessage:
    """rdkafka ``OwnedMessage``."""

    topic: str
    partition: int
    offset: int
    timestamp_ms: int
    key: Optional[bytes]
    payload: Optional[bytes]

    def size(self) -> int:
        return len(self.key or b"") + len(self.payload or b"")


@dataclass
class Watermarks:
    low: int
    high: int


@dataclass
class Partition:
    log: List[OwnedMessage] = field(default_factory=list)
    base_offset: int = 0  # low watermark (nothing is ever compacted here)

    @property
    def log_end_offset(self) -> int:
        return self.base_offset + len(self.log)


@dataclass
class Topic:
    name: str
    partitions: List[Partition]
    next_rr: int = 0  # round-robin cursor for keyless produce


@dataclass
class Group:
    """One consumer group: membership, the range assignment of the
    current generation, and committed offsets. **Beyond the reference**
    — madsim-rdkafka's sim models no consumer groups at all (assignment
    is manual, consumer.rs); this is classic group semantics with a
    deterministic assignor so sim schedules stay reproducible."""

    members: Dict[str, List[str]] = field(default_factory=dict)  # id -> topics
    generation: int = 0
    assignments: Dict[str, List[Tuple[str, int]]] = field(default_factory=dict)
    committed: Dict[Tuple[str, int], int] = field(default_factory=dict)
    next_member: int = 0


class Broker:
    """The single global broker (one mutex-guarded instance in the
    reference, sim_broker.rs:14-21)."""

    def __init__(self) -> None:
        self.topics: Dict[str, Topic] = {}
        self.groups: Dict[str, Group] = {}

    # -- admin -------------------------------------------------------------

    def create_topic(self, name: str, num_partitions: int) -> None:
        if name in self.topics:
            raise KafkaBrokerError(f"topic already exists: {name!r}")
        if num_partitions <= 0:
            raise KafkaBrokerError("num_partitions must be positive")
        self.topics[name] = Topic(name, [Partition() for _ in range(num_partitions)])

    def delete_topic(self, name: str) -> None:
        if name not in self.topics:
            raise KafkaBrokerError(f"unknown topic: {name!r}")
        del self.topics[name]

    def _topic(self, name: str) -> Topic:
        t = self.topics.get(name)
        if t is None:
            raise KafkaBrokerError(f"unknown topic: {name!r}")
        return t

    def _partition(self, topic: str, partition: int) -> Partition:
        t = self._topic(topic)
        if not 0 <= partition < len(t.partitions):
            raise KafkaBrokerError(f"unknown partition: {topic}[{partition}]")
        return t.partitions[partition]

    # -- produce (broker.rs:80-101) ----------------------------------------

    def produce(
        self,
        topic: str,
        partition: Optional[int],
        key: Optional[bytes],
        payload: Optional[bytes],
        timestamp_ms: int,
    ) -> Tuple[int, int]:
        """Append one message; keyless/partitionless records go round-robin.
        Returns (partition, offset)."""
        t = self._topic(topic)
        if partition is None:
            if key is not None:
                # stable key hash (rdkafka uses crc32 of the key)
                import zlib

                partition = zlib.crc32(key) % len(t.partitions)
            else:
                partition = t.next_rr % len(t.partitions)
                t.next_rr += 1
        p = self._partition(topic, partition)
        msg = OwnedMessage(
            topic=topic,
            partition=partition,
            offset=p.log_end_offset,
            timestamp_ms=timestamp_ms,
            key=key,
            payload=payload,
        )
        p.log.append(msg)
        return partition, msg.offset

    # -- fetch (broker.rs:104-146) -----------------------------------------

    def fetch(
        self,
        topic: str,
        partition: int,
        offset: int,
        fetch_max_bytes: int,
        max_partition_fetch_bytes: int,
    ) -> List[OwnedMessage]:
        p = self._partition(topic, partition)
        start = max(offset, p.base_offset) - p.base_offset
        out: List[OwnedMessage] = []
        budget = min(fetch_max_bytes, max_partition_fetch_bytes)
        for msg in p.log[start:]:
            if out and msg.size() > budget:
                break
            out.append(msg)
            budget -= msg.size()
            if budget <= 0:
                break
        return out

    # -- lookups -----------------------------------------------------------

    def watermarks(self, topic: str, partition: int) -> Watermarks:
        p = self._partition(topic, partition)
        return Watermarks(low=p.base_offset, high=p.log_end_offset)

    def offsets_for_times(
        self, queries: List[Tuple[str, int, int]]
    ) -> List[Tuple[str, int, Optional[int]]]:
        """For each (topic, partition, ts): the first offset with
        timestamp >= ts, or None past the end (broker.rs offset lookup)."""
        out = []
        for topic, partition, ts in queries:
            p = self._partition(topic, partition)
            found: Optional[int] = None
            for msg in p.log:
                if msg.timestamp_ms >= ts:
                    found = msg.offset
                    break
            out.append((topic, partition, found))
        return out

    def metadata(self, topic: Optional[str] = None) -> Dict[str, int]:
        """topic → partition count (FetchMetadata)."""
        if topic is not None:
            return {topic: len(self._topic(topic).partitions)}
        return {name: len(t.partitions) for name, t in sorted(self.topics.items())}

    # -- consumer groups (beyond the reference — see Group) -----------------

    def _group(self, group_id: str) -> Group:
        """Create-on-first-use — the JOIN path only."""
        g = self.groups.get(group_id)
        if g is None:
            g = self.groups[group_id] = Group()
        return g

    def _group_lookup(self, group_id: str) -> Group:
        """Every non-join path: a typo'd group id errors instead of
        silently creating an empty group (whose committed offsets nobody
        would ever read)."""
        g = self.groups.get(group_id)
        if g is None:
            raise KafkaBrokerError(f"unknown group: {group_id!r}")
        return g

    def _rebalance(self, g: Group) -> None:
        """Range assignment, deterministic: for each topic, contiguous
        partition spans over the topic's subscribers sorted by member id
        (the classic RangeAssignor; floor+remainder split)."""
        g.generation += 1
        g.assignments = {m: [] for m in g.members}
        topics = sorted({t for ts in g.members.values() for t in ts})
        for topic in topics:
            subs = sorted(m for m, ts in g.members.items() if topic in ts)
            if not subs or topic not in self.topics:
                continue
            n_parts = len(self.topics[topic].partitions)
            base, extra = divmod(n_parts, len(subs))
            start = 0
            for i, m in enumerate(subs):
                count = base + (1 if i < extra else 0)
                g.assignments[m].extend(
                    (topic, p) for p in range(start, start + count)
                )
                start += count

    def join_group(
        self, group_id: str, member_id: Optional[str], topics: List[str]
    ) -> Tuple[str, int, List[Tuple[str, int]]]:
        """Add (or re-subscribe) a member; returns (member_id, generation,
        this member's assignment). Every join triggers a rebalance, as in
        the eager group protocol."""
        for t in topics:
            self._topic(t)  # unknown topics fail the join loudly
        g = self._group(group_id)
        if member_id is not None and g.members.get(member_id) == list(topics):
            # rejoin with an unchanged subscription: answer from the
            # current generation instead of bumping it — the wire tier's
            # heartbeat-triggered rejoins (REBALANCE_IN_PROGRESS -> Join/
            # Sync) must converge, not storm every other member forever
            return member_id, g.generation, g.assignments.get(member_id, [])
        if member_id is None:
            member_id = f"member-{g.next_member}"
            g.next_member += 1
        g.members[member_id] = list(topics)
        self._rebalance(g)
        return member_id, g.generation, g.assignments[member_id]

    def leave_group(self, group_id: str, member_id: str) -> None:
        g = self._group_lookup(group_id)
        if member_id in g.members:
            del g.members[member_id]
            self._rebalance(g)

    def group_state(
        self, group_id: str, member_id: str
    ) -> Tuple[int, List[Tuple[str, int]]]:
        """Heartbeat: (current generation, this member's assignment) —
        consumers compare generations to detect a rebalance."""
        g = self._group_lookup(group_id)
        if member_id not in g.members:
            raise KafkaBrokerError(
                f"unknown member {member_id!r} in group {group_id!r}"
            )
        return g.generation, g.assignments.get(member_id, [])

    def commit_offsets(
        self,
        group_id: str,
        offsets: List[Tuple[str, int, int]],
        generation: Optional[int] = None,
    ) -> None:
        """Commit offsets, fenced by generation: a commit stamped with a
        generation below the group's current one is a zombie — a member
        still acting on an assignment a later rebalance revoked — and is
        rejected (real Kafka's ILLEGAL_GENERATION), because applying it
        could roll a partition's committed offset backward past the new
        owner's commits. ``generation=None`` (legacy callers, simple
        tooling) skips the fence."""
        g = self._group_lookup(group_id)
        if generation is not None and generation < g.generation:
            raise KafkaBrokerError(
                f"ILLEGAL_GENERATION: commit for group {group_id!r} carries "
                f"generation {generation} < current {g.generation} (zombie "
                "member — rejoin before committing)"
            )
        for topic, partition, offset in offsets:
            self._partition(topic, partition)  # validate
            g.committed[(topic, partition)] = offset

    def committed_offsets(
        self, group_id: str, tps: List[Tuple[str, int]]
    ) -> List[Tuple[str, int, Optional[int]]]:
        g = self._group_lookup(group_id)
        return [(t, p, g.committed.get((t, p))) for t, p in tps]
