"""The broker state machine (madsim-rdkafka/src/sim/broker.rs).

Pure deterministic state: topics → partitions → append-only message logs
with log-end-offset/low-watermark bookkeeping, round-robin partition
assignment for keyless produce (broker.rs:80-101), offset-for-timestamp
lookup, and fetch honoring ``fetch_max_bytes`` / ``max_partition_fetch_
bytes`` (broker.rs:104-146).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class KafkaBrokerError(Exception):
    """Broker-side error (serialized back to clients as KafkaError)."""


@dataclass
class OwnedMessage:
    """rdkafka ``OwnedMessage``."""

    topic: str
    partition: int
    offset: int
    timestamp_ms: int
    key: Optional[bytes]
    payload: Optional[bytes]

    def size(self) -> int:
        return len(self.key or b"") + len(self.payload or b"")


@dataclass
class Watermarks:
    low: int
    high: int


@dataclass
class Partition:
    log: List[OwnedMessage] = field(default_factory=list)
    base_offset: int = 0  # low watermark (nothing is ever compacted here)

    @property
    def log_end_offset(self) -> int:
        return self.base_offset + len(self.log)


@dataclass
class Topic:
    name: str
    partitions: List[Partition]
    next_rr: int = 0  # round-robin cursor for keyless produce


class Broker:
    """The single global broker (one mutex-guarded instance in the
    reference, sim_broker.rs:14-21)."""

    def __init__(self) -> None:
        self.topics: Dict[str, Topic] = {}

    # -- admin -------------------------------------------------------------

    def create_topic(self, name: str, num_partitions: int) -> None:
        if name in self.topics:
            raise KafkaBrokerError(f"topic already exists: {name!r}")
        if num_partitions <= 0:
            raise KafkaBrokerError("num_partitions must be positive")
        self.topics[name] = Topic(name, [Partition() for _ in range(num_partitions)])

    def delete_topic(self, name: str) -> None:
        if name not in self.topics:
            raise KafkaBrokerError(f"unknown topic: {name!r}")
        del self.topics[name]

    def _topic(self, name: str) -> Topic:
        t = self.topics.get(name)
        if t is None:
            raise KafkaBrokerError(f"unknown topic: {name!r}")
        return t

    def _partition(self, topic: str, partition: int) -> Partition:
        t = self._topic(topic)
        if not 0 <= partition < len(t.partitions):
            raise KafkaBrokerError(f"unknown partition: {topic}[{partition}]")
        return t.partitions[partition]

    # -- produce (broker.rs:80-101) ----------------------------------------

    def produce(
        self,
        topic: str,
        partition: Optional[int],
        key: Optional[bytes],
        payload: Optional[bytes],
        timestamp_ms: int,
    ) -> Tuple[int, int]:
        """Append one message; keyless/partitionless records go round-robin.
        Returns (partition, offset)."""
        t = self._topic(topic)
        if partition is None:
            if key is not None:
                # stable key hash (rdkafka uses crc32 of the key)
                import zlib

                partition = zlib.crc32(key) % len(t.partitions)
            else:
                partition = t.next_rr % len(t.partitions)
                t.next_rr += 1
        p = self._partition(topic, partition)
        msg = OwnedMessage(
            topic=topic,
            partition=partition,
            offset=p.log_end_offset,
            timestamp_ms=timestamp_ms,
            key=key,
            payload=payload,
        )
        p.log.append(msg)
        return partition, msg.offset

    # -- fetch (broker.rs:104-146) -----------------------------------------

    def fetch(
        self,
        topic: str,
        partition: int,
        offset: int,
        fetch_max_bytes: int,
        max_partition_fetch_bytes: int,
    ) -> List[OwnedMessage]:
        p = self._partition(topic, partition)
        start = max(offset, p.base_offset) - p.base_offset
        out: List[OwnedMessage] = []
        budget = min(fetch_max_bytes, max_partition_fetch_bytes)
        for msg in p.log[start:]:
            if out and msg.size() > budget:
                break
            out.append(msg)
            budget -= msg.size()
            if budget <= 0:
                break
        return out

    # -- lookups -----------------------------------------------------------

    def watermarks(self, topic: str, partition: int) -> Watermarks:
        p = self._partition(topic, partition)
        return Watermarks(low=p.base_offset, high=p.log_end_offset)

    def offsets_for_times(
        self, queries: List[Tuple[str, int, int]]
    ) -> List[Tuple[str, int, Optional[int]]]:
        """For each (topic, partition, ts): the first offset with
        timestamp >= ts, or None past the end (broker.rs offset lookup)."""
        out = []
        for topic, partition, ts in queries:
            p = self._partition(topic, partition)
            found: Optional[int] = None
            for msg in p.log:
                if msg.timestamp_ms >= ts:
                    found = msg.offset
                    break
            out.append((topic, partition, found))
        return out

    def metadata(self, topic: Optional[str] = None) -> Dict[str, int]:
        """topic → partition count (FetchMetadata)."""
        if topic is not None:
            return {topic: len(self._topic(topic).partitions)}
        return {name: len(t.partitions) for name, t in sorted(self.topics.items())}
