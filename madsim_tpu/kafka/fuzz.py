"""Seeded differential fuzz for the Kafka wire: one random operation mix
(produce / fetch / list-offsets / group join / heartbeat / commit /
offset-fetch, with a mid-run rebalance and a late leave) applied BOTH
through the genuine wire codec (a :class:`~madsim_tpu.kafka.probe.
ProbeClient` over any transport) and directly to a mirrored in-process
:class:`~madsim_tpu.kafka.broker.Broker`; every per-op result must agree.

Per-seed, the request versions themselves are drawn from the advertised
matrix (``SUPPORTED_APIS``), so the fuzz sweeps the version-gated field
layouts, not just one encoding. The wire-side results also fold into a
SHA-256 digest — ``scripts/wire_load_demo.py --fuzz`` writes those
digests to a report the determinism gate byte-diffs across processes.

Used by ``tests/test_wire_differential.py`` (loopback codec x many
seeds, real TCP x a few) and the determinism gate.
"""

from __future__ import annotations

import hashlib
import random
from typing import Dict, List, Optional, Tuple

from .broker import Broker, KafkaBrokerError
from .probe import ProbeClient
from .wire import (
    ERR_GROUP_ID_NOT_FOUND,
    ERR_ILLEGAL_GENERATION,
    ERR_NONE,
    ERR_REBALANCE_IN_PROGRESS,
    ERR_UNKNOWN_MEMBER_ID,
    ERR_UNKNOWN_TOPIC_OR_PARTITION,
)

TOPIC = "fz"
GROUP = "fz-group"


def _expected_heartbeat(mirror: Broker, group: str, member: str,
                        generation: int) -> int:
    """The coordinator fence, computed from the mirror's state — what the
    wire server must answer for (group, member, generation)."""
    g = mirror.groups.get(group)
    if g is None:
        return ERR_GROUP_ID_NOT_FOUND
    if member not in g.members:
        return ERR_UNKNOWN_MEMBER_ID
    if generation != g.generation:
        return ERR_REBALANCE_IN_PROGRESS
    return ERR_NONE


def _expected_commit(mirror: Broker, group: str, tpo, gen) -> int:
    try:
        mirror.commit_offsets(group, [tpo], gen)
        return ERR_NONE
    except KafkaBrokerError as e:
        msg = str(e)
        if "ILLEGAL_GENERATION" in msg:
            return ERR_ILLEGAL_GENERATION
        if "unknown group" in msg:
            return ERR_GROUP_ID_NOT_FOUND
        return ERR_UNKNOWN_TOPIC_OR_PARTITION


async def fuzz_seed(seed: int, client: ProbeClient, ops: int = 40) -> str:
    """Run one seed's op mix through ``client`` (bound to a FRESH
    wire-served broker) and a fresh mirror broker; assert equality per
    op; return the wire-side result digest (hex)."""
    rng = random.Random(seed)
    mirror = Broker()
    acc = hashlib.sha256()

    def note(tag: str, value) -> None:
        acc.update(f"{tag}:{value!r};".encode())

    # per-seed version picks from the advertised matrix
    pv = rng.choice([3, 5, 7])
    fv = rng.choice([4, 7, 10])
    lv = rng.choice([1, 2, 4, 5])
    jv = rng.choice([0, 2, 5])
    sv = rng.choice([0, 1, 3])
    hv = rng.choice([0, 1, 4])
    cv = rng.choice([2, 3, 5])
    ofv = rng.choice([1, 3, 5])
    note("versions", (pv, fv, lv, jv, sv, hv, cv, ofv))

    # -- setup: topic + two group members on both sides ---------------------
    nparts = rng.randrange(1, 4)
    out = await client.create_topics([(TOPIC, nparts)],
                                     ver=rng.choice([0, 1, 2, 4]))
    assert out[0][1] == ERR_NONE, out
    mirror.create_topic(TOPIC, nparts)
    note("topic", nparts)

    members: Dict[str, int] = {}  # member id -> generation it last adopted

    async def join(member_id: str = "") -> str:
        err, gen, member, _leader, _meta = await client.join_group(
            GROUP, member_id, [TOPIC], ver=jv
        )
        assert err == ERR_NONE, (seed, err)
        err, assignment = await client.sync_group(GROUP, gen, member, ver=sv)
        assert err == ERR_NONE, (seed, err)
        m_member, m_gen, m_assigned = mirror.join_group(
            GROUP, member_id or None, [TOPIC]
        )
        assert (member, gen) == (m_member, m_gen), (
            seed, member, gen, m_member, m_gen
        )
        assert sorted(assignment) == sorted(m_assigned), (
            seed, assignment, m_assigned
        )
        members[member] = gen
        note("join", (member, gen, sorted(assignment)))
        return member

    m0 = await join()
    m1 = await join()
    members[m0] = members[m1]  # both adopt the 2-member generation
    # keep the wire server's view of m0 in step too (rejoin, no bump)
    await join(m0)

    high: Dict[int, int] = {p: 0 for p in range(nparts)}
    seq = 0
    third: Optional[str] = None

    for step in range(ops):
        if step == ops // 2 and third is None:
            third = await join()  # mid-run rebalance
            continue
        if third is not None and step == (3 * ops) // 4:
            err = await client.leave_group(GROUP, third,
                                           ver=rng.choice([0, 1, 3]))
            assert err == ERR_NONE, (seed, err)
            mirror.leave_group(GROUP, third)
            members.pop(third, None)
            note("leave", third)
            third = None
            continue

        op = rng.choice(
            ["produce", "produce", "produce", "fetch", "fetch",
             "list_offsets", "heartbeat", "commit", "offset_fetch"]
        )
        if op == "produce":
            p = rng.randrange(nparts)
            key = None if rng.random() < 0.4 else f"k{rng.randrange(6)}".encode()
            val = f"v{seq}".encode() * rng.randrange(1, 3)
            ts = 1_000 + seq * 7
            seq += 1
            err, base = await client.produce(TOPIC, p, [(ts, key, val)], ver=pv)
            m_p, m_off = mirror.produce(TOPIC, p, key, val, ts)
            assert err == ERR_NONE and (p, base) == (m_p, m_off), (
                seed, step, err, base, m_off
            )
            high[p] = m_off + 1
            note("produce", (p, base))
        elif op == "fetch":
            p = rng.randrange(nparts)
            offset = rng.randrange(0, high[p] + 2)
            pmax = rng.choice([40, 1_048_576])
            err, got_high, rows = await client.fetch(
                TOPIC, p, offset, partition_max_bytes=pmax, ver=fv
            )
            m_msgs = mirror.fetch(TOPIC, p, offset, 52_428_800, pmax)
            assert err == ERR_NONE and got_high == high[p], (seed, step)
            assert rows == [
                (m.offset, m.timestamp_ms, m.key, m.payload) for m in m_msgs
            ], (seed, step, rows, m_msgs)
            note("fetch", (p, offset, len(rows)))
        elif op == "list_offsets":
            p = rng.randrange(nparts)
            ts = rng.choice([-1, -2, 1_000 + rng.randrange(max(seq, 1)) * 7])
            err, _rts, off = await client.list_offsets(TOPIC, p, ts, ver=lv)
            assert err == ERR_NONE, (seed, step)
            wm = mirror.watermarks(TOPIC, p)
            if ts == -1:
                expect: Optional[int] = wm.high
            elif ts == -2:
                expect = wm.low
            else:
                (_t, _p, expect), = mirror.offsets_for_times([(TOPIC, p, ts)])
            assert off == (-1 if expect is None else expect), (
                seed, step, off, expect
            )
            note("list_offsets", (p, ts, off))
        elif op == "heartbeat":
            member = rng.choice(sorted(members))
            gen = members[member] if rng.random() < 0.8 else members[member] - 1
            err = await client.heartbeat(GROUP, gen, member, ver=hv)
            expect = _expected_heartbeat(mirror, GROUP, member, gen)
            assert err == expect, (seed, step, err, expect)
            if err == ERR_REBALANCE_IN_PROGRESS and rng.random() < 0.7:
                await join(member)  # the eager protocol's rejoin
            note("heartbeat", (member, gen, err))
        elif op == "commit":
            member = rng.choice(sorted(members))
            p = rng.randrange(nparts)
            off = rng.randrange(0, high[p] + 1)
            gen = members[member] if rng.random() < 0.8 else members[member] - 1
            results = await client.offset_commit(
                GROUP, gen, member, [(TOPIC, p, off)], ver=cv
            )
            expect = _expected_commit(mirror, GROUP, (TOPIC, p, off), gen)
            assert results == [(TOPIC, p, expect)], (
                seed, step, results, expect
            )
            note("commit", (member, p, off, results[0][2]))
        else:  # offset_fetch
            tps = [(TOPIC, rng.randrange(nparts))]
            got = await client.offset_fetch(GROUP, tps, ver=ofv)
            expect = mirror.committed_offsets(GROUP, tps)
            assert got == expect, (seed, step, got, expect)
            note("offset_fetch", got)

    # -- final state: every partition's log identical, key for key ----------
    for p in range(nparts):
        err, got_high, rows = await client.fetch(TOPIC, p, 0, ver=fv)
        m_msgs = mirror.fetch(TOPIC, p, 0, 52_428_800, 52_428_800)
        assert err == ERR_NONE and rows == [
            (m.offset, m.timestamp_ms, m.key, m.payload) for m in m_msgs
        ], (seed, p)
        note("final", (p, got_high, len(rows)))

    return acc.hexdigest()
