"""Genuine Kafka binary wire protocol: the framework's ``Broker`` state
machine served over the REAL Kafka protocol, so a stock Kafka client can
connect, produce, fetch, and run a full consumer-group session against
it on either tier.

The reference's madsim-rdkafka compiles to the *real* rdkafka bindings
outside the sim — its std mode speaks the actual Kafka wire. No
librdkafka exists in this image, so this module holds the property from
the server side (the same move as ``etcd/wire.py`` for etcd gRPC and
``s3/wire.py`` for S3 REST): 4-byte big-endian length framing,
request/response headers with correlation ids (v1 and the v2
flexible/compact-tagged-field form), record-batch **v2** encoding with
CRC32C (Castagnoli, table-driven — no native crc32c dependency), and the
version-gated field layouts of the APIs below.

Advertised API matrix (``ApiVersions`` reports exactly this; ``flex`` is
the first flexible version served, ``-`` = none in the advertised span):

    ==================  ===  =========  ====
    API                 key  versions   flex
    ==================  ===  =========  ====
    Produce               0  3–7        -
    Fetch                 1  4–10       -
    ListOffsets           2  1–5        -
    Metadata              3  0–5        -
    OffsetCommit          8  2–5        -
    OffsetFetch           9  1–5        -
    FindCoordinator      10  0–3        3
    JoinGroup            11  0–5        -
    Heartbeat            12  0–4        4
    LeaveGroup           13  0–3        -
    SyncGroup            14  0–3        -
    ApiVersions          18  0–3        3
    CreateTopics         19  0–4        -
    DeleteTopics         20  0–3        -
    ==================  ===  =========  ====

Scope notes (deliberate test-double boundaries, like the S3 wire's):
this is a single-node "cluster" (node 0 is every partition's leader and
the one group coordinator), record batches are uncompressed (compressed
batches are refused loudly, never mis-decoded), Fetch answers
immediately (no ``max_wait``/``min_bytes`` long-poll parking) and clamps
out-of-range offsets to the log bounds exactly like the broker state
machine does, and the group coordinator ASSIGNS server-side: JoinGroup
keeps the classic shape (leader election, member-metadata echo) but
SyncGroup returns the broker's own deterministic range assignment,
ignoring leader-supplied assignments — identical subscriptions make a
stock client's RangeAssignor agree byte-for-byte anyway, and sim
schedules stay reproducible. Rejoining with an unchanged subscription
does not bump the generation (static-membership-flavored), which is what
lets a heartbeat-triggered rejoin converge instead of storming.

Two tiers, one engine: ``KafkaWire.handle_frame`` is a pure function of
(request bytes, clock) — ``SimWireServer`` serves it over the Endpoint /
``connect1`` pipe plumbing (bytes chunks over sim channels), and
``WireServer`` over real TCP via asyncio streams with the frame helpers
in ``real/stream.py``. Purity is the determinism story: the load gate
(``scripts/wire_load_demo.py``) re-feeds a recorded (frame, clock)
transcript through a fresh broker and requires byte-identical responses.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

from .broker import Broker, KafkaBrokerError

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — table-driven, reflected poly 0x82F63B78. Pure
# Python on purpose: the container has no crc32c wheel, and record-batch
# volumes here (tests + smoke gates) are far below the point where a
# native implementation would matter.

_CRC32C_TABLE: List[int] = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# primitive codec

_I8 = struct.Struct(">b")
_I16 = struct.Struct(">h")
_I32 = struct.Struct(">i")
_I64 = struct.Struct(">q")
_U32 = struct.Struct(">I")


class WireError(Exception):
    """A frame this server refuses to parse/serve — the connection dies,
    like a protocol violation against a real broker."""


class Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.data):
            raise WireError(f"truncated frame (want {n} bytes at {self.pos})")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def remaining(self) -> int:
        return len(self.data) - self.pos

    def i8(self) -> int:
        return _I8.unpack(self.read(1))[0]

    def i16(self) -> int:
        return _I16.unpack(self.read(2))[0]

    def i32(self) -> int:
        return _I32.unpack(self.read(4))[0]

    def i64(self) -> int:
        return _I64.unpack(self.read(8))[0]

    def u32(self) -> int:
        return _U32.unpack(self.read(4))[0]

    def boolean(self) -> bool:
        return self.i8() != 0

    def uvarint(self) -> int:
        out = shift = 0
        while True:
            b = self.read(1)[0]
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 63:
                raise WireError("varint overflow")

    def varint(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)

    varlong = varint  # same zigzag encoding, wider range

    def string(self) -> str:
        n = self.i16()
        if n < 0:
            raise WireError("null where a non-null string is required")
        return self.read(n).decode("utf-8")

    def nullable_string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self.read(n).decode("utf-8")

    def bytes32(self) -> bytes:
        n = self.i32()
        if n < 0:
            raise WireError("null where non-null bytes are required")
        return bytes(self.read(n))

    def nullable_bytes(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else bytes(self.read(n))

    def compact_string(self) -> str:
        n = self.uvarint() - 1
        if n < 0:
            raise WireError("null where a non-null compact string is required")
        return self.read(n).decode("utf-8")

    def compact_nullable_string(self) -> Optional[str]:
        n = self.uvarint() - 1
        return None if n < 0 else self.read(n).decode("utf-8")

    def compact_bytes(self) -> bytes:
        n = self.uvarint() - 1
        if n < 0:
            raise WireError("null where non-null compact bytes are required")
        return bytes(self.read(n))

    def array(self, fn: Callable[[], Any]) -> Optional[list]:
        n = self.i32()
        if n < 0:
            return None
        if n > 1_000_000:
            raise WireError(f"implausible array length {n}")
        return [fn() for _ in range(n)]

    def compact_array(self, fn: Callable[[], Any]) -> Optional[list]:
        n = self.uvarint() - 1
        if n < 0:
            return None
        if n > 1_000_000:
            raise WireError(f"implausible array length {n}")
        return [fn() for _ in range(n)]

    def tagged_fields(self) -> None:
        for _ in range(self.uvarint()):
            self.uvarint()  # tag
            self.read(self.uvarint())  # value


class Writer:
    __slots__ = ("b",)

    def __init__(self) -> None:
        self.b = bytearray()

    def raw(self, data: bytes) -> "Writer":
        self.b += data
        return self

    def i8(self, v: int) -> "Writer":
        self.b += _I8.pack(v)
        return self

    def i16(self, v: int) -> "Writer":
        self.b += _I16.pack(v)
        return self

    def i32(self, v: int) -> "Writer":
        self.b += _I32.pack(v)
        return self

    def i64(self, v: int) -> "Writer":
        self.b += _I64.pack(v)
        return self

    def u32(self, v: int) -> "Writer":
        self.b += _U32.pack(v)
        return self

    def boolean(self, v: bool) -> "Writer":
        return self.i8(1 if v else 0)

    def uvarint(self, v: int) -> "Writer":
        while True:
            if v < 0x80:
                self.b.append(v)
                return self
            self.b.append((v & 0x7F) | 0x80)
            v >>= 7

    def varint(self, v: int) -> "Writer":
        return self.uvarint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    varlong = varint

    def string(self, s: str) -> "Writer":
        raw = s.encode("utf-8")
        return self.i16(len(raw)).raw(raw)

    def nullable_string(self, s: Optional[str]) -> "Writer":
        return self.i16(-1) if s is None else self.string(s)

    def bytes32(self, data: bytes) -> "Writer":
        return self.i32(len(data)).raw(data)

    def nullable_bytes(self, data: Optional[bytes]) -> "Writer":
        return self.i32(-1) if data is None else self.bytes32(data)

    def compact_string(self, s: str) -> "Writer":
        raw = s.encode("utf-8")
        return self.uvarint(len(raw) + 1).raw(raw)

    def compact_nullable_string(self, s: Optional[str]) -> "Writer":
        return self.uvarint(0) if s is None else self.compact_string(s)

    def compact_bytes(self, data: bytes) -> "Writer":
        return self.uvarint(len(data) + 1).raw(data)

    def array(self, items, fn: Callable[["Writer", Any], Any]) -> "Writer":
        self.i32(len(items))
        for it in items:
            fn(self, it)
        return self

    def compact_array(self, items, fn: Callable[["Writer", Any], Any]) -> "Writer":
        self.uvarint(len(items) + 1)
        for it in items:
            fn(self, it)
        return self

    def tagged_fields(self) -> "Writer":
        return self.uvarint(0)

    def done(self) -> bytes:
        return bytes(self.b)


# version-aware string/array: one call site per field, the flexible flag
# picks the encoding — the two wire forms can never drift apart per field
def wstr(w: Writer, s: str, flex: bool) -> None:
    (w.compact_string if flex else w.string)(s)


def wnstr(w: Writer, s: Optional[str], flex: bool) -> None:
    (w.compact_nullable_string if flex else w.nullable_string)(s)


def warr(w: Writer, items, fn, flex: bool) -> None:
    (w.compact_array if flex else w.array)(items, fn)


def rstr(r: Reader, flex: bool) -> str:
    return r.compact_string() if flex else r.string()


def rnstr(r: Reader, flex: bool) -> Optional[str]:
    return r.compact_nullable_string() if flex else r.nullable_string()


def rarr(r: Reader, fn, flex: bool) -> Optional[list]:
    return r.compact_array(fn) if flex else r.array(fn)


# ---------------------------------------------------------------------------
# record batch v2 (magic 2) — the modern on-wire record format

#: (timestamp_ms, key|None, value|None) — the record triple both codec
#: directions and the probe client speak
Record = Tuple[int, Optional[bytes], Optional[bytes]]


def encode_record_batch(base_offset: int, records: List[Record]) -> bytes:
    """One uncompressed v2 batch; CRC32C covers attributes..end, exactly
    the span the spec names."""
    if not records:
        return b""
    body = Writer()
    body.i16(0)  # attributes: no compression, CREATE_TIME, not txn
    body.i32(len(records) - 1)  # lastOffsetDelta
    base_ts = records[0][0]
    body.i64(base_ts)
    body.i64(max(ts for ts, _k, _v in records))
    body.i64(-1).i16(-1).i32(-1)  # producerId / producerEpoch / baseSequence
    body.i32(len(records))
    for i, (ts, key, val) in enumerate(records):
        rec = Writer()
        rec.i8(0)  # record attributes
        rec.varlong(ts - base_ts)
        rec.varint(i)  # offsetDelta
        for blob in (key, val):
            if blob is None:
                rec.varint(-1)
            else:
                rec.varint(len(blob)).raw(blob)
        rec.varint(0)  # headers
        body.varint(len(rec.b)).raw(rec.b)
    out = Writer()
    out.i64(base_offset)
    out.i32(4 + 1 + 4 + len(body.b))  # partitionLeaderEpoch + magic + crc + rest
    out.i32(-1)  # partitionLeaderEpoch
    out.i8(2)  # magic
    out.u32(crc32c(bytes(body.b)))
    out.raw(body.b)
    return out.done()


def decode_record_batches(data: bytes) -> List[Tuple[int, int, Optional[bytes], Optional[bytes]]]:
    """Decode a concatenation of v2 batches into (offset, ts, key, value)
    rows, verifying each batch's CRC32C. Older magic or compressed
    batches are refused loudly."""
    out: List[Tuple[int, int, Optional[bytes], Optional[bytes]]] = []
    r = Reader(data)
    while r.remaining() > 0:
        if r.remaining() < 12:
            raise WireError("trailing garbage after last record batch")
        base = r.i64()
        batch = r.read(r.i32())
        br = Reader(batch)
        br.i32()  # partitionLeaderEpoch
        magic = br.i8()
        if magic != 2:
            raise WireError(f"unsupported record format magic {magic} (v2 only)")
        crc = br.u32()
        payload = batch[br.pos:]
        if crc32c(payload) != crc:
            raise WireError("record batch CRC32C mismatch")
        attrs = br.i16()
        if attrs & 0x07:
            raise WireError("compressed record batches are not supported")
        br.i32()  # lastOffsetDelta
        base_ts = br.i64()
        br.i64()  # maxTimestamp
        br.i64(); br.i16(); br.i32()  # producer id / epoch / base sequence
        for _ in range(br.i32()):
            rr = Reader(br.read(br.varint()))
            rr.i8()  # record attributes
            ts = base_ts + rr.varlong()
            off = base + rr.varint()
            kl = rr.varint()
            key = bytes(rr.read(kl)) if kl >= 0 else None
            vl = rr.varint()
            val = bytes(rr.read(vl)) if vl >= 0 else None
            for _h in range(max(rr.varint(), 0)):  # headers: skipped
                rr.read(max(rr.varint(), 0))
                rr.read(max(rr.varint(), 0))
            out.append((off, ts, key, val))
    return out


# ---------------------------------------------------------------------------
# consumer-protocol blobs (the opaque bytes inside JoinGroup/SyncGroup)


def encode_subscription(topics: List[str]) -> bytes:
    w = Writer()
    w.i16(0)  # ConsumerProtocolSubscription version
    w.array(sorted(topics), lambda ww, t: ww.string(t))
    w.i32(-1)  # user_data
    return w.done()


def decode_subscription(blob: bytes) -> List[str]:
    r = Reader(blob)
    r.i16()  # version — every version starts (version, [topics], ...)
    return list(r.array(r.string) or [])


def encode_assignment(tps: List[Tuple[str, int]]) -> bytes:
    by_topic: Dict[str, List[int]] = {}
    for t, p in tps:
        by_topic.setdefault(t, []).append(p)
    w = Writer()
    w.i16(0)  # ConsumerProtocolAssignment version
    w.i32(len(by_topic))
    for t in sorted(by_topic):
        w.string(t)
        w.array(sorted(by_topic[t]), lambda ww, p: ww.i32(p))
    w.i32(-1)  # user_data
    return w.done()


def decode_assignment(blob: bytes) -> List[Tuple[str, int]]:
    r = Reader(blob)
    r.i16()
    out: List[Tuple[str, int]] = []
    for _ in range(r.i32()):
        t = r.string()
        out.extend((t, p) for p in (r.array(r.i32) or []))
    return out


# ---------------------------------------------------------------------------
# API keys, version matrix, error codes

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14
API_VERSIONS = 18
API_CREATE_TOPICS = 19
API_DELETE_TOPICS = 20

#: api_key -> (min_version, max_version, first_flexible_version | None)
SUPPORTED_APIS: Dict[int, Tuple[int, int, Optional[int]]] = {
    API_PRODUCE: (3, 7, None),
    API_FETCH: (4, 10, None),
    API_LIST_OFFSETS: (1, 5, None),
    API_METADATA: (0, 5, None),
    API_OFFSET_COMMIT: (2, 5, None),
    API_OFFSET_FETCH: (1, 5, None),
    API_FIND_COORDINATOR: (0, 3, 3),
    API_JOIN_GROUP: (0, 5, None),
    API_HEARTBEAT: (0, 4, 4),
    API_LEAVE_GROUP: (0, 3, None),
    API_SYNC_GROUP: (0, 3, None),
    API_VERSIONS: (0, 3, 3),
    API_CREATE_TOPICS: (0, 4, None),
    API_DELETE_TOPICS: (0, 3, None),
}

#: api_key -> wire name, for telemetry labels (obs/metrics.py)
API_NAMES: Dict[int, str] = {
    API_PRODUCE: "Produce",
    API_FETCH: "Fetch",
    API_LIST_OFFSETS: "ListOffsets",
    API_METADATA: "Metadata",
    API_OFFSET_COMMIT: "OffsetCommit",
    API_OFFSET_FETCH: "OffsetFetch",
    API_FIND_COORDINATOR: "FindCoordinator",
    API_JOIN_GROUP: "JoinGroup",
    API_HEARTBEAT: "Heartbeat",
    API_LEAVE_GROUP: "LeaveGroup",
    API_SYNC_GROUP: "SyncGroup",
    API_VERSIONS: "ApiVersions",
    API_CREATE_TOPICS: "CreateTopics",
    API_DELETE_TOPICS: "DeleteTopics",
}

ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_COORDINATOR_NOT_AVAILABLE = 15
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27
ERR_UNSUPPORTED_VERSION = 35
ERR_TOPIC_ALREADY_EXISTS = 36
ERR_INVALID_PARTITIONS = 37
ERR_INVALID_REQUEST = 42
ERR_GROUP_ID_NOT_FOUND = 69

ERROR_NAMES = {
    ERR_NONE: "NONE",
    ERR_OFFSET_OUT_OF_RANGE: "OFFSET_OUT_OF_RANGE",
    ERR_UNKNOWN_TOPIC_OR_PARTITION: "UNKNOWN_TOPIC_OR_PARTITION",
    ERR_COORDINATOR_NOT_AVAILABLE: "COORDINATOR_NOT_AVAILABLE",
    ERR_ILLEGAL_GENERATION: "ILLEGAL_GENERATION",
    ERR_UNKNOWN_MEMBER_ID: "UNKNOWN_MEMBER_ID",
    ERR_REBALANCE_IN_PROGRESS: "REBALANCE_IN_PROGRESS",
    ERR_UNSUPPORTED_VERSION: "UNSUPPORTED_VERSION",
    ERR_TOPIC_ALREADY_EXISTS: "TOPIC_ALREADY_EXISTS",
    ERR_INVALID_PARTITIONS: "INVALID_PARTITIONS",
    ERR_INVALID_REQUEST: "INVALID_REQUEST",
    ERR_GROUP_ID_NOT_FOUND: "GROUP_ID_NOT_FOUND",
}


def is_flexible(api: int, version: int) -> bool:
    meta = SUPPORTED_APIS.get(api)
    return meta is not None and meta[2] is not None and version >= meta[2]


# ---------------------------------------------------------------------------
# the protocol engine


class KafkaWire:
    """Parse one Kafka request frame, apply it to the broker, encode the
    response frame. Pure: the only ambient input is ``clock_ms``, read
    exactly once per frame (which is what makes the recorded-transcript
    replay in the load gate a byte-identity check)."""

    def __init__(
        self,
        broker: Optional[Broker] = None,
        clock_ms: Callable[[], int] = lambda: 0,
        advertised: Tuple[str, int] = ("127.0.0.1", 9092),
        telemetry=None,
    ):
        self.broker = broker or Broker()
        self.clock_ms = clock_ms
        self.advertised = advertised
        self.telemetry = telemetry  # obs.Telemetry or None (frames/s,
        # per-API latency — wall-clock side, never in a response byte)
        self._now = 0  # per-frame clock sample
        #: (group, member) -> (protocol_name, metadata bytes) for the
        #: JoinGroup member-metadata echo the classic protocol shape needs
        self._member_meta: Dict[Tuple[str, str], Tuple[str, bytes]] = {}
        #: optional transcript sink: (request_frame, clock_ms, response|None)
        self.recorder: Optional[List[Tuple[bytes, int, Optional[bytes]]]] = None

    # -- entry point --------------------------------------------------------

    def handle_frame(self, frame: bytes) -> Optional[bytes]:
        """Request frame body (no length prefix) -> response frame body,
        or ``None`` when the protocol says not to respond (acks=0
        Produce). Raises :class:`WireError` on frames this server cannot
        serve in kind — the transport drops the connection, as a real
        broker does."""
        if self.telemetry is None:
            return self._handle_frame(frame)
        import time as _walltime

        t0 = _walltime.perf_counter()
        api = (
            int.from_bytes(frame[:2], "big", signed=True)
            if len(frame) >= 2
            else -1
        )
        name = API_NAMES.get(api, str(api))
        try:
            return self._handle_frame(frame)
        finally:
            self.telemetry.count(
                "kafka_frames_total", help="request frames served",
                api=name,
            )
            self.telemetry.observe(
                "kafka_api_seconds", _walltime.perf_counter() - t0,
                help="per-API handling latency", api=name,
            )

    def _handle_frame(self, frame: bytes) -> Optional[bytes]:
        r = Reader(frame)
        api = r.i16()
        version = r.i16()
        corr = r.i32()
        self._now = int(self.clock_ms())
        meta = SUPPORTED_APIS.get(api)
        if meta is None:
            raise WireError(f"unsupported api key {api}")
        lo, hi, _flex = meta
        if not lo <= version <= hi:
            if api == API_VERSIONS:
                # KIP-511: answer an unknown ApiVersions version with the
                # v0 body + UNSUPPORTED_VERSION so the client can downshift
                rsp = Writer().i32(corr)
                self._api_versions_body(rsp, 0, ERR_UNSUPPORTED_VERSION)
                out = rsp.done()
                self._record(frame, out)
                return out
            raise WireError(
                f"api {api} v{version} outside the served range {lo}-{hi}"
            )
        flexible = is_flexible(api, version)
        r.nullable_string()  # client_id (request header v1+: every served API)
        if flexible:
            r.tagged_fields()  # header v2 adds tagged fields

        w = Writer()
        w.i32(corr)
        # response header v1 carries tagged fields — except ApiVersions,
        # whose response header is pinned at v0 forever (KIP-511)
        if flexible and api != API_VERSIONS:
            w.tagged_fields()
        body = self._HANDLERS[api](self, r, version, w)
        if body is None:
            self._record(frame, None)
            return None
        out = w.done()
        self._record(frame, out)
        return out

    def _record(self, frame: bytes, rsp: Optional[bytes]) -> None:
        if self.recorder is not None:
            self.recorder.append((bytes(frame), self._now, rsp))

    # -- ApiVersions --------------------------------------------------------

    def _api_versions_body(self, w: Writer, version: int, error: int) -> None:
        flex = version >= 3
        keys = sorted(SUPPORTED_APIS)
        w.i16(error)

        def one(ww: Writer, k: int) -> None:
            lo, hi, _f = SUPPORTED_APIS[k]
            ww.i16(k).i16(lo).i16(hi)
            if flex:
                ww.tagged_fields()

        warr(w, keys, one, flex)
        if version >= 1:
            w.i32(0)  # throttle_time_ms
        if flex:
            w.tagged_fields()

    def _h_api_versions(self, r: Reader, version: int, w: Writer):
        if version >= 3:
            r.compact_string()  # client_software_name
            r.compact_string()  # client_software_version
            r.tagged_fields()
        self._api_versions_body(w, version, ERR_NONE)
        return w

    # -- Metadata -----------------------------------------------------------

    def _h_metadata(self, r: Reader, version: int, w: Writer):
        topics = r.array(r.string)
        if version >= 4:
            r.boolean()  # allow_auto_topic_creation — no auto-create here
        if version == 0 and topics == []:
            topics = None  # v0: empty array = all topics
        all_topics = self.broker.metadata()
        if topics is None:
            wanted = sorted(all_topics)
        else:
            wanted = list(topics)

        if version >= 3:
            w.i32(0)  # throttle
        host, port = self.advertised

        def one_broker(ww: Writer, _b) -> None:
            ww.i32(0).string(host).i32(int(port))
            if version >= 1:
                ww.nullable_string(None)  # rack

        w.array([0], one_broker)
        if version >= 2:
            w.nullable_string("madsim-kafka")  # cluster_id
        if version >= 1:
            w.i32(0)  # controller_id

        def one_topic(ww: Writer, name: str) -> None:
            n = all_topics.get(name)
            ww.i16(ERR_NONE if n is not None else ERR_UNKNOWN_TOPIC_OR_PARTITION)
            ww.string(name)
            if version >= 1:
                ww.boolean(False)  # is_internal

            def one_part(www: Writer, p: int) -> None:
                www.i16(ERR_NONE).i32(p).i32(0)  # error, index, leader
                www.array([0], lambda w4, rep: w4.i32(rep))  # replicas
                www.array([0], lambda w4, rep: w4.i32(rep))  # isr
                if version >= 5:
                    www.array([], lambda w4, rep: w4.i32(rep))  # offline

            ww.array(list(range(n or 0)), one_part)

        w.array(wanted, one_topic)
        return w

    # -- Produce ------------------------------------------------------------

    def _h_produce(self, r: Reader, version: int, w: Writer):
        r.nullable_string()  # transactional_id (v3+ — served span starts at 3)
        acks = r.i16()
        r.i32()  # timeout_ms

        def one_partition() -> Tuple[int, Optional[bytes]]:
            return r.i32(), r.nullable_bytes()

        def one_topic() -> Tuple[str, list]:
            return r.string(), r.array(one_partition) or []

        topics = r.array(one_topic) or []

        results: List[Tuple[str, List[Tuple[int, int, int, int]]]] = []
        for name, parts in topics:
            out_parts = []
            for index, records in parts:
                err, base_off, log_start = ERR_NONE, -1, 0
                try:
                    rows = decode_record_batches(records or b"")
                    first = None
                    for _off, ts, key, val in rows:
                        _p, off = self.broker.produce(
                            name, index, key, val,
                            ts if ts >= 0 else self._now,
                        )
                        if first is None:
                            first = off
                    base_off = first if first is not None else -1
                    log_start = self.broker.watermarks(name, index).low
                except KafkaBrokerError:
                    err = ERR_UNKNOWN_TOPIC_OR_PARTITION
                out_parts.append((index, err, base_off, log_start))
            results.append((name, out_parts))

        if acks == 0:
            return None  # the protocol: fire-and-forget gets no response

        def w_part(ww: Writer, part) -> None:
            index, err, base_off, log_start = part
            ww.i32(index).i16(err).i64(base_off)
            if version >= 2:
                ww.i64(-1)  # log_append_time (CREATE_TIME batches)
            if version >= 5:
                ww.i64(log_start)

        def w_topic(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name)
            ww.array(parts, w_part)

        w.array(results, w_topic)
        w.i32(0)  # throttle (v1+; served span starts at 3)
        return w

    # -- Fetch --------------------------------------------------------------

    def _h_fetch(self, r: Reader, version: int, w: Writer):
        r.i32()  # replica_id
        r.i32()  # max_wait_ms — answered immediately (scope note)
        r.i32()  # min_bytes
        max_bytes = r.i32()  # v3+ (served span starts at 4)
        if version >= 4:
            r.i8()  # isolation_level
        if version >= 7:
            r.i32()  # session_id
            r.i32()  # session_epoch

        def one_partition() -> Tuple[int, int, int]:
            index = r.i32()
            if version >= 9:
                r.i32()  # current_leader_epoch
            fetch_offset = r.i64()
            if version >= 5:
                r.i64()  # log_start_offset (follower fetches)
            return index, fetch_offset, r.i32()  # partition_max_bytes

        def one_topic() -> Tuple[str, list]:
            return r.string(), r.array(one_partition) or []

        topics = r.array(one_topic) or []
        if version >= 7:
            r.array(lambda: (r.string(), r.array(r.i32)))  # forgotten topics

        w.i32(0)  # throttle (v1+)
        if version >= 7:
            w.i16(ERR_NONE)  # top-level error
            w.i32(0)  # session_id

        def w_topic(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name)

            def w_part(www: Writer, part) -> None:
                index, offset, part_max = part
                err, high, low, batch = ERR_NONE, 0, 0, b""
                try:
                    wm = self.broker.watermarks(name, index)
                    high, low = wm.high, wm.low
                    msgs = self.broker.fetch(
                        name, index, offset, max_bytes, part_max
                    )
                    if msgs:
                        batch = encode_record_batch(
                            msgs[0].offset,
                            [(m.timestamp_ms, m.key, m.payload) for m in msgs],
                        )
                except KafkaBrokerError:
                    err = ERR_UNKNOWN_TOPIC_OR_PARTITION
                www.i32(index).i16(err).i64(high)
                www.i64(high)  # last_stable_offset (v4+; no transactions)
                if version >= 5:
                    www.i64(low)  # log_start_offset
                www.array([], lambda w4, _a: None)  # aborted_transactions
                if version >= 11:
                    www.i32(-1)  # preferred_read_replica
                www.nullable_bytes(batch)

            ww.array(parts, w_part)

        w.array(topics, w_topic)
        return w

    # -- ListOffsets ---------------------------------------------------------

    def _h_list_offsets(self, r: Reader, version: int, w: Writer):
        r.i32()  # replica_id
        if version >= 2:
            r.i8()  # isolation_level

        def one_partition() -> Tuple[int, int]:
            index = r.i32()
            if version >= 4:
                r.i32()  # current_leader_epoch
            return index, r.i64()

        def one_topic() -> Tuple[str, list]:
            return r.string(), r.array(one_partition) or []

        topics = r.array(one_topic) or []
        if version >= 2:
            w.i32(0)  # throttle

        def w_topic(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name)

            def w_part(www: Writer, part) -> None:
                index, ts = part
                err, out_ts, out_off = ERR_NONE, -1, -1
                try:
                    wm = self.broker.watermarks(name, index)
                    if ts == -1:  # latest
                        out_off = wm.high
                    elif ts == -2:  # earliest
                        out_off = wm.low
                    else:
                        (_t, _p, found), = self.broker.offsets_for_times(
                            [(name, index, ts)]
                        )
                        if found is not None:
                            out_off = found
                            part_obj = self.broker._partition(name, index)
                            out_ts = part_obj.log[
                                found - part_obj.base_offset
                            ].timestamp_ms
                except KafkaBrokerError:
                    err = ERR_UNKNOWN_TOPIC_OR_PARTITION
                www.i32(index).i16(err).i64(out_ts).i64(out_off)
                if version >= 4:
                    www.i32(-1)  # leader_epoch

            ww.array(parts, w_part)

        w.array(topics, w_topic)
        return w

    # -- FindCoordinator ------------------------------------------------------

    def _h_find_coordinator(self, r: Reader, version: int, w: Writer):
        flex = is_flexible(API_FIND_COORDINATOR, version)
        rstr(r, flex)  # key (the group id)
        if version >= 1:
            r.i8()  # key_type — groups and txn ids both land here
        if flex:
            r.tagged_fields()
        host, port = self.advertised
        if version >= 1:
            w.i32(0)  # throttle
        w.i16(ERR_NONE)
        if version >= 1:
            wnstr(w, None, flex)  # error_message
        w.i32(0)  # node_id
        wstr(w, host, flex)
        w.i32(int(port))
        if flex:
            w.tagged_fields()
        return w

    # -- group membership -----------------------------------------------------

    def _h_join_group(self, r: Reader, version: int, w: Writer):
        group = r.string()
        r.i32()  # session_timeout_ms
        if version >= 1:
            r.i32()  # rebalance_timeout_ms
        member_id = r.string()
        if version >= 5:
            r.nullable_string()  # group_instance_id
        protocol_type = r.string()
        protocols = r.array(lambda: (r.string(), r.bytes32())) or []

        err, gen, proto_name, leader, out_member = ERR_NONE, -1, "", "", member_id
        if protocol_type not in ("", "consumer") or not protocols:
            err = ERR_INVALID_REQUEST
        else:
            proto_name, meta_blob = protocols[0]
            try:
                topics = decode_subscription(meta_blob)
                out_member, gen, _assigned = self.broker.join_group(
                    group, member_id or None, topics
                )
                self._member_meta[(group, out_member)] = (proto_name, meta_blob)
                g = self.broker.groups[group]
                leader = next(iter(g.members))
            except KafkaBrokerError:
                err = ERR_UNKNOWN_TOPIC_OR_PARTITION
            except WireError:
                err = ERR_INVALID_REQUEST

        if version >= 2:
            w.i32(0)  # throttle
        w.i16(err).i32(gen).string(proto_name).string(leader).string(out_member)

        members: List[Tuple[str, bytes]] = []
        if err == ERR_NONE and out_member == leader:
            g = self.broker.groups[group]
            members = [
                (m, self._member_meta.get((group, m), ("", b""))[1])
                for m in g.members
            ]

        def w_member(ww: Writer, item) -> None:
            mid, blob = item
            ww.string(mid)
            if version >= 5:
                ww.nullable_string(None)  # group_instance_id
            ww.bytes32(blob)

        w.array(members, w_member)
        return w

    def _group_errcheck(self, group: str, member: str, generation: int) -> int:
        """The shared coordinator fence: unknown group/member, then a
        stale generation (the rejoin signal)."""
        g = self.broker.groups.get(group)
        if g is None:
            return ERR_GROUP_ID_NOT_FOUND
        if member not in g.members:
            return ERR_UNKNOWN_MEMBER_ID
        if generation != g.generation:
            return ERR_REBALANCE_IN_PROGRESS
        return ERR_NONE

    def _h_sync_group(self, r: Reader, version: int, w: Writer):
        group = r.string()
        generation = r.i32()
        member = r.string()
        if version >= 3:
            r.nullable_string()  # group_instance_id
        # leader-computed assignments: parsed, then deliberately ignored —
        # the broker's own deterministic range assignor answers (docstring)
        r.array(lambda: (r.string(), r.bytes32()))

        err = self._group_errcheck(group, member, generation)
        blob = b""
        if err == ERR_NONE:
            _gen, assigned = self.broker.group_state(group, member)
            blob = encode_assignment(assigned)
        if version >= 1:
            w.i32(0)  # throttle
        w.i16(err).bytes32(blob)
        return w

    def _h_heartbeat(self, r: Reader, version: int, w: Writer):
        flex = is_flexible(API_HEARTBEAT, version)
        group = rstr(r, flex)
        generation = r.i32()
        member = rstr(r, flex)
        if version >= 3:
            rnstr(r, flex)  # group_instance_id
        if flex:
            r.tagged_fields()
        err = self._group_errcheck(group, member, generation)
        if version >= 1:
            w.i32(0)  # throttle
        w.i16(err)
        if flex:
            w.tagged_fields()
        return w

    def _h_leave_group(self, r: Reader, version: int, w: Writer):
        group = r.string()
        if version >= 3:
            members = [
                m for m, _inst in
                (r.array(lambda: (r.string(), r.nullable_string())) or [])
            ]
        else:
            members = [r.string()]

        results: List[Tuple[str, int]] = []
        for m in members:
            try:
                self.broker.leave_group(group, m)
                self._member_meta.pop((group, m), None)
                results.append((m, ERR_NONE))
            except KafkaBrokerError:
                results.append((m, ERR_GROUP_ID_NOT_FOUND))

        if version >= 1:
            w.i32(0)  # throttle
        w.i16(ERR_NONE if all(e == ERR_NONE for _m, e in results)
              else results[0][1])
        if version >= 3:
            def w_member(ww: Writer, item) -> None:
                mid, err = item
                ww.string(mid).nullable_string(None).i16(err)

            w.array(results, w_member)
        return w

    # -- offsets ---------------------------------------------------------------

    def _h_offset_commit(self, r: Reader, version: int, w: Writer):
        group = r.string()
        generation = r.i32()
        r.string()  # member_id (the generation fence is the commit guard)
        if 2 <= version <= 4:
            r.i64()  # retention_time_ms

        def one_partition() -> Tuple[int, int]:
            index = r.i32()
            offset = r.i64()
            r.nullable_string()  # metadata
            return index, offset

        def one_topic() -> Tuple[str, list]:
            return r.string(), r.array(one_partition) or []

        topics = r.array(one_topic) or []

        # generation -1 = a groupless/simple committer: skip the zombie
        # fence, exactly like the legacy tuple protocol's 3-tuple commit
        fence: Optional[int] = None if generation < 0 else generation
        results: List[Tuple[str, List[Tuple[int, int]]]] = []
        for name, parts in topics:
            out_parts = []
            for index, offset in parts:
                try:
                    self.broker.commit_offsets(
                        group, [(name, index, offset)], fence
                    )
                    out_parts.append((index, ERR_NONE))
                except KafkaBrokerError as e:
                    msg = str(e)
                    if "ILLEGAL_GENERATION" in msg:
                        code = ERR_ILLEGAL_GENERATION
                    elif "unknown group" in msg:
                        code = ERR_GROUP_ID_NOT_FOUND
                    else:
                        code = ERR_UNKNOWN_TOPIC_OR_PARTITION
                    out_parts.append((index, code))
            results.append((name, out_parts))

        if version >= 3:
            w.i32(0)  # throttle

        def w_topic(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name)
            ww.array(parts, lambda www, p: www.i32(p[0]).i16(p[1]))

        w.array(results, w_topic)
        return w

    def _h_offset_fetch(self, r: Reader, version: int, w: Writer):
        group = r.string()
        topics = r.array(lambda: (r.string(), r.array(r.i32) or []))

        g = self.broker.groups.get(group)
        if topics is None:
            # null topics (v2+): every partition the group has committed
            by_topic: Dict[str, List[int]] = {}
            if g is not None:
                for (t, p) in sorted(g.committed):
                    by_topic.setdefault(t, []).append(p)
            topics = sorted(by_topic.items())

        if version >= 3:
            w.i32(0)  # throttle

        def w_topic(ww: Writer, item) -> None:
            name, parts = item
            ww.string(name)

            def w_part(www: Writer, index: int) -> None:
                off = -1
                if g is not None:
                    off = g.committed.get((name, index), -1)
                    if off is None:
                        off = -1
                www.i32(index).i64(off)
                if version >= 5:
                    www.i32(-1)  # leader_epoch
                www.nullable_string(None)  # metadata
                www.i16(ERR_NONE)

            ww.array(parts, w_part)

        w.array(topics, w_topic)
        if version >= 2:
            w.i16(ERR_NONE)  # top-level error
        return w

    # -- topic admin -----------------------------------------------------------

    def _h_create_topics(self, r: Reader, version: int, w: Writer):
        def one_topic():
            name = r.string()
            num_partitions = r.i32()
            r.i16()  # replication_factor
            r.array(lambda: (r.i32(), r.array(r.i32)))  # manual assignments
            r.array(lambda: (r.string(), r.nullable_string()))  # configs
            return name, num_partitions

        topics = r.array(one_topic) or []
        r.i32()  # timeout_ms
        validate_only = r.boolean() if version >= 1 else False

        results: List[Tuple[str, int, Optional[str]]] = []
        for name, num_partitions in topics:
            if num_partitions < 0:
                num_partitions = 1  # -1 = broker default
            try:
                if validate_only:
                    if name in self.broker.topics:
                        raise KafkaBrokerError(f"topic already exists: {name!r}")
                    if num_partitions <= 0:
                        raise KafkaBrokerError("num_partitions must be positive")
                else:
                    self.broker.create_topic(name, num_partitions)
                results.append((name, ERR_NONE, None))
            except KafkaBrokerError as e:
                code = (ERR_TOPIC_ALREADY_EXISTS if "already exists" in str(e)
                        else ERR_INVALID_PARTITIONS)
                results.append((name, code, str(e)))

        if version >= 2:
            w.i32(0)  # throttle

        def w_topic(ww: Writer, item) -> None:
            name, err, msg = item
            ww.string(name).i16(err)
            if version >= 1:
                ww.nullable_string(msg)

        w.array(results, w_topic)
        return w

    def _h_delete_topics(self, r: Reader, version: int, w: Writer):
        names = r.array(r.string) or []
        r.i32()  # timeout_ms
        results = []
        for name in names:
            try:
                self.broker.delete_topic(name)
                results.append((name, ERR_NONE))
            except KafkaBrokerError:
                results.append((name, ERR_UNKNOWN_TOPIC_OR_PARTITION))
        if version >= 1:
            w.i32(0)  # throttle
        w.array(results, lambda ww, it: ww.string(it[0]).i16(it[1]))
        return w

    _HANDLERS = {
        API_PRODUCE: _h_produce,
        API_FETCH: _h_fetch,
        API_LIST_OFFSETS: _h_list_offsets,
        API_METADATA: _h_metadata,
        API_OFFSET_COMMIT: _h_offset_commit,
        API_OFFSET_FETCH: _h_offset_fetch,
        API_FIND_COORDINATOR: _h_find_coordinator,
        API_JOIN_GROUP: _h_join_group,
        API_HEARTBEAT: _h_heartbeat,
        API_LEAVE_GROUP: _h_leave_group,
        API_SYNC_GROUP: _h_sync_group,
        API_VERSIONS: _h_api_versions,
        API_CREATE_TOPICS: _h_create_topics,
        API_DELETE_TOPICS: _h_delete_topics,
    }


# ---------------------------------------------------------------------------
# framing


class FrameBuffer:
    """Reassemble 4-byte length-prefixed frames from arbitrary byte
    chunks — one parser for both tiers (sim pipes may deliver a frame
    whole; TCP may split it anywhere)."""

    MAX_FRAME = 64 * 1024 * 1024

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, chunk: bytes) -> List[bytes]:
        self._buf += chunk
        out: List[bytes] = []
        while len(self._buf) >= 4:
            (n,) = _I32.unpack(self._buf[:4])
            if not 0 <= n <= self.MAX_FRAME:
                raise WireError(f"insane frame length {n}")
            if len(self._buf) < 4 + n:
                break
            out.append(bytes(self._buf[4:4 + n]))
            del self._buf[:4 + n]
        return out


def frame(body: bytes) -> bytes:
    """Length-prefix one wire frame (Kafka's framing is exactly the
    repo-wide 4-byte big-endian convention of ``real/stream.py``)."""
    return _I32.pack(len(body)) + body


# ---------------------------------------------------------------------------
# sim-tier serving: the Endpoint / connect1 pipe plumbing


class SimWireServer:
    """Serve the genuine Kafka wire inside the simulator: ``accept1``
    connections whose pipes carry raw byte chunks (framed by
    :func:`frame`), one conn task per client, virtual-clock timestamps.
    The sim twin of :class:`WireServer`, mirroring how ``kafka/server.py``
    and ``real/kafka.py`` split the legacy dispatcher."""

    def __init__(self, broker: Optional[Broker] = None, telemetry=None):
        self.broker = broker or Broker()
        self.telemetry = telemetry
        self.wire: Optional[KafkaWire] = None
        self.bound_addr: Optional[Tuple[str, int]] = None

    @staticmethod
    def _now_ms() -> int:
        from ..context import current_handle

        return current_handle().time.now_time_ns() // 1_000_000

    async def serve(self, addr: "str | tuple") -> None:
        from .. import task as mstask
        from ..net.endpoint import Endpoint

        ep = await Endpoint.bind(addr)
        self.bound_addr = ep.local_addr()
        self.wire = KafkaWire(
            self.broker, self._now_ms, self.bound_addr,
            telemetry=self.telemetry,
        )
        while True:
            tx, rx, _src = await ep.accept1()
            mstask.spawn(self._serve_conn(tx, rx), name="kafka-wire-conn")

    async def _serve_conn(self, tx: Any, rx: Any) -> None:
        buf = FrameBuffer()
        if self.telemetry is not None:
            self.telemetry.count(
                "kafka_connections_total", help="accepted connections"
            )
        try:
            while True:
                chunk = await rx.recv()
                if chunk is None:
                    return
                for req in buf.feed(chunk):
                    rsp = self.wire.handle_frame(req)
                    if rsp is not None:
                        await tx.send(frame(rsp))
        except (WireError, KeyError, ValueError, struct.error):
            rx.close()  # protocol violation: hard-drop, like a real broker
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            tx.close()


# ---------------------------------------------------------------------------
# real-tier serving: raw TCP via asyncio streams


class WireServer:
    """Serve the genuine Kafka wire on a real TCP port (wall-clock
    timestamps) — what ``real.kafka.SimBroker.serve`` now runs by
    default, and what a stock client connects to.

    The accept loop, framing, backpressure, and lifecycle metrics live
    in the shared serving core (``madsim_tpu/serve/``); this class is
    the thin Kafka adapter over it: ``KafkaWire.handle_frame`` stays a
    pure function of (request bytes, clock), so the live-vs-replay
    byte-identity gate holds through the core unchanged.
    ``clock_ms=`` injects a deterministic clock (the determinism leg);
    ``shards=`` spreads accepts over N SO_REUSEPORT loops.
    """

    def __init__(self, broker: Optional[Broker] = None, telemetry=None,
                 clock_ms: Optional[Callable[[], int]] = None,
                 shards: int = 1,
                 advertised: Optional[Tuple[str, int]] = None):
        self.broker = broker or Broker()
        self.telemetry = telemetry
        self.wire: Optional[KafkaWire] = None
        self.bound_addr: Optional[Tuple[str, int]] = None
        self._clock_ms = clock_ms
        self._shards = shards
        self._core = None
        # determinism legs pin this: Metadata/FindCoordinator responses
        # embed the advertised address, and an ephemeral bound port
        # would leak into the transcript hash
        self._advertised = advertised

    @staticmethod
    def _now_ms() -> int:
        import time as _walltime

        return _walltime.time_ns() // 1_000_000

    def _count_conn(self, _conn) -> None:
        if self.telemetry is not None:
            self.telemetry.count(
                "kafka_connections_total", help="accepted connections"
            )

    async def start(self, addr: "str | tuple") -> None:
        from ..serve import AsyncWireServer, PureFrameAdapter

        adapter = PureFrameAdapter(
            self._handle, name="kafka",
            drop_errors=(WireError, KeyError, ValueError, struct.error),
            connect_hook=self._count_conn,
        )
        self._core = AsyncWireServer(
            adapter, telemetry=self.telemetry, shards=self._shards
        )
        self.bound_addr = await self._core.start(addr)
        self.wire = KafkaWire(
            self.broker, self._clock_ms or self._now_ms,
            self._advertised or self.bound_addr,
            telemetry=self.telemetry,
        )

    def _handle(self, req: bytes) -> Optional[bytes]:
        return self.wire.handle_frame(req)

    async def serve(self, addr: "str | tuple") -> None:
        await self.start(addr)
        try:
            await self._core._stopped.wait()
        finally:
            self._core._teardown()

    def close(self) -> None:
        if self._core is not None:
            self._core.close()

    async def aclose(self, drain_timeout: float = 5.0) -> None:
        """Graceful drain — in-flight frames answered, queues flushed."""
        if self._core is not None:
            await self._core.aclose(drain_timeout)


class LegacyWireServer:
    """The pre-core thread-of-control per connection server (one
    asyncio streams task per conn, unbounded write buffering). Kept as
    the A/B baseline for the determinism and parity gates; deprecated
    for serving — see docs/wire.md."""

    def __init__(self, broker: Optional[Broker] = None, telemetry=None,
                 clock_ms: Optional[Callable[[], int]] = None,
                 advertised: Optional[Tuple[str, int]] = None):
        self.broker = broker or Broker()
        self.telemetry = telemetry
        self.wire: Optional[KafkaWire] = None
        self.bound_addr: Optional[Tuple[str, int]] = None
        self._clock_ms = clock_ms
        self._server = None
        self._advertised = advertised

    @staticmethod
    def _now_ms() -> int:
        import time as _walltime

        return _walltime.time_ns() // 1_000_000

    async def start(self, addr: "str | tuple") -> None:
        import asyncio

        from ..real.stream import parse_addr

        host, port = parse_addr(addr)
        self._server = await asyncio.start_server(self._conn, host, port)
        self.bound_addr = self._server.sockets[0].getsockname()[:2]
        self.wire = KafkaWire(
            self.broker, self._clock_ms or self._now_ms,
            self._advertised or self.bound_addr,
            telemetry=self.telemetry,
        )

    async def serve(self, addr: "str | tuple") -> None:
        await self.start(addr)
        async with self._server:
            await self._server.serve_forever()

    def close(self) -> None:
        if self._server is not None:
            self._server.close()

    async def _conn(self, reader, writer) -> None:
        from ..real.stream import read_frame_raw, write_frame_raw

        if self.telemetry is not None:
            self.telemetry.count(
                "kafka_connections_total", help="accepted connections"
            )
        try:
            while True:
                req = await read_frame_raw(reader)
                if req is None:
                    return
                rsp = self.wire.handle_frame(req)
                if rsp is not None:
                    await write_frame_raw(writer, rsp)
        except (WireError, KeyError, ValueError, struct.error,
                ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass
