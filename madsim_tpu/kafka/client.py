"""Kafka clients (madsim-rdkafka/src/sim/{producer,consumer,admin}.rs).

API mirrors rust-rdkafka's shape: a string-map ``ClientConfig``
(consumer.rs:70-103), ``BaseProducer`` buffering until ``flush``,
``FutureProducer`` with ``linger.ms`` batching delay, ``BaseConsumer`` with
assign/seek/poll fetch loops honoring the fetch byte budgets, a
``StreamConsumer`` that awaits messages, and an ``AdminClient``.
Consumer groups (group.id / rebalance / committed offsets / auto-commit)
ARE modeled — beyond the reference, whose sim leaves assignment manual
(see BaseConsumer's docstring and broker.py ``Group``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Type, TypeVar

from .. import time as mstime
from ..net.endpoint import connect1_ephemeral, exchange1
from .broker import OwnedMessage, Watermarks

T = TypeVar("T")


class KafkaError(Exception):
    pass


class ClientConfig:
    """String-map config (rdkafka ``ClientConfig``)."""

    def __init__(self) -> None:
        self._map: Dict[str, str] = {}

    def set(self, key: str, value: "str | int | float") -> "ClientConfig":
        self._map[key] = str(value)
        return self

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._map.get(key, default)

    def get_int(self, key: str, default: int) -> int:
        v = self._map.get(key)
        return int(v) if v is not None else default

    def get_float(self, key: str, default: float) -> float:
        v = self._map.get(key)
        return float(v) if v is not None else default

    async def create(self, cls: Type[T]) -> T:
        """rdkafka ``config.create::<T>()``."""
        return cls(self)  # type: ignore[call-arg]


class _BrokerConn:
    """One request/response exchange per operation (sim_broker protocol)."""

    # transport hook — real/kafka.py dials framed TCP instead
    _connect = staticmethod(connect1_ephemeral)

    def __init__(self, config: ClientConfig):
        servers = config.get("bootstrap.servers")
        if not servers:
            raise KafkaError("bootstrap.servers is required")
        self._addr = servers.split(",")[0]

    async def call(self, req: tuple) -> Any:
        try:
            tx, rx = await self._connect(self._addr)
            rsp = await exchange1(tx, rx, req)
        except (ConnectionError, OSError) as e:
            raise KafkaError(f"broker transport error: {e}") from None
        if rsp is None:
            raise KafkaError("broker connection closed")
        kind, payload = rsp
        if kind == "err":
            raise KafkaError(payload)
        return payload


# -- records ----------------------------------------------------------------


@dataclass
class BaseRecord:
    topic: str
    partition: Optional[int] = None
    key: Optional[bytes] = None
    payload: Optional[bytes] = None

    @staticmethod
    def to(topic: str) -> "BaseRecord":
        return BaseRecord(topic)

    def with_partition(self, p: int) -> "BaseRecord":
        self.partition = p
        return self

    def with_key(self, key: "bytes | str") -> "BaseRecord":
        self.key = key.encode() if isinstance(key, str) else key
        return self

    def with_payload(self, payload: "bytes | str") -> "BaseRecord":
        self.payload = payload.encode() if isinstance(payload, str) else payload
        return self


FutureRecord = BaseRecord  # same shape; only the send path differs


# -- producers (sim/producer.rs) --------------------------------------------


class BaseProducer:
    """Buffers records locally until ``flush`` (sim producer semantics)."""

    _conn_cls = _BrokerConn  # real/kafka.py overrides

    def __init__(self, config: ClientConfig):
        self._conn = self._conn_cls(config)
        self._buffer: List[BaseRecord] = []

    def send(self, record: BaseRecord) -> None:
        self._buffer.append(record)

    def poll(self, _timeout_s: float = 0.0) -> None:
        """librdkafka poll pump — a no-op here (no delivery callbacks)."""

    async def flush(self, _timeout_s: float = 30.0) -> None:
        buffered, self._buffer = self._buffer, []
        for rec in buffered:
            await self._conn.call(
                ("produce", rec.topic, rec.partition, rec.key, rec.payload)
            )

    def in_flight_count(self) -> int:
        return len(self._buffer)


class FutureProducer:
    """Per-record async send returning (partition, offset); honors a
    ``linger.ms`` batching delay on virtual time."""

    _conn_cls = _BrokerConn  # real/kafka.py overrides
    _sleep = staticmethod(mstime.sleep)

    def __init__(self, config: ClientConfig):
        self._conn = self._conn_cls(config)
        self._linger_s = config.get_float("linger.ms", 0.0) / 1000.0

    async def send(
        self, record: BaseRecord, _queue_timeout_s: float = 0.0
    ) -> Tuple[int, int]:
        if self._linger_s > 0:
            await self._sleep(self._linger_s)
        return tuple(
            await self._conn.call(
                ("produce", record.topic, record.partition, record.key, record.payload)
            )
        )


# -- consumers (sim/consumer.rs) --------------------------------------------


@dataclass
class _Assignment:
    topic: str
    partition: int
    position: int  # next offset to FETCH (fetch batches run ahead)
    consumed: int = 0  # next offset after the last message RETURNED by poll
    # (commits use `consumed`, not `position`: a fetch batch sitting
    # unread in the client buffer must not be committed away)


class TopicPartitionList:
    def __init__(self) -> None:
        self.elements: List[Tuple[str, int, Optional[int]]] = []

    def add_partition(self, topic: str, partition: int) -> "TopicPartitionList":
        self.elements.append((topic, partition, None))
        return self

    def add_partition_offset(
        self, topic: str, partition: int, offset: int
    ) -> "TopicPartitionList":
        self.elements.append((topic, partition, offset))
        return self


class BaseConsumer:
    """assign/seek/poll fetch loop (sim consumer; fetch byte budgets from
    config: fetch.max.bytes / max.partition.fetch.bytes).

    With a ``group.id`` in the config, ``subscribe`` joins a broker-side
    consumer group (range assignor, eager rebalance, committed offsets —
    **beyond the reference**, whose sim has no groups): partitions are
    split across the group's members, a generation bump observed at the
    next poll triggers reassignment from committed offsets, and
    ``enable.auto.commit`` (default true, interval
    ``auto.commit.interval.ms``) commits consumed positions on poll.
    Without a group id, ``subscribe`` keeps the reference sim's semantics:
    the consumer takes every partition from the low watermark."""

    POLL_TICK_S = 0.01

    _conn_cls = _BrokerConn  # real/kafka.py overrides
    _sleep = staticmethod(mstime.sleep)
    _now_instant = staticmethod(mstime.now_instant)

    def __init__(self, config: ClientConfig):
        self._conn = self._conn_cls(config)
        self._fetch_max = config.get_int("fetch.max.bytes", 52_428_800)
        self._partition_max = config.get_int("max.partition.fetch.bytes", 1_048_576)
        self._assignments: List[_Assignment] = []
        self._buffer: List[OwnedMessage] = []
        self._rr = 0
        self._group = config.get("group.id")
        self._member: Optional[str] = None
        self._generation = -1
        self._auto_commit = config.get("enable.auto.commit", "true") == "true"
        self._commit_interval_s = (
            config.get_float("auto.commit.interval.ms", 5000.0) / 1000.0
        )
        self._last_commit = None  # Instant of the last auto-commit

    async def subscribe(self, topics: List[str]) -> None:
        """Replaces any previous subscription, like rdkafka's subscribe.
        Group mode (``group.id`` set): join the group and take the range
        assignment. Groupless: assign every partition from the beginning
        (the reference sim's subscription = full assignment)."""
        self._assignments.clear()
        self._buffer.clear()
        if self._group is not None:
            member, gen, assigned = await self._conn.call(
                ("join_group", self._group, self._member, list(topics))
            )
            self._member = member
            await self._apply_assignment(gen, assigned)
            return
        for topic in topics:
            meta = await self._conn.call(("metadata", topic))
            for p in range(meta[topic]):
                await self._assign_one(topic, p, None)

    async def _apply_assignment(
        self, generation: int, assigned: List[Tuple[str, int]]
    ) -> None:
        """Adopt a group assignment: start each partition at its committed
        offset, or the low watermark when nothing was ever committed."""
        self._generation = generation
        self._assignments.clear()
        self._buffer.clear()
        self._rr = 0
        committed = await self._conn.call(
            ("committed", self._group, list(assigned))
        )
        for topic, partition, offset in committed:
            await self._assign_one(topic, partition, offset)

    async def _maybe_rebalance(self) -> None:
        """Group heartbeat: adopt the new assignment when the generation
        moved (another member joined or left). Commits consumed positions
        FIRST when auto-commit is on (librdkafka's commit-on-revoke),
        which narrows — but, as in Kafka's eager protocol, cannot close —
        the at-least-once redelivery window: a member that fetches a
        handed-over partition BEFORE the old owner's next poll commits
        will re-deliver that owner's uncommitted tail. Exactly-once needs
        explicit commit() discipline, same as the real system."""
        gen, assigned = await self._conn.call(
            ("heartbeat", self._group, self._member)
        )
        if gen != self._generation:
            had_generation = self._generation >= 0
            # adopt the observed generation, then commit ONLY the
            # positions this member retains under the new assignment.
            # Committing a revoked partition here could roll the group's
            # offset backward past the new owner's progress — the exact
            # rollback the broker's generation fence exists to stop; a
            # member that merely heard the new generation number must not
            # launder stale positions through it. The revoked tail is
            # redelivered to the new owner: the eager protocol's
            # at-least-once window, as in Kafka itself.
            self._generation = gen
            if self._auto_commit and had_generation:
                keep = {tuple(tp) for tp in assigned}
                offsets = [
                    (a.topic, a.partition, a.consumed)
                    for a in self._assignments
                    if (a.topic, a.partition) in keep
                ]
                if offsets:
                    await self._conn.call(
                        ("commit", self._group, offsets, gen)
                    )
            await self._apply_assignment(gen, assigned)

    async def commit(self) -> None:
        """Commit the current consume positions (rdkafka commit_consumer_
        state shape). No-op outside a group."""
        if self._group is None or not self._assignments:
            return
        await self._conn.call(
            ("commit", self._group,
             [(a.topic, a.partition, a.consumed) for a in self._assignments],
             self._generation)
        )

    async def committed(self, tpl: "TopicPartitionList") -> List[Tuple[str, int, Optional[int]]]:
        """The group's committed offsets for the listed partitions."""
        if self._group is None:
            raise KafkaError("committed() requires a group.id")
        return await self._conn.call(
            ("committed", self._group,
             [(t, p) for t, p, _o in tpl.elements])
        )

    async def unsubscribe(self) -> None:
        """Leave the group (triggering a rebalance for the survivors) and
        drop all assignments."""
        if self._group is not None and self._member is not None:
            if self._auto_commit:
                await self.commit()
            await self._conn.call(("leave_group", self._group, self._member))
            self._member = None
            self._generation = -1
        self._assignments.clear()
        self._buffer.clear()

    async def assign(self, tpl: TopicPartitionList) -> None:
        self._assignments.clear()
        self._buffer.clear()
        for topic, partition, offset in tpl.elements:
            await self._assign_one(topic, partition, offset)

    async def _assign_one(self, topic: str, partition: int, offset: Optional[int]) -> None:
        if offset is None:
            wm: Watermarks = await self._conn.call(("watermarks", topic, partition))
            offset = wm.low
        self._assignments.append(
            _Assignment(topic, partition, offset, consumed=offset)
        )

    def seek(self, topic: str, partition: int, offset: int) -> None:
        for a in self._assignments:
            if a.topic == topic and a.partition == partition:
                a.position = offset
                a.consumed = offset
                self._buffer = [
                    m for m in self._buffer
                    if not (m.topic == topic and m.partition == partition)
                ]
                return
        raise KafkaError(f"not assigned: {topic}[{partition}]")

    async def _fetch_round(self) -> None:
        if not self._assignments:
            return
        n = len(self._assignments)
        for i in range(n):
            a = self._assignments[(self._rr + i) % n]
            msgs: List[OwnedMessage] = await self._conn.call(
                ("fetch", a.topic, a.partition, a.position,
                 self._fetch_max, self._partition_max)
            )
            if msgs:
                a.position = msgs[-1].offset + 1
                self._buffer.extend(msgs)
                self._rr = (self._rr + i + 1) % n
                return
        self._rr = (self._rr + 1) % n

    async def poll(self, timeout_s: float = 1.0) -> Optional[OwnedMessage]:
        deadline = self._now_instant() + timeout_s
        heartbeated = False
        while True:
            if self._buffer:
                # buffered message ready: no broker round-trips at all —
                # draining a fetch batch must not pay a heartbeat per
                # message (rebalance detection waits for the next empty
                # poll, like librdkafka's background-interval heartbeat)
                return self._consume(self._buffer.pop(0))
            if (
                self._group is not None
                and self._member is not None
                and not heartbeated
            ):
                # at most one heartbeat per poll() call (idle 1 s polls
                # spin ~100 ticks; re-heartbeating each tick buys nothing)
                heartbeated = True
                await self._maybe_rebalance()
                await self._maybe_auto_commit()
                if self._buffer:  # rebalance may not clear a fresh fetch
                    return self._consume(self._buffer.pop(0))
            await self._fetch_round()
            if self._buffer:
                return self._consume(self._buffer.pop(0))
            if self._now_instant() >= deadline:
                return None
            await self._sleep(self.POLL_TICK_S)

    def _consume(self, msg: OwnedMessage) -> OwnedMessage:
        for a in self._assignments:
            if a.topic == msg.topic and a.partition == msg.partition:
                a.consumed = msg.offset + 1
                break
        return msg

    async def _maybe_auto_commit(self) -> None:
        """Commit positions once per auto.commit.interval.ms of virtual
        time (rdkafka's enable.auto.commit behavior)."""
        if not self._auto_commit:
            return
        now = self._now_instant()
        if self._last_commit is None:
            self._last_commit = now
            return
        if now >= self._last_commit + self._commit_interval_s:
            await self.commit()
            self._last_commit = now

    async def fetch_watermarks(
        self, topic: str, partition: int, _timeout_s: float = 1.0
    ) -> Tuple[int, int]:
        wm: Watermarks = await self._conn.call(("watermarks", topic, partition))
        return wm.low, wm.high

    async def offsets_for_times(
        self, tpl: TopicPartitionList, _timeout_s: float = 1.0
    ) -> List[Tuple[str, int, Optional[int]]]:
        queries = [(t, p, o or 0) for t, p, o in tpl.elements]
        return await self._conn.call(("offsets_for_times", queries))


class StreamConsumer(BaseConsumer):
    """Await-forever message stream (rdkafka ``StreamConsumer::recv``)."""

    async def recv(self) -> OwnedMessage:
        while True:
            msg = await self.poll(timeout_s=60.0)
            if msg is not None:
                return msg

    def stream(self) -> "StreamConsumer":
        return self

    def __aiter__(self) -> "StreamConsumer":
        return self

    async def __anext__(self) -> OwnedMessage:
        return await self.recv()


# -- admin (sim/admin.rs) ---------------------------------------------------


@dataclass
class NewTopic:
    name: str
    num_partitions: int = 1

    @staticmethod
    def new(name: str, num_partitions: int) -> "NewTopic":
        return NewTopic(name, num_partitions)


class AdminClient:
    _conn_cls = _BrokerConn  # real/kafka.py overrides

    def __init__(self, config: ClientConfig):
        self._conn = self._conn_cls(config)

    async def create_topics(self, topics: List[NewTopic]) -> List[Optional[str]]:
        """Returns per-topic error strings (None = success), like the
        rdkafka admin result vector."""
        out: List[Optional[str]] = []
        for t in topics:
            try:
                await self._conn.call(("create_topic", t.name, t.num_partitions))
                out.append(None)
            except KafkaError as e:
                out.append(str(e))
        return out

    async def delete_topics(self, names: List[str]) -> List[Optional[str]]:
        out: List[Optional[str]] = []
        for name in names:
            try:
                await self._conn.call(("delete_topic", name))
                out.append(None)
            except KafkaError as e:
                out.append(str(e))
        return out

    async def fetch_metadata(self, topic: Optional[str] = None) -> Dict[str, int]:
        return await self._conn.call(("metadata", topic))
