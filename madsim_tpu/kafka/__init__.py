"""Kafka simulation — the madsim-rdkafka analogue.

The reference vendors the rust-rdkafka API and swaps its transport for a
simulated broker (madsim-rdkafka/src/sim/, 3.1 kLoC): one global ``Broker``
served over Endpoint connections with a request enum
(sim_broker.rs:14-77). Here:

- :mod:`broker` — topics → partitions → message logs with
  log-end-offsets/watermarks, round-robin produce assignment, timestamp
  lookup, byte-budgeted fetch (broker.rs:80-146)
- :mod:`server` — ``SimBroker().serve(addr)`` node (sim_broker.rs)
- :mod:`client` — ``ClientConfig`` (string map, consumer.rs:70-103),
  ``BaseProducer`` (buffer until flush) / ``FutureProducer``,
  ``BaseConsumer`` (assign/seek/poll) / ``StreamConsumer``,
  ``AdminClient`` (create/delete topics)
- :mod:`wire` — the GENUINE Kafka binary protocol (framing, headers,
  record-batch v2 + CRC32C, full consumer-group API) serving the same
  ``Broker`` on both tiers (docs/wire.md); :mod:`probe` is the vendored
  wire client, :mod:`fuzz` the seeded wire-vs-broker differential
"""

from .broker import OwnedMessage, Watermarks
from .client import (
    AdminClient,
    BaseConsumer,
    BaseProducer,
    BaseRecord,
    ClientConfig,
    FutureProducer,
    FutureRecord,
    KafkaError,
    NewTopic,
    StreamConsumer,
    TopicPartitionList,
)
from .server import SimBroker

__all__ = [
    "AdminClient",
    "BaseConsumer",
    "BaseProducer",
    "BaseRecord",
    "ClientConfig",
    "FutureProducer",
    "FutureRecord",
    "KafkaError",
    "NewTopic",
    "OwnedMessage",
    "SimBroker",
    "StreamConsumer",
    "TopicPartitionList",
    "Watermarks",
]
