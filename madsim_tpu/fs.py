"""Simulated per-node filesystem (ref madsim/src/sim/fs.rs:24-257).

Each node has an in-memory file table ``path -> INode``.  Crash semantics
are a deliberate strengthening of the reference (whose ``power_fail`` is a
TODO stub, fs.rs:50-53): *every* mutation — writes, truncation by
``File.create`` over an existing path, and ``remove_file`` — is buffered in
a per-inode shadow state until ``sync_all``; node kill/restart triggers
``power_fail``, which discards unsynced data: dirty buffers are dropped,
never-synced files disappear, and unsynced removals are resurrected.
``remove_file(durable=True)`` opts into an immediately-durable unlink
(the "journaled fs + directory fsync" model).

Gray failures (docs/faults.md): a fault schedule can open a *slow-disk
window* on a node (``FsSim.stall_fsync``/``unstall_fsync``) during which
``sync_all`` defers — the call returns but nothing becomes durable until
the window closes; the schedule's ``power_fail`` action drives
``FsSim.power_fail`` directly, so crash-without-sync is a first-class
campaign fault rather than a side effect of kill.
"""

from __future__ import annotations

from typing import Dict, Optional

from .context import current_node
from .plugin import Simulator, simulator
from .task import NodeId


class _INode:
    __slots__ = ("synced", "dirty", "removed", "sync_requested",
                 "remove_requested")

    def __init__(self, durable: bool = False) -> None:
        # synced=None => the file has never been made durable
        self.synced: Optional[bytearray] = bytearray() if durable else None
        self.dirty: Optional[bytearray] = None  # copy-on-write until sync
        self.removed = False  # unsynced unlink tombstone
        # slow-disk bookkeeping (fsync-stall windows, engine/faults.py
        # gray failures): a sync issued while the node's disk is stalled
        # defers — the flag marks it pending so ``unstall_fsync`` can
        # apply it; a durable unlink issued while stalled likewise defers
        # its directory fsync
        self.sync_requested = False
        self.remove_requested = False

    def data(self) -> bytearray:
        if self.dirty is not None:
            return self.dirty
        if self.synced is not None:
            return self.synced
        return bytearray()

    def for_write(self) -> bytearray:
        if self.dirty is None:
            self.dirty = bytearray(self.synced or b"")
        return self.dirty

    def sync(self) -> None:
        self.removed = False
        self.sync_requested = False
        self.remove_requested = False
        if self.dirty is not None:
            self.synced = self.dirty
            self.dirty = None
        elif self.synced is None:
            self.synced = bytearray()

    def power_fail(self) -> bool:
        """Drop unsynced state; returns False if the inode itself vanishes
        (it was never synced)."""
        self.dirty = None
        self.removed = False
        self.sync_requested = False
        self.remove_requested = False
        return self.synced is not None


class FsSim(Simulator):
    """Filesystem simulator plugin (ref ``FsSim``, fs.rs:24-96)."""

    def __init__(self, rng, time, config):
        super().__init__(rng, time, config)
        self._nodes: Dict[NodeId, Dict[str, _INode]] = {}
        self._fsync_stalled: set = set()  # nodes inside a slow-disk window

    def create_node(self, id: NodeId) -> None:
        self._nodes.setdefault(id, {})

    def reset_node(self, id: NodeId) -> None:
        self.power_fail(id)

    def _table(self, id: NodeId) -> Dict[str, _INode]:
        return self._nodes.setdefault(id, {})

    def power_fail(self, id: NodeId) -> None:
        """Crash the node's storage back to its last-synced state
        (ref fs.rs:50-53, implemented here)."""
        table = self._table(id)
        for path in list(table):
            if not table[path].power_fail():
                del table[path]

    # -- slow-disk windows (gray failures, docs/faults.md) -----------------

    def fsync_stalled(self, id: NodeId) -> bool:
        return id in self._fsync_stalled

    def stall_fsync(self, id: NodeId) -> None:
        """Open a slow-disk window: syncs issued on the node defer (the
        write cache absorbs them — nothing becomes durable) until
        ``unstall_fsync``. A power fail inside the window drops them."""
        self._fsync_stalled.add(id)

    def unstall_fsync(self, id: NodeId) -> None:
        """Close the window: the disk catches up — every deferred sync
        applies, deferred durable unlinks finalize."""
        self._fsync_stalled.discard(id)
        table = self._table(id)
        for path in list(table):
            inode = table[path]
            if inode.remove_requested:
                del table[path]
            elif inode.sync_requested and not inode.removed:
                inode.sync()

    def get_file_size(self, id: NodeId, path: str) -> int:
        inode = self._table(id).get(str(path))
        if inode is None or inode.removed:
            raise FileNotFoundError(path)
        return len(inode.data())


def _fs() -> FsSim:
    return simulator(FsSim)


def _node_table() -> Dict[str, _INode]:
    return _fs()._table(current_node().id)


def _lookup(path: str) -> _INode:
    inode = _node_table().get(str(path))
    if inode is None or inode.removed:
        raise FileNotFoundError(path)
    return inode


class File:
    """Async file handle (ref ``fs::File``, fs.rs:98-220)."""

    def __init__(self, inode: _INode, path: str):
        self._inode = inode
        self.path = path

    @staticmethod
    async def open(path: str) -> "File":
        return File(_lookup(path), str(path))

    @staticmethod
    async def create(path: str) -> "File":
        """Create or truncate; the truncation is buffered until sync_all,
        so a crash before sync restores the previous durable contents."""
        table = _node_table()
        inode = table.get(str(path))
        if inode is None:
            inode = _INode()
            table[str(path)] = inode
        inode.removed = False
        # re-creating the path supersedes any deferred durable unlink
        # (else unstall_fsync would delete the re-created file)
        inode.remove_requested = False
        inode.dirty = bytearray()
        return File(inode, str(path))

    @staticmethod
    async def open_or_create(path: str) -> "File":
        table = _node_table()
        inode = table.get(str(path))
        if inode is None or inode.removed:
            if inode is None:
                inode = _INode()
                table[str(path)] = inode
            inode.removed = False
            inode.remove_requested = False
            inode.dirty = bytearray()
        return File(inode, str(path))

    async def read_at(self, buf_len: int, offset: int) -> bytes:
        data = self._inode.data()
        return bytes(data[offset : offset + buf_len])

    async def read_all(self) -> bytes:
        return bytes(self._inode.data())

    async def write_all_at(self, buf: bytes, offset: int) -> None:
        data = self._inode.for_write()
        end = offset + len(buf)
        if len(data) < end:
            data.extend(b"\x00" * (end - len(data)))
        data[offset:end] = buf

    async def write_all(self, buf: bytes) -> None:
        self._inode.for_write().extend(buf)

    async def set_len(self, size: int) -> None:
        data = self._inode.for_write()
        if size <= len(data):
            del data[size:]
        else:
            data.extend(b"\x00" * (size - len(data)))

    async def sync_all(self) -> None:
        # inside a slow-disk window the sync defers: the call returns (the
        # lying write cache) but durability is pending — a power fail
        # before the window closes drops the data (docs/faults.md)
        if _fs().fsync_stalled(current_node().id):
            self._inode.sync_requested = True
        else:
            self._inode.sync()

    async def metadata(self) -> "Metadata":
        return Metadata(len(self._inode.data()))


class Metadata:
    def __init__(self, size: int):
        self._size = size

    def len(self) -> int:
        return self._size

    def is_file(self) -> bool:
        return True


async def read(path: str) -> bytes:
    """ref ``fs::read`` (fs.rs:230-240)."""
    f = await File.open(path)
    return await f.read_all()


async def write(path: str, data: bytes) -> None:
    f = await File.create(path)
    await f.write_all(data)
    await f.sync_all()


async def metadata(path: str) -> Metadata:
    f = await File.open(path)
    return await f.metadata()


async def remove_file(path: str, durable: bool = False) -> None:
    """Unlink.  By default the removal is buffered (a crash before any
    subsequent sync resurrects the file); ``durable=True`` = unlink +
    directory fsync."""
    table = _node_table()
    inode = table.get(str(path))
    if inode is None or inode.removed:
        raise FileNotFoundError(path)
    if durable and _fs().fsync_stalled(current_node().id):
        # the directory fsync defers with the rest of the stalled disk:
        # tombstone now, finalize at unstall — a power fail in between
        # resurrects the file, exactly like a buffered removal
        inode.removed = True
        inode.dirty = None
        inode.remove_requested = True
    elif durable or inode.synced is None:
        del table[str(path)]
    else:
        inode.removed = True
        inode.dirty = None
