// simcore — the native runtime core of the host tier.
//
// The reference's native surface is Rust + libc interposition; ours is the
// executor's hot data structures in C++ (SURVEY.md §2 "native" mapping):
//
//  * TimerHeap  — the virtual-time timer queue (the naive-timer binary heap
//    of madsim/src/sim/time/mod.rs:21-230), ordered by (deadline, seq) with
//    the same FIFO tie-break as the Python heapq path, so swapping the
//    backend never changes a schedule.
//  * ReadyQueue — the random-pop ready queue (swap_remove semantics of
//    madsim/src/sim/utils/mpsc.rs:71-84); the *index* still comes from the
//    Python GlobalRng so the RNG draw sequence is byte-identical.
//  * threefry2x32 — JAX-compatible Threefry-2x32 (20 rounds, rotation
//    schedule and key constant per the Salmon et al. reference
//    implementation used by jax.random), for native bit-exact replay of
//    device-engine randomness without importing JAX.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 simcore.cpp -o _simcore.so

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- TimerHeap

struct TimerEntry {
  int64_t deadline;
  uint64_t seq;
  uint64_t id;
};

struct TimerHeap {
  std::vector<TimerEntry> heap;
  uint64_t next_seq = 0;
};

static bool timer_later(const TimerEntry& a, const TimerEntry& b) {
  // max-heap comparator inverted -> min-heap on (deadline, seq)
  if (a.deadline != b.deadline) return a.deadline > b.deadline;
  return a.seq > b.seq;
}

TimerHeap* timer_heap_new() { return new TimerHeap(); }

void timer_heap_free(TimerHeap* h) { delete h; }

void timer_heap_push(TimerHeap* h, int64_t deadline, uint64_t id) {
  h->heap.push_back(TimerEntry{deadline, h->next_seq++, id});
  std::push_heap(h->heap.begin(), h->heap.end(), timer_later);
}

// Returns 1 and fills (deadline,id) of the minimum without removing it.
int timer_heap_peek(TimerHeap* h, int64_t* deadline, uint64_t* id) {
  if (h->heap.empty()) return 0;
  *deadline = h->heap.front().deadline;
  *id = h->heap.front().id;
  return 1;
}

int timer_heap_pop(TimerHeap* h, int64_t* deadline, uint64_t* id) {
  if (h->heap.empty()) return 0;
  *deadline = h->heap.front().deadline;
  *id = h->heap.front().id;
  std::pop_heap(h->heap.begin(), h->heap.end(), timer_later);
  h->heap.pop_back();
  return 1;
}

uint64_t timer_heap_len(TimerHeap* h) { return h->heap.size(); }

// --------------------------------------------------------------- ReadyQueue

struct ReadyQueue {
  std::vector<uint64_t> items;
};

ReadyQueue* ready_queue_new() { return new ReadyQueue(); }

void ready_queue_free(ReadyQueue* q) { delete q; }

void ready_queue_push(ReadyQueue* q, uint64_t id) { q->items.push_back(id); }

uint64_t ready_queue_len(ReadyQueue* q) { return q->items.size(); }

// Swap-remove the element at `idx` (the caller draws idx from GlobalRng —
// ref try_recv_random, mpsc.rs:73-83). Returns the removed id.
uint64_t ready_queue_swap_remove(ReadyQueue* q, uint64_t idx) {
  uint64_t id = q->items[idx];
  q->items[idx] = q->items.back();
  q->items.pop_back();
  return id;
}

// -------------------------------------------------------------- threefry2x32

// JAX-compatible Threefry-2x32, 20 rounds (5 blocks of 4), rotations per
// the Random123 reference. key/ctr are two 32-bit words each.
static const unsigned ROT[8] = {13, 15, 26, 6, 17, 29, 16, 24};

static inline uint32_t rotl32(uint32_t x, unsigned d) {
  return (x << d) | (x >> (32 - d));
}

void threefry2x32(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1,
                  uint32_t* out0, uint32_t* out1) {
  uint32_t ks[3] = {k0, k1, k0 ^ k1 ^ 0x1BD11BDAu};
  uint32_t x0 = c0 + ks[0];
  uint32_t x1 = c1 + ks[1];
  for (unsigned block = 0; block < 5; ++block) {
    const unsigned* r = ROT + (block % 2 ? 4 : 0);
    for (unsigned i = 0; i < 4; ++i) {
      x0 += x1;
      x1 = rotl32(x1, r[i]);
      x1 ^= x0;
    }
    unsigned s = block + 1;
    x0 += ks[s % 3];
    x1 += ks[(s + 1) % 3] + s;
  }
  *out0 = x0;
  *out1 = x1;
}

// Batch helper: n counters (pairs), writes n output pairs.
void threefry2x32_batch(uint32_t k0, uint32_t k1, const uint32_t* ctr,
                        uint32_t* out, uint64_t n) {
  for (uint64_t i = 0; i < n; ++i) {
    threefry2x32(k0, k1, ctr[2 * i], ctr[2 * i + 1], &out[2 * i],
                 &out[2 * i + 1]);
  }
}

}  // extern "C"
