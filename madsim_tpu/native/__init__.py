"""Native runtime core: lazy g++ build + ctypes bindings.

Loads ``_simcore.so`` (building it from simcore.cpp on first import if
needed — no pybind11 in this image, so the ABI is plain C via ctypes).
``available()`` reports whether the native tier is usable; every consumer
has a pure-Python fallback, and ``MADSIM_NO_NATIVE=1`` forces it off.

The swap is *schedule-transparent*: the native TimerHeap orders by
(deadline, insertion seq) exactly like the Python heapq path, and the
ReadyQueue only executes swap-removes at indices drawn from the Python
GlobalRng — same draws, same order, same schedules.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "simcore.cpp")
_SO = os.path.join(_DIR, "_simcore.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _compile_atomic(cmd_prefix: list, src: str, dst: str) -> bool:
    """Compile to a pid-suffixed temp file, then os.rename into place.

    Concurrent first-builders (forked procs-sweep children, parallel pytest
    workers) would otherwise interleave compiler writes into the same .so
    and leave a corrupt artifact behind; rename is atomic, so a concurrent
    loader sees either the old or the complete new file.
    """
    tmp = f"{dst}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            cmd_prefix + [src, "-o", tmp],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.rename(tmp, dst)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _build() -> bool:
    return _compile_atomic(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17"], _SRC, _SO
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed or os.environ.get("MADSIM_NO_NATIVE"):
        return None
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
        if not _build():
            _load_failed = True  # don't re-run a failing compile per Runtime
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        _load_failed = True
        return None
    u64, i64, u32 = ctypes.c_uint64, ctypes.c_int64, ctypes.c_uint32
    p = ctypes.POINTER
    lib.timer_heap_new.restype = ctypes.c_void_p
    lib.timer_heap_free.argtypes = [ctypes.c_void_p]
    lib.timer_heap_push.argtypes = [ctypes.c_void_p, i64, u64]
    lib.timer_heap_peek.argtypes = [ctypes.c_void_p, p(i64), p(u64)]
    lib.timer_heap_pop.argtypes = [ctypes.c_void_p, p(i64), p(u64)]
    lib.timer_heap_len.argtypes = [ctypes.c_void_p]
    lib.timer_heap_len.restype = u64
    lib.ready_queue_new.restype = ctypes.c_void_p
    lib.ready_queue_free.argtypes = [ctypes.c_void_p]
    lib.ready_queue_push.argtypes = [ctypes.c_void_p, u64]
    lib.ready_queue_len.argtypes = [ctypes.c_void_p]
    lib.ready_queue_len.restype = u64
    lib.ready_queue_swap_remove.argtypes = [ctypes.c_void_p, u64]
    lib.ready_queue_swap_remove.restype = u64
    lib.threefry2x32.argtypes = [u32, u32, u32, u32, p(u32), p(u32)]
    lib.threefry2x32_batch.argtypes = [u32, u32, p(u32), p(u32), u64]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


class TimerHeap:
    """Native (deadline, seq)-ordered timer heap; callbacks stay in Python
    keyed by the u64 id."""

    __slots__ = ("_h", "_lib")

    def __init__(self) -> None:
        self._lib = _load()
        assert self._lib is not None, "native simcore unavailable"
        self._h = self._lib.timer_heap_new()

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_h", None):
            lib.timer_heap_free(self._h)
            self._h = None

    def push(self, deadline_ns: int, id: int) -> None:
        self._lib.timer_heap_push(self._h, deadline_ns, id)

    def peek(self) -> Optional[tuple]:
        d, i = ctypes.c_int64(), ctypes.c_uint64()
        if not self._lib.timer_heap_peek(self._h, ctypes.byref(d), ctypes.byref(i)):
            return None
        return d.value, i.value

    def pop(self) -> Optional[tuple]:
        d, i = ctypes.c_int64(), ctypes.c_uint64()
        if not self._lib.timer_heap_pop(self._h, ctypes.byref(d), ctypes.byref(i)):
            return None
        return d.value, i.value

    def __len__(self) -> int:
        return self._lib.timer_heap_len(self._h)


class ReadyQueue:
    """Native swap-remove vector (ref mpsc try_recv_random)."""

    __slots__ = ("_q", "_lib")

    def __init__(self) -> None:
        self._lib = _load()
        assert self._lib is not None, "native simcore unavailable"
        self._q = self._lib.ready_queue_new()

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_q", None):
            lib.ready_queue_free(self._q)
            self._q = None

    def push(self, id: int) -> None:
        self._lib.ready_queue_push(self._q, id)

    def swap_remove(self, idx: int) -> int:
        return self._lib.ready_queue_swap_remove(self._q, idx)

    def __len__(self) -> int:
        return self._lib.ready_queue_len(self._q)


def threefry2x32(k0: int, k1: int, c0: int, c1: int) -> tuple:
    """One JAX-compatible Threefry-2x32 block (for native replay of
    device-engine draws)."""
    lib = _load()
    assert lib is not None, "native simcore unavailable"
    o0, o1 = ctypes.c_uint32(), ctypes.c_uint32()
    lib.threefry2x32(k0, k1, c0, c1, ctypes.byref(o0), ctypes.byref(o1))
    return o0.value, o1.value


def fold_in(k0: int, k1: int, data: int) -> tuple:
    """jax.random.fold_in on raw key words: threefry(key, seed-words(data))."""
    return threefry2x32(k0, k1, (data >> 32) & 0xFFFFFFFF, data & 0xFFFFFFFF)


def random_bits(k0: int, k1: int, n: int) -> list:
    """jax.random.bits(key, (n,), uint32) under jax_threefry_partitionable
    (the default): word i is the XOR of the threefry output pair for
    counter (i >> 32, i & 0xffffffff). This is the exact draw stream the
    device engine consumes (engine/rng.py event_bits), reproduced natively."""
    out = []
    for i in range(n):
        o0, o1 = threefry2x32(k0, k1, (i >> 32) & 0xFFFFFFFF, i & 0xFFFFFFFF)
        out.append(o0 ^ o1)
    return out


# ---------------------------------------------------------------- simloop
# The compiled executor core (CPython extension, simloop.c): Future/Sleep/
# Timers/Loop. Unlike the ctypes structures above (whose per-call overhead
# caps their value), this runs the whole per-poll hot sequence in C.

_SIMLOOP_SRC = os.path.join(_DIR, "simloop.c")
_SIMLOOP_SO = os.path.join(_DIR, "_simloop.so")

_simloop_mod = None
_simloop_failed = False


def _build_simloop() -> bool:
    import sysconfig

    return _compile_atomic(
        [
            # plain C: tentative type definitions + the CPython C API
            "gcc", "-O2", "-shared", "-fPIC", "-std=c11",
            "-I" + sysconfig.get_paths()["include"],
        ],
        _SIMLOOP_SRC,
        _SIMLOOP_SO,
    )


def simloop():
    """The `_simloop` extension module, or None (build failure or
    MADSIM_NO_NATIVE=1). Built lazily like the ctypes core."""
    global _simloop_mod, _simloop_failed
    if _simloop_mod is not None:
        return _simloop_mod
    if _simloop_failed or os.environ.get("MADSIM_NO_NATIVE"):
        return None
    if not os.path.exists(_SIMLOOP_SO) or (
        os.path.getmtime(_SIMLOOP_SO) < os.path.getmtime(_SIMLOOP_SRC)
    ):
        if not _build_simloop():
            _simloop_failed = True
            return None
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "madsim_tpu.native._simloop", _SIMLOOP_SO
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception:
        _simloop_failed = True
        return None
    _simloop_mod = mod
    return mod
